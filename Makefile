.PHONY: proto test lint

proto:
	protoc --python_out=seldon_tpu/proto -I seldon_tpu/proto seldon_tpu/proto/prediction.proto

test:
	python -m pytest tests/ -x -q
