# CI targets (reference: Jenkinsfile -> Makefile.ci + per-module Makefiles).
.PHONY: proto test test-e2e tier1 lint sanitize trace-smoke compile-audit sched-audit pilot-audit spec-audit roof-audit mesh-audit heal-audit bench bench-compare bench-orchestrator native native-tsan ci fuzz-alloc fuzz-chaos fuzz-graftsan

# tier1 uses PIPESTATUS / pipefail (bash-isms).
tier1: SHELL := /bin/bash

proto:
	protoc --python_out=seldon_tpu/proto -I seldon_tpu/proto seldon_tpu/proto/prediction.proto

native:
	$(MAKE) -C native

# Static invariants (docs/operations.md "Static invariants: graftlint"):
# hot-sync, lock-guard, lockorder, retrace, outcome, env-knob vs the
# checked-in baseline, plus the graftflow dataflow trio (docs/operations.md
# "Static dataflow: graftflow"): shape-lattice certification, the
# (paged, chunked, prefix) config-reachability matrix with its dense-slab
# kill-list, and the sharding-consistency rules — plus the graftnum
# numerics/lifetime certifier (docs/operations.md "Numerics invariants:
# graftnum"): num-barrier (quantize scales + int8 dequant products must be
# optimization_barrier-pinned before materialization boundaries),
# use-after-donate (reads of donated jit buffers + host-side captures),
# and einsum-broadcast/mask-dtype (silent size-1 label broadcast, bf16
# mask fill). Prints per-pass graftnum counts next to the kill-list
# needle and fails if the lint run itself exceeds its 60 s self-runtime
# budget — then a bytecode-compile sweep of the serving + tools trees.
lint:
	python -m tools.graftlint
	python -m compileall -q seldon_tpu tools

# Dynamic half of the concurrency contract (docs/operations.md "Dynamic
# sanitizer: graftsan"): the engine-facing tier-1 subset re-run under
# GRAFTSAN=1 — order-asserting lock proxies, boundary refcount/slot
# audits, terminal-item enforcement, seeded interleaving perturbation.
sanitize:
	env JAX_PLATFORMS=cpu GRAFTSAN=1 GRAFTSAN_SEED=$${GRAFTSAN_SEED:-0} \
	  python -m pytest tests/test_graftsan.py tests/test_lifecycle.py \
	  tests/test_chaos.py tests/test_paged_kv.py \
	  tests/test_chunked_prefill.py tests/test_prefix_cache.py \
	  -x -q -m "not slow"

test:
	python -m pytest tests/ -x -q -m "not e2e"

test-e2e:
	python -m pytest tests/ -x -q -m e2e

# The ROADMAP.md tier-1 verify line, verbatim: CPU-pinned, no -x (full
# count), log at /tmp/_t1.log, prints DOTS_PASSED for the driver.
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# Long-haul randomized sweep of the paged-KV block allocator. The fast
# tier runs the same test at FUZZ_EXAMPLES=300 (the pytest default).
fuzz-alloc:
	env JAX_PLATFORMS=cpu FUZZ_EXAMPLES=20000 \
	  python -m pytest tests/test_paged_kv.py -q -m fuzz

# Long-haul chaos soak of the request lifecycle (deadlines, cancels,
# injected dispatch/alloc faults, drain). Seeded: CHAOS_SEED replays a
# failing fault sequence byte-for-byte; FUZZ_EXAMPLES scales the number
# of requests per soak. tier-1 runs only the fast deterministic chaos
# tests (the soak here is marked slow).
fuzz-chaos:
	env JAX_PLATFORMS=cpu FUZZ_EXAMPLES=1000 CHAOS_SEED=$${CHAOS_SEED:-0} \
	  python -m pytest tests/test_chaos.py -q -m fuzz

# Long-haul graftsan soak: >=200 mixed dense/paged/chunked requests per
# run under the sanitizer. GRAFTSAN_SEED replays an interleaving
# schedule; FUZZ_EXAMPLES scales the request count (split across modes).
fuzz-graftsan:
	env JAX_PLATFORMS=cpu GRAFTSAN_SEED=$${GRAFTSAN_SEED:-0} \
	  FUZZ_EXAMPLES=$${FUZZ_EXAMPLES:-600} \
	  python -m pytest tests/test_graftsan.py -q -m fuzz

# Observability smoke (docs/operations.md "Reading a flight recording"):
# short loadtester run against the tiny server with TRACING=1 +
# FLIGHT_RECORDER=1 + GRAFTSAN=1 — asserts a non-empty span sink,
# end-to-end trace-id adoption, a valid Perfetto conversion of
# /debug/timeline, and zero graftsan violations.
trace-smoke:
	env JAX_PLATFORMS=cpu python -m tools.trace_smoke

# Compile/device observatory gate (docs/operations.md "Diagnosing a
# retrace storm"): warmed tiny server + loadtester with COMPILE_LEDGER +
# HBM_LEDGER + DISPATCH_TIMING on — asserts ZERO live retraces after
# warmup, a dispatched-variant count within the budget, per-variant
# timing reaching stats/recorder/trace_view, and the /debug/compile +
# /debug/hbm schemas. --static-xcheck additionally proves the runtime
# dispatch set is contained in graftflow's closed-form static lattice
# (engine.static_lattice()) and that warmup declared exactly that set.
compile-audit:
	env JAX_PLATFORMS=cpu python -m tools.compile_audit --static-xcheck

# Scheduler waste observatory gate (docs/benchmarking.md "Reading the
# waste report"): warmed tiny server + loadtester with SCHED_LEDGER +
# FLIGHT_RECORDER on — asserts zero attribution on the idle engine, the
# conservation invariant (useful + pad tokens re-sum to dispatched
# cells; wait components re-sum to total wait), loadtester/route schema
# parity, the EngineStats mirror, and the trace_view waste counter lane.
sched-audit:
	env JAX_PLATFORMS=cpu python -m tools.sched_audit

# Pilot controller gate (docs/operations.md "Flying with the
# autopilot"): warmed tiny chunked server + mixed-deadline loadtester
# under PILOT=1 + GRAFTSAN=1 — asserts the controller converges to a
# ledgered decision, every knob stays inside its clamp envelope, the
# conservation audit and sanitizer stay clean under the pilot, route /
# loadtester parity, the jaxserver_pilot_* gauges, and the trace_view
# decision lane.
pilot-audit:
	env JAX_PLATFORMS=cpu python -m tools.pilot_audit

# Speculative-decoding gate (docs/benchmarking.md "Speculative
# decoding"): the tiny server booted twice — plain, then SPEC=1 behind
# the real REST app under a loadtester window with GRAFTSAN +
# SCHED_LEDGER + COMPILE_LEDGER on — asserts bit-exact greedy parity,
# zero live retraces with the verify ladder inside the static lattice,
# the acceptance identity (accepted + rejected == drafted) and four-way
# conservation, loadtester/route parity, the jaxserver_spec_* gauges,
# and the trace_view verify lanes + acceptance counter.
spec-audit:
	env JAX_PLATFORMS=cpu python -m tools.spec_audit

# Roofline observatory gate (docs/benchmarking.md "Reading the
# roofline"): warmed tiny server + loadtester with ROOF_LEDGER +
# FLIGHT_RECORDER on — asserts the /debug index lists every surface,
# zero attribution on the idle engine, per-variant mfu/mbu in [0, 1]
# with sane compute/bandwidth/host bound labels, the step-decomposition
# conservation invariant (host-pre + device + host-post + overlap
# re-sum to the boundary wall within 1%), predicted-vs-measured inside
# a generous CPU band, loadtester/route parity, the jaxserver_mfu/mbu/
# host_frac gauges, and the trace_view host/device lanes.
roof-audit:
	env JAX_PLATFORMS=cpu python -m tools.roof_audit

# Tensor-parallel serving gate (docs/operations.md "Serving on the
# mesh"): the tiny ragged server booted twice on the fake 8-device CPU
# mesh — pinned to an explicit single chip (tp=1), then as a TP=2
# group via the env knob behind the real REST app — under a loadtester
# window with GRAFTSAN + SCHED_LEDGER + COMPILE_LEDGER + HBM_LEDGER +
# ROOF_LEDGER on. Asserts bit-exact greedy parity across a mixed-length
# prompt matrix, one sealed lattice with zero live retraces for the
# whole group, four-way sched + roofline conservation, zero sanitizer
# violations, zero live KV bytes after the drain (leak-free), and the
# per-device HBM invariants (weights = per-device x devices, KV
# reservation halved per chip).
mesh-audit:
	env JAX_PLATFORMS=cpu python -m tools.mesh_audit

# Supervised fault-recovery gate (docs/operations.md "Surviving a wave
# fault"): the tiny server under HEAL=1 + CHAOS=1 — a seeded storm of
# dispatch faults, watchdog-length hangs and NaN injections with no
# poison source — asserts a greedy + sampled wave stays byte-identical
# to a clean reference engine, zero user-visible errors, /healthz ready
# through the storm, zero sanitizer violations and live retraces, the
# frozen /debug/health schema, the jaxserver_heal_* gauges, and the
# flight-recorder heal records + trace_view heal lane.
heal-audit:
	env JAX_PLATFORMS=cpu python -m tools.heal_audit

bench:
	python bench.py

# Perf-regression diff of two bench JSON files (docs/benchmarking.md
# "Comparing runs"): make bench-compare BASE=BENCH_r05.json CAND=BENCH_r06.json
bench-compare:
	python -m tools.bench_compare $(BASE) $(CAND)

bench-orchestrator:
	python bench_orchestrator.py

ci: lint test test-e2e sanitize trace-smoke compile-audit sched-audit pilot-audit spec-audit roof-audit mesh-audit heal-audit

native-tsan:
	$(MAKE) -C native tsan
