# CI targets (reference: Jenkinsfile -> Makefile.ci + per-module Makefiles).
.PHONY: proto test test-e2e bench bench-orchestrator native native-tsan ci

proto:
	protoc --python_out=seldon_tpu/proto -I seldon_tpu/proto seldon_tpu/proto/prediction.proto

native:
	$(MAKE) -C native

test:
	python -m pytest tests/ -x -q -m "not e2e"

test-e2e:
	python -m pytest tests/ -x -q -m e2e

bench:
	python bench.py

bench-orchestrator:
	python bench_orchestrator.py

ci: test test-e2e

native-tsan:
	$(MAKE) -C native tsan
