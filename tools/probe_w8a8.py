"""Probe: would W8A8 (int8 activations x int8 weights -> int32 on the
MXU) lift the compute-bound decode step? The round-5 profile shows
decode matmuls at ~75% of bf16 peak past the slot knee, so a native-rate
s8xs8 path would halve the compute floor IF the backend runs it at 2x.
This times the bench-1b MLP stack (same shapes as probe_qmm) three
ways: bf16 math (current path), s8xs8 -> s32 with output scaling, and
a dynamic per-token A8 quantize + s8xs8 (the real deployment shape of
the idea, quantize cost included).

Run alone on the real chip: python -m tools.probe_w8a8
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

B, D, F, L = 160, 2048, 5632, 16
CHUNK = 32


def quant_w(w):
    # graftlint: allow(num-barrier) probe: measures fusion alternatives
    # on purpose; cross-compilation bit-stability is not a contract here.
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    return jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8), s


def run(name, layer_fn, weights):
    @jax.jit
    def f(x, weights):
        def step(x, _):
            def body(h, ws):
                return layer_fn(h, ws), ()
            h, _ = jax.lax.scan(body, x, weights)
            return h * 1e-3 + x[0, 0] * 0, ()
        x, _ = jax.lax.scan(step, x, None, length=CHUNK)
        return x

    from tools.timing import slope_time

    x = jnp.ones((B, 1, D), jnp.bfloat16)
    dt, _ = slope_time(lambda s: f(s, weights), x, k1=2, k2=8)
    print(f"{name:16s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)


def main():
    ks = jax.random.split(jax.random.key(0), 3 * L)
    wg = jax.random.normal(ks[0], (L, D, F), jnp.float32) * 0.02
    wu = jax.random.normal(ks[1], (L, D, F), jnp.float32) * 0.02
    wd = jax.random.normal(ks[2], (L, F, D), jnp.float32) * 0.02
    (wgq, sg), (wuq, su), (wdq, sd) = quant_w(wg), quant_w(wu), quant_w(wd)

    def layer_bf16(h, ws):
        g, u, d = ws
        return h + jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.silu(jnp.einsum("bsd,df->bsf", h, g))
            * jnp.einsum("bsd,df->bsf", h, u), d)

    bf = (wg.astype(jnp.bfloat16), wu.astype(jnp.bfloat16),
          wd.astype(jnp.bfloat16))

    def mm_s8(x8, w8):
        # s8 x s8 -> s32: native-rate MXU path if the backend has one.
        return jax.lax.dot_general(
            x8, w8, (((x8.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    def quant_a(h):
        # dynamic per-token symmetric A8
        # graftlint: allow(num-barrier) probe leg: fusion freedom is the
        # measurement, not a hazard.
        s = jnp.max(jnp.abs(h), axis=-1, keepdims=True) / 127.0
        s = jnp.maximum(s, 1e-8)
        return jnp.clip(jnp.round(h / s), -127, 127).astype(jnp.int8), s

    def layer_w8a8_prequant(h, ws):
        # activations pretend-quantized for free (isolates MXU rate):
        g, sgv, u, suv, d, sdv = ws
        x8 = h.astype(jnp.int8)  # cast only; cost-free stand-in
        gate = mm_s8(x8, g).astype(jnp.bfloat16) * sgv.astype(jnp.bfloat16)
        up = mm_s8(x8, u).astype(jnp.bfloat16) * suv.astype(jnp.bfloat16)
        hid8 = (jax.nn.silu(gate) * up).astype(jnp.int8)
        down = mm_s8(hid8, d).astype(jnp.bfloat16) * sdv.astype(jnp.bfloat16)
        return h + down

    def layer_w8a8_dynamic(h, ws):
        # the real thing: quantize activations per token, scale outputs
        g, sgv, u, suv, d, sdv = ws
        x8, sa = quant_a(h)
        sc = sa.astype(jnp.bfloat16)
        gate = (mm_s8(x8, g).astype(jnp.bfloat16)
                * sc * sgv.astype(jnp.bfloat16))
        up = (mm_s8(x8, u).astype(jnp.bfloat16)
              * sc * suv.astype(jnp.bfloat16))
        hid = jax.nn.silu(gate) * up
        h8, sh = quant_a(hid)
        down = (mm_s8(h8, d).astype(jnp.bfloat16)
                * sh.astype(jnp.bfloat16) * sdv.astype(jnp.bfloat16))
        return h + down

    q = (wgq, sg, wuq, su, wdq, sd)
    run("bf16 (current)", layer_bf16, bf)
    run("s8xs8 cast-only", layer_w8a8_prequant, q)
    run("s8xs8 dynamic-A8", layer_w8a8_dynamic, q)


if __name__ == "__main__":
    main()
