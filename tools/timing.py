"""Slope-based timing for the tunneled TPU relay.

The axon relay adds a large fixed round-trip (~100 ms) to every host
sync, and enqueued executions run back-to-back server-side. Timing one
call therefore measures mostly the tunnel. `slope_time` times K1 and K2
chained executions with a single tiny fetch each and returns
(t(K2) - t(K1)) / (K2 - K1): pure per-execution device time, fixed
costs cancelled.
"""

from __future__ import annotations

import time
from typing import Callable

import jax


def _run_chain(step: Callable, state, k: int):
    t0 = time.perf_counter()
    for _ in range(k):
        state = step(state)
    # Fetch something tiny that depends on the chain.
    leaf = jax.tree.leaves(state)[0]
    _ = jax.device_get(jax.numpy.ravel(leaf)[:1])
    return time.perf_counter() - t0, state


def slope_time(step: Callable, state, k1: int = 2, k2: int = 10):
    """step: state -> state (chained device work). Returns (seconds per
    execution, final state)."""
    # Warm: compile + one round trip.
    _, state = _run_chain(step, state, 1)
    t1, state = _run_chain(step, state, k1)
    t2, state = _run_chain(step, state, k2)
    return (t2 - t1) / (k2 - k1), state
