#!/usr/bin/env python
"""CI heal audit: graftheal survives a seeded fault storm end to end.

Boots the tiny warmed JAXServer behind the real REST app with
``HEAL=1`` + ``CHAOS=1`` (+ ``GRAFTSAN=1``, ``FLIGHT_RECORDER=1``,
``COMPILE_LEDGER=1``) and a storm of dispatch faults, slow boundaries,
fetch hangs (past the watchdog) and NaN injections — every fault class
the supervisor recovers from without a user-visible error; disconnect
and the sticky poison rid stay off, so ZERO failed streams is the
contract, not a tolerance. One pass asserts:

 * idle engine -> the frozen /debug/health schema, state "healthy",
   every recovery counter at zero, pressure 0.0;
 * a fixed greedy + sampled submit wave through the CHAOS engine is
   BYTE-IDENTICAL to the same wave on a clean reference engine sharing
   the server's params (replay-based resurrection with per-position
   sampling keys makes that the contract, not a hope), with zero error
   items — and the storm really fired (the wave is topped up until at
   least one dispatch fault lands);
 * the supervisor recovered at least once and resurrected at least one
   request; quarantine and retry exhaustion stayed at zero (no poison
   source is armed);
 * /healthz stays ready THROUGH the storm (a recovering engine keeps
   serving — only not-loaded/draining read 503) and the loadtester
   completes requests against the faulting server;
 * the books stay clean: zero graftsan lock-contract violations and
   zero live retraces (resurrection re-enters existing prefill buckets,
   so recovery compiles nothing);
 * recoveries land as flight-recorder "heal" records carrying state +
   verdict counts, the jaxserver Prometheus surface exports the
   ``jaxserver_heal_*`` gauges, and ``tools/trace_view.py`` renders the
   heal lane + verdict counters.

Run via ``make heal-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import sys

# Frozen /debug/health top-level key set — tests/test_debug_schema.py
# carries the same golden; a mismatch here means the snapshot schema
# changed without updating its consumers.
HEALTH_TOP_KEYS = frozenset({
    "enabled", "state", "mode", "max_retries", "watchdog_ms",
    "resurrected", "quarantined", "watchdog_trips", "retry_exhausted",
    "sentinel_trips", "recoveries", "consecutive_faults",
    "clean_boundaries", "pen", "suspects", "probing", "pressure",
})


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"heal-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def _drain(q) -> tuple:
    """(tokens, error item or None) for one submit stream."""
    toks, err = [], None
    while True:
        item = q.get(timeout=300)
        if item is None:
            break
        if "error" in item:
            err = item
            continue
        toks.extend(item.get("tokens", []))
    return toks, err


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["HEAL"] = "1"
    os.environ["HEAL_MAX_RETRIES"] = "6"
    # Generous on shared CI iron: a legitimately slow CPU boundary must
    # not trip it, the injected 1.5 s hang always does.
    os.environ["HEAL_WATCHDOG_MS"] = "1000"
    os.environ["GRAFTSAN"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"
    os.environ["COMPILE_LEDGER"] = "1"
    # The storm: every recoverable fault class, no poison source
    # (disconnect cancels a victim and sticky_rid convicts one — both
    # would break the zero-visible-errors contract by design).
    os.environ["CHAOS"] = "1"
    os.environ["CHAOS_SEED"] = "17"
    os.environ["CHAOS_DISPATCH_FAIL"] = "0.05"
    os.environ["CHAOS_SLOW_BOUNDARY"] = "0.05"
    os.environ["CHAOS_SLOW_MS"] = "2"
    os.environ["CHAOS_HANG"] = "0.02"
    os.environ["CHAOS_HANG_MS"] = "1500"
    os.environ["CHAOS_NAN_INJECT"] = "0.02"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.chaos import ChaosConfig
    from seldon_tpu.servers.engine import InferenceEngine
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=128, warmup=1)
    srv.load()
    _check(srv.engine._chaos is not None,
           "CHAOS=1 armed but the engine has no chaos monkey")
    _check(srv.engine._heal is not None,
           "HEAL=1 armed but the engine has no heal supervisor")

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=30) as resp:
            return json.loads(resp.read())

    # --- idle engine: frozen schema + neutral state ---------------------
    idle = get("/debug/health")
    _check(set(idle) == HEALTH_TOP_KEYS,
           f"/debug/health keys drifted: got {sorted(idle)}")
    _check(idle["enabled"] is True, "idle heal reports enabled=false")
    _check(idle["state"] == "healthy", f"idle state = {idle['state']}")
    for key in ("resurrected", "quarantined", "watchdog_trips",
                "retry_exhausted", "sentinel_trips", "recoveries", "pen"):
        _check(idle[key] == 0, f"idle engine counts {key}={idle[key]}")
    _check(idle["pressure"] == 0.0,
           f"idle pressure = {idle['pressure']}")

    # --- byte-identity under the storm ----------------------------------
    # The same greedy + seeded-sampled wave on the CHAOS server engine
    # and on a clean reference engine sharing its params. Per-position
    # sampling keys make the healed streams bit-identical, greedy and
    # sampled alike. 300 s stream timeouts keep a wedged recovery from
    # hanging CI silently.
    eng = srv.engine
    vocab = eng.cfg.vocab_size
    prompts = [[3 + (7 * i + j) % (vocab - 4) for j in range(16)]
               for i in range(24)]

    def params_for(i: int) -> SamplingParams:
        if i % 2 == 0:
            return SamplingParams(temperature=0.0, top_k=0, top_p=1.0,
                                  max_new_tokens=12, seed=i)
        return SamplingParams(temperature=0.8, top_k=40, top_p=0.95,
                              max_new_tokens=12, seed=i)

    def run_wave(engine) -> tuple:
        qs = [engine.submit(p, params_for(i))
              for i, p in enumerate(prompts)]
        drained = [_drain(q) for q in qs]
        return ([toks for toks, _ in drained],
                [err for _, err in drained])

    storm_streams, storm_errs = run_wave(eng)
    # Top up until the storm demonstrably fired: fault draws ride the
    # boundary count, which shifts a little with scheduling, so a fixed
    # wave can't PROVE a fault landed. Bounded at 20 extra waves.
    extra_waves = 0
    while (eng.chaos_counts().get("dispatch_faults", 0) == 0
           and eng.chaos_counts().get("hangs", 0) == 0
           and extra_waves < 20):
        extra_waves += 1
        more_streams, more_errs = run_wave(eng)
        storm_streams.extend(more_streams)
        storm_errs.extend(more_errs)
    chaos = eng.chaos_counts()
    _check(sum(chaos.values()) > 0,
           f"chaos storm never fired after {extra_waves} extra waves")

    ref = InferenceEngine(
        eng.params, eng.cfg,
        # Same engine config, chaos explicitly disarmed (an all-zero
        # ChaosConfig wins over the CHAOS=1 env the server read).
        dataclasses.replace(eng.ecfg, chaos=ChaosConfig()),
    )
    _check(ref._chaos is None, "reference engine armed the chaos monkey")
    ref.warmup()
    ref.start()
    ref_streams, ref_errs = run_wave(ref)
    for _ in range(extra_waves):
        more_streams, more_errs = run_wave(ref)
        ref_streams.extend(more_streams)
        ref_errs.extend(more_errs)
    ref.stop()
    ref_bad = [e for e in ref_errs if e]
    _check(not ref_bad, f"clean reference leg errored: {ref_bad[:1]}")

    storm_bad = [e for e in storm_errs if e]
    visible = len(storm_bad)
    _check(visible == 0,
           f"{visible} user-visible errors under a storm with no poison "
           f"source: {storm_bad[:1]}")
    for i, (got, want) in enumerate(zip(storm_streams, ref_streams)):
        _check(
            got == want,
            f"stream {i} diverged after recovery "
            f"({'greedy' if i % 2 == 0 else 'sampled'}): "
            f"healed {got[:8]}... != clean {want[:8]}...",
        )

    health = get("/debug/health")
    _check(health["recoveries"] >= 1,
           f"storm fired ({chaos}) but the supervisor never recovered")
    _check(health["resurrected"] >= 1,
           f"recoveries={health['recoveries']} but nothing resurrected")
    _check(health["quarantined"] == 0,
           f"{health['quarantined']} quarantined with no poison source")
    _check(health["retry_exhausted"] == 0,
           f"{health['retry_exhausted']} exhausted retry budgets "
           f"(heal_max_retries=6)")

    # --- the server stays ready through live HTTP traffic ---------------
    ready = get("/healthz")
    _check(ready.get("status") == "ready",
           f"/healthz = {ready} mid-storm (recovering must stay ready)")
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "8",
                "--seconds", "3",
                "--prompt", "p" * 64,
                "--max-new-tokens", "8",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["requests"] >= 1,
               "loadtester completed no requests against the storm")
        snap = get("/debug/timeline")
        health = get("/debug/health")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- books stay clean under recovery --------------------------------
    san = srv.engine._san
    _check(san is not None, "GRAFTSAN=1 but the engine has no sanitizer")
    _check(not san.violations,
           f"graftsan violations under recovery: {san.violations}")
    comp = srv.engine.debug_compile()
    _check(comp is not None, "COMPILE_LEDGER=1 but no compile ledger")
    _check(comp["live_retrace_count"] == 0,
           f"{comp['live_retrace_count']} live retraces — resurrection "
           f"must re-enter existing prefill buckets, not compile")

    # --- Prometheus surface ---------------------------------------------
    gauges = {m["key"]: m["value"] for m in srv.metrics()}
    for key in ("jaxserver_heal_resurrected", "jaxserver_heal_quarantined",
                "jaxserver_heal_watchdog_trips",
                "jaxserver_heal_retry_exhausted", "jaxserver_heal_pressure"):
        _check(key in gauges, f"metrics() missing gauge {key}")
    _check(gauges["jaxserver_heal_resurrected"] >= 1,
           "jaxserver_heal_resurrected gauge stayed zero")

    # --- flight recorder + trace_view heal lane -------------------------
    heal_recs = [r for r in snap.get("records", [])
                 if r["kind"] == "heal"]
    _check(heal_recs, "no heal records in the timeline")
    for r in heal_recs:
        d = r.get("detail") or {}
        _check("state" in d and "error" in d,
               f"heal record missing state/error: {sorted(d)}")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    lanes = {e["args"]["name"] for e in out["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    _check("seldon-tpu heal" in lanes,
           f"trace_view rendered no heal process (got {lanes})")
    counters = {e["name"] for e in out["traceEvents"] if e["ph"] == "C"}
    _check("heal_verdicts" in counters,
           f"trace_view rendered no heal verdict counters "
           f"(got {counters})")

    srv.engine.stop()

    print(json.dumps({
        "metric": "heal_audit",
        "value": 1,
        "detail": {
            "streams": len(storm_streams),
            "extra_waves": extra_waves,
            "user_visible_errors": visible,
            "loadtester_requests": detail["requests"],
            "chaos": chaos,
            "recoveries": health["recoveries"],
            "resurrected": health["resurrected"],
            "watchdog_trips": health["watchdog_trips"],
            "sentinel_trips": health["sentinel_trips"],
            "state": health["state"],
            "heal_records": len(heal_recs),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
