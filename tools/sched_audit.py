#!/usr/bin/env python
"""CI sched audit: the scheduler waste observatory end to end.

Boots the tiny warmed JAXServer behind the real REST app with
``SCHED_LEDGER=1`` + ``FLIGHT_RECORDER=1``, polls ``/debug/sched`` on
the idle engine, drives it with a short closed-loop loadtester run,
then asserts the observatory contract in one pass:

 * idle engine -> ZERO attribution: no dispatch cells, no useful or
   pad tokens, no wait decomposition — only idle boundaries tick;
 * after load, ``/debug/sched`` returns the documented schema and the
   conservation invariant holds: useful + bucket-pad + group-pad +
   spec-rejected tokens re-sum to the dispatched cells within 1% (the
   ledger's own
   ``audit()`` — run under ``_book`` at every boundary — must report
   zero breaches, and this script recomputes the sum independently);
 * the queue-wait components (pool / bucket / budget / sched) re-sum
   to the total measured wait within 1%;
 * the loadtester ledger carries the same ``padding_waste_frac`` /
   ``goodput_gap`` numbers as the route (schema parity — the token
   counters are static once the load window closes);
 * EngineStats mirrors the ledger (``sched_boundaries`` matches the
   waste histogram mass, ``padding_waste_frac`` agrees), and the
   jaxserver Prometheus surface exports the gauges;
 * boundary records carry ``waste_frac`` and ``tools/trace_view.py``
   renders the ``padding_waste_frac`` counter lane from them.

Run via ``make sched-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

# Frozen /debug/sched top-level key set — tests/test_debug_schema.py
# carries the same golden; a mismatch here means the snapshot schema
# changed without updating its consumers.
SCHED_TOP_KEYS = frozenset({
    "boundaries", "dispatch_boundaries", "idle_boundaries",
    "dispatch_cells", "useful_tokens", "bucket_pad_tokens",
    "group_pad_tokens", "spec_rejected_tokens", "frag_tokens",
    "budget_offered_tokens", "budget_used_tokens",
    "budget_starved_passes", "padding_waste_frac",
    "budget_utilization", "goodput_gap", "spec", "pool_stall_events",
    "pool_stall_requests", "preemptions", "preempted_tokens", "wait",
    "conservation", "by_shape",
})


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"sched-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["SCHED_LEDGER"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64, warmup=1)
    srv.load()

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        # --- idle engine: zero attribution ------------------------------
        idle = get("/debug/sched")
        _check(set(idle) == SCHED_TOP_KEYS,
               f"/debug/sched keys drifted: got {sorted(idle)}")
        for key in ("dispatch_cells", "useful_tokens", "bucket_pad_tokens",
                    "group_pad_tokens", "frag_tokens", "pool_stall_events",
                    "preemptions"):
            _check(idle[key] == 0, f"idle engine has {key}={idle[key]}")
        _check(idle["wait"]["requests"] == 0,
               f"idle engine attributed {idle['wait']['requests']} waits")
        _check(idle["padding_waste_frac"] == 0.0,
               "idle engine reports nonzero padding waste")

        # --- load window ------------------------------------------------
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "4",
                "--seconds", "2", "--prompt", "hi",
                "--max-new-tokens", "4",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["errors"] == 0,
               f"loadtester saw {detail['errors']} transport errors")
        _check(detail["requests"] >= 1, "loadtester completed no requests")

        sched = get("/debug/sched")
        snap = get("/debug/timeline")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- schema + conservation -----------------------------------------
    _check(set(sched) == SCHED_TOP_KEYS,
           f"/debug/sched keys drifted: got {sorted(sched)}")
    cons = sched["conservation"]
    _check(cons["checked"] > 0, "conservation audit never ran")
    _check(
        cons["breaches"] == 0,
        f"{cons['breaches']} conservation breaches: {cons['last_breach']}",
    )
    cells = sched["dispatch_cells"]
    attributed = (sched["useful_tokens"] + sched["bucket_pad_tokens"]
                  + sched["group_pad_tokens"]
                  + sched["spec_rejected_tokens"])
    _check(cells > 0, "no cells dispatched under load")
    _check(
        abs(attributed - cells) <= max(1, cells // 100),
        f"attributed tokens {attributed} != dispatched cells {cells}",
    )
    _check(sched["useful_tokens"] > 0, "no useful tokens attributed")
    _check(sched["dispatch_boundaries"] > 0, "no dispatch boundaries")
    _check(
        sched["boundaries"]
        == sched["dispatch_boundaries"] + sched["idle_boundaries"],
        "boundary counts do not re-sum",
    )
    _check(0.0 <= sched["padding_waste_frac"] <= 1.0,
           f"padding_waste_frac out of range: {sched['padding_waste_frac']}")
    by_shape_cells = sum(e["cells"] for e in sched["by_shape"])
    _check(by_shape_cells == cells,
           f"by_shape cells {by_shape_cells} != total {cells}")

    wait = sched["wait"]
    _check(wait["requests"] >= 1, "no queue waits attributed")
    parts = (wait["pool_ms"] + wait["bucket_ms"] + wait["budget_ms"]
             + wait["sched_ms"])
    _check(
        abs(parts - wait["total_ms"]) <= max(1.0, 0.01 * wait["total_ms"]),
        f"wait components {parts} != total {wait['total_ms']}",
    )

    # --- loadtester ledger parity (counters static post-run) ------------
    _check(
        detail.get("padding_waste_frac") == sched["padding_waste_frac"],
        f"ledger padding_waste_frac {detail.get('padding_waste_frac')} != "
        f"/debug/sched {sched['padding_waste_frac']}",
    )
    gap = sched["goodput_gap"]
    route_gap = round(gap["bucket_pad_frac"] + gap["group_pad_frac"]
                      + gap["spec_rejected_frac"] + gap["frag_frac"], 6)
    _check(
        detail.get("goodput_gap") == route_gap,
        f"ledger goodput_gap {detail.get('goodput_gap')} != "
        f"/debug/sched {route_gap}",
    )
    _check(detail.get("sched_conservation_breaches") == 0,
           f"ledger breaches = {detail.get('sched_conservation_breaches')}")

    # --- EngineStats mirror + Prometheus surface ------------------------
    stats = srv.engine.stats.snapshot()
    # The stats snapshot is taken after the route poll; allow the slack
    # of the fetch-queue depth for any trailing drain boundaries.
    _check(abs(stats["sched_boundaries"]
               - sched["dispatch_boundaries"]) <= 4,
           f"stats sched_boundaries {stats['sched_boundaries']} != ledger "
           f"{sched['dispatch_boundaries']}")
    _check(sum(stats["waste_counts"]) == stats["sched_boundaries"],
           "waste histogram mass != sched_boundaries")
    _check(
        abs(stats["padding_waste_frac"] - sched["padding_waste_frac"])
        < 1e-4,
        f"stats padding_waste_frac {stats['padding_waste_frac']} != "
        f"ledger {sched['padding_waste_frac']}",
    )
    gauges = {m["key"] for m in srv.metrics()}
    for key in ("jaxserver_padding_waste_frac", "jaxserver_goodput_gap",
                "jaxserver_queue_wait_ms_total",
                "jaxserver_sched_conservation_breaches"):
        _check(key in gauges, f"metrics() missing gauge {key}")

    # --- flight recorder + trace_view counter lane ----------------------
    boundaries = [r for r in snap.get("records", [])
                  if r["kind"] == "boundary"]
    _check(boundaries, "no boundary records in timeline")
    _check(any("waste_frac" in (r.get("detail") or {})
               for r in boundaries),
           "boundary records carry no waste_frac")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    counters = {e["name"] for e in out["traceEvents"] if e["ph"] == "C"}
    _check("padding_waste_frac" in counters,
           f"trace_view rendered no waste counter lane (got {counters})")

    srv.engine.stop()

    print(json.dumps({
        "metric": "sched_audit",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "dispatch_cells": cells,
            "useful_tokens": sched["useful_tokens"],
            "padding_waste_frac": sched["padding_waste_frac"],
            "goodput_gap": route_gap,
            "idle_boundaries": sched["idle_boundaries"],
            "conservation_checked": cons["checked"],
            "wait_requests": wait["requests"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
