"""Probe: does XLA fuse int8 weight dequant into the matmul, and is
output-side scaling faster? Times a 16-layer scan of the bench-1b MLP
stack three ways: bf16 weights, dequant-then-dot, dot-then-scale."""

import functools
import time

import jax
import jax.numpy as jnp

B, D, F, L = 160, 2048, 5632, 16
CHUNK = 32


def mk(key):
    ks = jax.random.split(key, 3 * L)
    wg = jax.random.normal(ks[0], (L, D, F), jnp.float32) * 0.02
    wu = jax.random.normal(ks[1], (L, D, F), jnp.float32) * 0.02
    wd = jax.random.normal(ks[2], (L, F, D), jnp.float32) * 0.02
    return wg, wu, wd


def quant(w):
    # graftlint: allow(num-barrier) probe: measures fusion alternatives
    # on purpose; cross-compilation bit-stability is not a contract here.
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    return jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8), s


def run(name, layer_fn, weights):
    @jax.jit
    def f(x, weights):
        def step(x, _):
            def body(h, ws):
                return layer_fn(h, ws), ()
            h, _ = jax.lax.scan(body, x, weights)
            return h * 1e-3 + x[0, 0] * 0, ()
        x, _ = jax.lax.scan(step, x, None, length=CHUNK)
        return x

    from tools.timing import slope_time

    x = jnp.ones((B, 1, D), jnp.bfloat16)
    dt, _ = slope_time(lambda s: f(s, weights), x, k1=2, k2=8)
    print(f"{name:12s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)


def main():
    wg, wu, wd = mk(jax.random.key(0))
    bf = (wg.astype(jnp.bfloat16), wu.astype(jnp.bfloat16),
          wd.astype(jnp.bfloat16))
    (wgq, sg), (wuq, su), (wdq, sd) = quant(wg), quant(wu), quant(wd)

    def layer_bf16(h, ws):
        g, u, d = ws
        return h + jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.silu(jnp.einsum("bsd,df->bsf", h, g))
            * jnp.einsum("bsd,df->bsf", h, u), d)

    def layer_deq(h, ws):
        g, sg, u, su, d, sd = ws
        gd = g.astype(h.dtype) * sg.astype(h.dtype)
        ud = u.astype(h.dtype) * su.astype(h.dtype)
        dd = d.astype(h.dtype) * sd.astype(h.dtype)
        return layer_bf16(h, (gd, ud, dd))

    def layer_outscale(h, ws):
        g, sg, u, su, d, sd = ws
        hid = jax.nn.silu(
            jnp.einsum("bsd,df->bsf", h, g.astype(h.dtype)) * sg.astype(h.dtype)
        ) * (jnp.einsum("bsd,df->bsf", h, u.astype(h.dtype)) * su.astype(h.dtype))
        return h + jnp.einsum("bsf,fd->bsd", hid, d.astype(h.dtype)) * sd.astype(h.dtype)

    run("bf16", layer_bf16, bf)
    run("deq-then-mm", layer_deq, (wgq, sg, wuq, su, wdq, sd))
    run("mm-then-sc", layer_outscale, (wgq, sg, wuq, su, wdq, sd))
    attn_probe()


def attn_probe():
    """Cache-attention strategies at serving shape [160 slots, 257 win]."""
    import jax.numpy as jnp

    B2, T, Hkv, G, Dh, L2 = 160, 257, 8, 2, 128, 16
    q = jax.random.normal(jax.random.key(1), (B2, 1, Hkv, G, Dh), jnp.bfloat16)
    kbf = jax.random.normal(jax.random.key(2), (L2, B2, T, Hkv, Dh), jnp.bfloat16)
    vbf = jax.random.normal(jax.random.key(3), (L2, B2, T, Hkv, Dh), jnp.bfloat16)
    ki = jnp.clip(jnp.round(kbf.astype(jnp.float32) * 50), -127, 127).astype(jnp.int8)
    vi = jnp.clip(jnp.round(vbf.astype(jnp.float32) * 50), -127, 127).astype(jnp.int8)
    ks = jnp.ones((L2, B2, T, Hkv), jnp.float32) / 50
    vs = jnp.ones((L2, B2, T, Hkv), jnp.float32) / 50
    mask = jnp.arange(T)[None, None, :] <= 128

    def attend(qx, ck, cv):
        scores = jnp.einsum("bskgd,btkd->bkgst", qx, ck,
                            preferred_element_type=jnp.float32) / Dh**0.5
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(qx.dtype)
        return jnp.einsum("bkgst,btkd->bskgd", w, cv)

    def run2(name, fn, *ops):
        from tools.timing import slope_time

        @jax.jit
        def f(q, *ops):
            def step(q, _):
                def layer(a, sl):
                    return a + fn(q, *sl) * 1e-3, ()
                a, _ = jax.lax.scan(layer, q, ops)
                return a, ()
            q, _ = jax.lax.scan(step, q, None, length=CHUNK)
            return q

        dt, _ = slope_time(lambda s: f(s, *ops), q, k1=2, k2=8)
        print(f"attn {name:14s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)

    # 1) bf16 cache
    run2("bf16", lambda qx, ck, cv: attend(qx, ck, cv), kbf, vbf)
    # 2) int8: dequant then attend (materializing?)
    run2("int8-deq",
         lambda qx, ck, cs, cv, vs_: attend(
             qx,
             ck.astype(qx.dtype) * cs[..., None].astype(qx.dtype),
             cv.astype(qx.dtype) * vs_[..., None].astype(qx.dtype)),
         ki, ks, vi, vs)
    # 3) int8: factored scales (convert-only operands)
    def factored(qx, ck, cs, cv, vs_):
        scores = jnp.einsum("bskgd,btkd->bkgst", qx, ck.astype(qx.dtype),
                            preferred_element_type=jnp.float32) / Dh**0.5
        scores = scores * cs.transpose(0, 2, 1)[:, :, None, None, :]
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        wv = (w * vs_.transpose(0, 2, 1)[:, :, None, None, :]).astype(qx.dtype)
        return jnp.einsum("bkgst,btkd->bskgd", wv, cv.astype(qx.dtype))
    run2("int8-factored", factored, ki, ks, vi, vs)
    # 4) int8 via direct int8 dot (int32 accum) then scale
    def int8dot(qx, ck, cs, cv, vs_):
        # graftlint: allow(num-barrier) probe leg: fusion freedom is the
        # measurement, not a hazard.
        qs = jnp.max(jnp.abs(qx.astype(jnp.float32)), axis=-1) / 127.0
        qi = jnp.clip(jnp.round(qx.astype(jnp.float32) / qs[..., None]),
                      -127, 127).astype(jnp.int8)
        raw = jnp.einsum("bskgd,btkd->bkgst", qi, ck,
                         preferred_element_type=jnp.int32)
        scores = raw.astype(jnp.float32)
        scores = scores * (qs.transpose(0, 2, 3, 1)[..., None]
                           * cs.transpose(0, 2, 1)[:, :, None, None, :]) / Dh**0.5
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        wv = (w * vs_.transpose(0, 2, 1)[:, :, None, None, :]).astype(jnp.bfloat16)
        return jnp.einsum("bkgst,btkd->bskgd", wv, cv.astype(jnp.bfloat16))
    run2("int8-qdot", int8dot, ki, ks, vi, vs)

    # 5/6) REAL structure: cache rides the scan carry; per layer we
    # scatter-write the fresh token then dynamic-slice-read for attention.
    from tools.timing import slope_time

    pos = jnp.full((B2,), 128, jnp.int32)
    rows = jnp.arange(B2)
    qflat = q

    def carry_probe(name, cache, quant):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def f(cache, q):
            def step(carry, _):
                c, acc = carry
                kf = jax.random.normal(jax.random.key(9), (B2, 1, Hkv, Dh),
                                       jnp.bfloat16) + acc[:, :1, :, 0, :] * 1e-3

                def layer(inner, li):
                    c, a = inner
                    idx = pos[:, None] + jnp.arange(1)[None, :]
                    if quant:
                        # graftlint: allow(num-barrier) probe leg: fusion freedom is the
                        # measurement, not a hazard.
                        sc = jnp.max(jnp.abs(kf.astype(jnp.float32)), -1) / 127.0
                        kq = jnp.clip(jnp.round(kf.astype(jnp.float32) / sc[..., None]), -127, 127).astype(jnp.int8)
                        c = dict(c)
                        c["k"] = c["k"].at[li, rows[:, None], idx].set(
                            kq, indices_are_sorted=True, unique_indices=True)
                        c["v"] = c["v"].at[li, rows[:, None], idx].set(
                            kq, indices_are_sorted=True, unique_indices=True)
                        c["ks"] = c["ks"].at[li, rows[:, None], idx].set(
                            sc, indices_are_sorted=True, unique_indices=True)
                        c["vs"] = c["vs"].at[li, rows[:, None], idx].set(
                            sc, indices_are_sorted=True, unique_indices=True)
                        out = factored(
                            a,
                            jax.lax.dynamic_index_in_dim(c["k"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["ks"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["v"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["vs"], li, 0, False))
                    else:
                        c = dict(c)
                        c["k"] = c["k"].at[li, rows[:, None], idx].set(
                            kf.astype(jnp.bfloat16), indices_are_sorted=True,
                            unique_indices=True)
                        c["v"] = c["v"].at[li, rows[:, None], idx].set(
                            kf.astype(jnp.bfloat16), indices_are_sorted=True,
                            unique_indices=True)
                        ck = jax.lax.dynamic_index_in_dim(c["k"], li, 0, False)
                        cv = jax.lax.dynamic_index_in_dim(c["v"], li, 0, False)
                        out = attend(a, ck, cv)
                    return (c, a + out * 1e-3), ()

                (c, acc), _ = jax.lax.scan(layer, (c, acc), jnp.arange(L2))
                return (c, acc), ()

            (cache, accf), _ = jax.lax.scan(step, (cache, q), None, length=CHUNK)
            return cache, accf

        def one(state):
            c, qq = state
            return f(c, qq)

        dt, _ = slope_time(one, (cache, qflat), k1=2, k2=6)
        print(f"attn {name:14s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)

    carry_probe("bf16-carry", {"k": jnp.copy(kbf), "v": jnp.copy(vbf)}, False)
    carry_probe("int8-carry", {"k": jnp.copy(ki), "v": jnp.copy(vi),
                               "ks": jnp.copy(ks), "vs": jnp.copy(vs)}, True)

    # 7/8) split: attend over the PRE-write cache (mask < pos) + fresh-token
    # correction; scatter-write carries no read-after-write dependency.
    def split_probe(name, cache, quant):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def f(cache, q):
            def step(carry, _):
                c, acc = carry
                kf = jax.random.normal(jax.random.key(9), (B2, 1, Hkv, Dh),
                                       jnp.bfloat16) + acc[:, :1, :, 0, :] * 1e-3
                mask_lt = jnp.arange(T)[None, None, :] < pos[:, None, None]

                def layer(inner, li):
                    c, a = inner
                    # --- read OLD cache (pre-write) ---
                    if quant:
                        out = factored_masked(
                            a,
                            jax.lax.dynamic_index_in_dim(c["k"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["ks"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["v"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["vs"], li, 0, False),
                            kf, mask_lt)
                    else:
                        out = attend_fresh(
                            a,
                            jax.lax.dynamic_index_in_dim(c["k"], li, 0, False),
                            jax.lax.dynamic_index_in_dim(c["v"], li, 0, False),
                            kf, mask_lt)
                    # --- scatter write (independent of the read) ---
                    idx = pos[:, None] + jnp.arange(1)[None, :]
                    c = dict(c)
                    if quant:
                        # graftlint: allow(num-barrier) probe leg: fusion freedom is the
                        # measurement, not a hazard.
                        sc = jnp.max(jnp.abs(kf.astype(jnp.float32)), -1) / 127.0
                        kq = jnp.clip(jnp.round(kf.astype(jnp.float32) / sc[..., None]), -127, 127).astype(jnp.int8)
                        c["k"] = c["k"].at[li, rows[:, None], idx].set(
                            kq, indices_are_sorted=True, unique_indices=True)
                        c["v"] = c["v"].at[li, rows[:, None], idx].set(
                            kq, indices_are_sorted=True, unique_indices=True)
                        c["ks"] = c["ks"].at[li, rows[:, None], idx].set(
                            sc, indices_are_sorted=True, unique_indices=True)
                        c["vs"] = c["vs"].at[li, rows[:, None], idx].set(
                            sc, indices_are_sorted=True, unique_indices=True)
                    else:
                        c["k"] = c["k"].at[li, rows[:, None], idx].set(
                            kf.astype(jnp.bfloat16), indices_are_sorted=True,
                            unique_indices=True)
                        c["v"] = c["v"].at[li, rows[:, None], idx].set(
                            kf.astype(jnp.bfloat16), indices_are_sorted=True,
                            unique_indices=True)
                    return (c, a + out * 1e-3), ()

                (c, acc), _ = jax.lax.scan(layer, (c, acc), jnp.arange(L2))
                return (c, acc), ()

            (cache, accf), _ = jax.lax.scan(step, (cache, q), None, length=CHUNK)
            return cache, accf

        def attend_fresh(qx, ck, cv, kf, mask_lt):
            scores = jnp.einsum("bskgd,btkd->bkgst", qx, ck,
                                preferred_element_type=jnp.float32) / Dh**0.5
            s_fresh = jnp.einsum("bskgd,bukd->bkgsu", qx, kf,
                                 preferred_element_type=jnp.float32)[..., 0] / Dh**0.5
            scores = jnp.where(mask_lt[:, None, None, :, :], scores, -1e30)
            at_pos = jnp.arange(T)[None, None, None, None, :] == pos[:, None, None, None, None]
            scores = jnp.where(at_pos, s_fresh[..., None], scores)
            w = jax.nn.softmax(scores, axis=-1)
            w_pos = jnp.take_along_axis(
                w, pos[:, None, None, None, None] * jnp.ones(w.shape[:-1], jnp.int32)[..., None], axis=-1)[..., 0]
            w_cache = jnp.where(at_pos, 0.0, w).astype(qx.dtype)
            out = jnp.einsum("bkgst,btkd->bskgd", w_cache, cv)
            out = out + jnp.einsum("bkgs,bukd->bskgd", w_pos.astype(qx.dtype), kf)[..., :, :]
            return out

        def factored_masked(qx, ck, cs, cv, vs_, kf, mask_lt):
            scores = jnp.einsum("bskgd,btkd->bkgst", qx, ck.astype(qx.dtype),
                                preferred_element_type=jnp.float32) / Dh**0.5
            scores = scores * cs.transpose(0, 2, 1)[:, :, None, None, :]
            s_fresh = jnp.einsum("bskgd,bukd->bkgsu", qx, kf,
                                 preferred_element_type=jnp.float32)[..., 0] / Dh**0.5
            scores = jnp.where(mask_lt[:, None, None, :, :], scores, -1e30)
            at_pos = jnp.arange(T)[None, None, None, None, :] == pos[:, None, None, None, None]
            scores = jnp.where(at_pos, s_fresh[..., None], scores)
            w = jax.nn.softmax(scores, axis=-1)
            w_pos = jnp.take_along_axis(
                w, pos[:, None, None, None, None] * jnp.ones(w.shape[:-1], jnp.int32)[..., None], axis=-1)[..., 0]
            w_cache = jnp.where(at_pos, 0.0, w)
            wv = (w_cache * vs_.transpose(0, 2, 1)[:, :, None, None, :]).astype(qx.dtype)
            out = jnp.einsum("bkgst,btkd->bskgd", wv, cv.astype(qx.dtype))
            out = out + jnp.einsum("bkgs,bukd->bskgd", w_pos.astype(qx.dtype), kf)
            return out

        def one(state):
            c, qq = state
            return f(c, qq)

        dt, _ = slope_time(one, (cache, qflat), k1=2, k2=6)
        print(f"attn {name:14s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)

    split_probe("bf16-split", {"k": jnp.copy(kbf), "v": jnp.copy(vbf)}, False)
    split_probe("int8-split", {"k": jnp.copy(ki), "v": jnp.copy(vi),
                               "ks": jnp.copy(ks), "vs": jnp.copy(vs)}, True)


if __name__ == "__main__":
    main()
