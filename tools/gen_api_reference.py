"""Render docs/api-reference.md from core/openapi.py — one source of
truth, so the API reference cannot drift from the servers that mount the
spec (tests/test_docs.py pins the rendered output).

Run:  python tools/gen_api_reference.py [--check]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from seldon_tpu.core.openapi import (  # noqa: E402
    SELDON_MESSAGE_SCHEMA, engine_openapi, unit_openapi,
)

OUT = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "api-reference.md")


def _routes_table(spec: dict, skip_prefix: str | None = None) -> str:
    rows = ["| Route | Method | Summary | Responses |",
            "|---|---|---|---|"]
    for route in spec["paths"]:
        if skip_prefix and route.startswith(skip_prefix):
            continue
        for method, op in spec["paths"][route].items():
            responses = ", ".join(
                f"{code} ({d.get('description', '')})"
                for code, d in op.get("responses", {}).items()
            )
            body = op.get("requestBody", {}).get("content", {})
            content = " + ".join(sorted(body)) if body else "—"
            rows.append(
                f"| `{route}` | {method.upper()} | {op.get('summary', '')} "
                f"[{content}] | {responses} |"
            )
    return "\n".join(rows)


def _schema_fields(schema: dict, prefix: str = "") -> list[str]:
    out = []
    for name, sub in schema.get("properties", {}).items():
        t = sub.get("type", "object")
        if t == "object" and "properties" in sub:
            out.append(f"- `{prefix}{name}` (object)")
            out.extend(_schema_fields(sub, prefix + name + "."))
        elif t == "array":
            item = sub.get("items", {}).get("type", "any")
            out.append(f"- `{prefix}{name}` (array of {item})")
        else:
            enum = sub.get("enum")
            suffix = f", one of {enum}" if enum else ""
            out.append(f"- `{prefix}{name}` ({t}{suffix})")
    return out


def render() -> str:
    engine = engine_openapi()
    unit = unit_openapi()
    return f"""# API reference

Generated from `seldon_tpu/core/openapi.py` by
`tools/gen_api_reference.py` — do not edit by hand; regenerate with
`python tools/gen_api_reference.py`. The same spec is served live at
`GET /seldon.json` by both the engine and every unit microservice
(reference: `openapi/` apife.oas3.json + engine.oas3.json).

## Engine (service orchestrator) external API

`orchestrator/server.py` — the per-deployment entrypoint the ingress
routes to.

{_routes_table(engine)}

`POST /api/v0.1/predictions` content types: JSON `SeldonMessage`,
binary proto (`application/x-protobuf`), HTML form (`json=` field), and
`multipart/form-data` — file parts land in `binData` (bytes) or
`strData` (text, key matched case-insensitively), plain fields are
parsed as JSON subtrees (`data`, `meta`, `jsonData`).

## Unit microservice API

`runtime/wrapper.py` — what the engine dials internally and what a
foreign-language unit must implement (see `docs/wrappers.md`). Routes
are also mounted under `/api/v0.1/...` and `/api/v1.0/...` aliases
(elided below).

{_routes_table(unit, skip_prefix="/api/v0.1")}

## SeldonMessage

The one message shape of the whole protocol
(`seldon_tpu/proto/prediction.proto`). Exactly one of the data kinds is
set: `data` (names + one of ndarray / tensor / dense), `binData`,
`strData`, `jsonData`.

{chr(10).join(_schema_fields(SELDON_MESSAGE_SCHEMA))}

`data.dense` is the TPU-native zero-copy kind: raw little-endian bytes
plus dtype + shape (bf16-capable) — what the TPU units speak among
themselves.

## Meta merge semantics

How `meta` accumulates as a request walks the graph
(`orchestrator/walker.py:_RequestCtx`; reference
`PredictiveUnitBean.java:370-388`):

- **puid** — minted by the engine when the inbound request carries
  none; stamped on the request IN PLACE (the engine owns the request
  message) and echoed on the response. Every unit sees the same puid.
- **tags** — merged across every unit response in completion order;
  later writers override earlier ones key-by-key (`merge_response_meta`
  copies per key). The final response carries the union.
- **routing** — written by the engine, not the units: for each ROUTER
  unit, the branch index it chose (`-1` = fan-out to all children).
  Feedback follows these breadcrumbs back down
  (`walker.py:send_feedback`): a feedback's `response.meta.routing`
  decides which child subtree receives it.
- **requestPath** — written by the engine: every unit the request
  actually visited, mapped to its serving image (audit trail; the A/B
  test assertions in `tests/test_orchestrator.py` key off it).
- **metrics** — APPEND-only across units (no dedup by key: two units
  emitting the same counter key both appear; the prometheus registry
  sums COUNTERs and last-writes GAUGEs when absorbing them). Custom
  entries are absorbed into the engine's registry
  (`metrics_server.py:record_custom`) AND returned to the caller.
- **feedback rewards** — `POST /api/v0.1/feedback` routes
  `Feedback.reward` to every MODEL/ROUTER unit on the stored routing
  path; the engine counts them per unit
  (`seldon_api_model_feedback_reward_total`, negative rewards on the
  `_negative` series since counters cannot decrease).

## gRPC

Same surface over gRPC (`seldon_tpu/proto/prediction.proto`):
`Seldon.Predict` / `Seldon.SendFeedback` on the engine;
`Model.Predict`, `Generic.Transform{{Input,Output}}`, `Router.Route`,
`Combiner.Aggregate`, `Generic.SendFeedback` on units. Method paths:
`/seldon_tpu.protos.<Service>/<Method>`. In-process graphs ride a
sync thread-pool servicer; graphs with network units ride asyncio
(`orchestrator/server.py`).
"""


def main() -> None:
    text = render()
    if "--check" in sys.argv:
        with open(OUT) as f:
            if f.read() != text:
                print("docs/api-reference.md is stale — rerun "
                      "python tools/gen_api_reference.py", file=sys.stderr)
                sys.exit(1)
        print("api-reference.md up to date")
        return
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {os.path.normpath(OUT)} ({len(text.splitlines())} lines)")


if __name__ == "__main__":
    main()
