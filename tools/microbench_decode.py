"""Microbenchmark: decode-chunk step time for weight/kv dtype combos.

Times ONE jitted decode chunk (the engine's `_chunk_impl` equivalent:
`decode_chunk` lax.scan steps over all slots) on the bench-1b serving
shape, isolating the HBM-bound hot loop from scheduler/host effects.
Usage: python tools/microbench_decode.py [--spec k] [combos...]
  combo = weights:kv[:attn] e.g. int8:bf16  int8:int8  bf16:bf16

``--spec k`` switches to the graftspec kernel pair: one paged verify
wave over k drafts (models/spec_decode.verify_wave, Sq = k + 1 query
rows) against the same wave at k = 0 — which IS a plain paged decode
step through the identical code path, so the ratio isolates the extra
width's cost. Prints the break-even emitted-tokens/wave (spec wins
when mean acceptance clears it) and the full-acceptance speedup bound.
``MB_DRAFT=<preset>`` additionally times the resident draft model's
proposal dispatch (models/spec_decode.draft_tokens); without it the
n-gram drafter's host cost (~0) is assumed.

``--ragged`` switches to the graftkern kernel legs: ONE ragged decode
wave (models/ragged_attention.ragged_wave — the decode-only regime
that dominates a serving trace) over all slots at MIXED context
lengths, timed per kernel leg: masked (full-width baseline) vs sparse
(block-sparse walker); ``MB_PALLAS=1`` adds the pallas leg (interpret
mode off-TPU — slow on CPU, so opt-in). Prints ms/wave per leg and
the sparse-vs-masked speedup.

``--roof`` adds graftroof's analytical prediction next to every
measured number (servers/cost_model.cost_of_key at this bench's exact
geometry, peaks resolved per platform env > table > microbench): the
predicted ms per decode step / per verify wave and the measured-over-
predicted ratio — the cost model's calibration check. Under
``--ragged`` it prints BOTH pricings: live occupancy
(cost_model.ragged_occupancy_cost at the wave's real descriptor
occupancy — the post-graftkern ledger number) and the static
capacity bound, against each leg's measured wave.
"""

from __future__ import annotations

import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp

from seldon_tpu.models import get_config, init_params, transformer
from seldon_tpu.models.sampling import sample_per_row

import os
PRESET = os.environ.get("MB_PRESET", "bench-1b")
SLOTS = int(os.environ.get("MB_SLOTS", 160))
WINDOW = int(os.environ.get("MB_WINDOW", 257))  # prompt 128 + decode 128 + 1
CHUNK = 64


def act_for(weights: str) -> str:
    """MB_ACT mirrors BENCH_ACT/TUNE_ACT: int8 (the adopted W8A8
    serving default) unless reverted, and only when weights are int8 —
    shared by the microbench and tools/profile_decode so the profiler
    can never desynchronize from the benchmark it explains."""
    return os.environ.get("MB_ACT", "int8" if weights == "int8" else "bf16")


def chunk_impl(params, state, *, cfg, n_steps):

    def step(carry, _):
        run = carry["active"]
        logits, cache = transformer.decode_step(
            params, carry["last_tok"], carry["pos"], carry["cache"], cfg,
        )
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
        )(carry["seeds"], carry["pos"])
        tok = sample_per_row(
            logits, keys, carry["temp"],
            jnp.where(run, carry["top_k"], 0),
            jnp.where(run, carry["top_p"], 1.0),
        )
        tok = jnp.where(run, tok, cfg.pad_token_id)
        pos = carry["pos"] + run.astype(jnp.int32)
        new_carry = {
            **carry,
            "cache": cache,
            "last_tok": jnp.where(run, tok, carry["last_tok"]),
            "pos": pos,
        }
        return new_carry, tok

    state, toks = jax.lax.scan(step, state, None, length=n_steps)
    return state, toks


def bench(weights: str, kv: str, attn: str = "xla") -> float:
    cfg = get_config(PRESET, weight_dtype=weights, kv_cache_dtype=kv,
                     attn_impl=attn, act_dtype=act_for(weights))
    if weights == "int8":
        # Memory-aware: 8B geometry can't materialize bf16 then quantize.
        from seldon_tpu.models.quantize import init_params_int8

        params = init_params_int8(cfg, jax.random.key(0))
    else:
        params = init_params(cfg, jax.random.key(0))
    B = SLOTS
    state = {
        "cache": transformer.init_cache(cfg, B, WINDOW),
        "last_tok": jnp.ones((B,), jnp.int32),
        "pos": jnp.full((B,), 128, jnp.int32),
        "active": jnp.ones((B,), jnp.bool_),
        "temp": jnp.full((B,), 0.7, jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.arange(B, dtype=jnp.uint32),
    }
    fn = jax.jit(functools.partial(chunk_impl, cfg=cfg, n_steps=CHUNK),
                 donate_argnums=(1,))

    def one(state):
        # Reset pos each chain link so the window stays comparable.
        state = dict(state)
        state["pos"] = jnp.full((B,), 128, jnp.int32)
        state["active"] = jnp.ones((B,), jnp.bool_)
        state, toks = fn(params, state)
        return state

    from tools.timing import slope_time

    dt, _ = slope_time(one, state, k1=2, k2=6)
    ms_per_step = 1000.0 * dt / CHUNK
    toks_per_s = SLOTS * CHUNK / dt
    print(
        f"w={weights:5s} kv={kv:5s} act={cfg.act_dtype:5s} attn={attn:5s} "
        f"{ms_per_step:7.3f} ms/step  {toks_per_s:9.0f} tok/s",
        flush=True,
    )
    if ROOF:
        pred = _roof_predict_ms(("decode", CHUNK), cfg) / CHUNK
        print(
            f"  roof: predicted {pred:7.3f} ms/step  "
            f"measured/predicted {ms_per_step / pred:6.2f}x",
            flush=True,
        )
    return ms_per_step


def _roof_predict_ms(key, cfg) -> float:
    """Analytical roofline estimate of one dispatch of `key` at this
    microbench's geometry, against the platform peaks."""
    from seldon_tpu.servers import cost_model

    dev = jax.devices()[0]
    peaks = cost_model.resolve_peaks(
        getattr(dev, "device_kind", "") or dev.platform
    )
    flops, bytes_ = cost_model.cost_of_key(
        key, cfg, max_slots=SLOTS, max_seq_len=WINDOW, kv_block=64,
    )
    return cost_model.roofline_ms(flops, bytes_, peaks)


def bench_spec(k: int, weights: str, kv: str, attn: str = "xla") -> None:
    """graftspec kernel pair: verify wave at width k vs k = 0 (a plain
    paged decode step through the same code path)."""
    from seldon_tpu.models import spec_decode as spec_model

    cfg = get_config(PRESET, weight_dtype=weights, kv_cache_dtype=kv,
                     attn_impl=attn, act_dtype=act_for(weights))
    if weights == "int8":
        from seldon_tpu.models.quantize import init_params_int8

        params = init_params_int8(cfg, jax.random.key(0))
    else:
        params = init_params(cfg, jax.random.key(0))
    B = SLOTS
    block = 64
    nbs = -(-WINDOW // block)
    # Block 0 is the trash block; row i owns blocks [1 + i*nbs, ...).
    table = jnp.arange(1, B * nbs + 1, dtype=jnp.int32).reshape(B, nbs)
    wave = jnp.ones((B,), jnp.bool_)

    from tools.timing import slope_time

    # One pool for the whole pair: each width's jit donates the state
    # in and slope_time hands the final state to the next leg — the
    # idiomatic donation chain (every chain link resets pos/active, so
    # timings are width-comparable regardless of who ran before).
    state = {
        "cache": transformer.init_paged_cache(cfg, B * nbs + 1, block),
        "last_tok": jnp.ones((B,), jnp.int32),
        "pos": jnp.full((B,), 128, jnp.int32),
        "active": jnp.ones((B,), jnp.bool_),
        "remaining": jnp.full((B,), 64, jnp.int32),
        "temp": jnp.zeros((B,), jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.arange(B, dtype=jnp.uint32),
    }

    def time_width(kk: int, state: dict):
        drafts = jnp.ones((B, kk), jnp.int32)
        fn = jax.jit(functools.partial(spec_model.verify_wave, cfg=cfg),
                     donate_argnums=(1,))

        def one(st):
            st = dict(st, pos=jnp.full((B,), 128, jnp.int32),
                      remaining=jnp.full((B,), 64, jnp.int32),
                      active=jnp.ones((B,), jnp.bool_))
            st, _, _ = fn(params, st, table, drafts, wave)
            return st

        dt, state = slope_time(one, state, k1=2, k2=6)
        return 1000.0 * dt, state

    ms_plain, state = time_width(0, state)
    ms_verify, state = time_width(k, state)
    draft_ms = 0.0
    draft_preset = os.environ.get("MB_DRAFT", "")
    if draft_preset:
        dcfg = get_config(draft_preset, act_dtype="bf16")
        dparams = init_params(dcfg, jax.random.key(1))
        W = 64
        dfn = jax.jit(functools.partial(
            spec_model.draft_tokens, dparams, cfg=dcfg, k=k))
        window = jnp.ones((B, W), jnp.int32)
        wlens = jnp.full((B,), W, jnp.int32)
        dt, _ = slope_time(lambda s: (dfn(window, wlens), s)[1],
                           state, k1=2, k2=6)
        draft_ms = 1000.0 * dt
    wave_ms = ms_verify + draft_ms
    # Spec emits E tokens/wave; plain emits 1/dispatch. Break-even when
    # wave_ms / E == ms_plain.
    break_even = wave_ms / ms_plain
    speedup_full = (k + 1) * ms_plain / wave_ms
    print(
        f"w={weights:5s} kv={kv:5s} act={cfg.act_dtype:5s} spec k={k} "
        f"plain {ms_plain:7.3f} ms/step  verify {ms_verify:7.3f} ms/wave"
        + (f"  draft {draft_ms:7.3f} ms/wave" if draft_preset else "")
        + f"  break-even {break_even:.2f} tok/wave"
        f"  full-accept speedup {speedup_full:.2f}x",
        flush=True,
    )
    if ROOF:
        pred_plain = _roof_predict_ms(("decode", 1), cfg)
        pred_verify = _roof_predict_ms(("verify", k), cfg)
        print(
            f"  roof: predicted plain {pred_plain:7.3f} ms/step  "
            f"verify {pred_verify:7.3f} ms/wave  "
            f"measured/predicted {ms_plain / pred_plain:6.2f}x / "
            f"{ms_verify / pred_verify:6.2f}x",
            flush=True,
        )


def bench_ragged(weights: str, kv: str, attn: str = "xla") -> None:
    """graftkern kernel legs: one ragged decode wave at mixed context
    lengths, per RAGGED_KERNEL leg. The wave is decode-only (the
    steady-state regime): masked still pays its full-width prefill leg
    and full-window attention reads; sparse skips the dead prefill via
    the wave cond and walks only ceil(pos/block) live blocks per row —
    exactly the serving-trace gap the engine's kernel knob toggles."""
    from seldon_tpu.models import ragged_attention as ra

    cfg = get_config(PRESET, weight_dtype=weights, kv_cache_dtype=kv,
                     attn_impl=attn, act_dtype=act_for(weights))
    if weights == "int8":
        from seldon_tpu.models.quantize import init_params_int8

        params = init_params_int8(cfg, jax.random.key(0))
    else:
        params = init_params(cfg, jax.random.key(0))
    B = SLOTS
    block = 64
    nbs = -(-WINDOW // block)
    Smax = nbs * block
    C = int(os.environ.get("MB_RAGGED_CHUNK", "16"))
    # Block 0 is the trash block; row i owns blocks [1 + i*nbs, ...).
    table = jnp.arange(1, B * nbs + 1, dtype=jnp.int32).reshape(B, nbs)
    # Mixed live contexts: cycle a spread across the window so the
    # sparse walker's per-row trip counts genuinely differ.
    ctx = [max(1, (Smax * f) // 8) for f in (1, 2, 4, 7)]
    pos0 = jnp.asarray([ctx[i % len(ctx)] for i in range(B)], jnp.int32)
    pos0 = jnp.minimum(pos0, Smax - 2)
    tokens = jnp.ones((B * C,), jnp.int32)
    plens = jnp.zeros((B,), jnp.int32)
    starts = jnp.full((B,), Smax, jnp.int32)  # idle rows, engine-style
    finals = jnp.zeros((B,), jnp.bool_)
    is_prefill = jnp.zeros((B,), jnp.bool_)
    seeds = jnp.arange(B, dtype=jnp.uint32)
    temps = jnp.zeros((B,), jnp.float32)
    top_ks = jnp.zeros((B,), jnp.int32)
    top_ps = jnp.ones((B,), jnp.float32)
    max_news = jnp.full((B,), 64, jnp.int32)

    from tools.timing import slope_time

    # One pool chained through every leg: each kernel's jit donates the
    # state in and slope_time's final state seeds the next leg. Every
    # chain link resets pos/active/remaining, so leg timings stay
    # comparable regardless of order.
    # State arrays are copies — the wave args stay undonated.
    state = {
        "cache": transformer.init_paged_cache(cfg, B * nbs + 1, block),
        "last_tok": jnp.ones((B,), jnp.int32),
        "pos": pos0 + 0,
        "active": jnp.ones((B,), jnp.bool_),
        "remaining": jnp.full((B,), 64, jnp.int32),
        "temp": jnp.zeros((B,), jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.arange(B, dtype=jnp.uint32),
    }

    def time_kernel(kern: str, state: dict):
        fn = jax.jit(
            functools.partial(ra.ragged_wave, cfg=cfg, kernel=kern),
            donate_argnums=(1,))

        def one(st):
            st = dict(st, pos=pos0 + 0, active=jnp.ones((B,), jnp.bool_),
                      remaining=jnp.full((B,), 64, jnp.int32))
            st, _, _, _, _ = fn(params, st, table, tokens, plens, starts,
                                seeds, temps, top_ks, top_ps, max_news,
                                finals, is_prefill)
            return st

        dt, state = slope_time(one, state, k1=2, k2=6)
        return 1000.0 * dt, state

    kernels = ["masked", "sparse"]
    if os.environ.get("MB_PALLAS", ""):
        kernels.append("pallas")
    ms = {}
    for kern in kernels:
        ms[kern], state = time_kernel(kern, state)
    line = (f"w={weights:5s} kv={kv:5s} act={cfg.act_dtype:5s} ragged "
            f"B={B} ctx~{int(pos0.mean())}/{Smax}")
    for kern in kernels:
        line += f"  {kern} {ms[kern]:7.3f} ms/wave"
    line += f"  sparse speedup {ms['masked'] / ms['sparse']:.2f}x"
    print(line, flush=True)
    if ROOF:
        from seldon_tpu.servers import cost_model

        dev = jax.devices()[0]
        peaks = cost_model.resolve_peaks(
            getattr(dev, "device_kind", "") or dev.platform
        )
        live_qk = int(pos0.sum())
        lf, lb = cost_model.ragged_occupancy_cost(
            cfg, q_tokens=B, kv_read_tokens=live_qk, attn_qk=live_qk)
        pred_live = cost_model.roofline_ms(lf, lb, peaks)
        cf, cb = cost_model.cost_of_key(
            ("ragged", C), cfg, max_slots=B, max_seq_len=Smax,
            kv_block=block)
        pred_cap = cost_model.roofline_ms(cf, cb, peaks)
        print(
            f"  roof: live-occupancy predicted {pred_live:7.3f} ms/wave  "
            f"capacity predicted {pred_cap:7.3f} ms/wave  "
            f"measured/predicted sparse {ms['sparse'] / pred_live:6.2f}x  "
            f"masked {ms['masked'] / pred_cap:6.2f}x",
            flush=True,
        )


ROOF = False

if __name__ == "__main__":
    args = sys.argv[1:]
    spec_k = 0
    ragged = False
    if "--roof" in args:
        args.remove("--roof")
        ROOF = True
    if "--ragged" in args:
        args.remove("--ragged")
        ragged = True
    if "--spec" in args:
        i = args.index("--spec")
        spec_k = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    combos = args or ["int8:bf16", "int8:int8", "bf16:bf16", "bf16:int8"]
    for c in combos:
        parts = c.split(":")
        if ragged:
            bench_ragged(*parts[:3])
        elif spec_k:
            bench_spec(spec_k, *parts[:3])
        else:
            bench(*parts[:3])
