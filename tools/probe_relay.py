"""Characterize the axon relay: fixed round-trip of device_get, whether
block_until_ready actually waits, and chained-exec timing methodology."""

import time

import jax
import jax.numpy as jnp


def main():
    x = jnp.ones((8,), jnp.float32)
    _ = jax.device_get(x)
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        _ = jax.device_get(x)
    rt = (time.perf_counter() - t0) / n
    print(f"device_get tiny round-trip: {rt*1000:.2f} ms", flush=True)

    y = jnp.ones((1 << 22,), jnp.float32)  # 16MB
    _ = jax.device_get(y)
    t0 = time.perf_counter()
    for _ in range(3):
        _ = jax.device_get(y)
    dt = (time.perf_counter() - t0) / 3
    print(f"device_get 16MB: {dt*1000:.1f} ms -> {16/1000/dt:.1f} MB/ms", flush=True)

    # block_until_ready: does it wait? Time a big reduction with it.
    big = jnp.ones((1 << 29,), jnp.bfloat16)  # 1GiB

    @jax.jit
    def red(a):
        return a.astype(jnp.float32).sum()

    r = red(big)
    _ = jax.device_get(r)
    t0 = time.perf_counter()
    r = red(big)
    r.block_until_ready()  # graftlint: allow(hot-sync) the probe measures sync latency
    t1 = time.perf_counter()
    _ = jax.device_get(r)
    t2 = time.perf_counter()
    print(f"red(1GiB): block_until_ready={1000*(t1-t0):.2f} ms, "
          f"then get={1000*(t2-t1):.2f} ms", flush=True)

    # chained execs, one sync: 8 reductions then one get
    t0 = time.perf_counter()
    acc = big
    outs = [red(acc) for _ in range(8)]
    _ = jax.device_get(outs[-1])
    t1 = time.perf_counter()
    print(f"8x red(1GiB)+1 get: {1000*(t1-t0):.2f} ms "
          f"-> per red {1000*(t1-t0)/8:.2f} ms", flush=True)
    # NOTE outs are independent -> device may run them; per-red time
    # approximates exec time if queue depth works.
    t0 = time.perf_counter()
    outs = [red(big) for _ in range(32)]
    _ = jax.device_get(outs[-1])
    t1 = time.perf_counter()
    print(f"32x red(1GiB)+1 get: per red {1000*(t1-t0)/32:.2f} ms "
          f"-> {1024*32/(t1-t0)/1000:.0f} GB/s", flush=True)


if __name__ == "__main__":
    main()
