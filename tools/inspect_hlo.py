"""Compile the real decode chunk and report XLA's cost analysis plus any
large copy/convert ops in the optimized HLO (fusion failures show up as
full-cache-sized copies)."""

import functools
import re
import sys

import jax
import jax.numpy as jnp

from seldon_tpu.models import get_config, init_params, transformer
from seldon_tpu.models.quantize import quantize_params
from tools.microbench_decode import chunk_impl, SLOTS, WINDOW, CHUNK


def main():
    kv = sys.argv[1] if len(sys.argv) > 1 else "int8"
    wd = sys.argv[2] if len(sys.argv) > 2 else "int8"
    cfg = get_config("bench-1b", kv_cache_dtype=kv, weight_dtype=wd)
    params = init_params(cfg, jax.random.key(0))
    if wd == "int8":
        params = quantize_params(params)
    B = SLOTS
    state = {
        "cache": transformer.init_cache(cfg, B, WINDOW),
        "last_tok": jnp.ones((B,), jnp.int32),
        "pos": jnp.full((B,), 128, jnp.int32),
        "active": jnp.ones((B,), jnp.bool_),
        "temp": jnp.full((B,), 0.7, jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.arange(B, dtype=jnp.uint32),
    }
    fn = jax.jit(functools.partial(chunk_impl, cfg=cfg, n_steps=CHUNK),
                 donate_argnums=(1,))
    lowered = fn.lower(params, state)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if ca:
        for key in sorted(ca):
            if "bytes" in key or "flops" in key or "time" in key:
                v = ca[key]
                if isinstance(v, float) and v > 1e6:
                    print(f"{key}: {v/1e9:.2f} G")
    txt = compiled.as_text()
    # find big copies / converts / broadcasts over cache-sized shapes
    pat = re.compile(r"(copy|convert|transpose)[^\n]*", re.I)
    sizes = {}
    for m in re.finditer(r"\n\s*(\S+)\s*=\s*(\w+)\[([\d,]+)\][^\n]*(copy|transpose)\(", txt):
        shape = m.group(3)
        n = 1
        for d in shape.split(","):
            n *= int(d)
        if n >= (1 << 22):
            sizes[f"{m.group(2)}[{shape}] {m.group(4)}"] = sizes.get(
                f"{m.group(2)}[{shape}] {m.group(4)}", 0) + 1
    for k, v in sorted(sizes.items(), key=lambda kv: -kv[1]):
        print(f"BIG {k} x{v}")
    # fusion count and total size hints
    print("n_fusions:", txt.count(" fusion("), " n_copy:", txt.count(" copy("))


if __name__ == "__main__":
    main()
