#!/usr/bin/env python
"""CI spec audit: graftspec speculative decoding end to end.

Boots the tiny warmed JAXServer twice — once plain, once with
``SPEC=1`` — plus ``GRAFTSAN=1`` + ``SCHED_LEDGER=1`` +
``COMPILE_LEDGER=1`` + ``FLIGHT_RECORDER=1``, and asserts the
speculation contract in one pass:

 * BIT-EXACT PARITY: the spec engine reproduces the plain engine's
   greedy streams token for token on a mixed-length prompt matrix —
   speculation may only change how many dispatches a token costs,
   never which token lands;
 * the verify ladder is DECLARED: ``static_lattice()`` carries the
   pow2 ``verify/k`` family, every dispatched variant is inside the
   static set, and the compile ledger reports ZERO live retraces under
   a real loadtester window (speculation must not reopen the shape
   lattice graftflow closed);
 * the books re-sum while speculating: the sched ledger's spec
   accounting satisfies accepted + rejected == drafted, the
   acceptance rate is the ratio of those counters, the four-way
   conservation audit (useful + bucket pad + group pad +
   spec-rejected == dispatched cells) reports zero breaches, and the
   runtime sanitizer reports zero lock-contract violations;
 * the surfaces agree: ``/debug/sched`` carries the spec sub-report,
   the loadtester ledger mirrors its acceptance rate, the jaxserver
   Prometheus surface exports the ``jaxserver_spec_*`` gauges, and
   ``tools/trace_view.py`` renders the verify waves as their own
   variant lanes in the flight-recorder timeline.

Run via ``make spec-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

# Mixed-length greedy parity matrix: lengths straddle the tiny server's
# prompt buckets so admission grouping, chunked tails and block-table
# growth all get exercised under speculation.
PARITY_PROMPTS = [
    list(range(2, 2 + n)) for n in (4, 11, 24, 17)
]
PARITY_NEW = 12


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"spec-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def _streams(engine) -> list:
    """Greedy token streams for the parity matrix, in submit order."""
    from seldon_tpu.models.sampling import SamplingParams

    qs = [engine.submit(p, SamplingParams(
              temperature=0.0, top_k=0, top_p=1.0,
              max_new_tokens=PARITY_NEW, seed=i))
          for i, p in enumerate(PARITY_PROMPTS)]
    out = []
    for q in qs:
        toks = []
        while True:
            item = q.get(timeout=120)
            if item is None:
                break
            if "error" in item:
                raise RuntimeError(item["error"])
            toks.extend(item.get("tokens", []))
        out.append(toks)
    return out


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["SPEC"] = "1"
    os.environ["GRAFTSAN"] = "1"
    os.environ["SCHED_LEDGER"] = "1"
    os.environ["COMPILE_LEDGER"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"
    os.environ["DISPATCH_TIMING"] = "1"  # verify lanes in the timeline

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    # --- reference leg: the same weights with speculation off ----------
    # (spec=0 overrides the SPEC=1 env; init_seed-determined weights are
    # identical across the two boots.)
    ref = JAXServer(preset="tiny", max_slots=4, max_seq_len=64,
                    warmup=1, spec=0)
    ref.load()
    ref.engine.start()
    want = _streams(ref.engine)
    ref.engine.stop()
    del ref
    _check(all(len(s) >= 1 for s in want),
           "reference engine produced an empty stream")

    # --- audited leg: SPEC=1 through the real REST app ------------------
    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64, warmup=1)
    srv.load()
    _check(srv.spec, "SPEC=1 did not arm the jaxserver spec path")

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        # --- bit-exact parity ------------------------------------------
        got = _streams(srv.engine)
        _check(
            got == want,
            "spec engine diverged from the plain greedy streams: "
            f"want {want} got {got}",
        )

        # --- loadtester window under speculation ------------------------
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "4",
                "--seconds", "2", "--prompt", "hi",
                "--max-new-tokens", "8",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["errors"] == 0,
               f"loadtester saw {detail['errors']} transport errors")
        _check(detail["requests"] >= 1, "loadtester completed no requests")

        sched = get("/debug/sched")
        comp = get("/debug/compile")
        snap = get("/debug/timeline")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- lattice stays closed under speculation -------------------------
    static = set(srv.engine.static_lattice())
    _check(any(k.startswith("verify/") for k in static),
           f"static lattice declares no verify family: {sorted(static)}")
    dispatched = {row["key"] for row in comp["lattice"]}
    _check(dispatched <= static,
           f"dispatched variants escaped the static lattice: "
           f"{sorted(dispatched - static)}")
    _check(comp["live_retrace_count"] == 0,
           f"{comp['live_retrace_count']} live retraces under SPEC=1")
    _check(any(row["key"].startswith("verify/") for row in comp["lattice"]),
           "no verify wave was ever dispatched")

    # --- spec books re-sum ----------------------------------------------
    spec = sched["spec"]
    _check(spec["verify_waves"] > 0, "sched ledger counted no verify waves")
    _check(spec["drafted_tokens"] > 0, "sched ledger counted no drafts")
    _check(
        spec["accepted_tokens"] + spec["rejected_tokens"]
        == spec["drafted_tokens"],
        f"acceptance identity broken: {spec}",
    )
    _check(
        abs(spec["acceptance_rate"]
            - spec["accepted_tokens"] / spec["drafted_tokens"]) < 1e-6,
        f"acceptance_rate does not re-derive: {spec}",  # snapshot rounds
    )
    cells = sched["dispatch_cells"]
    attributed = (sched["useful_tokens"] + sched["bucket_pad_tokens"]
                  + sched["group_pad_tokens"]
                  + sched["spec_rejected_tokens"])
    _check(attributed == cells,
           f"4-way attribution {attributed} != dispatched cells {cells}")
    cons = sched["conservation"]
    _check(cons["checked"] > 0, "conservation audit never ran")
    _check(cons["breaches"] == 0,
           f"{cons['breaches']} conservation breaches while speculating: "
           f"{cons['last_breach']}")
    san = srv.engine._san
    _check(san is not None, "GRAFTSAN=1 but the engine has no sanitizer")
    _check(not san.violations,
           f"graftsan violations while speculating: {san.violations}")

    # --- surface parity (counters static once the load window closed) ---
    _check(
        detail.get("spec_acceptance_rate") == spec["acceptance_rate"],
        f"ledger spec_acceptance_rate {detail.get('spec_acceptance_rate')} "
        f"!= /debug/sched {spec['acceptance_rate']}",
    )
    gauges = {m["key"] for m in srv.metrics()}
    for key in ("jaxserver_spec_acceptance_rate",
                "jaxserver_spec_drafted_tokens",
                "jaxserver_spec_accepted_tokens",
                "jaxserver_spec_rejected_tokens",
                "jaxserver_spec_verify_waves"):
        _check(key in gauges, f"metrics() missing gauge {key}")

    # --- flight recorder + trace_view verify lanes -----------------------
    waves = [r for r in snap.get("records", [])
             if r["kind"] == "dispatch"
             and str((r.get("detail") or {}).get("variant", ""))
             .startswith("verify/")]
    _check(waves, "no verify-wave dispatch records in the timeline")
    _check(any("verify_k" in (r.get("detail") or {})
               for r in snap.get("records", [])
               if r["kind"] == "boundary"),
           "spec boundary records carry no verify_k acceptance detail")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    lanes = {e["args"]["name"] for e in out["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    _check(any(name.startswith("verify/") for name in lanes),
           f"trace_view rendered no verify variant lane (got {lanes})")
    counters = {e["name"] for e in out["traceEvents"] if e["ph"] == "C"}
    _check("spec_accepted_tokens" in counters,
           f"trace_view rendered no spec acceptance counter "
           f"(got {counters})")

    srv.engine.stop()

    print(json.dumps({
        "metric": "spec_audit",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "parity_streams": len(want),
            "verify_waves": spec["verify_waves"],
            "drafted_tokens": spec["drafted_tokens"],
            "accepted_tokens": spec["accepted_tokens"],
            "acceptance_rate": round(spec["acceptance_rate"], 4),
            "spec_rejected_tokens": sched["spec_rejected_tokens"],
            "live_retraces": comp["live_retrace_count"],
            "conservation_checked": cons["checked"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
