#!/usr/bin/env python
"""CI trace smoke: the flight-recorder/tracing stack end to end.

Boots the tiny JAXServer behind the real REST app, drives it with a
short closed-loop loadtester run at ``--trace-sample 1.0`` with
``TRACING=1`` + ``FLIGHT_RECORDER=1`` (and ``GRAFTSAN=1`` unless the
caller overrides), then asserts the whole observability contract in one
pass:

 * the loadtester ledger completes with zero transport errors and a
   non-zero ``trace_sampled`` count;
 * the span sink is non-empty, contains ``engine.request`` terminal
   spans, and every loadtester-stamped trace id was adopted by the
   engine (one trace id spans HTTP entry -> engine lifecycle);
 * ``/debug/timeline`` returns a snapshot that ``tools/trace_view.py``
   converts into valid Perfetto trace_event JSON (round-trips through
   ``json``, non-empty ``traceEvents``, only legal ``ph`` values);
 * the graftsan violation log is empty after the run.

Run via ``make trace-smoke`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"trace-smoke FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sink = os.path.join(tempfile.mkdtemp(prefix="trace-smoke-"),
                        "spans.jsonl")
    os.environ["TRACING"] = "1"
    os.environ["TRACING_FILE"] = sink
    os.environ["FLIGHT_RECORDER"] = "1"
    os.environ.setdefault("GRAFTSAN", "1")

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64)
    srv.load()

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "2",
                "--seconds", "2", "--prompt", "hi",
                "--max-new-tokens", "4", "--trace-sample", "1.0",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["errors"] == 0,
               f"loadtester saw {detail['errors']} transport errors")
        _check(detail["requests"] >= 1, "loadtester completed no requests")
        _check(detail.get("trace_sampled", 0) >= 1,
               "--trace-sample 1.0 stamped no trace ids")

        # Snapshot the timeline while the engine is still up, through the
        # real debug route (exercises the wrapper endpoint too).
        with urllib.request.urlopen(f"{url}/debug/timeline",
                                    timeout=10) as resp:
            snap = json.loads(resp.read())
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- span sink: non-empty, terminal spans, trace-id adoption -------
    with open(sink) as f:
        spans = [json.loads(line) for line in f if line.strip()]
    _check(len(spans) > 0, "span sink is empty")
    roots = [s for s in spans if s["name"] == "engine.request"]
    _check(len(roots) >= detail["requests"],
           f"{len(roots)} engine.request spans < "
           f"{detail['requests']} completed requests")
    sink_traces = {s["trace_id"] for s in spans}
    missing = [tid for tid in detail.get("trace_ids", [])
               if tid not in sink_traces]
    _check(not missing,
           f"stamped trace ids never reached the span sink: {missing}")

    # --- /debug/timeline -> Perfetto trace_event JSON ------------------
    _check(snap.get("records"), "/debug/timeline returned no records")
    kinds = {r["kind"] for r in snap["records"]}
    _check("terminal" in kinds,
           f"no terminal records in timeline (kinds: {sorted(kinds)})")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    events = out["traceEvents"]
    _check(len(events) > 0, "trace_view produced no traceEvents")
    bad_ph = {e["ph"] for e in events} - {"X", "i", "C", "M"}
    _check(not bad_ph, f"illegal trace_event phases: {sorted(bad_ph)}")

    # --- graftsan: zero violations -------------------------------------
    san = getattr(srv.engine, "_san", None)
    if san is not None:
        san.check()  # raises on the first recorded violation
        _check(not san.violations, "graftsan recorded violations")
    srv.engine.stop()

    print(json.dumps({
        "metric": "trace_smoke",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "spans": len(spans),
            "engine_request_spans": len(roots),
            "timeline_records": len(snap["records"]),
            "trace_events": len(events),
            "graftsan": "on" if san is not None else "off",
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
