#!/usr/bin/env python
"""CI mesh audit: graftmesh tensor-parallel serving end to end.

Boots the tiny warmed JAXServer twice — once pinned to an explicit
single-chip mesh (``tp=1``), once as a ``TP=2`` group on the fake
8-device CPU mesh (``XLA_FLAGS=--xla_force_host_platform_device_count``
set here, matching tests/conftest.py) — with ``GRAFTSAN=1`` +
``SCHED_LEDGER=1`` + ``COMPILE_LEDGER=1`` + ``HBM_LEDGER=1`` +
``ROOF_LEDGER=1``, and asserts the graftmesh contract in one pass:

 * BIT-EXACT PARITY: the TP group reproduces the single-chip greedy
   streams token for token on a mixed-length prompt matrix (ragged
   paged serving — the full unified dispatch stack runs SPMD);
 * ONE SEALED LATTICE serves the whole group: ``/debug/compile``
   reports the TP geometry (tp=2, mesh_devices=2), every dispatched
   variant sits inside ``static_lattice()``, and a real loadtester
   window produces ZERO live retraces — SPMD partitioning must not
   reopen the shape lattice, and the donated-state sharding pins mean
   jit cache keys cannot drift;
 * the books stay clean on the mesh: the sched ledger's four-way
   attribution re-sums with zero conservation breaches, the roof
   ledger decomposes boundaries with zero breaches and carries the
   per-chip ``tp`` field, and the runtime sanitizer reports zero
   lock-contract violations;
 * LEAK-FREE: after the load window drains, live KV bytes return to
   zero — TP sharding must not strand paged blocks;
 * PER-DEVICE HBM: ``/debug/hbm`` reports the mesh size, mesh-wide
   weight bytes equal per-device x devices, and the KV reservation
   shards exactly in half on its head axis.

Run via ``make mesh-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

# Mixed-length greedy parity matrix: lengths straddle the tiny server's
# prompt buckets so admission grouping, chunked tails and block-table
# growth all get exercised on the mesh.
PARITY_PROMPTS = [
    list(range(2, 2 + n)) for n in (4, 11, 24, 17)
]
PARITY_NEW = 12


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"mesh-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def _streams(engine) -> list:
    """Greedy token streams for the parity matrix, in submit order."""
    from seldon_tpu.models.sampling import SamplingParams

    qs = [engine.submit(p, SamplingParams(
              temperature=0.0, top_k=0, top_p=1.0,
              max_new_tokens=PARITY_NEW, seed=i))
          for i, p in enumerate(PARITY_PROMPTS)]
    out = []
    for q in qs:
        toks = []
        while True:
            item = q.get(timeout=120)
            if item is None:
                break
            if "error" in item:
                raise RuntimeError(item["error"])
            toks.extend(item.get("tokens", []))
        out.append(toks)
    return out


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # The TP group needs real (fake) devices; harmless if already set.
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ["TP"] = "2"  # the audited leg arms via the env knob
    os.environ["GRAFTSAN"] = "1"
    os.environ["SCHED_LEDGER"] = "1"
    os.environ["COMPILE_LEDGER"] = "1"
    os.environ["HBM_LEDGER"] = "1"
    os.environ["ROOF_LEDGER"] = "1"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer

    SERVE = dict(preset="tiny", max_slots=4, max_seq_len=64, warmup=1,
                 ragged=1)

    # --- reference leg: same weights on an explicit single chip --------
    # (tp=1 unit param overrides the TP=2 env; init_seed-determined
    # weights are identical across the two boots.)
    ref = JAXServer(tp=1, **SERVE)
    ref.load()
    ref.engine.start()
    want = _streams(ref.engine)
    ref.engine.stop()
    del ref
    _check(all(len(s) >= 1 for s in want),
           "reference engine produced an empty stream")

    # --- audited leg: TP=2 through the real REST app --------------------
    srv = JAXServer(**SERVE)
    srv.load()
    _check(srv.tp == 2, "TP=2 env did not arm the jaxserver mesh path")
    _check(srv.engine.ecfg.tp == 2, "EngineConfig.tp did not pick up TP=2")

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        # --- occupancy probe: ratchet the kv_live watermark --------------
        # HBM gauges are evaluated only at snapshot, so observe a slot
        # mid-stream once; the leak check after the drain then proves
        # live KV genuinely returned to zero rather than never moving.
        from seldon_tpu.models.sampling import SamplingParams

        q = srv.engine.submit(PARITY_PROMPTS[2], SamplingParams(
            temperature=0.0, max_new_tokens=PARITY_NEW))
        _check(q.get(timeout=120) is not None,
               "occupancy probe stream produced nothing")
        probe = get("/debug/hbm")
        _check(probe["categories"]["kv_live"]["bytes"] > 0,
               "no live KV bytes with an occupied slot on the mesh")
        while q.get(timeout=120) is not None:
            pass

        # --- bit-exact parity ------------------------------------------
        got = _streams(srv.engine)
        _check(
            got == want,
            "TP group diverged from the single-chip greedy streams: "
            f"want {want} got {got}",
        )

        # --- loadtester window on the mesh -------------------------------
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "4",
                "--seconds", "2", "--prompt", "hi",
                "--max-new-tokens", "8",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["errors"] == 0,
               f"loadtester saw {detail['errors']} transport errors")
        _check(detail["requests"] >= 1, "loadtester completed no requests")

        srv.engine.drain(timeout=120)
        sched = get("/debug/sched")
        comp = get("/debug/compile")
        hbm = get("/debug/hbm")
        roof = get("/debug/roof")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- one sealed lattice for the whole TP group -----------------------
    _check(comp["tp"] == 2, f"/debug/compile tp={comp['tp']}, want 2")
    _check(comp["mesh_devices"] == 2,
           f"/debug/compile mesh_devices={comp['mesh_devices']}, want 2")
    static = set(srv.engine.static_lattice())
    dispatched = {row["key"] for row in comp["lattice"]}
    _check(dispatched <= static,
           f"dispatched variants escaped the static lattice: "
           f"{sorted(dispatched - static)}")
    _check(comp["live_retrace_count"] == 0,
           f"{comp['live_retrace_count']} live retraces on the mesh: "
           f"{comp['live_retraces']}")
    _check(comp["warmup_complete"] is True, "warmup never sealed")

    # --- books stay clean on the mesh ------------------------------------
    cells = sched["dispatch_cells"]
    attributed = (sched["useful_tokens"] + sched["bucket_pad_tokens"]
                  + sched["group_pad_tokens"]
                  + sched["spec_rejected_tokens"])
    _check(attributed == cells,
           f"4-way attribution {attributed} != dispatched cells {cells}")
    cons = sched["conservation"]
    _check(cons["checked"] > 0, "conservation audit never ran")
    _check(cons["breaches"] == 0,
           f"{cons['breaches']} sched conservation breaches on the mesh: "
           f"{cons['last_breach']}")
    _check(roof["tp"] == 2, f"/debug/roof tp={roof['tp']}, want 2")
    _check(roof["boundaries"] > 0, "roof ledger observed no boundaries")
    rcons = roof["conservation"]
    _check(rcons["breaches"] == 0,
           f"{rcons['breaches']} roof conservation breaches on the mesh: "
           f"{rcons['last_breach']}")
    san = srv.engine._san
    _check(san is not None, "GRAFTSAN=1 but the engine has no sanitizer")
    _check(not san.violations,
           f"graftsan violations on the mesh: {san.violations}")

    # --- leak-free: live KV returns to zero after the drain --------------
    kv_live = hbm["categories"]["kv_live"]
    _check(kv_live["bytes"] == 0,
           f"{kv_live['bytes']} live KV bytes stranded after drain")
    _check(kv_live["high_bytes"] > 0,
           "kv_live watermark never moved — the window served nothing?")

    # --- per-device HBM accounting ---------------------------------------
    _check(hbm["devices"] == 2, f"/debug/hbm devices={hbm['devices']}")
    w = hbm["categories"]["weights"]
    _check(w["bytes"] == 2 * w["bytes_per_device"],
           f"weights mesh-wide {w['bytes']} != 2 x per-device "
           f"{w['bytes_per_device']}")
    kv = hbm["categories"]["kv_cache"]
    _check(kv["bytes_per_device"] == kv["bytes"] // 2,
           f"KV reservation did not shard in half: {kv}")
    _check(hbm["total_bytes_per_device"] < hbm["total_bytes"],
           "per-device total did not drop below the mesh-wide total")

    srv.engine.stop()

    print(json.dumps({
        "metric": "mesh_audit",
        "value": 1,
        "detail": {
            "tp": comp["tp"],
            "mesh_devices": comp["mesh_devices"],
            "requests": detail["requests"],
            "parity_streams": len(want),
            "declared_variants": comp["declared_variants"],
            "dispatched_variants": comp["dispatched_variants"],
            "live_retraces": comp["live_retrace_count"],
            "weights_bytes_per_device": w["bytes_per_device"],
            "kv_bytes_per_device": kv["bytes_per_device"],
            "sched_conservation_checked": cons["checked"],
            "roof_conservation_checked": rcons["checked"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
