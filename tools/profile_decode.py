"""Capture a device profile of the decode chunk and print the op-level
time breakdown (parses the perfetto trace.json.gz jax.profiler emits)."""

import functools
import glob
import gzip
import json
import os
import sys

import jax
import jax.numpy as jnp

from seldon_tpu.models import get_config, init_params, transformer
from tools.microbench_decode import chunk_impl, SLOTS, WINDOW, CHUNK


def main():
    kv = sys.argv[1] if len(sys.argv) > 1 else "int8"
    wd = sys.argv[2] if len(sys.argv) > 2 else "int8"
    from tools.microbench_decode import act_for

    cfg = get_config(os.environ.get("MB_PRESET", "bench-1b"),
                     kv_cache_dtype=kv, weight_dtype=wd,
                     act_dtype=act_for(wd))
    if wd == "int8":
        from seldon_tpu.models.quantize import init_params_int8

        params = init_params_int8(cfg, jax.random.key(0))
    else:
        params = init_params(cfg, jax.random.key(0))
    B = SLOTS
    state = {
        "cache": transformer.init_cache(cfg, B, WINDOW),
        "last_tok": jnp.ones((B,), jnp.int32),
        "pos": jnp.full((B,), 128, jnp.int32),
        "active": jnp.ones((B,), jnp.bool_),
        "temp": jnp.full((B,), 0.7, jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.arange(B, dtype=jnp.uint32),
    }
    fn = jax.jit(functools.partial(chunk_impl, cfg=cfg, n_steps=CHUNK),
                 donate_argnums=(1,))
    state, toks = fn(params, state)
    _ = jax.device_get(toks)

    outdir = "/tmp/jaxprof"
    os.system(f"rm -rf {outdir}")
    with jax.profiler.trace(outdir):
        state, toks = fn(params, state)
        _ = jax.device_get(toks)

    files = glob.glob(f"{outdir}/**/*.trace.json.gz", recursive=True)
    if not files:
        print("NO TRACE FILES; dir contents:")
        for f in glob.glob(f"{outdir}/**/*", recursive=True):
            print(" ", f)
        return
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X" and "dur" in e]
    # Keep device-side events (TPU op track); aggregate by name.
    agg = {}
    for e in events:
        name = e.get("name", "?")
        agg[name] = agg.get(name, 0) + e["dur"]
    total = sum(agg.values())
    print(f"total traced op-us: {total} ({len(events)} events)")
    for name, us in sorted(agg.items(), key=lambda kv: -kv[1])[:25]:
        print(f"{us/1000.0:9.2f} ms  {100.0*us/total:5.1f}%  {name[:110]}")


if __name__ == "__main__":
    main()
