"""Probe: prefix-cache admission economics. Prints ONE JSON line.

Measures what EngineConfig.prefix_cache actually buys at admission time:
cold admissions (disjoint prefixes, full-prompt prefill) vs warm
admissions (shared block-aligned prefix, suffix-only prefill off the
trie's retained KV), on the live engine path — submit -> TTFT — so the
delta includes the host-side trie lookup, the device gather/scatter of
reused KV, and the smaller prefill bucket. Requests run sequentially to
isolate admission cost from queueing.

Knobs (env): PB_PRESET (tiny), PB_PROMPT (128), PB_BLOCK (16),
PB_NREQ (16), PB_KV (cfg default), PB_SHARED_FRAC (0.5 of the prompt).
CPU smoke: JAX_PLATFORMS=cpu python tools/probe_prefix.py
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PRESET = os.environ.get("PB_PRESET", "tiny")
PROMPT_LEN = int(os.environ.get("PB_PROMPT", 128))
BLOCK = int(os.environ.get("PB_BLOCK", 16))
N_REQ = int(os.environ.get("PB_NREQ", 16))
KV = os.environ.get("PB_KV", "")
SHARED_FRAC = float(os.environ.get("PB_SHARED_FRAC", 0.5))


def main() -> None:
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:  # explicit pin beats the image's sitecustomize (see bench.py)
        jax.config.update("jax_platforms", plat)

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config(PRESET)
    if KV:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=KV)
    shared = max(BLOCK, int(PROMPT_LEN * SHARED_FRAC) // BLOCK * BLOCK)
    params = init_params(cfg, jax.random.key(0))
    ecfg = EngineConfig(
        max_slots=8,
        max_seq_len=PROMPT_LEN + 16 + 1,
        prompt_buckets=(PROMPT_LEN - shared, PROMPT_LEN),
        max_admit=4,
        prefix_cache=True,
        prefix_block=BLOCK,
    )
    engine = InferenceEngine(params, cfg, ecfg)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    engine.start()
    rng = np.random.default_rng(3)
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)

    def prompt_row(prefix_seed: int):
        r = np.random.default_rng(prefix_seed)
        pre = r.integers(3, cfg.vocab_size, size=(shared,))
        suf = rng.integers(3, cfg.vocab_size, size=(PROMPT_LEN - shared,))
        return np.concatenate([pre, suf]).tolist()

    def one_ttft(prompt) -> float:
        q = engine.submit(prompt, sp)
        first = q.get(timeout=300)
        ttft = first.get("ttft_ms", float("inf")) if first else float("inf")
        while first is not None:
            first = q.get()
        return ttft

    for i in range(3):  # host-side dispatch warm-in
        one_ttft(prompt_row(10_000 + i))

    cold = [one_ttft(prompt_row(20_000 + i)) for i in range(N_REQ)]
    s0 = engine.stats.snapshot()
    one_ttft(prompt_row(7))  # seed the shared prefix into the trie
    warm = [one_ttft(prompt_row(7)) for i in range(N_REQ)]
    s1 = engine.stats.snapshot()
    trie = engine._prefix.snapshot()
    engine.stop()

    hits = s1["prefix_hits"] - s0["prefix_hits"]
    cold_p50 = float(np.percentile(cold, 50))
    warm_p50 = float(np.percentile(warm, 50))
    print(json.dumps({
        "metric": "prefix_warm_admission_speedup",
        "value": round(cold_p50 / warm_p50, 3) if warm_p50 else 0.0,
        "unit": (
            f"x (cold/warm p50 TTFT, {PRESET} {cfg.kv_cache_dtype} kv, "
            f"prompt {PROMPT_LEN}, shared {shared}, block {BLOCK})"
        ),
        "detail": {
            "hit_rate": round(hits / (N_REQ + 1), 3),
            "tokens_saved": int(s1["prefix_tokens_saved"]
                                - s0["prefix_tokens_saved"]),
            "cold_p50_ttft_ms": round(cold_p50, 2),
            "cold_p99_ttft_ms": round(float(np.percentile(cold, 99)), 2),
            "warm_p50_ttft_ms": round(warm_p50, 2),
            "warm_p99_ttft_ms": round(float(np.percentile(warm, 99)), 2),
            "trie_nodes": trie["nodes"],
            "trie_bytes": trie["bytes"],
            "evictions": trie["evictions"],
            "warmup_s": round(warmup_s, 1),
            "device": str(jax.devices()[0]),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
