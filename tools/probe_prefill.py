"""Prefill/admission cost at serving shapes: group size x weights x attn.

The bench showed admission (batched prefill) costs ~25 ms per [8,128]
group — 1/3 of total bench time. This measures where it goes and what
group size / attention impl / weight dtype do to it.
"""

import functools
import sys

import jax
import jax.numpy as jnp

from seldon_tpu.models import get_config, init_params, transformer
from seldon_tpu.models.quantize import quantize_params
from seldon_tpu.models.sampling import sample_per_row
from tools.timing import slope_time

SLOTS = 160
WINDOW = 257
SB = 128


def admit_impl(params, state, toks, plens, slots, *, cfg):
    """Mirror of engine._admit_impl (prefill + scatter + first sample)."""
    G, Sb = toks.shape
    sub = transformer.init_cache(cfg, G, Sb)
    logits, sub = transformer.prefill(params, toks, plens, sub, cfg)
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.key(s), p)
    )(jnp.arange(G, dtype=jnp.uint32), plens)
    first = sample_per_row(
        logits, keys, jnp.full((G,), 0.7), jnp.zeros((G,), jnp.int32),
        jnp.ones((G,)))
    cache = state["cache"]
    new_cache = {
        key: cache[key].at[:, slots, :, :Sb].set(
            sub[key].astype(cache[key].dtype))
        for key in cache
    }
    return {**state, "cache": new_cache}, first


def run(G, weights, kv, attn):
    cfg = get_config("bench-1b", weight_dtype=weights, kv_cache_dtype=kv,
                     attn_impl=attn or "xla")
    params = init_params(cfg, jax.random.key(0))
    if weights == "int8":
        params = quantize_params(params)
    state = {"cache": transformer.init_cache(cfg, SLOTS, WINDOW)}
    fn = jax.jit(functools.partial(admit_impl, cfg=cfg), donate_argnums=(1,))
    toks = jnp.ones((G, SB), jnp.int32)
    plens = jnp.full((G,), SB, jnp.int32)
    slots = jnp.arange(G, dtype=jnp.int32)

    def one(state):
        state, first = fn(params, state, toks, plens, slots)
        return state

    dt, _ = slope_time(one, state, k1=3, k2=23)
    tok_s = G * SB / dt
    print(f"G={G:3d} w={weights:5s} attn={attn or 'xla':6s} "
          f"{dt*1000:8.2f} ms/admission  {tok_s/1000:8.1f}k tok/s prefill",
          flush=True)


if __name__ == "__main__":
    combos = sys.argv[1:] or [
        "8:int8:", "16:int8:", "32:int8:", "8:bf16:", "32:bf16:",
        "8:int8:flash", "32:int8:flash",
    ]
    for c in combos:
        g, w, a = c.split(":")
        run(int(g), w, "int8", a)
