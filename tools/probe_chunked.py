"""Probe: chunked-prefill stall economics. Prints ONE JSON line.

Measures what EngineConfig.chunked_prefill actually buys under mixed
traffic: PC_STREAMS short-prompt decode streams run steadily, then ONE
long prompt (PC_LONG tokens) arrives mid-decode. The recorded number is
the p99 client-side burst gap (inter-token latency) of the short
streams AFTER the interloper lands — uninterleaved, the whole long
prefill runs before the next decode chunk; chunked, at most
PC_BUDGET prefill tokens separate consecutive decode chunks.

Knobs (env): PC_PRESET (tiny), PC_PROMPT (32), PC_LONG (8x prompt),
PC_CHUNK (= prompt), PC_BUDGET (= chunk), PC_STREAMS (4), PC_NEW (64),
PC_KV (cfg default).
CPU smoke: JAX_PLATFORMS=cpu python tools/probe_chunked.py
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PRESET = os.environ.get("PC_PRESET", "tiny")
PROMPT_LEN = int(os.environ.get("PC_PROMPT", 32))
LONG_LEN = int(os.environ.get("PC_LONG", 8 * PROMPT_LEN))
CHUNK = int(os.environ.get("PC_CHUNK", PROMPT_LEN))
BUDGET = int(os.environ.get("PC_BUDGET", CHUNK))
N_STREAMS = int(os.environ.get("PC_STREAMS", 4))
NEW_TOKENS = int(os.environ.get("PC_NEW", 64))
KV = os.environ.get("PC_KV", "")


def main() -> None:
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:  # explicit pin beats the image's sitecustomize (see bench.py)
        jax.config.update("jax_platforms", plat)

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config(PRESET)
    if KV:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=KV)
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(17)
    shorts = [
        rng.integers(3, cfg.vocab_size, size=(PROMPT_LEN,)).tolist()
        for _ in range(N_STREAMS)
    ]
    long_prompt = rng.integers(3, cfg.vocab_size, size=(LONG_LEN,)).tolist()
    warm_s = [0.0]

    def run(chunked: bool):
        ecfg = EngineConfig(
            max_slots=N_STREAMS + 2,
            max_seq_len=LONG_LEN + NEW_TOKENS + 1,
            prompt_buckets=(PROMPT_LEN, LONG_LEN),
            max_admit=4,
            decode_chunk=4,
            adaptive_chunk=False,
            chunked_prefill=chunked,
            prefill_chunk=CHUNK,
            dispatch_token_budget=BUDGET,
        )
        engine = InferenceEngine(params, cfg, ecfg)
        t0 = time.perf_counter()
        engine.warmup()
        warm_s[0] += time.perf_counter() - t0
        engine.start()
        gaps: list = []
        glock = threading.Lock()
        first_burst = threading.Barrier(N_STREAMS + 1)

        def consume(q):
            last = None
            waited = False
            while True:
                item = q.get()
                if item is None:
                    break
                if "error" in item:
                    raise RuntimeError(item["error"])
                now = time.perf_counter()
                if last is not None and item["tokens"]:
                    with glock:
                        gaps.append((now, now - last))
                last = now
                if not waited:
                    waited = True
                    first_burst.wait(timeout=300)

        threads = []
        for i, p in enumerate(shorts):
            q = engine.submit(
                p,
                SamplingParams(
                    temperature=0.0, max_new_tokens=NEW_TOKENS, seed=i
                ),
            )
            t = threading.Thread(target=consume, args=(q,), daemon=True)
            t.start()
            threads.append(t)
        first_burst.wait(timeout=300)  # all streams mid-decode
        t_long = time.perf_counter()
        lq = engine.submit(
            long_prompt,
            SamplingParams(temperature=0.0, max_new_tokens=8, seed=99),
        )
        for t in threads:
            t.join(timeout=300)
        while lq.get(timeout=300) is not None:
            pass
        snap = engine.stats.snapshot()
        engine.stop()
        tail = [g for ts, g in gaps if ts >= t_long]
        p99 = 1000.0 * float(np.percentile(tail or [0.0], 99))
        return p99, snap

    base_p99, _ = run(chunked=False)
    chunked_p99, snap = run(chunked=True)
    print(json.dumps({
        "metric": "chunked_prefill_p99_itl_speedup",
        "value": (
            round(base_p99 / chunked_p99, 3) if chunked_p99 else 0.0
        ),
        "unit": (
            f"x (uninterleaved/chunked p99 ITL, {PRESET} "
            f"{cfg.kv_cache_dtype} kv, {N_STREAMS} streams prompt "
            f"{PROMPT_LEN}, interloper {LONG_LEN}, chunk {CHUNK}, "
            f"budget {BUDGET})"
        ),
        "detail": {
            "baseline_p99_itl_ms": round(base_p99, 2),
            "chunked_p99_itl_ms": round(chunked_p99, 2),
            "prefill_chunks": int(snap["prefill_chunks"]),
            "prefill_chunk_tokens": int(snap["prefill_chunk_tokens"]),
            "budget_utilization": round(
                float(snap["budget_utilization"]), 3
            ),
            "engine_itl_p99_ms": float(snap["itl_p99_ms"]),
            "mean_queue_wait_ms": round(
                float(snap["mean_queue_wait_ms"]), 2
            ),
            "warmup_s": round(warm_s[0], 1),
            "device": str(jax.devices()[0]),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
