#!/usr/bin/env python
"""CI pilot audit: graftpilot flies a real server end to end.

Boots the tiny warmed JAXServer (chunked prefill, so the budget knob is
live) behind the real REST app with ``PILOT=1`` + ``GRAFTSAN=1`` +
``FLIGHT_RECORDER=1``, polls ``/debug/pilot`` on the idle engine, then
drives a short mixed-deadline closed-loop loadtester run and asserts
the controller contract in one pass:

 * idle engine -> the documented schema with ZERO boundaries, windows
   and decisions, and every knob already inside its clamp envelope;
 * under load the controller CONVERGES: at least one decision lands in
   the ledger (the tiny budget is deterministically starved by
   multi-chunk prompts), every entry carries a non-empty rationale +
   signal snapshot, and every live knob stays inside the envelope;
 * the books stay clean while the pilot flies: the sched ledger
   (implied by PILOT) reports zero conservation breaches and the
   runtime sanitizer reports zero lock-contract violations;
 * the loadtester's ``/debug/pilot`` poll agrees with the route
   (decision counts can only grow between the two reads), and the
   jaxserver Prometheus surface exports the ``jaxserver_pilot_*``
   gauges;
 * decisions land as flight-recorder "pilot" records and
   ``tools/trace_view.py`` renders the decision lane + knob counters.

Run via ``make pilot-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

# Frozen /debug/pilot top-level key set — tests/test_debug_schema.py
# carries the same golden; a mismatch here means the snapshot schema
# changed without updating its consumers.
PILOT_TOP_KEYS = frozenset({
    "enabled", "mode", "boundaries", "windows", "period_boundaries",
    "decisions_total", "decisions_by_knob", "knobs", "envelope", "edf",
    "counterfactual", "ledger",
})
PILOT_LEDGER_KEYS = frozenset({
    "ts", "knob", "old", "new", "rationale", "expected_effect",
    "signal_snapshot", "effect",
})


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"pilot-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def _knobs_in_envelope(pilot: dict) -> None:
    env = pilot["envelope"]
    knobs = pilot["knobs"]
    _check(
        env["budget_min"] <= knobs["dispatch_token_budget"]
        <= env["budget_max"],
        f"budget {knobs['dispatch_token_budget']} left the envelope "
        f"[{env['budget_min']}, {env['budget_max']}]",
    )
    _check(
        env["admit_min"] <= knobs["max_admit"] <= env["admit_max"],
        f"max_admit {knobs['max_admit']} left the envelope "
        f"[{env['admit_min']}, {env['admit_max']}]",
    )
    _check(knobs["max_admit"] & (knobs["max_admit"] - 1) == 0,
           f"max_admit {knobs['max_admit']} is not a power of two")
    _check(
        env["bias_min"] <= knobs["chunk_bias"] <= env["bias_max"],
        f"chunk_bias {knobs['chunk_bias']} left the envelope "
        f"[{env['bias_min']}, {env['bias_max']}]",
    )
    _check(
        env["speck_min"] <= knobs["spec_k"] <= env["speck_max"],
        f"spec_k {knobs['spec_k']} left the envelope "
        f"[{env['speck_min']}, {env['speck_max']}]",
    )


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PILOT"] = "1"
    os.environ["GRAFTSAN"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    # Chunked prefill with the minimum legal chunk (16 = prefix_block)
    # and the default budget (= one chunk): the loadtester's multi-chunk
    # prompts then starve the budget deterministically, so the
    # convergence check below observes a real control decision, not a
    # lucky race.
    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=128,
                    warmup=1, chunked_prefill=1, prefill_chunk=16)
    srv.load()

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        # --- idle engine: schema + neutral state ------------------------
        idle = get("/debug/pilot")
        _check(set(idle) == PILOT_TOP_KEYS,
               f"/debug/pilot keys drifted: got {sorted(idle)}")
        _check(idle["enabled"] is True, "idle pilot reports enabled=false")
        _check(idle["mode"] == "auto", f"idle mode = {idle['mode']}")
        _check(idle["boundaries"] == 0,
               f"idle engine counted {idle['boundaries']} boundaries")
        _check(idle["decisions_total"] == 0,
               f"idle engine took {idle['decisions_total']} decisions")
        _check(idle["ledger"] == [], "idle engine has ledger entries")
        _knobs_in_envelope(idle)

        # --- mixed-deadline load window ---------------------------------
        # ~64-byte prompts = 4 prefill chunks each; 3 s TTL on half the
        # requests gives the EDF queue real deadline/no-deadline mixing.
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "8",
                "--seconds", "3",
                "--prompt", "p" * 64,
                "--max-new-tokens", "8",
                "--deadline-ms", "3000", "--deadline-frac", "0.5",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["requests"] >= 1, "loadtester completed no requests")
        _check("pilot_decisions" in detail,
               "loadtester ledger carries no pilot counters")

        pilot = get("/debug/pilot")
        sched = get("/debug/sched")
        snap = get("/debug/timeline")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- convergence: the controller actually decided -------------------
    _check(set(pilot) == PILOT_TOP_KEYS,
           f"/debug/pilot keys drifted: got {sorted(pilot)}")
    _check(pilot["boundaries"] > 0, "pilot observed no boundaries")
    _check(pilot["windows"] > 0, "pilot closed no decision windows")
    _check(
        pilot["decisions_total"] >= 1,
        f"controller never converged to a decision "
        f"({pilot['windows']} windows, knobs {pilot['knobs']})",
    )
    _check(len(pilot["ledger"]) >= 1, "decision ledger is empty")
    for entry in pilot["ledger"]:
        _check(set(entry) == PILOT_LEDGER_KEYS,
               f"ledger entry keys drifted: got {sorted(entry)}")
        _check(bool(entry["rationale"]),
               f"decision on {entry['knob']} carries no rationale")
        _check(bool(entry["signal_snapshot"]),
               f"decision on {entry['knob']} carries no signal snapshot")
        _check(entry["old"] != entry["new"],
               f"no-op decision recorded on {entry['knob']}")
    _knobs_in_envelope(pilot)
    _check(
        pilot["decisions_total"] == sum(
            pilot["decisions_by_knob"].values()
        ),
        "decisions_by_knob does not re-sum to decisions_total",
    )

    # --- books stay clean under the controller --------------------------
    cons = sched["conservation"]
    _check(cons["checked"] > 0, "conservation audit never ran")
    _check(
        cons["breaches"] == 0,
        f"{cons['breaches']} conservation breaches under the pilot: "
        f"{cons['last_breach']}",
    )
    san = srv.engine._san
    _check(san is not None, "GRAFTSAN=1 but the engine has no sanitizer")
    _check(
        not san.violations,
        f"graftsan violations under the pilot: {san.violations}",
    )

    # --- loadtester ledger / route parity -------------------------------
    # The route poll ran after the loadtester's; trailing in-flight
    # decode can only ADD decisions/boundaries between the two reads.
    _check(
        detail["pilot_decisions"] <= pilot["decisions_total"],
        f"ledger pilot_decisions {detail['pilot_decisions']} > route "
        f"{pilot['decisions_total']}",
    )
    _check(
        detail["pilot_edf_inversions"] <= pilot["edf"]["inversions"],
        f"ledger inversions {detail['pilot_edf_inversions']} > route "
        f"{pilot['edf']['inversions']}",
    )

    # --- Prometheus surface ---------------------------------------------
    gauges = {m["key"] for m in srv.metrics()}
    for key in ("jaxserver_pilot_decisions_total",
                "jaxserver_pilot_budget_current",
                "jaxserver_pilot_edf_inversions",
                "jaxserver_pilot_goodput_delta"):
        _check(key in gauges, f"metrics() missing gauge {key}")

    # --- flight recorder + trace_view decision lane ---------------------
    pilot_recs = [r for r in snap.get("records", [])
                  if r["kind"] == "pilot"]
    _check(pilot_recs, "no pilot records in the timeline")
    for r in pilot_recs:
        d = r.get("detail") or {}
        _check("knob" in d and "rationale" in d,
               f"pilot record missing knob/rationale: {sorted(d)}")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    lanes = {e["args"]["name"] for e in out["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    _check("seldon-tpu pilot" in lanes,
           f"trace_view rendered no pilot process (got {lanes})")
    counters = {e["name"] for e in out["traceEvents"] if e["ph"] == "C"}
    _check("pilot_budget" in counters,
           f"trace_view rendered no pilot knob counters (got {counters})")

    srv.engine.stop()

    print(json.dumps({
        "metric": "pilot_audit",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "boundaries": pilot["boundaries"],
            "windows": pilot["windows"],
            "decisions_total": pilot["decisions_total"],
            "decisions_by_knob": pilot["decisions_by_knob"],
            "final_knobs": pilot["knobs"],
            "edf": pilot["edf"],
            "counterfactual": pilot["counterfactual"],
            "conservation_checked": cons["checked"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
