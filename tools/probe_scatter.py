"""Bisect what makes the real decode's cache-xs scan materialize slices:
A) plain xs-read attention scan (baseline, known fast)
B) + final batched scatter into the same cache (no donation)
C) + donation of the cache
D) + weights-in-xs MLP work interleaved
"""

import functools

import jax
import jax.numpy as jnp

from tools.timing import slope_time

B, T, Hkv, G, Dh, L = 160, 257, 8, 2, 128, 16
D_MODEL, F = 2048, 5632
CHUNK = 32


def attend(qx, ck, cv, mask):
    scores = jnp.einsum("bskgd,bktd->bkgst", qx, ck,
                        preferred_element_type=jnp.float32) / Dh**0.5
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(qx.dtype)
    return jnp.einsum("bkgst,bktd->bskgd", w, cv)


def mk_cache(key):
    kbf = jax.random.normal(key, (L, B, Hkv, T, Dh), jnp.bfloat16)
    return {"k": kbf, "v": kbf + 1}


def run(name, with_scatter, donate, with_mlp):
    pos = jnp.full((B,), 128, jnp.int32)
    rows = jnp.arange(B)
    mask = (jnp.arange(T)[None, None, :] < 128)

    if with_mlp:
        wk = jax.random.split(jax.random.key(7), 3)
        weights = {
            "g": jax.random.normal(wk[0], (L, D_MODEL, F), jnp.bfloat16) * 0.02,
            "u": jax.random.normal(wk[1], (L, D_MODEL, F), jnp.bfloat16) * 0.02,
            "d": jax.random.normal(wk[2], (L, F, D_MODEL), jnp.bfloat16) * 0.02,
        }
    else:
        weights = {}

    donate_args = (0,) if donate else ()

    @functools.partial(jax.jit, donate_argnums=donate_args)
    def f(cache, q, h):
        def step(carry, _):
            c, q, h = carry

            def layer(inner, xs):
                acc, hh = inner
                cl, w = xs
                out = attend(acc, cl["k"], cl["v"], mask)
                acc = acc + out * 1e-3
                if with_mlp:
                    hid = jax.nn.silu(jnp.einsum("bd,df->bf", hh, w["g"])) \
                        * jnp.einsum("bd,df->bf", hh, w["u"])
                    hh = hh + jnp.einsum("bf,fd->bd", hid, w["d"])
                fresh = (acc[:, 0, :, 0, :] * 1e-3).astype(jnp.bfloat16)
                return (acc, hh), fresh

            (q, h), fresh = jax.lax.scan(layer, (q, h), (c, weights))
            if with_scatter:
                # fresh: [L, B, Hkv, Dh] -> [B, L, Hkv, Dh] at [:, rows, :, pos]
                upd = jnp.swapaxes(fresh, 0, 1)
                c = dict(c)
                c["k"] = c["k"].at[:, rows, :, pos].set(
                    upd, unique_indices=True)
                c["v"] = c["v"].at[:, rows, :, pos].set(
                    upd, unique_indices=True)
            return (c, q, h), ()

        (cache, q, h), _ = jax.lax.scan(step, (cache, q, h), None,
                                        length=CHUNK)
        return cache, q, h

    cache = mk_cache(jax.random.key(1))
    q = jax.random.normal(jax.random.key(2), (B, 1, Hkv, G, Dh), jnp.bfloat16)
    h = jnp.ones((B, D_MODEL), jnp.bfloat16)

    def one(state):
        c, qq, hh = state
        return f(c, qq, hh)

    dt, _ = slope_time(one, (cache, q, h), k1=2, k2=6)
    print(f"{name:24s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)


if __name__ == "__main__":
    run("A xs-read only", False, False, False)
    run("B +scatter", True, False, False)
    run("C +scatter+donate", True, True, False)
    run("D +scatter+donate+mlp", True, True, True)
