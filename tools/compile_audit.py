#!/usr/bin/env python
"""CI compile audit: the compile/device observatory end to end.

Boots the tiny warmed JAXServer behind the real REST app with
``COMPILE_LEDGER=1`` + ``HBM_LEDGER=1`` + ``DISPATCH_TIMING=1`` +
``FLIGHT_RECORDER=1``, drives it with a short closed-loop loadtester
run, then asserts the observatory contract in one pass:

 * ``/debug/compile`` returns the documented schema with
   ``warmup_complete`` true, **zero live retraces** — the regression
   tripwire for the static-shape lattice: any new dispatch site or
   bucketing change that compiles on the serving path fails CI here —
   and a dispatched-variant count within ``VARIANT_BUDGET``;
 * the loadtester ledger carries the same ``compile_variants`` /
   ``live_retraces`` numbers (the bench/ledger surface);
 * per-variant dispatch timing reached EngineStats and the flight
   recorder ("dispatch" records convert to variant lanes in
   ``tools/trace_view.py``);
 * ``/debug/hbm`` returns the documented schema with non-zero weight
   and KV-reservation bytes.

With ``--static-xcheck`` the audit additionally cross-checks the
runtime against graftflow's closed-form model: every key the engine
actually dispatched must be a member of ``engine.static_lattice()``
(the ``shape_lattice.dispatch_keys`` enumeration), and the declared
variant count must equal the static lattice size — i.e. warmup
declared exactly the statically-certified set, nothing ad hoc.
Before booting anything it also runs the graftnum certifier passes
(num-barrier / use-after-donate / einsum-broadcast + mask-dtype) over
``seldon_tpu/`` and fails if any finding survives the inline waivers:
a tree the audit is about to *measure* must already be numerics- and
lifetime-clean, or the measured bits aren't the contract bits.

The audit then runs a second, RAGGED leg — once per attention-kernel
leg (``RAGGED_KERNEL=masked`` and ``sparse``; graftkern): the same
warmed tiny server under ``RAGGED=1`` driven by the same loadtester
mix, asserting the graftragged collapse holds on EVERY kernel leg —
compile-variant count ≤ ``RAGGED_VARIANT_BUDGET`` (deactivate + the
one ``ragged/C`` wave kernel; the kernel string is closed over at jit
time, so swapping it must not widen the lattice) and zero live
retraces. The masked leg's numbers ride the metric line
(``ragged_compile_variants`` / ``ragged_live_retraces``) and the
sparse leg adds ``ragged_sparse_*`` twins, so ``bench_compare`` gates
both strictly. The pallas leg is exercised by
tests/test_ragged_kernel.py instead — interpret-mode through a full
server drive is too slow for this audit's budget.

A third, SPEC leg boots the same server under ``SPEC=1`` and asserts
the graftspec lattice contract: the pow2 ``verify/k`` ladder replaces
the ``decode/n`` chunk rungs (a verify wave dispatched, no decode
variant did), every dispatched key stays inside ``static_lattice()``,
and zero live retraces — speculation must not reopen the shape lattice
graftflow closed.

Run via ``make compile-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys

# Dispatched-variant ceiling for the tiny CPU config (2 prompt buckets
# x 3 admission group sizes + decode rungs + deactivate ~= 9 today).
# Roadmap items 1-2 drive this DOWN; raising it needs a written
# justification in the PR that does so.
VARIANT_BUDGET = 32

# The graftragged contract is exact, not a ceiling with headroom: one
# unified wave kernel + deactivate. A third variant means the collapse
# broke (ISSUE 12 acceptance: static_lattice() size ≤ 2 under RAGGED=1).
RAGGED_VARIANT_BUDGET = 2


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"compile-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.compile_audit")
    ap.add_argument(
        "--static-xcheck", action="store_true",
        help="also assert the runtime-dispatched key set is contained in "
             "engine.static_lattice() and that warmup declared exactly "
             "the static lattice (graftflow's closed-form model)")
    args = ap.parse_args(argv)

    if args.static_xcheck:
        # graftnum gate first: static, cheap, and a prerequisite — if
        # the tree has an uncertified fusion boundary or a use-after-
        # donate path, the runtime numbers below measure the bug.
        from pathlib import Path

        from tools.graftlint import core, donate, einsumcheck, numbarrier

        root = Path(__file__).resolve().parent.parent
        files = core.load_tree([root / "seldon_tpu"], root)
        ctx = core.Context(root)
        findings = core.run_passes(
            files, ctx, [numbarrier.run, donate.run, einsumcheck.run])
        for f in findings:
            print(f"compile-audit graftnum: {f.render()}", file=sys.stderr)
        _check(not findings,
               f"graftnum: {len(findings)} uncertified finding(s) in "
               "seldon_tpu/ — fix or waive inline before auditing")
        print(f"compile-audit: graftnum clean over {len(files)} file(s)")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["COMPILE_LEDGER"] = "1"
    os.environ["HBM_LEDGER"] = "1"
    os.environ["DISPATCH_TIMING"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    def _drive(**srv_kwargs):
        """Boot a warmed tiny server behind the REST app, run the
        short closed-loop loadtester mix, return (srv, loadtester
        ledger detail, /debug/compile, /debug/hbm, /debug/timeline)."""
        # warmup=1 is the point: the audit asserts the declared lattice
        # covers live traffic, so warmup must actually run.
        srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64,
                        warmup=1, **srv_kwargs)
        srv.load()

        holder, started = {}, threading.Event()

        async def amain() -> None:
            runner = web.AppRunner(build_rest_app(srv))
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["port"] = site._server.sockets[0].getsockname()[1]
            started.set()
            while not holder.get("stop"):
                await asyncio.sleep(0.05)
            await runner.cleanup()

        t = threading.Thread(target=lambda: asyncio.run(amain()),
                             daemon=True)
        t.start()
        _check(started.wait(60), "REST app failed to start within 60s")
        url = f"http://127.0.0.1:{holder['port']}"

        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                lt_main([
                    url, "--transport", "generate", "--clients", "2",
                    "--seconds", "2", "--prompt", "hi",
                    "--max-new-tokens", "4",
                ])
            ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
            detail = ledger["detail"]
            _check(detail["errors"] == 0,
                   f"loadtester saw {detail['errors']} transport errors")
            _check(detail["requests"] >= 1,
                   "loadtester completed no requests")

            with urllib.request.urlopen(f"{url}/debug/compile",
                                        timeout=10) as resp:
                comp = json.loads(resp.read())
            with urllib.request.urlopen(f"{url}/debug/hbm",
                                        timeout=10) as resp:
                hbm = json.loads(resp.read())
            with urllib.request.urlopen(f"{url}/debug/timeline",
                                        timeout=10) as resp:
                snap = json.loads(resp.read())
        finally:
            holder["stop"] = True
            t.join(timeout=10)
        return srv, detail, comp, hbm, snap

    srv, detail, comp, hbm, snap = _drive()

    # --- /debug/compile: schema + the zero-retrace gate -----------------
    for key in ("warmup_complete", "declared_variants",
                "dispatched_variants", "warmup_coverage",
                "compile_s_total", "live_retrace_count", "live_retraces",
                "lattice"):
        _check(key in comp, f"/debug/compile missing '{key}'")
    _check(comp["warmup_complete"], "warmup never sealed the lattice")
    _check(
        comp["live_retrace_count"] == 0,
        f"{comp['live_retrace_count']} live retraces after warmup: "
        f"{comp['live_retraces']}",
    )
    _check(comp["dispatched_variants"] >= 1, "no variants dispatched")
    _check(
        comp["dispatched_variants"] <= VARIANT_BUDGET,
        f"{comp['dispatched_variants']} variants exceed the "
        f"budget of {VARIANT_BUDGET}",
    )
    _check(comp["compile_s_total"] > 0.0, "zero cumulative compile time")
    undeclared = [e["key"] for e in comp["lattice"] if not e["declared"]]
    _check(not undeclared, f"undeclared lattice keys: {undeclared}")

    # --- --static-xcheck: runtime vs graftflow's closed-form lattice ----
    static_size = None
    if args.static_xcheck:
        static = set(srv.engine.static_lattice())
        static_size = len(static)
        dispatched = {e["key"] for e in comp["lattice"]}
        rogue = sorted(dispatched - static)
        _check(
            not rogue,
            f"runtime dispatched {len(rogue)} key(s) outside the static "
            f"lattice: {rogue}",
        )
        _check(
            comp["declared_variants"] == static_size,
            f"warmup declared {comp['declared_variants']} variants but "
            f"the static lattice holds {static_size} — warmup and "
            f"shape_lattice.dispatch_keys have drifted apart",
        )

    # --- loadtester ledger carries the compile counters -----------------
    _check(
        detail.get("compile_variants") == comp["dispatched_variants"],
        f"ledger compile_variants {detail.get('compile_variants')} != "
        f"/debug/compile {comp['dispatched_variants']}",
    )
    _check(detail.get("live_retraces") == 0,
           f"ledger live_retraces = {detail.get('live_retraces')}")

    # --- per-variant timing: stats histogram + recorder lanes -----------
    stats = srv.engine.stats.snapshot()
    timing = stats.get("variant_timing", {})
    _check(timing, "DISPATCH_TIMING=1 populated no variant histograms")
    _check(any(k.startswith("decode/") for k in timing),
           f"no decode variant timed (got: {sorted(timing)})")
    kinds = {r["kind"] for r in snap.get("records", [])}
    _check("dispatch" in kinds,
           f"no dispatch records in timeline (kinds: {sorted(kinds)})")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    lanes = {
        e["args"]["name"] for e in out["traceEvents"]
        if e["ph"] == "M" and e.get("pid") == trace_view._VARIANT_PID
        and e["name"] == "thread_name"
    }
    _check(lanes, "trace_view rendered no per-variant lanes")

    # --- /debug/hbm: schema + non-trivial accounting --------------------
    for key in ("categories", "total_bytes", "total_high_bytes"):
        _check(key in hbm, f"/debug/hbm missing '{key}'")
    cats = hbm["categories"]
    for name in ("weights", "kv_cache", "kv_live", "workspace"):
        _check(name in cats, f"/debug/hbm missing category '{name}'")
    _check(cats["weights"]["bytes"] > 0, "zero weight bytes")
    _check(cats["kv_cache"]["bytes"] > 0, "zero KV reservation bytes")
    _check(cats["workspace"]["high_bytes"] > 0,
           "workspace high-watermark never moved")

    srv.engine.stop()

    # --- RAGGED leg: the graftragged collapse, once per kernel leg ------
    ragged_legs = {}
    ragged_static_size = None
    for kern in ("masked", "sparse"):
        tag = f"ragged[{kern}]"
        rsrv, rdetail, rcomp, _, _ = _drive(ragged=1, ragged_kernel=kern)
        _check(rcomp["warmup_complete"],
               f"{tag}: warmup never sealed the lattice")
        _check(
            rcomp["live_retrace_count"] == 0,
            f"{tag}: {rcomp['live_retrace_count']} live retraces after "
            f"warmup: {rcomp['live_retraces']}",
        )
        _check(
            1 <= rcomp["dispatched_variants"] <= RAGGED_VARIANT_BUDGET,
            f"{tag}: {rcomp['dispatched_variants']} variants dispatched "
            f"— the collapse contract is ≤ {RAGGED_VARIANT_BUDGET} "
            f"(deactivate + one ragged/C wave kernel)",
        )
        rogue = [e["key"] for e in rcomp["lattice"] if not e["declared"]]
        _check(not rogue, f"{tag}: undeclared lattice keys: {rogue}")
        _check(
            any(e["key"].startswith("ragged/") for e in rcomp["lattice"]),
            f"{tag}: no ragged/C variant dispatched "
            f"(got: {sorted(e['key'] for e in rcomp['lattice'])})",
        )
        _check(
            rdetail.get("compile_variants") == rcomp["dispatched_variants"],
            f"{tag}: ledger compile_variants "
            f"{rdetail.get('compile_variants')} != /debug/compile "
            f"{rcomp['dispatched_variants']}",
        )
        if args.static_xcheck:
            rstatic = set(rsrv.engine.static_lattice())
            _check(
                len(rstatic) <= RAGGED_VARIANT_BUDGET,
                f"{tag}: static lattice holds {len(rstatic)} keys "
                f"({sorted(rstatic)}) — the closed-form collapse broke",
            )
            rdispatched = {e["key"] for e in rcomp["lattice"]}
            rrogue = sorted(rdispatched - rstatic)
            _check(
                not rrogue,
                f"{tag}: runtime dispatched {len(rrogue)} key(s) outside "
                f"the static lattice: {rrogue}",
            )
            _check(
                rcomp["declared_variants"] == len(rstatic),
                f"{tag}: warmup declared {rcomp['declared_variants']} "
                f"variants but the static lattice holds {len(rstatic)}",
            )
            if kern == "masked":
                ragged_static_size = len(rstatic)
        rsrv.engine.stop()
        ragged_legs[kern] = (rdetail, rcomp)
    rdetail, rcomp = ragged_legs["masked"]
    sdetail_sparse, scomp_sparse = ragged_legs["sparse"]

    # --- SPEC leg: the verify ladder stays inside the lattice -----------
    # graftspec replaces the decode-chunk rungs with the pow2
    # ("verify", k) ladder; the contract here is containment + zero
    # retraces, not a fixed count (the admission grid is still live).
    ssrv, sdetail, scomp, _, _ = _drive(spec=1)
    _check(scomp["warmup_complete"],
           "spec: warmup never sealed the lattice")
    _check(
        scomp["live_retrace_count"] == 0,
        f"spec: {scomp['live_retrace_count']} live retraces after "
        f"warmup: {scomp['live_retraces']}",
    )
    srogue = [e["key"] for e in scomp["lattice"] if not e["declared"]]
    _check(not srogue, f"spec: undeclared lattice keys: {srogue}")
    _check(
        any(e["key"].startswith("verify/") for e in scomp["lattice"]),
        f"spec: no verify/k variant dispatched "
        f"(got: {sorted(e['key'] for e in scomp['lattice'])})",
    )
    _check(
        not any(e["key"].startswith("decode/") for e in scomp["lattice"]),
        "spec: a decode/ chunk variant dispatched — the verify ladder "
        "should have replaced the decode rungs",
    )
    _check(
        sdetail.get("compile_variants") == scomp["dispatched_variants"],
        f"spec: ledger compile_variants "
        f"{sdetail.get('compile_variants')} != /debug/compile "
        f"{scomp['dispatched_variants']}",
    )
    spec_static_size = None
    if args.static_xcheck:
        sstatic = set(ssrv.engine.static_lattice())
        spec_static_size = len(sstatic)
        _check(
            any(k.startswith("verify/") for k in sstatic),
            f"spec: static lattice declares no verify family "
            f"({sorted(sstatic)})",
        )
        sdispatched = {e["key"] for e in scomp["lattice"]}
        sstray = sorted(sdispatched - sstatic)
        _check(
            not sstray,
            f"spec: runtime dispatched {len(sstray)} key(s) outside "
            f"the static lattice: {sstray}",
        )
        _check(
            scomp["declared_variants"] == spec_static_size,
            f"spec: warmup declared {scomp['declared_variants']} "
            f"variants but the static lattice holds {spec_static_size}",
        )
    ssrv.engine.stop()

    print(json.dumps({
        "metric": "compile_audit",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "compile_variants": comp["dispatched_variants"],
            "declared_variants": comp["declared_variants"],
            "variant_budget": VARIANT_BUDGET,
            "live_retraces": comp["live_retrace_count"],
            "compile_s_total": comp["compile_s_total"],
            "warmup_coverage": comp["warmup_coverage"],
            "variant_lanes": sorted(lanes),
            "hbm_total_bytes": hbm["total_bytes"],
            "static_lattice": static_size,
            "ragged_requests": rdetail["requests"],
            "ragged_compile_variants": rcomp["dispatched_variants"],
            "ragged_variant_budget": RAGGED_VARIANT_BUDGET,
            "ragged_live_retraces": rcomp["live_retrace_count"],
            "ragged_static_lattice": ragged_static_size,
            "ragged_sparse_requests": sdetail_sparse["requests"],
            "ragged_sparse_compile_variants":
                scomp_sparse["dispatched_variants"],
            "ragged_sparse_live_retraces":
                scomp_sparse["live_retrace_count"],
            "spec_requests": sdetail["requests"],
            "spec_compile_variants": scomp["dispatched_variants"],
            "spec_live_retraces": scomp["live_retrace_count"],
            "spec_static_lattice": spec_static_size,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
