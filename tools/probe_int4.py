"""Probe: int4 weight storage on this TPU. Native s4 jit arguments hit a
device_put recursion bug in this jax build, so int4 must ride PACKED in
int8 (two nibbles per byte) and unpack inside the consuming jit. This
times the llama3-8b MLP layer scan for: bf16, int8 per-channel, packed
int4 with group scales (two unpack variants), to see whether the nibble
unpack fuses into the matmul operand read (HBM traffic halves) or
materializes (traffic worse than int8).
"""

import sys

import jax
import jax.numpy as jnp

B = int(sys.argv[1]) if len(sys.argv) > 1 else 96
D, F, L = 4096, 14336, 8
CHUNK = 16
GROUP = 128


def quant8(w):
    # graftlint: allow(num-barrier) probe: measures fusion alternatives
    # on purpose; cross-compilation bit-stability is not a contract here.
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    return jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8), s


@jax.jit
def quant4_packed(w):
    """[.., K, N] -> (uint8 packed [.., K//2, N], scale [.., K//GROUP, 1, N]).
    Byte k holds w[2k] in the low nibble, w[2k+1] in the high nibble,
    both offset-7 biased (value range [-7, 7] -> [0, 14])."""
    *lead, K, N = w.shape
    wg = w.reshape(*lead, K // GROUP, GROUP, N)
    # graftlint: allow(num-barrier) probe: one compilation, host-checked
    # against its own reference; no cross-leg bit contract.
    s = jnp.maximum(jnp.max(jnp.abs(wg), axis=-2, keepdims=True) / 7.0, 1e-12)
    q = jnp.clip(jnp.round(wg / s), -7, 7).astype(jnp.int8)
    q = q.reshape(*lead, K, N) + 7  # [0, 14]
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    packed = (lo | (hi << 4)).astype(jnp.uint8)
    return packed, s.astype(jnp.float32)


def unpack4_interleave(packed, s, dtype):
    """packed [.., K//2, N] -> bf16 [.., K, N] via stack+reshape."""
    *lead, Kh, N = packed.shape
    lo = (packed & 0xF).astype(jnp.int8) - 7
    hi = (packed >> 4).astype(jnp.int8) - 7
    w = jnp.stack([lo, hi], axis=-2)  # [.., K//2, 2, N]
    w = w.reshape(*lead, Kh * 2, N).astype(dtype)
    G = s.shape[-3]
    wf = w.reshape(*lead, G, (Kh * 2) // G, N) * s.astype(dtype)
    return wf.reshape(*lead, Kh * 2, N)


def run(name, layer_fn, weights):
    @jax.jit
    def f(x, weights):
        def step(x, _):
            def body(h, ws):
                return layer_fn(h, ws), ()

            h, _ = jax.lax.scan(body, x, weights)
            return h * 1e-3 + x[0, 0] * 0, ()

        x, _ = jax.lax.scan(step, x, None, length=CHUNK)
        return x

    from tools.timing import slope_time

    x = jnp.ones((B, 1, D), jnp.bfloat16)
    dt, _ = slope_time(lambda s: f(s, weights), x, k1=2, k2=8)
    print(f"{name:16s} {dt/CHUNK*1000:7.3f} ms/step", flush=True)
    return dt / CHUNK


def main():
    ks = jax.random.split(jax.random.key(0), 3)
    wg = jax.random.normal(ks[0], (L, D, F), jnp.float32) * 0.02
    wu = jax.random.normal(ks[1], (L, D, F), jnp.float32) * 0.02
    wd = jax.random.normal(ks[2], (L, F, D), jnp.float32) * 0.02

    bf = tuple(w.astype(jnp.bfloat16) for w in (wg, wu, wd))
    q8 = sum((quant8(w) for w in (wg, wu, wd)), ())
    q4 = sum((tuple(quant4_packed(w)) for w in (wg, wu, wd)), ())

    def layer_bf16(h, ws):
        g, u, d = ws
        return h + jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.silu(jnp.einsum("bsd,df->bsf", h, g))
            * jnp.einsum("bsd,df->bsf", h, u), d)

    def layer_q8(h, ws):
        g, sg, u, su, d, sd = ws
        dq = lambda q, s: q.astype(h.dtype) * s.astype(h.dtype)
        return layer_bf16(h, (dq(g, sg), dq(u, su), dq(d, sd)))

    def layer_q4(h, ws):
        g, sg, u, su, d, sd = ws
        return layer_bf16(
            h, (unpack4_interleave(g, sg, h.dtype),
                unpack4_interleave(u, su, h.dtype),
                unpack4_interleave(d, sd, h.dtype)))

    def layer_q4_split(h, ws):
        """Two-matmul variant: even/odd K rows as separate fused-dequant
        int8-pattern matmuls; x sliced even/odd (tiny)."""
        g, sg, u, su, d, sd = ws

        def mm(x, packed, s):  # x [B,1,K] @ w [K,N]
            *lead, Kh, N = packed.shape
            G = s.shape[-3]
            half = s  # group scales apply to both nibbles (groups >= 2)

            def deq(nib):
                w = nib.astype(h.dtype).reshape(*lead, G, Kh // G, N)
                return (w * half.astype(h.dtype)).reshape(*lead, Kh, N)

            lo = deq((packed & 0xF).astype(jnp.int8) - 7)
            hi = deq((packed >> 4).astype(jnp.int8) - 7)
            return (jnp.einsum("bsk,kn->bsn", x[..., 0::2], lo)
                    + jnp.einsum("bsk,kn->bsn", x[..., 1::2], hi))

        gate = jax.nn.silu(mm(h, g, sg)) * mm(h, u, su)
        return h + mm(gate, d, sd)

    gb_bf = 3 * D * F * L * 2 / 1e9
    t_bf = run("bf16", layer_bf16, bf)
    t_8 = run("int8", layer_q8, q8)
    t_4 = run("int4-interleave", layer_q4, q4)
    t_4s = run("int4-split", layer_q4_split, q4)
    print(f"layer HBM bf16={gb_bf:.2f}GB  eff BW: "
          f"bf16={gb_bf/t_bf:.0f}  int8={gb_bf/2/t_8:.0f}  "
          f"int4-il={gb_bf/4/t_4:.0f}  int4-sp={gb_bf/4/t_4s:.0f} GB/s")


if __name__ == "__main__":
    main()
