"""Quick llama3-8b engine sweep: (slots, max_admit, decode_chunk) →
req/s on a short saturation wave. Run alone on the real chip.

    python -m tools.tune_8b "96:8:64" "160:8:64" "160:16:64" ...

Each config runs N_REQ = 2×slots requests (prefill 128 + decode 128)
through a fresh engine and prints one line. ~4-6 min per config (8B
compile + init dominate the first; params are built once)."""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

from seldon_tpu.models import get_config
from seldon_tpu.models.quantize import init_params_int8
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT, NEW = 128, 128


def run(params, cfg, slots, max_admit, chunk):
    ecfg = EngineConfig(
        max_slots=slots,
        max_seq_len=PROMPT + NEW + 1,
        prompt_buckets=(PROMPT,),
        max_admit=max_admit,
        decode_chunk=chunk,
    )
    eng = InferenceEngine(params, cfg, ecfg)
    eng.warmup()
    eng.start()
    rng = np.random.default_rng(0)
    n_req = 2 * slots
    prompts = rng.integers(3, cfg.vocab_size, size=(n_req, PROMPT))

    def sp(i):
        return SamplingParams(temperature=0.7, top_k=0, top_p=1.0,
                              max_new_tokens=NEW, seed=i)

    # settle
    for q in [eng.submit(prompts[i].tolist(), sp(i)) for i in range(8)]:
        while q.get() is not None:
            pass
    t0 = time.perf_counter()
    qs = [eng.submit(prompts[i].tolist(), sp(i)) for i in range(n_req)]
    toks = 0
    for q in qs:
        while (item := q.get()) is not None:
            if "error" in item:
                raise RuntimeError(item["error"])
            toks += len(item.get("tokens", []))
    dt = time.perf_counter() - t0
    eng.stop()
    print(
        f"slots={slots:4d} admit={max_admit:3d} chunk={chunk:3d}  "
        f"{n_req/dt:7.2f} req/s  {toks/dt:8.0f} tok/s  "
        f"vs_north_star={n_req/dt/125.0:.3f}",
        flush=True,
    )


def main():
    combos = []
    for arg in sys.argv[1:] or ["96:8:64", "160:8:64", "160:16:64"]:
        s, a, c = (int(x) for x in arg.split(":"))
        combos.append((s, a, c))
    import os

    cfg = get_config("llama3-8b", kv_cache_dtype="int8", weight_dtype="int8",
                     act_dtype=os.environ.get("TUNE_ACT", "int8"))
    params = init_params_int8(cfg, jax.random.key(0))
    for s, a, c in combos:
        run(params, cfg, s, a, c)


if __name__ == "__main__":
    main()
