#!/usr/bin/env python
"""CI roof audit: the MFU/MBU roofline observatory end to end.

Boots the tiny warmed JAXServer behind the real REST app with
``ROOF_LEDGER=1`` + ``FLIGHT_RECORDER=1``, polls ``/debug/roof`` on the
idle engine, drives it with a short closed-loop loadtester run, then
asserts the observatory contract in one pass:

 * ``/debug`` indexes every observability surface with its arming
   knob, and the roof reads armed;
 * idle engine -> ZERO attribution: no boundaries decomposed, no
   variants priced, empty totals;
 * after load, ``/debug/roof`` returns the documented schema, every
   variant's mfu/mbu sits in [0, 1] with the utilization of a
   device-timed priced variant strictly positive, and the bound label
   is one of compute/bandwidth/host;
 * the step decomposition re-sums: host-pre + device + host-post +
   overlap match the measured boundary wall within 1%, and the
   ledger's own ``audit()`` (run under ``_book`` at every dispatched
   boundary) reports zero breaches;
 * predicted vs measured stays sane: the roofline's total predicted_ms
   against the measured device_ms lands in a generous band (CPU smoke
   runs calibrate against the one-shot microbench, so only gross
   divergence — a broken formula or broken peaks — trips this);
 * the loadtester ledger carries the same roof numbers as the route
   (tolerant parity — trailing drain boundaries may tick after the
   loadtester's poll), and the jaxserver Prometheus surface exports
   the per-variant ``jaxserver_mfu`` / ``jaxserver_mbu`` gauges plus
   ``jaxserver_host_frac``;
 * boundary "roof" records reach the flight recorder and
   ``tools/trace_view.py`` renders the host/device lanes from them.

Run via ``make roof-audit`` (wired into ``make ci``); exits non-zero
with a one-line diagnosis on the first failed check.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

# Frozen /debug/roof key sets — tests/test_debug_schema.py carries the
# same goldens; a mismatch here means the snapshot schema changed
# without updating its consumers.
ROOF_TOP_KEYS = frozenset({
    "enabled", "platform", "peaks", "tp", "boundaries", "waves", "step",
    "host_frac", "device_frac", "conservation", "variants", "totals",
})
ROOF_VARIANT_KEYS = frozenset({
    "key", "family", "dispatches", "flops", "bytes", "device_ms",
    "predicted_ms", "capacity_flops", "capacity_bytes",
    "capacity_predicted_ms", "mfu", "mbu", "bound",
})
DEBUG_ROUTES = frozenset({
    "/debug/timeline", "/debug/compile", "/debug/hbm", "/debug/sched",
    "/debug/pilot", "/debug/roof",
})


def _check(cond: bool, msg: str) -> None:
    if not cond:
        print(f"roof-audit FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["ROOF_LEDGER"] = "1"
    os.environ["FLIGHT_RECORDER"] = "1"

    import asyncio
    import threading
    import urllib.request

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer
    from tools import trace_view

    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64, warmup=1)
    srv.load()

    holder, started = {}, threading.Event()

    async def amain() -> None:
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    _check(started.wait(60), "REST app failed to start within 60s")
    url = f"http://127.0.0.1:{holder['port']}"

    def get(path: str) -> dict:
        with urllib.request.urlopen(url + path, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        # --- /debug index: every surface listed, the roof armed ---------
        index = get("/debug")
        routes = {s["route"]: s for s in index["surfaces"]}
        _check(set(routes) == DEBUG_ROUTES,
               f"/debug index drifted: got {sorted(routes)}")
        for s in index["surfaces"]:
            _check(set(s) == {"route", "knob", "supported", "armed"},
                   f"/debug entry keys drifted: {sorted(s)}")
            _check(s["supported"], f"{s['route']} unsupported on JAXServer")
        _check(routes["/debug/roof"]["armed"],
               "ROOF_LEDGER=1 but /debug lists the roof unarmed")
        _check(routes["/debug/roof"]["knob"] == "ROOF_LEDGER",
               "roof surface lists the wrong arming knob")
        _check(routes["/debug/timeline"]["armed"],
               "FLIGHT_RECORDER=1 but /debug lists the timeline unarmed")

        # --- idle engine: zero attribution ------------------------------
        idle = get("/debug/roof")
        _check(set(idle) == ROOF_TOP_KEYS,
               f"/debug/roof keys drifted: got {sorted(idle)}")
        _check(idle["boundaries"] == 0,
               f"idle engine decomposed {idle['boundaries']} boundaries")
        _check(idle["variants"] == [], "idle engine priced variants")
        _check(idle["totals"]["dispatches"] == 0,
               "idle engine counted dispatches")
        _check(idle["peaks"]["tflops"] > 0.0 and idle["peaks"]["gbs"] > 0.0,
               f"degenerate peaks {idle['peaks']}")
        _check(idle["peaks"]["source"] in ("env", "table", "microbench"),
               f"unknown peak source {idle['peaks']['source']}")

        # --- load window ------------------------------------------------
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            lt_main([
                url, "--transport", "generate", "--clients", "4",
                "--seconds", "2", "--prompt", "hi",
                "--max-new-tokens", "4",
            ])
        ledger = json.loads(buf.getvalue().strip().splitlines()[-1])
        detail = ledger["detail"]
        _check(detail["errors"] == 0,
               f"loadtester saw {detail['errors']} transport errors")
        _check(detail["requests"] >= 1, "loadtester completed no requests")

        roof = get("/debug/roof")
        snap = get("/debug/timeline")
    finally:
        holder["stop"] = True
        t.join(timeout=10)

    # --- schema + per-variant roofline ---------------------------------
    _check(set(roof) == ROOF_TOP_KEYS,
           f"/debug/roof keys drifted: got {sorted(roof)}")
    _check(roof["boundaries"] > 0, "no boundaries decomposed under load")
    _check(roof["waves"] > 0, "no waves joined under load")
    _check(roof["variants"], "no variants priced under load")
    for v in roof["variants"]:
        _check(set(v) == ROOF_VARIANT_KEYS,
               f"variant keys drifted: {sorted(v)}")
        _check(0.0 <= v["mfu"] <= 1.0, f"{v['key']} mfu={v['mfu']}")
        _check(0.0 <= v["mbu"] <= 1.0, f"{v['key']} mbu={v['mbu']}")
        _check(v["bound"] in ("compute", "bandwidth", "host"),
               f"{v['key']} bound={v['bound']!r}")
        _check(v["dispatches"] >= 1, f"{v['key']} has zero dispatches")
        if v["device_ms"] > 0.0 and v["bytes"] > 0.0:
            _check(max(v["mfu"], v["mbu"]) > 0.0,
                   f"{v['key']} priced + timed but utilization is zero")
    tot = roof["totals"]
    _check(tot["dispatches"] == sum(v["dispatches"]
                                    for v in roof["variants"]),
           "totals dispatches != sum of variants")
    _check(abs(tot["device_ms"] - sum(v["device_ms"]
                                      for v in roof["variants"])) <= 0.5,
           "wave device time not conserved across variants")
    _check(0.0 <= tot["mfu"] <= 1.0 and 0.0 <= tot["mbu"] <= 1.0,
           f"totals utilization out of range: {tot}")
    _check(max(tot["mfu"], tot["mbu"]) > 0.0,
           "total utilization is zero after a real load window")

    # --- step decomposition conservation --------------------------------
    cons = roof["conservation"]
    _check(cons["checked"] > 0, "conservation audit never ran")
    _check(
        cons["breaches"] == 0,
        f"{cons['breaches']} conservation breaches: {cons['last_breach']}",
    )
    step = roof["step"]
    parts = (step["host_pre_ms"] + step["device_ms"]
             + step["host_post_ms"] + step["overlap_ms"])
    _check(
        abs(parts - step["wall_ms"]) <= max(1.0, 0.01 * step["wall_ms"]),
        f"step components {parts} != boundary wall {step['wall_ms']}",
    )
    _check(step["wall_ms"] > 0.0, "zero boundary wall after load")
    _check(0.0 <= roof["host_frac"] <= 1.0,
           f"host_frac out of range: {roof['host_frac']}")
    _check(0.0 <= roof["device_frac"] <= 1.0,
           f"device_frac out of range: {roof['device_frac']}")

    # --- predicted vs measured: generous CPU band ------------------------
    _check(tot["predicted_ms"] > 0.0, "roofline predicted zero total time")
    ratio = tot["predicted_ms"] / max(tot["device_ms"], 1e-9)
    _check(1e-4 < ratio < 1e4,
           f"predicted/measured ratio {ratio:.2e} outside sanity band "
           f"(predicted {tot['predicted_ms']} ms, "
           f"measured {tot['device_ms']} ms)")

    # --- loadtester ledger parity (tolerant: drain boundaries tick) ------
    for key in ("mfu", "mbu", "host_frac"):
        _check(key in detail, f"loadtester ledger missing roof {key}")
        _check(0.0 <= detail[key] <= 1.0,
               f"ledger {key}={detail[key]} out of range")
    _check(
        abs(detail["mfu"] - tot["mfu"]) <= max(0.01, 0.5 * tot["mfu"]),
        f"ledger mfu {detail['mfu']} != route {tot['mfu']}",
    )
    _check(detail.get("roof_conservation_breaches") == 0,
           f"ledger breaches = {detail.get('roof_conservation_breaches')}")

    # --- Prometheus surface ---------------------------------------------
    metrics = srv.metrics()
    gauges = {m["key"] for m in metrics}
    for key in ("jaxserver_mfu", "jaxserver_mbu", "jaxserver_host_frac",
                "jaxserver_roof_conservation_breaches"):
        _check(key in gauges, f"metrics() missing gauge {key}")
    mfu_variants = {m["tags"]["variant"] for m in metrics
                    if m["key"] == "jaxserver_mfu"}
    _check(mfu_variants == {v["key"] for v in roof["variants"]},
           f"jaxserver_mfu variants {sorted(mfu_variants)} != route")

    # --- flight recorder + trace_view host/device lanes ------------------
    roof_records = [r for r in snap.get("records", [])
                    if r["kind"] == "roof"]
    _check(roof_records, "no roof records in timeline")
    out = json.loads(json.dumps(trace_view.convert(snap)))
    lanes = {e["name"] for e in out["traceEvents"]
             if e["ph"] == "X" and e["pid"] == trace_view._ROOF_PID}
    _check("host-pre" in lanes and "fetch" in lanes,
           f"trace_view rendered no roof lanes (got {sorted(lanes)})")
    counters = {e["name"] for e in out["traceEvents"] if e["ph"] == "C"}
    _check("roof_host_ms" in counters,
           f"trace_view rendered no roof_host_ms counter (got {counters})")

    srv.engine.stop()

    print(json.dumps({
        "metric": "roof_audit",
        "value": 1,
        "detail": {
            "requests": detail["requests"],
            "platform": roof["platform"],
            "peak_source": roof["peaks"]["source"],
            "boundaries": roof["boundaries"],
            "variants": len(roof["variants"]),
            "mfu": tot["mfu"],
            "mbu": tot["mbu"],
            "host_frac": roof["host_frac"],
            "predicted_vs_measured": round(ratio, 4),
            "conservation_checked": cons["checked"],
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
