"""Fusion-barrier certification for quantization numerics (graftnum).

Two silent bug classes cost PRs 15/16 days each, and both are invisible
to tests until a near-tied greedy argmax flips:

 * an int8 **quantization scale** (``max(abs(x))`` feeding a round/clip
   to int8) fused into its producer reads *unrounded f32 intermediates*
   — the scale, and hence the int8 bits, become a function of XLA's
   fusion choices, which differ between the single-chip and the
   SPMD-partitioned compilations of the same model (the PR 15 tp=2 vs
   tp=1 divergence);
 * a bf16 **dequant product** (``w.astype(dt) * scale.astype(dt)``)
   inside a fusion runs in f32 and only rounds at materialization
   boundaries — consumed unrounded it drifts ~2e-3 from the value the
   masked twin materializes (the PR 16 sparse-vs-masked greedy flips).

Both are fixed by ``jax.lax.optimization_barrier``: it pins the
intermediate to ONE materialized value shared by every consumer and
every compilation.  This pass makes the two hand-placed barriers
(``models/transformer._quantize_act``/``_quantize_kv`` and
``ops/ragged_paged_attention._sparse_block``) machine-certified
instead of folklore, and every future kernel leg inherits the check.

Rule ``num-barrier``:

 * a ``max(abs(X))`` reduction in a function that also casts to int8
   must read a barrier-pinned ``X`` (assigned from
   ``jax.lax.optimization_barrier`` in the same function, or wrapped in
   the barrier call directly);
 * a dequant product — a ``*`` whose operands BOTH carry an
   ``.astype(...)`` (directly or through a one-level local) and at
   least one of which references a ``*scale*``-named value — must pass
   through ``optimization_barrier`` before flowing into a
   materialization boundary: a ``return``, a ``concatenate``/``stack``,
   or a ``lax.scan`` argument (the scan carry).

Waive with ``# graftlint: allow(num-barrier) why`` on the flagged line
(or the ``def`` line for the whole function) — e.g. load-time weight
quantization that runs once on the host outside any serving jit, or a
single-consumer dequant whose unique consumer IS the materialization
boundary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint import core

RULE = "num-barrier"

_BOUNDARY_CALLS = {"concatenate", "stack", "hstack", "vstack", "scan"}
_INT8_NAMES = {"int8", "int4"}


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _is_barrier_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _call_tail(node.func) == "optimization_barrier")


def _contains_barrier(node: ast.AST) -> bool:
    return any(_is_barrier_call(n) for n in ast.walk(node))


def _has_int8_cast(fn: ast.AST) -> bool:
    """Function rounds something to int8: ``.astype(jnp.int8)`` /
    ``.astype("int8")`` (int4 packing counts — same hazard)."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and _call_tail(node.func) == "astype" and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) and arg.attr in _INT8_NAMES:
            return True
        if isinstance(arg, ast.Name) and arg.id in _INT8_NAMES:
            return True
        if isinstance(arg, ast.Constant) and arg.value in _INT8_NAMES:
            return True
    return False


def _assign_names(target: ast.expr) -> List[str]:
    out = []
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            out.append(node.id)
    return out


def _barriered_names(fn: ast.AST) -> Set[str]:
    """Locals assigned (anywhere in fn) from an optimization_barrier
    call — the canonical ``x = jax.lax.optimization_barrier(x)`` pin."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and _is_barrier_call(node.value)):
            for t in node.targets:
                out.update(_assign_names(t))
    return out


def _first_name(node: ast.AST) -> Optional[str]:
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            return n.id
    return None


def _scaleish(node: ast.AST, scale_locals: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and (
                "scale" in n.id.lower() or n.id in scale_locals):
            return True
        if isinstance(n, ast.Attribute) and "scale" in n.attr.lower():
            return True
        if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                and "scale" in n.value.lower()):
            return True
    return False


def _has_astype(node: ast.AST, astype_locals: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _call_tail(n.func) == "astype":
            return True
        if isinstance(n, ast.Name) and n.id in astype_locals:
            return True
    return False


def _local_facts(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(astype_locals, scale_locals): one-level dataflow — a local
    assigned from an expression that carries an ``.astype`` call /
    references a ``*scale*`` value inherits that fact (e.g.
    ``pk = pl["k"].astype(dt)``, ``ks = pool["k_scale"][bids]``)."""
    astype_locals: Set[str] = set()
    scale_locals: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        names = [n for t in node.targets for n in _assign_names(t)]
        if any(isinstance(n, ast.Call)
               and _call_tail(n.func) == "astype"
               for n in ast.walk(node.value)):
            astype_locals.update(names)
        if _scaleish(node.value, set()):
            scale_locals.update(names)
    return astype_locals, scale_locals


def _dequant_mults(fn: ast.AST, astype_locals: Set[str],
                   scale_locals: Set[str]) -> List[ast.BinOp]:
    """Unbarriered dequant products in fn: ``L * R`` with astype on
    both sides and a scale reference on either.  Products wrapped in
    optimization_barrier (anywhere up the same expression) are the
    certified fix, not a finding."""
    barrier_spans: List[ast.AST] = [
        n for n in ast.walk(fn) if _is_barrier_call(n)
    ]
    inside_barrier: Set[int] = set()
    for b in barrier_spans:
        for n in ast.walk(b):
            inside_barrier.add(id(n))
    out = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Mult)):
            continue
        if id(node) in inside_barrier:
            continue
        if not (_has_astype(node.left, astype_locals)
                and _has_astype(node.right, astype_locals)):
            continue
        if not (_scaleish(node.left, scale_locals)
                or _scaleish(node.right, scale_locals)):
            continue
        out.append(node)
    return out


def _index_parents(fn: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _enclosing_stmt(node: ast.AST, parents: Dict[int, ast.AST],
                    fn: ast.AST) -> ast.AST:
    cur = node
    while id(cur) in parents and parents[id(cur)] is not fn:
        nxt = parents[id(cur)]
        if isinstance(nxt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        cur = nxt
    return cur


def _boundary_hit(fn: ast.AST, mults: List[ast.BinOp],
                  parents: Dict[int, ast.AST]) -> Dict[int, str]:
    """Which dequant products reach a materialization boundary.
    Returns {mult line: boundary description}.  A product reaches a
    boundary directly (its expression sits inside a return / concat /
    scan) or through taint: locals assigned from it (transitively)
    that appear inside one."""
    hits: Dict[int, str] = {}
    mult_ids = {id(m): m for m in mults}

    # Direct containment: boundary node whose subtree holds the mult.
    def note_direct(container: ast.AST, what: str) -> None:
        for n in ast.walk(container):
            if id(n) in mult_ids:
                hits.setdefault(mult_ids[id(n)].lineno, what)

    # Taint: name -> origin mult lines.
    taint: Dict[str, Set[int]] = {}
    for _ in range(2):  # two passes ~ transitive enough for real code
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            origins: Set[int] = set()
            for n in ast.walk(node.value):
                if id(n) in mult_ids and not _contains_ancestral_barrier(
                        n, node.value):
                    origins.add(mult_ids[id(n)].lineno)
                if isinstance(n, ast.Name) and n.id in taint:
                    origins |= taint[n.id]
            if _is_barrier_call(node.value):
                origins = set()  # barrier at assignment = the fix
            for t in node.targets:
                for name in _assign_names(t):
                    if origins:
                        taint[name] = taint.get(name, set()) | origins
                    else:
                        taint.pop(name, None)

    def note_tainted(container: ast.AST, what: str) -> None:
        for n in ast.walk(container):
            if isinstance(n, ast.Name) and n.id in taint:
                for ln in taint[n.id]:
                    hits.setdefault(ln, what)

    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            note_direct(node.value, "the jit return")
            note_tainted(node.value, "the jit return")
        elif (isinstance(node, ast.Call)
              and _call_tail(node.func) in _BOUNDARY_CALLS):
            what = (f"a {_call_tail(node.func)}() materialization"
                    if _call_tail(node.func) != "scan"
                    else "a lax.scan carry")
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                note_direct(arg, what)
                note_tainted(arg, what)
    return hits


def _contains_ancestral_barrier(node: ast.AST, root: ast.AST) -> bool:
    """True when `node` sits under an optimization_barrier call inside
    `root` (the barrier wraps the product in the same expression)."""
    for b in ast.walk(root):
        if _is_barrier_call(b):
            for n in ast.walk(b):
                if n is node:
                    return True
    return False


def run(files: List[core.SourceFile], ctx: core.Context) -> List[core.Finding]:
    findings: List[core.Finding] = []
    scale_sites = 0
    dequant_sites = 0
    certified = 0

    for sf in files:
        core.attach_parents(sf.tree)
        fns = [n for n in ast.walk(sf.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            # Innermost ownership: nodes belonging to a nested def are
            # analyzed with THAT def's barriers/locals, not the outer's.
            nested = [n for n in ast.walk(fn)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not fn]
            owned_elsewhere: Set[int] = set()
            for sub in nested:
                for n in ast.walk(sub):
                    if n is not sub:
                        owned_elsewhere.add(id(n))

            def owned(node: ast.AST) -> bool:
                return id(node) not in owned_elsewhere

            has_int8 = _has_int8_cast(fn)
            barriered = _barriered_names(fn)
            parents = _index_parents(fn)

            # --- quantize-scale leg: max(abs(X)) -> int8 -------------
            if has_int8:
                for node in ast.walk(fn):
                    if not owned(node):
                        continue
                    if not (isinstance(node, ast.Call)
                            and _call_tail(node.func) == "max"
                            and node.args):
                        continue
                    absarg = None
                    for n in ast.walk(node.args[0]):
                        if (isinstance(n, ast.Call)
                                and _call_tail(n.func) == "abs"
                                and n.args):
                            absarg = n.args[0]
                            break
                    if absarg is None:
                        continue
                    scale_sites += 1
                    root = _first_name(absarg)
                    if (root in barriered
                            or _contains_barrier(node.args[0])):
                        certified += 1
                        continue
                    if core.allowed_above(sf, RULE, node.lineno, fn.lineno):
                        continue
                    findings.append(core.make_finding(
                        sf, RULE, node.lineno,
                        f"int8 quantization scale reduces max(abs("
                        f"{root or '?'})) without an optimization_barrier "
                        f"pin — fused into the producer it reads "
                        f"unrounded f32 intermediates, so the scale (and "
                        f"the int8 bits) depend on XLA fusion choices "
                        f"and diverge between tp=1 and SPMD compilations",
                        hint="pin the input first: "
                             "x = jax.lax.optimization_barrier(x) "
                             "(models/transformer._quantize_act)",
                        qualname=core.qualname_of(node),
                    ))

            # --- dequant-product leg ---------------------------------
            astype_locals, scale_locals = _local_facts(fn)
            # Barriered products are filtered out of _dequant_mults —
            # count them here as certified sites for the headline.
            for n in ast.walk(fn):
                if _is_barrier_call(n) and owned(n):
                    for m in ast.walk(n):
                        if (isinstance(m, ast.BinOp)
                                and isinstance(m.op, ast.Mult)
                                and _has_astype(m, astype_locals)):
                            certified += 1
                            dequant_sites += 1
                            break
            mults = [m for m in _dequant_mults(fn, astype_locals,
                                               scale_locals)
                     if owned(m)]
            if not mults:
                continue
            dequant_sites += len(mults)
            hits = _boundary_hit(fn, mults, parents)
            seen_lines: Set[int] = set()
            for m in mults:
                what = hits.get(m.lineno)
                if what is None or m.lineno in seen_lines:
                    continue
                seen_lines.add(m.lineno)
                if core.allowed_above(sf, RULE, m.lineno, fn.lineno):
                    continue
                findings.append(core.make_finding(
                    sf, RULE, m.lineno,
                    f"int8 dequant product flows into {what} without an "
                    f"optimization_barrier — inside a fusion the bf16 "
                    f"multiply runs in f32 and rounds only at "
                    f"materialization, so its value drifts (~2e-3) "
                    f"between kernel legs that materialize at different "
                    f"points",
                    hint="wrap the product: jax.lax.optimization_barrier"
                         "(w.astype(dt) * scale.astype(dt)) "
                         "(ops/ragged_paged_attention._sparse_block)",
                    qualname=core.qualname_of(m),
                ))

    stats = getattr(ctx, "stats", None)
    if stats is not None:
        stats["numbarrier"] = {
            "scale_sites": scale_sites,
            "dequant_sites": dequant_sites,
            "certified": certified,
        }
    return findings
