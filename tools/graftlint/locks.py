"""lock-guard pass: declared fields are only touched under their lock.

Fields are declared at their initialising assignment with a trailing
comment::

    self._slots = [None] * B  # graftlint: guarded-by(_book)

Every ``self._slots`` read/write in the declaring class must then sit
lexically inside ``with self._book:`` — or inside a method whose def line
carries ``# graftlint: holds(_book)``, documenting that the caller owns
the lock (the scheduler's ``_dispatch_once`` helpers, the ``*_locked``
convention).

A declaration may add ``via(<role>)``::

    self.pool_gauges = None  # graftlint: guarded-by(lock) via(stats)

which extends checking across the tree: any ``<base>.stats.pool_gauges``
access in any scanned file must sit inside ``with <base>.stats.lock:``
(same base expression).  This is how engine-side mutations of
``EngineStats`` counters are kept honest.

``__init__`` bodies are exempt (the object is not yet published to other
threads).  Waive a deliberate lock-free access with
``# graftlint: allow(lock-guard) why``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   enclosing_class, enclosing_function, make_finding,
                   qualname_of)

RULE = "lock-guard"


@dataclasses.dataclass
class _Decl:
    cls: str      # declaring class name
    field: str
    lock: str
    role: Optional[str]  # via(<role>) — cross-class attribute path
    file: str
    line: int


def _collect_decls(files: List[SourceFile]) -> List[_Decl]:
    decls: List[_Decl] = []
    for sf in files:
        if not sf.guarded:
            continue
        # map declaration lines to their enclosing class
        classes = [n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)]
        # a declaration must sit on a real `self.<field> = ...` statement —
        # this keeps guarded-by examples in docstrings from registering
        assign_lines: Set[int] = set()
        for n in ast.walk(sf.tree):
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                assign_lines.add(n.lineno)
        for field, lock, role, line in sf.guarded:
            if line not in assign_lines:
                continue
            owner = ""
            for c in classes:
                end = getattr(c, "end_lineno", c.lineno)
                if c.lineno <= line <= end:
                    owner = c.name  # innermost match wins (last in walk order)
            decls.append(_Decl(owner, field, lock, role, sf.rel, line))
    return decls


def _with_locks(node: ast.AST) -> Set[str]:
    """Lock expressions (ast.dump of the context expr) held at `node`,
    walking With ancestors."""
    held: Set[str] = set()
    cur = getattr(node, "_graftlint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                held.add(ast.dump(item.context_expr))
        cur = getattr(cur, "_graftlint_parent", None)
    return held


def _self_lock_dump(lock: str) -> str:
    return ast.dump(ast.parse(f"self.{lock}", mode="eval").body)


def _holds_lock(sf: SourceFile, node: ast.AST, lock: str) -> bool:
    fn = enclosing_function(node)
    while fn is not None:
        if sf.holds.get(fn.lineno) == lock:
            return True
        fn = enclosing_function(fn)
    return False


def _in_init(node: ast.AST) -> bool:
    fn = enclosing_function(node)
    while fn is not None:
        if fn.name == "__init__":
            return True
        fn = enclosing_function(fn)
    return False


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    decls = _collect_decls(files)
    if not decls:
        return []
    by_field: Dict[str, List[_Decl]] = {}
    for d in decls:
        by_field.setdefault(d.field, []).append(d)

    findings: List[Finding] = []
    for sf in files:
        attach_parents(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Attribute) or node.attr not in by_field:
                continue
            for d in by_field[node.attr]:
                fin = _check_access(sf, node, d)
                if fin is not None:
                    findings.append(fin)
                    break
    return findings


def _check_access(sf: SourceFile, node: ast.Attribute,
                  d: _Decl) -> Optional[Finding]:
    base = node.value
    in_decl_class = (isinstance(base, ast.Name) and base.id == "self"
                     and (enclosing_class(node) is not None
                          and enclosing_class(node).name == d.cls))
    via_match = (d.role is not None and isinstance(base, ast.Attribute)
                 and base.attr == d.role)
    outside = (d.role is None
               and not (isinstance(base, ast.Name) and base.id in ("self", "cls")))
    if not in_decl_class and not via_match and not outside:
        return None
    if _in_init(node):
        return None
    if node.lineno == d.line and sf.rel == d.file:
        return None  # the declaration itself

    if outside:
        fn = enclosing_function(node)
        if allowed(sf, RULE, node.lineno, fn.lineno if fn else 0):
            return None
        return make_finding(
            sf, RULE, node.lineno,
            f"guarded field '{d.field}' (lock {d.lock}, declared "
            f"{d.file}:{d.line}) accessed from outside {d.cls} — the lock "
            "cannot be taken correctly from here",
            f"add a locked accessor on {d.cls} and call that instead",
            qualname_of(node))

    if in_decl_class:
        required = _self_lock_dump(d.lock)
        lock_desc = f"self.{d.lock}"
    else:
        # require `with <base>.<role>.<lock>:` over the same base expression
        lock_expr = ast.Attribute(
            value=base, attr=d.lock, ctx=ast.Load())
        required = ast.dump(lock_expr)
        lock_desc = f"<obj>.{d.role}.{d.lock}"

    if required in _with_locks(node):
        return None
    if _holds_lock(sf, node, d.lock):
        return None
    fn = enclosing_function(node)
    fn_line = fn.lineno if fn is not None else 0
    if allowed(sf, RULE, node.lineno, fn_line):
        return None
    return make_finding(
        sf, RULE, node.lineno,
        f"field '{d.field}' (guarded by {d.lock}, declared "
        f"{d.file}:{d.line}) accessed outside `with {lock_desc}:`",
        f"wrap the access in `with {lock_desc}:`, or annotate the method "
        f"`# graftlint: holds({d.lock})` if every caller owns the lock",
        qualname_of(node))
