"""graftlint — project-specific static analysis for the seldon-tpu tree.

Composable AST/dataflow passes enforce the invariants the chaos soak can
only sample dynamically:

  hot-sync       no host synchronisation inside the scheduler dispatch loop
  lock-guard     fields declared ``# graftlint: guarded-by(<lock>)`` are
                 only touched under ``with self.<lock>:``
  retrace        jitted functions must not pick up per-request Python state
                 that forces recompiles
  outcome        request finalization emits exactly one terminal item
  env-knob       every env var read appears in the generated knob table

plus the graftflow dataflow trio (docs/operations.md "Static dataflow:
graftflow"):

  shape-lattice  warmup's closed-form variant lattice must equal the
                 operationally dispatchable key set (static retrace proof
                 / warmup-waste detection)
  config-matrix  per-method (paged, chunked, prefix) reachability; emits
                 docs/config_matrix.md + the dense-slab kill-list
  shard-*        PartitionSpec/collective axis names vs mesh.AXES, host
                 pulls on sharded arrays, sharding-dropping jit boundaries

Run as ``python -m tools.graftlint seldon_tpu tools``.  Accepted findings
live in ``graftlint_baseline.json``; CI fails only on regressions.
"""

from .core import Finding, SourceFile, load_tree, run_passes  # noqa: F401
