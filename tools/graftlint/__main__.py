"""CLI: python -m tools.graftlint [paths...] [options]

Exit codes: 0 clean (vs baseline), 1 findings, 2 internal error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from . import (configmatrix, hotpath, knobs, lockorder, locks, outcome,
               retrace, shapelattice, shardcheck)
from .core import (Context, Finding, PLACEHOLDER_NOTE, load_baseline,
                   load_tree, run_passes, write_baseline)

PASSES = [hotpath.run, locks.run, lockorder.run, retrace.run, outcome.run,
          knobs.run, shapelattice.run, configmatrix.run, shardcheck.run]


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def default_targets(root: Path) -> List[Path]:
    """The trees and top-level entry points CI lints. bench.py,
    bench_orchestrator.py and __graft_entry__.py are single files, not
    packages, so a bare directory list used to let them escape every
    pass."""
    return [root / "seldon_tpu", root / "tools", root / "bench.py",
            root / "bench_orchestrator.py", root / "__graft_entry__.py"]


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="seldon-tpu invariant checker (hot-sync, lock-guard, "
                    "lockorder, retrace, outcome, env-knob, shape-lattice, "
                    "config-matrix, shard-axis/-host-pull/-jit)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files/dirs to lint (default: seldon_tpu tools "
                         "bench.py bench_orchestrator.py "
                         "__graft_entry__.py)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "(requires --note)")
    ap.add_argument("--note", default=None, metavar="REASON",
                    help="justification stamped on new baseline entries; "
                         "required with --write-baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report findings without baseline suppression")
    ap.add_argument("--gen-knobs", action="store_true",
                    help="regenerate docs/knobs.md and exit")
    ap.add_argument("--gen-config-matrix", action="store_true",
                    help="regenerate docs/config_matrix.md and exit")
    args = ap.parse_args(argv)

    if args.write_baseline and not (args.note and args.note.strip()):
        ap.error("--write-baseline requires --note \"<reason>\" — every "
                 "suppression must say why it is safe to keep")

    root = _repo_root()
    targets = [Path(p).resolve() for p in args.paths] or \
        default_targets(root)
    for t in targets:
        if not t.exists():
            print(f"graftlint: no such path: {t}", file=sys.stderr)
            return 2

    ctx = Context(root)
    files = load_tree(targets, root)

    if args.gen_knobs:
        reads = knobs.scan_reads(files)
        ctx.knobs_doc.parent.mkdir(parents=True, exist_ok=True)
        ctx.knobs_doc.write_text(knobs.generate_knobs_md(reads))
        print(f"graftlint: wrote {ctx.knobs_doc.relative_to(root)}")
        return 0

    if args.gen_config_matrix:
        model = configmatrix.analyze(files)
        if model is None:
            print("graftlint: no engine-like class (warmup + submit) in "
                  "the scan set", file=sys.stderr)
            return 2
        ctx.matrix_doc.parent.mkdir(parents=True, exist_ok=True)
        ctx.matrix_doc.write_text(configmatrix.generate_matrix_md(model))
        print(f"graftlint: wrote {ctx.matrix_doc.relative_to(root)}")
        return 0

    findings = run_passes(files, ctx, PASSES)

    # graftflow headline for CI logs: the dense-slab kill-list size is
    # the ROADMAP item-2 progress needle (acceptance wants it visible).
    model = configmatrix.analyze(files)
    if model is not None:
        kill = model.kill_list()
        print(f"graftflow: dense-slab kill-list: {len(kill)} method(s) "
              f"reachable only with paged_kv=False "
              f"(docs/config_matrix.md)")

    baseline = {} if args.no_baseline else load_baseline(ctx.baseline_path)
    if args.write_baseline:
        write_baseline(ctx.baseline_path, findings, baseline,
                       note=args.note.strip())
        print(f"graftlint: baselined {len(findings)} finding(s) -> "
              f"{ctx.baseline_path.name}")
        return 0

    for fp, e in sorted(baseline.items()):
        if e.get("note", PLACEHOLDER_NOTE) == PLACEHOLDER_NOTE:
            print(f"graftlint: warning: baseline entry {fp} "
                  f"({e.get('rule')} in {e.get('file')}) has a "
                  f"placeholder note — rerun --write-baseline with "
                  f"--note \"<reason>\"", file=sys.stderr)

    fresh: List[Finding] = []
    used = set()
    for f in findings:
        if f.fingerprint in baseline:
            used.add(f.fingerprint)
        else:
            fresh.append(f)

    stale = set(baseline) - used
    for fp in sorted(stale):
        e = baseline[fp]
        print(f"graftlint: warning: stale baseline entry {fp} "
              f"({e.get('rule')} in {e.get('file')}) — safe to drop",
              file=sys.stderr)

    for f in fresh:
        print(f.render())
    if fresh:
        print(f"\ngraftlint: {len(fresh)} finding(s) "
              f"({len(used)} suppressed by baseline)")
        return 1
    print(f"graftlint: OK — {len(findings)} finding(s), all accepted in "
          f"baseline" if findings else "graftlint: OK — no findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
