"""CLI: python -m tools.graftlint [paths...] [options]

Exit codes: 0 clean (vs baseline), 1 findings, 2 internal error.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path
from typing import List

from . import (configmatrix, donate, einsumcheck, hotpath, knobs,
               lockorder, locks, numbarrier, outcome, retrace,
               shapelattice, shardcheck)
from .core import (Context, Finding, PLACEHOLDER_NOTE, load_baseline,
                   load_tree, run_passes, write_baseline)

PASSES = [hotpath.run, locks.run, lockorder.run, retrace.run, outcome.run,
          knobs.run, shapelattice.run, configmatrix.run, shardcheck.run,
          numbarrier.run, donate.run, einsumcheck.run]

# Self-runtime budget: pass growth must not make `make lint` unusable.
DEFAULT_BUDGET_S = 60.0


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def default_targets(root: Path) -> List[Path]:
    """The trees and top-level entry points CI lints. bench.py,
    bench_orchestrator.py and __graft_entry__.py are single files, not
    packages, so a bare directory list used to let them escape every
    pass."""
    return [root / "seldon_tpu", root / "tools", root / "bench.py",
            root / "bench_orchestrator.py", root / "__graft_entry__.py"]


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="seldon-tpu invariant checker (hot-sync, lock-guard, "
                    "lockorder, retrace, outcome, env-knob, shape-lattice, "
                    "config-matrix, shard-axis/-host-pull/-jit, "
                    "num-barrier, use-after-donate, einsum-broadcast/"
                    "mask-dtype)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files/dirs to lint (default: seldon_tpu tools "
                         "bench.py bench_orchestrator.py "
                         "__graft_entry__.py)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "(requires --note)")
    ap.add_argument("--note", default=None, metavar="REASON",
                    help="justification stamped on new baseline entries; "
                         "required with --write-baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report findings without baseline suppression")
    ap.add_argument("--gen-knobs", action="store_true",
                    help="regenerate docs/knobs.md and exit")
    ap.add_argument("--gen-config-matrix", action="store_true",
                    help="regenerate docs/config_matrix.md and exit")
    ap.add_argument("--budget-s", type=float, default=DEFAULT_BUDGET_S,
                    metavar="SECONDS",
                    help="fail (exit 1) if the lint run itself exceeds "
                         "this wall-clock budget; 0 disables "
                         f"(default {DEFAULT_BUDGET_S:.0f})")
    args = ap.parse_args(argv)
    t_start = time.monotonic()

    if args.write_baseline and not (args.note and args.note.strip()):
        ap.error("--write-baseline requires --note \"<reason>\" — every "
                 "suppression must say why it is safe to keep")

    root = _repo_root()
    targets = [Path(p).resolve() for p in args.paths] or \
        default_targets(root)
    for t in targets:
        if not t.exists():
            print(f"graftlint: no such path: {t}", file=sys.stderr)
            return 2

    ctx = Context(root)
    files = load_tree(targets, root)

    if args.gen_knobs:
        reads = knobs.scan_reads(files)
        ctx.knobs_doc.parent.mkdir(parents=True, exist_ok=True)
        ctx.knobs_doc.write_text(knobs.generate_knobs_md(reads))
        print(f"graftlint: wrote {ctx.knobs_doc.relative_to(root)}")
        return 0

    if args.gen_config_matrix:
        model = configmatrix.analyze(files)
        if model is None:
            print("graftlint: no engine-like class (warmup + submit) in "
                  "the scan set", file=sys.stderr)
            return 2
        ctx.matrix_doc.parent.mkdir(parents=True, exist_ok=True)
        ctx.matrix_doc.write_text(configmatrix.generate_matrix_md(model))
        print(f"graftlint: wrote {ctx.matrix_doc.relative_to(root)}")
        return 0

    findings = run_passes(files, ctx, PASSES)

    # graftflow headline for CI logs: the dense-slab kill-list size is
    # the ROADMAP item-2 progress needle (acceptance wants it visible).
    model = configmatrix.analyze(files)
    if model is not None:
        kill = model.kill_list()
        print(f"graftflow: dense-slab kill-list: {len(kill)} method(s) "
              f"reachable only with paged_kv=False "
              f"(docs/config_matrix.md)")

    # graftnum headline: per-pass site/finding counts next to the
    # kill-list needle, so the certified-numerics surface is visible
    # in the same CI line block.
    num_rules = {"num-barrier": "numbarrier",
                 "use-after-donate": "donate",
                 "einsum-broadcast": "einsumcheck",
                 "mask-dtype": "einsumcheck"}
    per_pass = {"numbarrier": 0, "donate": 0, "einsumcheck": 0}
    for f in findings:
        p = num_rules.get(f.rule)
        if p is not None:
            per_pass[p] += 1
    nb = ctx.stats.get("numbarrier", {})
    dn = ctx.stats.get("donate", {})
    es = ctx.stats.get("einsumcheck", {})
    print(f"graftnum: numbarrier {per_pass['numbarrier']} finding(s) "
          f"({nb.get('scale_sites', 0)} scale + "
          f"{nb.get('dequant_sites', 0)} dequant site(s), "
          f"{nb.get('certified', 0)} barrier-certified) | "
          f"donate {per_pass['donate']} finding(s) "
          f"({dn.get('donating_jits', 0)} donating jit(s), "
          f"{dn.get('donating_calls', 0)} call site(s)) | "
          f"einsumcheck {per_pass['einsumcheck']} finding(s) "
          f"({es.get('shape_traced', 0)}/"
          f"{es.get('contraction_sites', 0)} contraction(s) "
          f"shape-traced)")

    baseline = {} if args.no_baseline else load_baseline(ctx.baseline_path)
    if args.write_baseline:
        write_baseline(ctx.baseline_path, findings, baseline,
                       note=args.note.strip())
        print(f"graftlint: baselined {len(findings)} finding(s) -> "
              f"{ctx.baseline_path.name}")
        return 0

    for fp, e in sorted(baseline.items()):
        if e.get("note", PLACEHOLDER_NOTE) == PLACEHOLDER_NOTE:
            print(f"graftlint: warning: baseline entry {fp} "
                  f"({e.get('rule')} in {e.get('file')}) has a "
                  f"placeholder note — rerun --write-baseline with "
                  f"--note \"<reason>\"", file=sys.stderr)

    fresh: List[Finding] = []
    used = set()
    for f in findings:
        if f.fingerprint in baseline:
            used.add(f.fingerprint)
        else:
            fresh.append(f)

    stale = set(baseline) - used
    for fp in sorted(stale):
        e = baseline[fp]
        print(f"graftlint: warning: stale baseline entry {fp} "
              f"({e.get('rule')} in {e.get('file')}) — safe to drop",
              file=sys.stderr)

    for f in fresh:
        print(f.render())

    elapsed = time.monotonic() - t_start
    over_budget = bool(args.budget_s) and elapsed > args.budget_s
    if over_budget:
        print(f"graftlint: self-runtime budget exceeded: {elapsed:.1f}s "
              f"> {args.budget_s:.0f}s — trim or parallelize passes "
              f"before adding more", file=sys.stderr)

    if fresh:
        print(f"\ngraftlint: {len(fresh)} finding(s) "
              f"({len(used)} suppressed by baseline)")
        return 1
    print(f"graftlint: OK — {len(findings)} finding(s), all accepted in "
          f"baseline" if findings else "graftlint: OK — no findings")
    return 1 if over_budget else 0


if __name__ == "__main__":
    sys.exit(main())
