"""Shape-lattice certifier: static proof that warmup covers exactly the
dispatchable variant set.

Two legs, one rule family:

AST leg (any scanned file) — every ``self._note_dispatch((<key>), ...)``
site must use a tuple-literal key whose family tag is a string constant
registered in ``shape_lattice.FAMILIES`` with the registered arity, and
the ``_warm_key`` dispatcher must carry a handler comparison for every
family the dispatch sites use.  This pins the engine's dispatch-site
spellings to the closed-form model: a new jit entry point that skips the
model registration is a lint error before it ever runs.

Numeric leg (full-tree runs only — gated on BOTH ``servers/engine.py``
and ``servers/shape_lattice.py`` being in the scan set, the knobs-pass
registry idiom) — run :func:`shape_lattice.check_spec` over the
representative config grid and compare the two independently written
derivations of the lattice:

 * a key the operational simulation reaches but the closed form misses
   is a **statically proven live retrace** (warmup iterates the closed
   form, so it would skip the key) -> ``shape-lattice`` error;
 * a closed-form key the exhaustive simulation never produces is
   **warmup waste** (a multi-second prefill compile no request can
   reach) -> ``shape-lattice-waste``.

The runtime third leg lives in ``tools/compile_audit.py
--static-xcheck``: on the warmed tiny server, every runtime-dispatched
key must be inside ``engine.static_lattice()``.

Waive with ``# graftlint: allow(shape-lattice) why`` /
``allow(shape-lattice-waste)`` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint import core

RULE = "shape-lattice"
RULE_WASTE = "shape-lattice-waste"

ENGINE_REL = "seldon_tpu/servers/engine.py"
MODEL_REL = "seldon_tpu/servers/shape_lattice.py"


def _families() -> Dict[str, int]:
    from seldon_tpu.servers import shape_lattice

    return dict(shape_lattice.FAMILIES)


def _check_grid() -> List[Tuple[str, List[tuple], List[tuple]]]:
    """(spec label, holes, waste) per grid spec — the closed-form vs
    operational cross-check. Separated out so tests can monkeypatch a
    disagreement in."""
    from seldon_tpu.servers import shape_lattice

    out = []
    for spec in shape_lattice.grid():
        holes, waste = shape_lattice.check_spec(spec)
        label = "".join((
            "P" if spec.paged else "-",
            "C" if spec.chunked else "-",
            "X" if spec.prefix else "-",
        )) + f" buckets={spec.buckets} smax={spec.max_seq_len}"
        out.append((label, holes, waste))
    return out


def _key_tuple(call: ast.Call) -> Optional[ast.expr]:
    """The key argument of a self._note_dispatch(...) call, else None."""
    fn = call.func
    if (isinstance(fn, ast.Attribute) and fn.attr == "_note_dispatch"
            and isinstance(fn.value, ast.Name) and fn.value.id == "self"
            and call.args):
        return call.args[0]
    return None


def _dispatch_sites(sf: core.SourceFile):
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            key = _key_tuple(node)
            if key is not None:
                yield node, key


def _warm_key_families(sf: core.SourceFile) -> Optional[Tuple[int, Set[str]]]:
    """(def line, family tags compared) for a _warm_key def, if any.
    A handler is any ``== "family"`` comparison inside the function —
    the dispatcher's if/elif chain on the key's tag."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_warm_key":
            handled: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Compare):
                    for cmp in [sub.left] + list(sub.comparators):
                        if (isinstance(cmp, ast.Constant)
                                and isinstance(cmp.value, str)):
                            handled.add(cmp.value)
            return node.lineno, handled
    return None


def run(files: List[core.SourceFile], ctx: core.Context) -> List[core.Finding]:
    findings: List[core.Finding] = []
    families = _families()

    # -- AST leg: dispatch-site keys vs the registered family table ----------
    site_families: Dict[str, Set[str]] = {}
    for sf in files:
        core.attach_parents(sf.tree)
        for call, key in _dispatch_sites(sf):
            ln = call.lineno
            qn = core.qualname_of(call)
            if core.allowed(sf, RULE, ln):
                continue
            if not isinstance(key, ast.Tuple) or not key.elts:
                findings.append(core.make_finding(
                    sf, RULE, ln,
                    "_note_dispatch key is not a non-empty tuple literal "
                    "— the certifier cannot tie this site to the static "
                    "lattice",
                    hint="spell the key inline: "
                         "self._note_dispatch((\"family\", ...), ...)",
                    qualname=qn,
                ))
                continue
            tag = key.elts[0]
            if not (isinstance(tag, ast.Constant)
                    and isinstance(tag.value, str)):
                findings.append(core.make_finding(
                    sf, RULE, ln,
                    "_note_dispatch key family tag is not a string "
                    "constant",
                    hint="the first tuple element names the variant "
                         "family statically",
                    qualname=qn,
                ))
                continue
            fam = tag.value
            site_families.setdefault(sf.rel, set()).add(fam)
            if fam not in families:
                findings.append(core.make_finding(
                    sf, RULE, ln,
                    f"dispatch key family \"{fam}\" is not registered in "
                    f"shape_lattice.FAMILIES — warmup and the static "
                    f"certifier cannot see it",
                    hint="register the family (and its key arity) in "
                         "seldon_tpu/servers/shape_lattice.py and teach "
                         "dispatch_keys()/simulate_keys() its domain",
                    qualname=qn,
                ))
            elif len(key.elts) != families[fam]:
                findings.append(core.make_finding(
                    sf, RULE, ln,
                    f"dispatch key family \"{fam}\" has {len(key.elts)} "
                    f"components here but FAMILIES registers "
                    f"{families[fam]}",
                    hint="a drifting key arity means the ledger and the "
                         "static lattice key different variants",
                    qualname=qn,
                ))

    # -- AST leg: _warm_key must handle every family its file dispatches -----
    for sf in files:
        wk = _warm_key_families(sf)
        if wk is None:
            continue
        def_line, handled = wk
        used = site_families.get(sf.rel, set()) & set(families)
        missing = sorted(used - handled)
        if missing and not core.allowed(sf, RULE, def_line):
            findings.append(core.make_finding(
                sf, RULE, def_line,
                f"_warm_key has no handler comparison for dispatch "
                f"famil{'y' if len(missing) == 1 else 'ies'} "
                f"{', '.join(missing)} — warmup would raise on a "
                f"lattice key it is supposed to compile",
                hint="add an elif arm matching the family tag",
                qualname="_warm_key",
            ))

    # -- numeric leg: closed form vs operational simulation (full tree) ------
    eng_sf = next((sf for sf in files if sf.rel == ENGINE_REL), None)
    model_sf = next((sf for sf in files if sf.rel == MODEL_REL), None)
    if eng_sf is None or model_sf is None:
        return findings
    anchor = next(
        (n.lineno for n in ast.walk(model_sf.tree)
         if isinstance(n, ast.FunctionDef) and n.name == "dispatch_keys"),
        1,
    )
    for label, holes, waste in _check_grid():
        if holes and not core.allowed(model_sf, RULE, anchor):
            findings.append(core.make_finding(
                model_sf, RULE, anchor,
                f"static retrace proof [{label}]: scheduler arithmetic "
                f"reaches {len(holes)} key(s) the closed-form lattice "
                f"misses, e.g. {holes[0]!r} — warmup skips them, so the "
                f"first live hit compiles on the serving path",
                hint="extend dispatch_keys() to cover the hole (or fix "
                     "simulate_keys if the scheduler cannot actually "
                     "produce it)",
                qualname="dispatch_keys",
            ))
        if waste and not core.allowed(model_sf, RULE_WASTE, anchor):
            findings.append(core.make_finding(
                model_sf, RULE_WASTE, anchor,
                f"warmup waste [{label}]: closed-form lattice declares "
                f"{len(waste)} key(s) no request can reach, e.g. "
                f"{waste[0]!r} — each is a wasted warmup compile",
                hint="tighten dispatch_keys() reachability pruning",
                qualname="dispatch_keys",
            ))
    return findings
