"""Use-after-donate certification for jit buffer donation (graftnum).

``jax.jit(..., donate_argnums=...)`` hands the donated argument's
device buffer to the output: after the call the old array object still
*exists* on the host but its buffer is dead, and touching it raises (on
TPU) or silently reads stale memory (some backends).  The engine leans
on donation everywhere — every decode/chunk/verify step donates the KV
state in, gets the updated state out — so the ONLY safe shapes are:

 * same-statement rebind: ``self._state = self._jit_step(p, self._state)``
 * tuple rebind:          ``self._state, tok = self._jit_step(p, self._state)``
 * hand-off return:       ``return self._jit_step(p, self._state)`` with
   the caller rebinding immediately (the callee never reads it again)

This pass builds the donated-callable registry from every jit site in
the file — ``x = jax.jit(f, donate_argnums=(1,))``, attribute bindings
``self._jit_x = ...``, dict-of-jits comprehensions called through
``self._jit_chunks[n](...)``, ``@functools.partial(jax.jit, ...,
donate_argnums=...)`` decorators, and conditional aliases
``fn = a if c else b`` — then walks each function flagging any read of
a donated buffer's binding after the donating call on any path.
``donate_argnums`` indices refer to the jitted callable's positional
call-site arguments; keyword pre-binding via ``functools.partial`` and
``static_argnums`` do NOT shift them.

Host-side capture is the sneaky variant: ``book[k] = state`` stores a
reference, and a later donation of ``state`` invalidates the book's
entry too — exactly the hazard that forced ``microbench_decode.py``'s
old fresh-pool-per-width workaround.  Donating a binding that a
container captured earlier is therefore also a finding.

Rule ``use-after-donate``.  Waive with
``# graftlint: allow(use-after-donate) why`` when the read is provably
metadata-only (``.shape``/``.dtype`` survive donation) or the capture
is of a copy.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftlint import core

RULE = "use-after-donate"

# Binding key: ("n", name) for locals, ("a", attr) for self.<attr>.
Key = Tuple[str, str]


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _donate_idxs(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums of a jax.jit(...) call, or None if not a
    donating jit call."""
    if _call_tail(call.func) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, int):
                        out.append(e.value)
                return tuple(out)
            return ()  # dynamic spec: treat as donating, unknown idxs
    return None


def _find_jit_call(node: ast.AST) -> Optional[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            idxs = _donate_idxs(n)
            if idxs is not None:
                return n
    return None


def _binding_key(node: ast.expr) -> Optional[Key]:
    if isinstance(node, ast.Name):
        return ("n", node.id)
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return ("a", node.attr)
    return None


class _Registry:
    """Donated callables visible in a file: name / self-attr ->
    donate idx tuple.  Dict-of-jits bindings are called through a
    Subscript of the same name/attr, so the key covers both."""

    def __init__(self) -> None:
        self.keys: Dict[Key, Tuple[int, ...]] = {}

    def callee_idxs(self, func: ast.expr) -> Optional[Tuple[int, ...]]:
        # fn(...) / self._jit_x(...) / self._jit_chunks[n](...) / d[n](...)
        base = func
        if isinstance(base, ast.Subscript):
            base = base.value
        key = _binding_key(base)
        if key is None:
            return None
        return self.keys.get(key)


def _collect_registry(tree: ast.AST) -> _Registry:
    reg = _Registry()
    for node in ast.walk(tree):
        # x = jax.jit(..., donate_argnums=...) / self._jit_x = ...
        # x = {n: jax.jit(...) for ...} / x = (a if c else b)
        if isinstance(node, ast.Assign):
            jc = _find_jit_call(node.value)
            idxs: Optional[Tuple[int, ...]] = None
            if jc is not None:
                idxs = _donate_idxs(jc)
            elif isinstance(node.value, ast.IfExp):
                a = _binding_key(node.value.body)
                b = _binding_key(node.value.orelse)
                got: Set[int] = set()
                for k in (a, b):
                    if k is not None and k in reg.keys:
                        got.update(reg.keys[k])
                if got:
                    idxs = tuple(sorted(got))
            else:
                src = _binding_key(node.value)
                if src is not None and src in reg.keys:
                    idxs = reg.keys[src]
            if idxs is not None:
                for t in node.targets:
                    key = _binding_key(t)
                    if key is not None:
                        reg.keys[key] = idxs
        # @functools.partial(jax.jit, ..., donate_argnums=...)
        # @jax.jit -> no donation; plain decorated fn with donate
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    idxs = _donate_idxs(dec)
                    if idxs is None and _call_tail(dec.func) == "partial":
                        for arg in ast.walk(dec):
                            if isinstance(arg, ast.Call):
                                got2 = _donate_idxs(arg)
                                if got2 is not None:
                                    idxs = got2
                                    break
                        if idxs is None:
                            for kw in dec.keywords:
                                if kw.arg in ("donate_argnums",
                                              "donate_argnames"):
                                    fake = ast.Call(
                                        func=ast.Name(id="jit",
                                                      ctx=ast.Load()),
                                        args=[], keywords=[kw])
                                    idxs = _donate_idxs(fake)
                    if idxs is not None:
                        reg.keys[("n", node.name)] = idxs
    return reg


def _read_keys(node: ast.AST) -> List[Tuple[Key, int]]:
    """(key, line) for every Load of a trackable binding in node.
    Metadata-only reads (.shape/.dtype/.ndim) survive donation and are
    skipped; so is the attribute base 'self' itself."""
    out: List[Tuple[Key, int]] = []
    meta = {"shape", "dtype", "ndim", "size"}
    skip: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in meta:
            for sub in ast.walk(n.value):
                skip.add(id(sub))
    for n in ast.walk(node):
        if id(n) in skip:
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.append((("n", n.id), n.lineno))
        elif (isinstance(n, ast.Attribute)
              and isinstance(n.ctx, ast.Load)
              and isinstance(n.value, ast.Name)
              and n.value.id == "self"):
            out.append((("a", n.attr), n.lineno))
    return out


def _target_keys(target: ast.expr) -> List[Key]:
    out: List[Key] = []
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            out.extend(_target_keys(e))
    elif isinstance(target, ast.Starred):
        out.extend(_target_keys(target.value))
    else:
        k = _binding_key(target)
        if k is not None:
            out.append(k)
    return out


class _State:
    def __init__(self) -> None:
        self.donated: Dict[Key, int] = {}     # key -> donation line
        self.captured: Dict[Key, int] = {}    # key -> capture line

    def copy(self) -> "_State":
        st = _State()
        st.donated = dict(self.donated)
        st.captured = dict(self.captured)
        return st

    def merge(self, other: "_State") -> None:
        # Union: donated-on-ANY-path is the hazard.
        for k, v in other.donated.items():
            self.donated.setdefault(k, v)
        for k, v in other.captured.items():
            self.captured.setdefault(k, v)


class _FnChecker:
    def __init__(self, sf: core.SourceFile, reg: _Registry,
                 fn: ast.AST, findings: List[core.Finding]) -> None:
        self.sf = sf
        self.reg = reg
        self.fn = fn
        self.findings = findings
        self.stats_calls = 0

    # -- helpers ---------------------------------------------------

    def _flag(self, line: int, msg: str, hint: str,
              anchor: ast.AST) -> None:
        if core.allowed_above(self.sf, RULE, line, self.fn.lineno):
            return
        self.findings.append(core.make_finding(
            self.sf, RULE, line, msg, hint=hint,
            qualname=core.qualname_of(anchor)))

    def _donations(self, stmt: ast.AST) -> List[Tuple[Key, ast.Call]]:
        """Donated-binding keys handed to donating calls in stmt."""
        out: List[Tuple[Key, ast.Call]] = []
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            idxs = self.reg.callee_idxs(n.func)
            if idxs is None:
                continue
            self.stats_calls += 1
            if not idxs:  # dynamic donate spec: every positional arg
                idxs = tuple(range(len(n.args)))
            for i in idxs:
                if i < len(n.args):
                    k = _binding_key(n.args[i])
                    if k is not None:
                        out.append((k, n))
        return out

    # -- statement walk --------------------------------------------

    def run(self) -> None:
        st = _State()
        self._walk_body(getattr(self.fn, "body", []), st)

    def _walk_body(self, body: Sequence[ast.stmt], st: _State) -> bool:
        """Walk a statement list; True when the body provably leaves
        this scope (return/raise/break/continue) — statements after
        the terminator are unreachable and a terminated branch's state
        must NOT merge back at an If join."""
        for stmt in body:
            if self._walk_stmt(stmt, st):
                return True
        return False

    def _walk_stmt(self, stmt: ast.stmt, st: _State) -> bool:
        if isinstance(stmt, ast.If):
            self._check_expr(stmt.test, st)
            a = st.copy()
            b = st.copy()
            ta = self._walk_body(stmt.body, a)
            tb = self._walk_body(stmt.orelse, b)
            if ta and tb:
                return True
            if ta:
                st.donated, st.captured = b.donated, b.captured
            elif tb:
                st.donated, st.captured = a.donated, a.captured
            else:
                st.donated = {}
                st.captured = {}
                st.merge(a)
                st.merge(b)
            return False
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter, st)
            # Two sweeps: the second sees donations from the first
            # iteration, catching donate-then-read-across-iterations.
            for _ in range(2):
                for k in _target_keys(stmt.target):
                    st.donated.pop(k, None)
                    st.captured.pop(k, None)
                self._walk_body(stmt.body, st)
            self._walk_body(stmt.orelse, st)
            return False
        if isinstance(stmt, ast.While):
            for _ in range(2):
                self._check_expr(stmt.test, st)
                self._walk_body(stmt.body, st)
            self._walk_body(stmt.orelse, st)
            return False
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._check_expr(item.context_expr, st)
                if item.optional_vars is not None:
                    for k in _target_keys(item.optional_vars):
                        st.donated.pop(k, None)
            return self._walk_body(stmt.body, st)
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, st)
            for h in stmt.handlers:
                hs = st.copy()
                self._walk_body(h.body, hs)
                st.merge(hs)
            self._walk_body(stmt.orelse, st)
            return self._walk_body(stmt.finalbody, st)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False  # nested defs get their own checker
        # Flat statement: reads -> captures -> donations -> clears.
        self._flat_stmt(stmt, st)
        return isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue))

    def _check_expr(self, expr: Optional[ast.AST], st: _State) -> None:
        if expr is None:
            return
        donations = self._donations(expr)
        donated_args = {id(c.args[i]) for k, c in donations
                        for i in range(len(c.args))
                        if _binding_key(c.args[i]) == k}
        for key, line in _read_keys(expr):
            if key in st.donated:
                self._note_read(key, line, st, expr, donated_args)
        for key, call in donations:
            self._apply_donation(key, call, st)

    def _note_read(self, key: Key, line: int, st: _State,
                   stmt: ast.AST, donated_args: Set[int]) -> None:
        label = key[1] if key[0] == "n" else f"self.{key[1]}"
        self._flag(
            line,
            f"reads {label} after its buffer was donated at line "
            f"{st.donated[key]} — donate_argnums hands the device "
            f"buffer to the jit output, so this read sees a dead "
            f"(deleted or reused) buffer",
            hint="rebind in the same statement: "
                 "state = jit_step(params, state); or drop the "
                 "donation if the old value is still needed",
            anchor=stmt)
        # Flag once per binding, not once per subsequent read.
        st.donated.pop(key, None)

    def _apply_donation(self, key: Key, call: ast.Call,
                        st: _State) -> None:
        if key in st.captured:
            label = key[1] if key[0] == "n" else f"self.{key[1]}"
            self._flag(
                call.lineno,
                f"donates {label} while a host-side container still "
                f"holds a reference captured at line "
                f"{st.captured[key]} — the captured entry's buffer "
                f"dies with the donation",
                hint="capture a copy (jnp.copy / jax.device_get) or "
                     "move the capture after the last donation",
                anchor=call)
            st.captured.pop(key, None)
        st.donated[key] = call.lineno

    def _flat_stmt(self, stmt: ast.stmt, st: _State) -> None:
        donations = self._donations(stmt)
        donation_keys = {k for k, _ in donations}

        # 1) reads of already-dead bindings (donated BEFORE this
        #    statement).  The donated argument of this statement's own
        #    call is the hand-off, not a use-after.
        for key, line in _read_keys(stmt):
            if key in st.donated:
                self._note_read(key, line, st, stmt, set())

        # 2) host-side capture: container[i] = x / book.append(x).
        #    A key donated in this same statement is consumed by the
        #    call, not captured (the stored value is the call result).
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    for key, line in _read_keys(stmt.value):
                        if key not in donation_keys:
                            st.captured.setdefault(key, line)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if _call_tail(call.func) in ("append", "add", "update",
                                         "setdefault", "insert"):
                for arg in call.args:
                    for key, line in _read_keys(arg):
                        if key not in donation_keys:
                            st.captured.setdefault(key, line)

        # 3) donations fire
        for key, call in donations:
            self._apply_donation(key, call, st)

        # 4) assignment targets are fresh bindings
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Subscript):
                continue  # container store, not a rebind
            for k in _target_keys(t):
                st.donated.pop(k, None)
                st.captured.pop(k, None)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                for k in _target_keys(t):
                    st.donated.pop(k, None)
                    st.captured.pop(k, None)


def run(files: List[core.SourceFile], ctx: core.Context) -> List[core.Finding]:
    findings: List[core.Finding] = []
    jit_sites = 0
    call_sites = 0
    for sf in files:
        core.attach_parents(sf.tree)
        reg = _collect_registry(sf.tree)
        jit_sites += len(reg.keys)
        if not reg.keys:
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            chk = _FnChecker(sf, reg, fn, findings)
            chk.run()
            call_sites += chk.stats_calls
    stats = getattr(ctx, "stats", None)
    if stats is not None:
        stats["donate"] = {
            "donating_jits": jit_sites,
            "donating_calls": call_sites,
        }
    return findings
