"""Sharding-consistency pass over the tensor-parallel layer.

The ROADMAP item-3 TP engine rides on ``parallel/``: one mesh-axis
vocabulary (``mesh.AXES``), PartitionSpec rules for every tensor, and
shard_map collectives.  Three failure shapes are cheap to write and
expensive to debug — a misspelled axis name silently replicates the
tensor it was meant to split, a host pull on a sharded array gathers
the full global value through one host, and a ``jax.jit`` without
sharding annotations lets GSPMD re-decide layouts at the boundary.
Three rules:

``shard-axis``
    Every string axis inside a ``P(...)``/``PartitionSpec(...)``
    literal, an ``axis_name=``/``axis_names=`` kwarg, or a
    ``lax.p*`` collective's first string argument must be declared in
    the scanned tree's ``AXES`` tuple (the mesh-axis vocabulary;
    skipped when no scanned file declares one).  Module-level axis
    aliases (``FOO_AXIS = "..."``) are held to the same vocabulary:
    graftmesh's ``models/tp_sharding.py`` derives ``TP_AXIS`` from
    ``AXES[-1]`` precisely so it cannot drift, and a re-declared
    string alias elsewhere would undo that.

``shard-host-pull``
    ``.item()`` / ``np.asarray()`` / ``np.array()`` / ``float()`` /
    ``int()`` on a local holding a shard_map / device_put result —
    a host gather of device-sharded data on what is usually a hot
    path.

``shard-jit``
    ``jax.jit(...)`` without ``in_shardings``/``out_shardings`` in a
    sharding-centric file (one that touches PartitionSpec or
    shard_map) — the boundary drops the layout contract the rest of
    the file spells out.  Engine-style files that never name a
    PartitionSpec are exempt: their jits are keyed on donation, not
    layouts.

Waive with ``# graftlint: allow(shard-axis|shard-host-pull|shard-jit)
why`` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.graftlint import core

RULE_AXIS = "shard-axis"
RULE_PULL = "shard-host-pull"
RULE_JIT = "shard-jit"

# Collectives that take an axis name (positionally or via axis_name=).
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "pswapaxes",
    "axis_index", "all_gather", "all_to_all", "psum_scatter", "pcast",
}
# Call names whose result lives sharded on device. shard_params /
# shard_state are graftmesh's tp_sharding sharders: their return values
# are NamedSharding-committed trees, so a host pull on them gathers the
# whole TP group's weights or KV state through one host.
_SHARDED_SOURCES = {"shard_map", "device_put", "shard_tree", "make_array",
                    "shard_params", "shard_state"}
_HOST_PULLS = {"asarray", "array"}  # np.<name>(tainted)


def _declared_axes(files: List[core.SourceFile]) -> Optional[Set[str]]:
    """Union of module-level AXES tuples in the scan set, or None."""
    axes: Optional[Set[str]] = None
    for sf in files:
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "AXES"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                names = {
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
                axes = (axes or set()) | names
    return axes


def _spec_axis_names(call: ast.Call):
    """String axis names used inside a P(...) / PartitionSpec(...)."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                yield node


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _uses_sharding(sf: core.SourceFile) -> bool:
    """Sharding-centric = the file IMPORTS the sharding vocabulary
    (PartitionSpec / shard_map). A textual mention in comments — e.g.
    the engine explaining why it does NOT shard — does not qualify."""
    for node in sf.tree.body:
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in ("PartitionSpec", "shard_map"):
                    return True
    return False


def run(files: List[core.SourceFile], ctx: core.Context) -> List[core.Finding]:
    findings: List[core.Finding] = []
    axes = _declared_axes(files)

    for sf in files:
        core.attach_parents(sf.tree)
        sharding_file = _uses_sharding(sf)

        # -- shard-axis: module-level FOO_AXIS = "..." aliases -----------
        # tp_sharding derives TP_AXIS from AXES[-1] (a Subscript, never
        # flagged); only a raw string re-declaration can drift, and that
        # is exactly the misspelled-axis failure shape at its root.
        # Scoped to sharding-centric files: an _AXIS constant in a file
        # that never names a PartitionSpec (e.g. this pass's own
        # RULE_AXIS) is not a mesh-axis alias.
        if axes is not None and sharding_file:
            for node in sf.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id.endswith("_AXIS")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    continue
                if node.value.value in axes:
                    continue
                if core.allowed(sf, RULE_AXIS, node.lineno):
                    continue
                findings.append(core.make_finding(
                    sf, RULE_AXIS, node.lineno,
                    f"axis alias {node.targets[0].id} = "
                    f"\"{node.value.value}\" names an axis outside the "
                    f"declared mesh vocabulary {tuple(sorted(axes))}",
                    hint="derive the alias from mesh.AXES (e.g. "
                         "TP_AXIS = AXES[-1]) so it cannot drift",
                ))

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            ln = node.lineno
            qn = core.qualname_of(node)

            # -- shard-axis: P(...) literals -----------------------------
            if axes is not None and name in ("P", "PartitionSpec"):
                for const in _spec_axis_names(node):
                    if const.value in axes:
                        continue
                    if core.allowed(sf, RULE_AXIS, const.lineno, ln):
                        continue
                    findings.append(core.make_finding(
                        sf, RULE_AXIS, const.lineno,
                        f"PartitionSpec axis \"{const.value}\" is not a "
                        f"declared mesh axis {tuple(sorted(axes))} — the "
                        f"dimension silently replicates instead of "
                        f"sharding",
                        hint="use an axis from mesh.AXES (or add the new "
                             "axis there first)",
                        qualname=qn,
                    ))

            # -- shard-axis: collectives' axis_name ----------------------
            if axes is not None and name in _COLLECTIVES:
                cands = []
                if node.args:
                    cands.append(node.args[-1])
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis_names", "axis"):
                        cands.append(kw.value)
                for cand in cands:
                    for const in ast.walk(cand):
                        if not (isinstance(const, ast.Constant)
                                and isinstance(const.value, str)):
                            continue
                        if const.value in axes:
                            continue
                        if core.allowed(sf, RULE_AXIS, const.lineno, ln):
                            continue
                        findings.append(core.make_finding(
                            sf, RULE_AXIS, const.lineno,
                            f"collective {name}() names axis "
                            f"\"{const.value}\" which is not a declared "
                            f"mesh axis {tuple(sorted(axes))}",
                            hint="collective axis names must match the "
                                 "mesh axes the surrounding shard_map "
                                 "declares manual",
                            qualname=qn,
                        ))

            # -- shard-jit ----------------------------------------------
            if (sharding_file and name == "jit"
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "jax"):
                kws = {kw.arg for kw in node.keywords}
                if not kws & {"in_shardings", "out_shardings"}:
                    if not core.allowed(sf, RULE_JIT, ln):
                        findings.append(core.make_finding(
                            sf, RULE_JIT, ln,
                            "jax.jit in a sharding-centric file carries "
                            "no in_shardings/out_shardings — the jit "
                            "boundary drops the layout contract and "
                            "GSPMD re-decides it",
                            hint="pass NamedShardings (or move the jit "
                                 "out of the sharded layer)",
                            qualname=qn,
                        ))

        # -- shard-host-pull: function-local taint tracking --------------
        for fn in (n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.FunctionDef)):
            tainted: Set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    src = node.value.func
                    # direct: x = device_put(...); curried:
                    # x = shard_map(...)(...)
                    names = {_call_name(src)}
                    if isinstance(src, ast.Call):
                        names.add(_call_name(src.func))
                    if names & _SHARDED_SOURCES:
                        tainted.add(node.targets[0].id)
            if not tainted:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                ln = node.lineno
                qn = core.qualname_of(node)
                pulled: Optional[str] = None
                # x.item()
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in tainted):
                    pulled = f"{node.func.value.id}.item()"
                # np.asarray(x) / np.array(x) / float(x) / int(x)
                elif node.args and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in tainted:
                    name = _call_name(node.func)
                    is_np = (isinstance(node.func, ast.Attribute)
                             and isinstance(node.func.value, ast.Name)
                             and node.func.value.id in ("np", "numpy")
                             and name in _HOST_PULLS)
                    is_builtin = (isinstance(node.func, ast.Name)
                                  and name in ("float", "int"))
                    if is_np or is_builtin:
                        pulled = f"{name}({node.args[0].id})"
                if pulled is None or core.allowed(sf, RULE_PULL, ln):
                    continue
                findings.append(core.make_finding(
                    sf, RULE_PULL, ln,
                    f"{pulled} pulls a sharded array to the host — a "
                    f"cross-host gather of device-sharded data",
                    hint="keep the reduction device-side (jnp) or fetch "
                         "an addressable shard explicitly",
                    qualname=qn,
                ))
    return findings
