"""lockorder pass: interprocedural lock-order, holds() call sites, and
blocking-while-locked.

The lock-guard pass checks that guarded *fields* are touched under the
right lock; this pass checks the *locks themselves* compose.  It builds
the interprocedural lock-acquisition graph from three sources — lexical
``with self.<lock>:`` nesting, ``# graftlint: holds(<lock>)``
annotations (which seed the held set of the annotated method), and the
call graph (same ``self.<m>()`` resolution as hotpath.py, extended
across classes through attribute bindings like ``self.stats =
EngineStats()``) — and enforces three rules against the canonical order
in ``seldon_tpu/servers/lock_order.py``:

  holds-site   every call site of a ``holds(X)``-annotated method must
               itself be in an X-held context (lexical ``with``, its own
               holds() annotation, or ``__init__`` pre-publication).
               A holds() annotation that is a lie at a call site is a
               data race the lock-guard pass can no longer see.

  lock-order   every acquired-before edge (direct or through a callee)
               must respect the documented rank/leaf table; acquiring a
               held non-reentrant lock is a self-deadlock; any cycle in
               the derived graph — including among locks the table does
               not rank — is a deadlock between two threads.

  lock-block   no blocking call while the scheduler lock ``_book`` is
               held: ``time.sleep``, blocking ``Queue.get``/bounded
               ``Queue.put``, ``jax.device_get``, ``block_until_ready``,
               ``.join()``.  A stalled ``_book`` freezes admission,
               cancel, metrics, and drain all at once.

Lock identity: a lock attribute assigned ``threading.Lock()`` /
``RLock()`` in class C is canonicalized through
``lock_order.canonical_name(C, attr)`` so the same physical lock has one
name on every path (``self.stats.lock`` in the engine and ``self.lock``
inside EngineStats are both ``stats.lock``).  Cross-class paths resolve
through attribute bindings (``self.attr = ClassName(...)``, or a
class-annotated ctor parameter) and simple local aliases
(``x = self.attr``).  Unresolvable receivers are skipped — this pass is
deliberately under-approximate; the graftsan runtime witness covers the
dynamic remainder.

Waive a deliberate edge/stall with ``# graftlint: allow(<rule>) why`` on
the acquisition/call line; waived lines also drop out of callee
summaries so callers are not re-flagged for them.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from seldon_tpu.servers.lock_order import canonical_name, edge_violation

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   make_finding)

RULE_HOLDS = "holds-site"
RULE_ORDER = "lock-order"
RULE_BLOCK = "lock-block"

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


@dataclasses.dataclass
class _ClassInfo:
    name: str
    sf: SourceFile
    node: ast.ClassDef
    locks: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # lock attr -> reentrant (RLock)
    queues: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # queue attr -> bounded
    bindings: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # attr -> class names it may hold
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    holds: Dict[str, str] = dataclasses.field(default_factory=dict)
    # method name -> lock attr from `# graftlint: holds(<lock>)`


_Site = Tuple[SourceFile, int, str]  # file, line, qualname


def _is_lock_ctor(expr: ast.AST) -> Optional[bool]:
    """None if not a lock constructor, else reentrancy (RLock -> True)."""
    for node in ast.walk(expr):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOCK_CTORS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "threading"):
            return node.func.attr == "RLock"
    return None


def _is_queue_ctor(expr: ast.AST) -> Optional[bool]:
    """None if not a Queue constructor, else boundedness (any maxsize)."""
    if not isinstance(expr, ast.Call):
        return None
    f = expr.func
    named = (isinstance(f, ast.Attribute) and f.attr == "Queue") or \
        (isinstance(f, ast.Name) and f.id == "Queue")
    if not named:
        return None
    return bool(expr.args) or any(k.arg == "maxsize" for k in expr.keywords)


def _ctor_class(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
        return expr.func.id
    return None


def _ann_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Class name from a parameter annotation (Name or string literal)."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip("'\" ").split(".")[-1] or None
    return None


def _iter_own(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class scopes
    (their bodies run at some other time, under some other held set)."""
    work = list(ast.iter_child_nodes(node))
    while work:
        n = work.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        work.extend(ast.iter_child_nodes(n))


def _collect_classes(files: List[SourceFile]) -> Dict[str, _ClassInfo]:
    classes: Dict[str, _ClassInfo] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = _ClassInfo(node.name, sf, node)
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[m.name] = m
                    lock = sf.holds.get(m.lineno)
                    if lock:
                        ci.holds[m.name] = lock
            init = ci.methods.get("__init__")
            params: Dict[str, Optional[str]] = {}
            if init is not None:
                for a in init.args.args + init.args.kwonlyargs:
                    params[a.arg] = _ann_class(a.annotation)
            for n in ast.walk(node):
                if isinstance(n, ast.Assign):
                    targets, value = n.targets, n.value
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    targets, value = [n.target], n.value
                else:
                    continue
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    attr = t.attr
                    reent = _is_lock_ctor(value)
                    if reent is not None:
                        ci.locks[attr] = reent
                        continue
                    bounded = _is_queue_ctor(value)
                    if bounded is not None:
                        ci.queues[attr] = bounded
                        continue
                    for v in ([value.body, value.orelse]
                              if isinstance(value, ast.IfExp) else [value]):
                        cn = _ctor_class(v)
                        if cn is None and isinstance(v, ast.Name):
                            cn = params.get(v.id)  # annotated ctor param
                        if cn:
                            ci.bindings.setdefault(attr, set()).add(cn)
            classes[ci.name] = ci
    return classes


class _Resolver:
    """Expression -> canonical locks / callees, inside one method."""

    def __init__(self, classes: Dict[str, _ClassInfo], ci: _ClassInfo,
                 fn: ast.AST):
        self.classes = classes
        self.ci = ci
        # local aliases: name -> class names (x = self.attr / x = Cls())
        self.local: Dict[str, Set[str]] = {}
        for _ in range(2):  # two passes cover x = y chains
            for n in _iter_own(fn):
                if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                    continue
                t = n.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                got: Set[str] = set()
                vals = ([n.value.body, n.value.orelse]
                        if isinstance(n.value, ast.IfExp) else [n.value])
                for v in vals:
                    got |= self._classes_of(v)
                if got:
                    self.local[t.id] = got

    def _classes_of(self, expr: ast.AST) -> Set[str]:
        """Class names an expression may evaluate to an instance of."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return {self.ci.name}
            return set(self.local.get(expr.id, ()))
        if isinstance(expr, ast.Attribute):
            out: Set[str] = set()
            for base in self._classes_of(expr.value):
                bci = self.classes.get(base)
                if bci:
                    out |= bci.bindings.get(expr.attr, set())
            return out
        cn = _ctor_class(expr)
        if cn and cn in self.classes:
            return {cn}
        return set()

    def locks_of(self, expr: ast.AST) -> Set[Tuple[str, bool]]:
        """(canonical, reentrant) for a `with` context expression."""
        if not isinstance(expr, ast.Attribute):
            return set()
        out: Set[Tuple[str, bool]] = set()
        for base in self._classes_of(expr.value):
            bci = self.classes.get(base)
            if bci and expr.attr in bci.locks:
                out.add((canonical_name(base, expr.attr),
                         bci.locks[expr.attr]))
        return out

    def callees(self, call: ast.Call) -> List[Tuple[_ClassInfo, str]]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            cn = _ctor_class(call)
            if cn and cn in self.classes \
                    and "__init__" in self.classes[cn].methods:
                return [(self.classes[cn], "__init__")]
            return []
        out = []
        for base in self._classes_of(f.value):
            bci = self.classes.get(base)
            if bci and f.attr in bci.methods:
                out.append((bci, f.attr))
        return out

    def blocking_desc(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                and f.value.id == "time":
            return "time.sleep"
        if f.attr == "device_get" and isinstance(f.value, ast.Name) \
                and f.value.id == "jax":
            return "jax.device_get"
        if f.attr == "block_until_ready":
            return "block_until_ready"
        if f.attr == "join" and isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id == "self":
            return f"self.{f.value.attr}.join"
        if f.attr in ("get", "put"):
            recv = f.value
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" \
                    and recv.attr in self.ci.queues:
                if any(k.arg == "block"
                       and isinstance(k.value, ast.Constant)
                       and k.value.value is False for k in call.keywords):
                    return None
                if f.attr == "get":
                    return f"blocking self.{recv.attr}.get"
                if self.ci.queues[recv.attr]:  # put blocks only when bounded
                    return f"self.{recv.attr}.put on a bounded queue"
        return None


def _seed_holds(ci: _ClassInfo, mname: str) -> Tuple[str, ...]:
    attr = ci.holds.get(mname)
    if attr:
        return (canonical_name(ci.name, attr),)
    return ()


def _summaries(classes: Dict[str, _ClassInfo]):
    """Fixpoint may-acquire / may-block summaries per (class, method).
    Lines waived with allow(lock-order)/allow(lock-block) are excluded,
    so an explicitly sanctioned site does not re-flag every caller."""
    acquires: Dict[Tuple[str, str], Set[str]] = {}
    blocks: Dict[Tuple[str, str], Set[str]] = {}
    resolvers: Dict[Tuple[str, str], _Resolver] = {}
    for ci in classes.values():
        for mname, fn in ci.methods.items():
            resolvers[(ci.name, mname)] = _Resolver(classes, ci, fn)
            acquires[(ci.name, mname)] = set()
            blocks[(ci.name, mname)] = set()

    changed = True
    while changed:
        changed = False
        for ci in classes.values():
            for mname, fn in ci.methods.items():
                key = (ci.name, mname)
                res = resolvers[key]
                acq = set(acquires[key])
                blk = set(blocks[key])
                for n in _iter_own(fn):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        if allowed(ci.sf, RULE_ORDER, n.lineno):
                            continue
                        for item in n.items:
                            for canon, _ in res.locks_of(item.context_expr):
                                acq.add(canon)
                    elif isinstance(n, ast.Call):
                        desc = res.blocking_desc(n)
                        if desc and not allowed(ci.sf, RULE_BLOCK,
                                                n.lineno, fn.lineno):
                            blk.add(desc)
                        for dci, dm in res.callees(n):
                            if allowed(ci.sf, RULE_ORDER, n.lineno):
                                pass
                            else:
                                acq |= acquires[(dci.name, dm)]
                            if not allowed(ci.sf, RULE_BLOCK,
                                           n.lineno, fn.lineno):
                                blk |= blocks[(dci.name, dm)]
                if acq != acquires[key] or blk != blocks[key]:
                    acquires[key], blocks[key] = acq, blk
                    changed = True
    return acquires, blocks, resolvers


def _is_sched_lock(canon: str) -> bool:
    return canon == "_book" or canon.endswith("._book")


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components of size >= 2 (Tarjan)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def visit(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                visit(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) >= 2:
                out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            visit(v)
    return out


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    classes = _collect_classes(files)
    if not classes:
        return []
    for sf in files:
        attach_parents(sf.tree)
    acquires, blocks, resolvers = _summaries(classes)

    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], List[_Site]] = {}
    reentrant: Set[str] = set()
    for ci in classes.values():
        for attr, reent in ci.locks.items():
            if reent:
                reentrant.add(canonical_name(ci.name, attr))

    def check_edge(sf: SourceFile, line: int, qn: str, held: str,
                   acq: str, how: str) -> None:
        if held != acq:
            edges.setdefault((held, acq), []).append((sf, line, qn))
        reason = edge_violation(held, acq)
        if reason is None:
            return
        if held == acq and acq in reentrant:
            return
        if allowed(sf, RULE_ORDER, line):
            return
        findings.append(make_finding(
            sf, RULE_ORDER, line, f"{how}: {reason}",
            "follow the documented order in seldon_tpu/servers/"
            "lock_order.py (outermost first): restructure so the inner "
            "lock is taken before the outer one is held, or not at all",
            qn))

    for ci in classes.values():
        sf = ci.sf
        for mname, fn in ci.methods.items():
            res = resolvers[(ci.name, mname)]
            qn = f"{ci.name}.{mname}"
            in_init = mname == "__init__"

            def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda, ast.ClassDef)):
                        continue
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        inner = held
                        for item in child.items:
                            for canon, _ in res.locks_of(item.context_expr):
                                for h in inner:
                                    check_edge(
                                        sf, child.lineno, qn, h, canon,
                                        f"`with` acquires '{canon}' while "
                                        f"'{h}' is held")
                                inner = inner + (canon,)
                        # recurse through the With node itself so a body
                        # statement that is ITSELF a With gets dispatched
                        # (walking the body statements directly would
                        # skip the isinstance check above for them)
                        walk(child, inner)
                        continue
                    if isinstance(child, ast.Call):
                        _check_call(child, held)
                    walk(child, held)

            def _check_call(call: ast.Call, held: Tuple[str, ...]) -> None:
                line = call.lineno
                callees = res.callees(call)
                # holds-site: callee documents a lock the caller must own
                for dci, dm in callees:
                    attr = dci.holds.get(dm)
                    if not attr:
                        continue
                    need = canonical_name(dci.name, attr)
                    if need in held or in_init:
                        continue
                    if allowed(sf, RULE_HOLDS, line, fn.lineno):
                        continue
                    dline = dci.methods[dm].lineno
                    findings.append(make_finding(
                        sf, RULE_HOLDS, line,
                        f"call to {dci.name}.{dm} requires '{need}' held "
                        f"(holds({attr}) at {dci.sf.rel}:{dline}) but no "
                        "path here acquires it",
                        f"wrap the call in `with self.{attr}:` (or the "
                        f"owning object's lock), or annotate the caller "
                        f"`# graftlint: holds({attr})` if every entry "
                        "point owns it",
                        qn))
                if held:
                    # lock-order: callee may acquire under what we hold
                    for dci, dm in callees:
                        for acq in acquires[(dci.name, dm)]:
                            for h in held:
                                check_edge(
                                    sf, line, qn, h, acq,
                                    f"call to {dci.name}.{dm} acquires "
                                    f"'{acq}' while '{h}' is held")
                    # lock-block: stalls with the scheduler lock held
                    if any(_is_sched_lock(h) for h in held):
                        descs = []
                        d = res.blocking_desc(call)
                        if d:
                            descs.append(d)
                        for dci, dm in callees:
                            for d in sorted(blocks[(dci.name, dm)]):
                                descs.append(f"{dci.name}.{dm} -> {d}")
                        for d in descs:
                            if allowed(sf, RULE_BLOCK, line, fn.lineno):
                                continue
                            findings.append(make_finding(
                                sf, RULE_BLOCK, line,
                                f"{d} while '_book' is held stalls every "
                                "scheduler client (admission, cancel, "
                                "metrics, drain)",
                                "move the blocking operation outside "
                                "`with self._book:` (fetch at the "
                                "boundary, use *_nowait, sleep outside "
                                "the lock), or waive a deliberate stall "
                                "with `# graftlint: allow(lock-block) "
                                "<why>`",
                                qn))

            walk(fn, _seed_holds(ci, mname))

    # Cycle detection over the full derived graph (ranked or not).
    graph: Dict[str, Set[str]] = {}
    for (h, a) in edges:
        graph.setdefault(h, set()).add(a)
        graph.setdefault(a, set())
    for comp in _cycles(graph):
        cyc = " -> ".join(comp + [comp[0]])
        for (h, a), sites in sorted(edges.items()):
            if h in comp and a in comp:
                sf, line, qn = sites[0]
                if allowed(sf, RULE_ORDER, line):
                    continue
                findings.append(make_finding(
                    sf, RULE_ORDER, line,
                    f"lock-order cycle: {cyc} (this edge acquires "
                    f"'{a}' while '{h}' is held)",
                    "impose a single acquisition order for these locks "
                    "(see seldon_tpu/servers/lock_order.py) — a cycle "
                    "means two threads can deadlock against each other",
                    qn))
    return findings
