"""outcome pass: request finalization emits exactly one terminal item.

The PR-4 lifecycle invariant: every submitted request terminates with
exactly one of {completion, typed error item, cancel}, followed by exactly
one ``None`` sentinel on ``req.out`` — so no waiter ever hangs and no
waiter sees two outcomes.  The chaos soak samples this; here we check the
shape of the code that has to uphold it.

Scope: any class containing a sentinel put (``<x>.out.put(None)``) is a
*finalizer class*.  Within it:

  O1  only one method (the completer) may put the ``None`` sentinel; a
      rogue sentinel elsewhere risks double-None or an early sentinel
      racing the real outcome
  O2  a typed error item (dict with an ``"error"`` key put on ``.out``)
      must be emitted by a method that also reaches the completer —
      otherwise the error is delivered but the waiter hangs forever
      waiting for its sentinel
  O3  a broad ``except Exception`` / bare ``except`` inside the class
      must finalize (call a method that transitively reaches the
      completer) or re-raise; swallowing the exception silently leaks
      every in-flight request

Waive with ``# graftlint: allow(outcome) why``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   enclosing_function, make_finding, qualname_of)

RULE = "outcome"


def _is_out_put(node: ast.AST) -> Optional[ast.Call]:
    """Match `<expr>.out.put(arg)`; return the Call."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "put":
        recv = node.func.value
        if isinstance(recv, ast.Attribute) and recv.attr == "out":
            return node
    return None


def _dict_has_error_key(d: ast.Dict) -> bool:
    return any(isinstance(k, ast.Constant) and k.value == "error"
               for k in d.keys)


def _error_dict_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict) \
                and _dict_has_error_key(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _classify_puts(fn: ast.AST):
    sentinels: List[ast.Call] = []
    errors: List[ast.Call] = []
    err_names = _error_dict_names(fn)
    for node in ast.walk(fn):
        call = _is_out_put(node)
        if call is None or not call.args:
            continue
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and arg.value is None:
            sentinels.append(call)
        elif isinstance(arg, ast.Dict) and _dict_has_error_key(arg):
            errors.append(call)
        elif isinstance(arg, ast.Name) and arg.id in err_names:
            errors.append(call)
    return sentinels, errors


def _self_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        attach_parents(sf.tree)
        for cls in [n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)]:
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
            puts = {name: _classify_puts(fn) for name, fn in methods.items()}
            sentinel_methods = [n for n, (s, _) in puts.items() if s]
            if not sentinel_methods:
                continue  # not a finalizer class

            # the designated completer: prefer a method named *complete*
            completer = next((n for n in sentinel_methods if "complete" in n),
                             sentinel_methods[0])

            # finalizers: methods that transitively reach the completer
            finalizers: Set[str] = {completer}
            changed = True
            while changed:
                changed = False
                for name, fn in methods.items():
                    if name in finalizers:
                        continue
                    if _self_calls(fn) & finalizers:
                        finalizers.add(name)
                        changed = True

            for name, fn in methods.items():
                sentinels, errors = puts[name]
                # O1: rogue sentinel outside the completer
                if name != completer:
                    for call in sentinels:
                        if allowed(sf, RULE, call.lineno, fn.lineno):
                            continue
                        findings.append(make_finding(
                            sf, RULE, call.lineno,
                            f"None sentinel put outside the designated "
                            f"completer '{completer}' — risks a double or "
                            "premature end-of-stream",
                            f"route termination through self.{completer}()",
                            f"{cls.name}.{name}"))
                # O2: error item without a path to the sentinel
                if errors and name not in finalizers:
                    for call in errors:
                        if allowed(sf, RULE, call.lineno, fn.lineno):
                            continue
                        findings.append(make_finding(
                            sf, RULE, call.lineno,
                            "typed error item emitted but this method never "
                            f"reaches the completer '{completer}' — the "
                            "waiter hangs waiting for its sentinel",
                            f"call self.{completer}() after putting the "
                            "error item",
                            f"{cls.name}.{name}"))

            # O3: broad except handlers must finalize or re-raise
            for name, fn in methods.items():
                for node in ast.walk(fn):
                    if not isinstance(node, ast.ExceptHandler):
                        continue
                    broad = node.type is None or (
                        isinstance(node.type, ast.Name)
                        and node.type.id == "Exception")
                    if not broad:
                        continue
                    body_calls: Set[str] = set()
                    has_raise = False
                    for n in ast.walk(node):
                        if isinstance(n, ast.Raise):
                            has_raise = True
                        c = n if isinstance(n, ast.Call) else None
                        if c is not None and isinstance(c.func, ast.Attribute) \
                                and isinstance(c.func.value, ast.Name) \
                                and c.func.value.id == "self":
                            body_calls.add(c.func.attr)
                    if has_raise or (body_calls & finalizers):
                        continue
                    if allowed(sf, RULE, node.lineno, fn.lineno):
                        continue
                    findings.append(make_finding(
                        sf, RULE, node.lineno,
                        "broad except swallows the failure without "
                        "finalizing — every in-flight request leaks "
                        "(waiters hang)",
                        f"call a finalizer ({', '.join(sorted(finalizers))}) "
                        "or re-raise",
                        f"{cls.name}.{name}"))
    return findings
