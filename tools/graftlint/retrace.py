"""retrace pass: jitted functions must not pick up per-request Python state.

Every recompile of a dispatch kernel stalls serving for seconds; the
engine's kernels are shaped so that everything varying per request is a
traced array and everything static is bound once at construction
(``functools.partial`` kwargs, ``static_argnums``/``static_argnames``).
This pass checks that discipline stays intact:

  R1  ``jax.jit(...)`` created inside a for/while loop — a fresh jit
      wrapper per iteration defeats the compile cache
  R2  a jitted def/lambda closing over a loop variable of an enclosing
      scope — late binding means the trace constant silently varies
  R3  ``if``/``while``/ternary branching on a traced value inside a
      jitted body — TracerBoolConversionError at best, shape-dependent
      retrace at worst.  Static launder points: ``.shape``/``.ndim``/
      ``.dtype``/``.size`` attribute reads, ``len()``, ``isinstance()``,
      partial-bound kwargs and declared static args
  R4  list/dict/set literals passed in a static position — unhashable,
      so the jit cache lookup itself raises

Waive with ``# graftlint: allow(retrace) why``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   enclosing_function, make_finding, qualname_of)

RULE = "retrace"

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance", "issubclass", "type"}


def _is_jax_jit(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" \
            and isinstance(f.value, ast.Name) and f.value.id in ("jax", "_jax"):
        return True
    return False


def _is_partial(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "partial":
        return True
    if isinstance(f, ast.Attribute) and f.attr == "partial" \
            and isinstance(f.value, ast.Name) and f.value.id == "functools":
        return True
    return False


def _static_names_from_kwargs(kws: Sequence[ast.keyword]) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in kws:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


class _Jitted:
    """A function object known to be traced by jax.jit."""

    def __init__(self, fn: ast.AST, static_names: Set[str],
                 static_nums: Set[int], bound_kwargs: Set[str],
                 public_name: str):
        self.fn = fn  # FunctionDef or Lambda
        self.static_names = static_names
        self.static_nums = static_nums
        self.bound_kwargs = bound_kwargs
        self.public_name = public_name  # name call sites use, "" if unknown


def _decorator_jit(fn: ast.FunctionDef) -> Optional[Tuple[Set[str], Set[int]]]:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Attribute) and dec.attr == "jit" \
                and isinstance(dec.value, ast.Name) and dec.value.id in ("jax", "_jax"):
            return set(), set()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec):
                return _static_names_from_kwargs(dec.keywords)
            if _is_partial(dec) and dec.args and isinstance(dec.args[0], (ast.Attribute, ast.Name)):
                inner = dec.args[0]
                is_jit = (isinstance(inner, ast.Attribute) and inner.attr == "jit") \
                    or (isinstance(inner, ast.Name) and inner.id == "jit")
                if is_jit:
                    return _static_names_from_kwargs(dec.keywords)
    return None


def _collect_jitted(sf: SourceFile) -> List[_Jitted]:
    out: List[_Jitted] = []
    # name -> def node, for resolving jax.jit(fn_name) and self._x_impl
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    for fn in list(defs.values()):
        res = _decorator_jit(fn)
        if res is not None:
            out.append(_Jitted(fn, res[0], res[1], set(), fn.name))

    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node) and node.args):
            continue
        static_names, static_nums = _static_names_from_kwargs(node.keywords)
        target = node.args[0]
        bound: Set[str] = set()
        if isinstance(target, ast.Call) and _is_partial(target):
            bound = {kw.arg for kw in target.keywords if kw.arg}
            target = target.args[0] if target.args else None
        public = ""
        parent = getattr(node, "_graftlint_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                public = t.id
            elif isinstance(t, ast.Attribute):
                public = t.attr
        fn_node: Optional[ast.AST] = None
        if isinstance(target, ast.Lambda):
            fn_node = target
        elif isinstance(target, ast.Name) and target.id in defs:
            fn_node = defs[target.id]
        elif isinstance(target, ast.Attribute) and target.attr in defs:
            fn_node = defs[target.attr]
        if fn_node is not None:
            out.append(_Jitted(fn_node, static_names, static_nums, bound, public))
    return out


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    return names


def _expr_static(e: ast.AST, traced: Set[str]) -> bool:
    """True when `e` cannot carry a traced value (safe to branch on)."""
    if isinstance(e, ast.Attribute) and e.attr in _STATIC_ATTRS:
        return True
    if isinstance(e, ast.Call):
        f = e.func
        if isinstance(f, ast.Name) and f.id in _STATIC_CALLS:
            return True
    if isinstance(e, ast.Name):
        return e.id not in traced
    if isinstance(e, ast.Constant):
        return True
    return all(_expr_static(c, traced) for c in ast.iter_child_nodes(e)
               if isinstance(c, ast.expr))


def _traced_locals(fn: ast.AST, traced: Set[str]) -> Set[str]:
    traced = set(traced)
    body = fn.body if isinstance(fn.body, list) else []
    for _ in range(2):
        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            if value is None:
                continue
            is_traced = not _expr_static(value, traced)
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        if is_traced:
                            traced.add(n.id)
                        else:
                            traced.discard(n.id)
    return traced


def _loop_targets_above(fn: ast.AST) -> Set[str]:
    """Names bound as for-loop targets in scopes enclosing `fn`."""
    out: Set[str] = set()
    cur = getattr(fn, "_graftlint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.For):
            for n in ast.walk(cur.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        cur = getattr(cur, "_graftlint_parent", None)
    return out


def _free_names(fn: ast.AST) -> Set[str]:
    bound = set(_param_names(fn))
    a = fn.args
    bound.update(p.arg for p in a.kwonlyargs)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    loads: Set[str] = set()
    nodes = ast.walk(fn.body if isinstance(fn, ast.Lambda) else fn)
    for node in nodes:
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                bound.add(node.id)
            else:
                loads.add(node.id)
    return loads - bound


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        attach_parents(sf.tree)
        jitted = _collect_jitted(sf)

        # R1: jit wrapper built inside a loop
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node):
                cur = getattr(node, "_graftlint_parent", None)
                while cur is not None:
                    if isinstance(cur, (ast.For, ast.While)):
                        fn = enclosing_function(node)
                        if not allowed(sf, RULE, node.lineno,
                                       fn.lineno if fn else 0):
                            findings.append(make_finding(
                                sf, RULE, node.lineno,
                                "jax.jit created inside a loop — a fresh "
                                "wrapper per iteration defeats the compile cache",
                                "hoist the jit out of the loop and pass the "
                                "varying value as a traced argument",
                                qualname_of(node)))
                        break
                    cur = getattr(cur, "_graftlint_parent", None)

        for j in jitted:
            fn = j.fn
            fn_line = fn.lineno
            qn = qualname_of(fn) or j.public_name

            # R2: closure over an enclosing loop variable
            hazards = _free_names(fn) & _loop_targets_above(fn)
            for name in sorted(hazards):
                if allowed(sf, RULE, fn_line):
                    break
                findings.append(make_finding(
                    sf, RULE, fn_line,
                    f"jitted function closes over loop variable '{name}' — "
                    "late binding makes the baked-in constant vary per "
                    "iteration (silent retrace or wrong results)",
                    f"bind it explicitly: functools.partial(fn, {name}={name}) "
                    "or pass it as a traced argument",
                    qn))

            # R3: branch on traced value
            params = _param_names(fn)
            static = set(j.static_names) | set(j.bound_kwargs)
            for i in j.static_nums:
                if i < len(params):
                    static.add(params[i])
            traced0 = {p for p in params if p not in static and p != "self"}
            traced = _traced_locals(fn, traced0)
            body_nodes = ast.walk(fn)
            for node in body_nodes:
                test: Optional[ast.AST] = None
                kind = ""
                if isinstance(node, (ast.If, ast.While)):
                    test, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.IfExp):
                    test, kind = node.test, "conditional expression"
                if test is None or _expr_static(test, traced):
                    continue
                efn = enclosing_function(node)
                if allowed(sf, RULE, node.lineno, efn.lineno if efn else 0):
                    continue
                findings.append(make_finding(
                    sf, RULE, node.lineno,
                    f"{kind} branches on a traced value inside a jitted "
                    "function — TracerBoolConversionError or per-shape retrace",
                    "replace with jnp.where / lax.cond, or mark the argument "
                    "static if it is genuinely per-config",
                    qualname_of(node) or qn))

            # R4: unhashable literal at a static call site
            if j.public_name and (j.static_nums or j.static_names):
                _check_static_call_sites(sf, j, findings)
    return findings


def _check_static_call_sites(sf: SourceFile, j: _Jitted,
                             findings: List[Finding]) -> None:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else "")
        if name != j.public_name:
            continue
        # positional static args (account for bound self when calling a method)
        params = _param_names(j.fn)
        offset = 1 if params[:1] == ["self"] else 0
        for i, arg in enumerate(node.args):
            if (i + offset) in j.static_nums and \
                    isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                _flag_unhashable(sf, node, arg, j, findings)
        for kw in node.keywords:
            if kw.arg in j.static_names and \
                    isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                _flag_unhashable(sf, node, kw.value, j, findings)


def _flag_unhashable(sf: SourceFile, call: ast.Call, arg: ast.AST,
                     j: _Jitted, findings: List[Finding]) -> None:
    efn = enclosing_function(call)
    if allowed(sf, RULE, call.lineno, efn.lineno if efn else 0):
        return
    findings.append(make_finding(
        sf, RULE, call.lineno,
        f"unhashable {type(arg).__name__.lower()} literal passed in a "
        f"static position of jitted '{j.public_name}' — the jit cache "
        "lookup raises TypeError",
        "pass a tuple (hashable) or make the argument traced",
        qualname_of(call)))
