"""Shared plumbing for graftlint passes.

A pass is a callable ``run(files, ctx) -> List[Finding]``.  This module
owns everything rule-agnostic: source loading, ``# graftlint:`` control
comments, stable fingerprints, and the baseline file that lets CI fail
only on regressions.

Control comments (all live in real comments, invisible to the AST):

  # graftlint: guarded-by(<lock>)            field declaration; may only be
  # graftlint: guarded-by(<lock>) via(<role>)  touched under with self.<lock>
  # graftlint: holds(<lock>)                 on a def line: the caller holds
                                             <lock>; body is in-lock context
  # graftlint: allow(<rule>[, <rule>]) why   waive <rule> on this line, or
                                             for the whole function when the
                                             comment sits on its def line

Fingerprints are ``rule:relpath:qualname:sha1(normalized source line)`` so
baseline entries survive unrelated line drift but die when the flagged
code actually changes.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

BASELINE_NAME = "graftlint_baseline.json"

_ALLOW_RE = re.compile(r"#\s*graftlint:\s*allow\(([\w\-, ]+)\)")
_GUARDED_RE = re.compile(
    r"#\s*graftlint:\s*guarded-by\((\w+)\)(?:\s+via\((\w+)\))?"
)
_HOLDS_RE = re.compile(r"#\s*graftlint:\s*holds\((\w+)\)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""
    qualname: str = ""
    fingerprint: str = ""

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        out += f"\n    fingerprint: {self.fingerprint}"
        return out


@dataclasses.dataclass
class SourceFile:
    path: Path
    rel: str
    text: str
    lines: List[str]
    tree: ast.Module
    # line -> set of waived rule ids ("*" waives everything on the line)
    allows: Dict[int, Set[str]]
    # line of a `def` -> lock name the caller is documented to hold
    holds: Dict[int, str]
    # (field, lock, via-role) declarations found in this file
    guarded: List[Tuple[str, str, Optional[str], int]]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Context:
    """Run-wide state handed to every pass."""

    def __init__(self, repo_root: Path):
        self.repo_root = repo_root
        self.baseline_path = repo_root / BASELINE_NAME
        self.knobs_doc = repo_root / "docs" / "knobs.md"
        self.matrix_doc = repo_root / "docs" / "config_matrix.md"
        # Passes drop per-pass counters here (sites scanned, sites
        # certified, ...); the CLI prints them as the run headline.
        self.stats: Dict[str, dict] = {}


def _parse_controls(lines: Sequence[str]):
    allows: Dict[int, Set[str]] = {}
    holds: Dict[int, str] = {}
    guarded: List[Tuple[str, str, Optional[str], int]] = []
    for i, raw in enumerate(lines, start=1):
        m = _ALLOW_RE.search(raw)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            allows.setdefault(i, set()).update(rules)
        m = _HOLDS_RE.search(raw)
        if m:
            holds[i] = m.group(1)
        m = _GUARDED_RE.search(raw)
        if m:
            fm = re.search(r"self\.(\w+)", raw)
            if fm:
                guarded.append((fm.group(1), m.group(1), m.group(2), i))
    return allows, holds, guarded


def load_source(path: Path, repo_root: Path) -> SourceFile:
    text = path.read_text()
    lines = text.splitlines()
    tree = ast.parse(text, filename=str(path))
    allows, holds, guarded = _parse_controls(lines)
    try:
        rel = path.resolve().relative_to(repo_root.resolve()).as_posix()
    except ValueError:  # fixture outside the repo (tests)
        rel = path.resolve().as_posix()
    return SourceFile(path, rel, text, lines, tree, allows, holds, guarded)


def load_tree(targets: Sequence[Path], repo_root: Path) -> List[SourceFile]:
    """Collect .py files under the target dirs, skipping generated code."""
    files: List[SourceFile] = []
    seen: Set[Path] = set()
    for target in targets:
        if target.is_file():
            cands = [target]
        else:
            cands = sorted(target.rglob("*.py"))
        for p in cands:
            rp = p.resolve()
            if rp in seen:
                continue
            seen.add(rp)
            try:
                rel = rp.relative_to(repo_root.resolve()).as_posix()
            except ValueError:  # fixture outside the repo (tests)
                rel = rp.as_posix()
            if "/proto/" in f"/{rel}" and rel.endswith("_pb2.py"):
                continue  # protoc output
            if "__pycache__" in rel:
                continue
            try:
                files.append(load_source(p, repo_root))
            except SyntaxError as exc:  # surfaced as a finding, not a crash
                files.append(
                    SourceFile(p, rel, "", [], ast.Module(body=[], type_ignores=[]),
                               {}, {}, [])
                )
                print(f"graftlint: syntax error in {rel}: {exc}", file=sys.stderr)
    return files


def allowed(sf: SourceFile, rule: str, *linenos: int) -> bool:
    """True when any of the lines carries an allow() for this rule."""
    for ln in linenos:
        rules = sf.allows.get(ln)
        if rules and (rule in rules or "*" in rules):
            return True
    return False


def allowed_above(sf: SourceFile, rule: str, line: int,
                  *also: int) -> bool:
    """allowed(), plus the comment block immediately preceding `line` —
    multi-line waiver reasons don't fit a trailing comment, so

        # graftlint: allow(<rule>) long reason
        # continuing over several lines
        flagged_statement()

    waives the statement it directly precedes (blank/comment lines only
    between the allow and the flagged line)."""
    if allowed(sf, rule, line, *also):
        return True
    ln = line - 1
    while ln >= 1:
        text = sf.line_text(ln).strip()
        if text and not text.startswith("#"):
            return False
        if allowed(sf, rule, ln):
            return True
        ln -= 1
    return False


def make_finding(sf: SourceFile, rule: str, line: int, message: str,
                 hint: str = "", qualname: str = "") -> Finding:
    norm = " ".join(sf.line_text(line).split())
    digest = hashlib.sha1(
        f"{rule}|{sf.rel}|{qualname}|{norm}".encode()
    ).hexdigest()[:12]
    return Finding(rule, sf.rel, line, message, hint, qualname, digest)


# --- enclosing-scope helpers -------------------------------------------------

def attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._graftlint_parent = node  # type: ignore[attr-defined]


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "_graftlint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_graftlint_parent", None)
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "_graftlint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_graftlint_parent", None)
    return None


def qualname_of(node: ast.AST) -> str:
    parts: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_graftlint_parent", None)
    return ".".join(reversed(parts))


# --- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> Dict[str, dict]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {e["fingerprint"]: e for e in data.get("suppressions", [])}


# Historical default note; lint warns on any baseline entry still
# carrying it (see __main__), and write_baseline now demands a real one.
PLACEHOLDER_NOTE = "TODO: justify"


def write_baseline(path: Path, findings: Sequence[Finding],
                   old: Dict[str, dict], note: str = PLACEHOLDER_NOTE) -> None:
    """Persist findings as suppressions. Entries already in `old` keep
    their existing note; new entries are stamped with `note` (the CLI
    requires a real --note, so the placeholder only appears via direct
    API use in tests)."""
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
        note_for = old.get(f.fingerprint, {}).get("note", note)
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "file": f.path,
            "qualname": f.qualname,
            "note": note_for,
        })
    path.write_text(json.dumps({"version": 1, "suppressions": entries},
                               indent=2) + "\n")


def run_passes(files: List[SourceFile], ctx: Context,
               passes: Sequence) -> List[Finding]:
    findings: List[Finding] = []
    for p in passes:
        findings.extend(p(files, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    seen: Set[str] = set()
    unique = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            unique.append(f)
    return unique
