"""hot-sync pass: no host synchronisation inside the dispatch hot loop.

The engine's throughput rests on the dispatch loop never blocking on the
device: kernels are enqueued asynchronously and results come back through
``copy_to_host_async`` + the boundary fetcher thread.  A single stray
``block_until_ready`` / ``.item()`` / ``device_get`` in that loop
serialises every dispatch against device completion.

Mechanics: per class, build a ``self.<method>()`` call graph rooted at the
scheduler loop methods (``_loop`` / ``_loop_async`` / ``_loop_sync`` /
``_fetch_loop`` / ``_dispatch_once`` / ``step``) and flag, inside the
reachable set:

  * ``jax.device_get(...)`` and ``.item()`` calls (always a sync)
  * ``float(x)`` / ``int(x)`` / ``np.asarray(x)`` where ``x`` is
    device-tainted (assigned from a ``self._jit*`` dispatch or from
    ``self._state``) — implicit device->host transfer

``block_until_ready`` is flagged everywhere in the scanned tree, not just
in the reachable set: outside an explicitly allowed warmup/boundary site
it is never correct in serving code.

Waive intentional boundary syncs with ``# graftlint: allow(hot-sync) why``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   enclosing_function, make_finding, qualname_of)

RULE = "hot-sync"

ROOT_NAMES = {"_loop", "_loop_async", "_loop_sync", "_fetch_loop",
              "_dispatch_once", "step"}


def _self_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            out.add(node.func.attr)
    return out


def _reachable_methods(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    roots = [n for n in methods if n in ROOT_NAMES]
    seen: Set[str] = set()
    work = list(roots)
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for callee in _self_calls(methods[name]):
            if callee in methods and callee not in seen:
                work.append(callee)
    return {n: methods[n] for n in seen}


def _is_device_source(expr: ast.AST) -> bool:
    """Expressions whose value lives on-device: jit dispatch results and
    reads of the engine's device-resident state pytree."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            if node.attr.startswith("_jit"):
                return True
            if (node.attr == "_state" and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return True
    return False


def _tainted_locals(fn: ast.AST) -> Set[str]:
    tainted: Set[str] = set()

    def expr_tainted(e: ast.AST) -> bool:
        if _is_device_source(e):
            return True
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(e))

    def mark(target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                mark(el)

    for _ in range(2):  # fixpoint-ish; two passes cover forward chains
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    mark(t)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and expr_tainted(node.value):
                mark(node.target)
            elif isinstance(node, ast.For) and expr_tainted(node.iter):
                mark(node.target)
    return tainted


def _def_line(node: ast.AST) -> int:
    fn = enclosing_function(node)
    return fn.lineno if fn is not None else 0


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        attach_parents(sf.tree)

        # block_until_ready: flagged anywhere in the tree.
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                if allowed(sf, RULE, node.lineno, _def_line(node)):
                    continue
                findings.append(make_finding(
                    sf, RULE, node.lineno,
                    "block_until_ready stalls the host on device completion",
                    "move the sync to a warmup/boundary site and annotate it "
                    "`# graftlint: allow(hot-sync) <why>`",
                    qualname_of(node)))

        # The rest only applies inside the dispatch-reachable set.
        for cls in [n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)]:
            for mname, fn in _reachable_methods(cls).items():
                tainted = _tainted_locals(fn)
                qn = f"{cls.name}.{mname}"
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    hit: Optional[str] = None
                    hint = ""
                    f = node.func
                    if isinstance(f, ast.Attribute) and f.attr == "item":
                        hit = ".item() forces a device->host sync"
                        hint = "keep the value on device, or fetch it at the boundary"
                    elif isinstance(f, ast.Attribute) and f.attr == "device_get" \
                            and isinstance(f.value, ast.Name) and f.value.id == "jax":
                        hit = "jax.device_get blocks on device completion"
                        hint = "use copy_to_host_async and read at the next boundary"
                    elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                            and node.args and any(
                                isinstance(n, ast.Name) and n.id in tainted
                                for n in ast.walk(node.args[0])):
                        hit = (f"{f.id}() on a device value implies a blocking "
                               "transfer")
                        hint = "fetch at the boundary, then convert on host"
                    elif isinstance(f, ast.Attribute) and f.attr == "asarray" \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id in ("np", "numpy") and node.args \
                            and any(isinstance(n, ast.Name) and n.id in tainted
                                    for n in ast.walk(node.args[0])):
                        hit = "np.asarray of a device array copies synchronously"
                        hint = "use copy_to_host_async + boundary fetch"
                    if hit is None:
                        continue
                    if allowed(sf, RULE, node.lineno, fn.lineno):
                        continue
                    findings.append(make_finding(
                        sf, RULE, node.lineno,
                        f"{hit} (reachable from the dispatch loop via {qn})",
                        hint, qn))
    return findings
