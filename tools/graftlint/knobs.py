"""env-knob pass: every environment variable read appears in the knob table.

Serving behaviour is steered by dozens of env knobs (PAGED_KV, KV_BLOCK,
MAX_QUEUE, CHAOS, ...).  An undocumented knob is an operational landmine:
it changes production behaviour and appears in no runbook.  This pass
keeps ``docs/knobs.md`` honest by construction:

  K1  [``env-knob``] an ``os.environ`` / ``os.getenv`` read whose name
      is not registered in ``tools/graftlint/knob_registry.py``
  K2  [``env-knob-dead``] a registered knob no scanned file reads — a
      dead knob is worse than an unregistered one, because
      ``docs/knobs.md`` keeps advertising a control the code no longer
      honors.  Groups listed in ``EXTERNAL_GROUPS`` are exempt (read by
      JAX, the kubelet, cloud SDKs, tests, ...)
  K3  [``env-knob``] ``docs/knobs.md`` differs from the generated
      table — regenerate with ``python -m tools.graftlint --gen-knobs``

Name resolution handles string literals, module-level string constants
(``ENV_FOO = "FOO"; os.environ.get(ENV_FOO)``), function parameter
defaults resolving to either, and local aliases of ``os.environ``
(direct rebinds only — a *value* read out of environ, like
``flags = os.environ.get("XLA_FLAGS", "")``, is not the mapping and
substring tests against it are not env reads).  Reads through genuinely
dynamic names are skipped.  Writes are skipped.

Waive with ``# graftlint: allow(env-knob) why`` (or ``env-knob-dead``
on the registry line).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (Context, Finding, SourceFile, allowed, attach_parents,
                   enclosing_function, make_finding, qualname_of)
from .knob_registry import EXTERNAL_GROUPS, KNOBS

RULE = "env-knob"
RULE_DEAD = "env-knob-dead"

REGISTRY_REL = "tools/graftlint/knob_registry.py"


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = node.value.value
    return consts


def _param_defaults(fn: ast.AST, consts: Dict[str, str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    a = fn.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        v = _resolve(d, consts, {})
        if v is not None:
            out[p.arg] = v
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            v = _resolve(d, consts, {})
            if v is not None:
                out[p.arg] = v
    return out


def _resolve(expr: ast.AST, consts: Dict[str, str],
             locals_: Dict[str, str]) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        return locals_.get(expr.id) or consts.get(expr.id)
    return None


def _os_names(tree: ast.Module) -> Set[str]:
    """Module names the `os` module is bound to (`import os as _os`)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "os":
                    names.add(alias.asname or "os")
    return names or {"os"}


def _environ_aliases(tree: ast.Module, os_names: Set[str]) -> Set[str]:
    """Names rebound to the os.environ MAPPING itself, including
    `env = environ if environ is not None else os.environ`.  A value
    merely derived from environ (`flags = os.environ.get(...)`) is NOT
    an alias — `"x" in flags` is a substring test, not an env read."""

    aliases: Set[str] = set()

    def is_environ_expr(e: ast.AST) -> bool:
        if isinstance(e, ast.Attribute) and e.attr == "environ" \
                and isinstance(e.value, ast.Name) and e.value.id in os_names:
            return True
        if isinstance(e, ast.Name) and e.id in aliases:
            return True
        if isinstance(e, ast.IfExp):
            return is_environ_expr(e.body) or is_environ_expr(e.orelse)
        return False

    for _ in range(2):  # second pass resolves alias-of-alias chains
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and is_environ_expr(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
    return aliases


def _is_environ(expr: ast.AST, aliases: Set[str], os_names: Set[str]) -> bool:
    if isinstance(expr, ast.Attribute) and expr.attr == "environ" \
            and isinstance(expr.value, ast.Name) and expr.value.id in os_names:
        return True
    if isinstance(expr, ast.Name) and expr.id in aliases:
        return True
    return False


def scan_reads(files: List[SourceFile]) -> List[Tuple[str, SourceFile, int, str]]:
    """All resolvable env reads: (var name, file, line, qualname)."""
    reads: List[Tuple[str, SourceFile, int, str]] = []
    for sf in files:
        attach_parents(sf.tree)
        consts = _module_str_constants(sf.tree)
        os_names = _os_names(sf.tree)
        aliases = _environ_aliases(sf.tree, os_names)

        for node in ast.walk(sf.tree):
            name_expr: Optional[ast.AST] = None
            if isinstance(node, ast.Call):
                f = node.func
                # os.getenv("X") / os.environ.get("X")
                if isinstance(f, ast.Attribute) and f.attr == "getenv" \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in os_names and node.args:
                    name_expr = node.args[0]
                elif isinstance(f, ast.Attribute) and f.attr == "get" \
                        and _is_environ(f.value, aliases, os_names) and node.args:
                    name_expr = node.args[0]
            elif isinstance(node, ast.Subscript) \
                    and _is_environ(node.value, aliases, os_names) \
                    and not isinstance(node.ctx, (ast.Store, ast.Del)):
                name_expr = node.slice
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                    and _is_environ(node.comparators[0], aliases, os_names):
                name_expr = node.left
            if name_expr is None:
                continue
            fn = enclosing_function(node)
            locals_: Dict[str, str] = _param_defaults(fn, consts) if fn else {}
            var = _resolve(name_expr, consts, locals_)
            if var is None:
                continue  # dynamic read — not statically knowable
            reads.append((var, sf, node.lineno, qualname_of(node)))
    return reads


def generate_knobs_md(reads: List[Tuple[str, SourceFile, int, str]]) -> str:
    sites: Dict[str, Set[str]] = {}
    for var, sf, _line, _qn in reads:
        sites.setdefault(var, set()).add(sf.rel)
    groups: Dict[str, List[str]] = {}
    for name, meta in KNOBS.items():
        groups.setdefault(meta["group"], []).append(name)

    lines = [
        "# Environment knobs",
        "",
        "<!-- generated by `python -m tools.graftlint --gen-knobs` — do not edit by hand -->",
        "",
        "Every environment variable the serving tree reads, kept in sync with",
        "the code by graftlint's env-knob pass (an unregistered read fails",
        "`make lint`).  Registry: `tools/graftlint/knob_registry.py`.",
        "Bench-harness methodology behind the `BENCH_*` knobs lives in",
        "[benchmarking.md](benchmarking.md).",
        "",
    ]
    for group in sorted(groups):
        title = group.replace("-", " ").capitalize()
        lines += [f"## {title}", "",
                  "| Knob | Default | Read in | Description |",
                  "| --- | --- | --- | --- |"]
        for name in sorted(groups[group]):
            meta = KNOBS[name]
            where = ", ".join(f"`{s}`" for s in sorted(sites.get(name, set()))) \
                or "_(external reader)_"
            lines.append(f"| `{name}` | `{meta['default']}` | {where} | "
                         f"{meta['desc']} |")
        lines.append("")
    return "\n".join(lines)


def run(files: List[SourceFile], ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    reads = scan_reads(files)
    seen: Set[str] = set()
    for var, sf, line, qn in reads:
        seen.add(var)
        if var in KNOBS:
            continue
        fn_lines = []
        if allowed(sf, RULE, line, *fn_lines):
            continue
        findings.append(make_finding(
            sf, RULE, line,
            f"env var '{var}' read here but not registered in the knob table",
            f"add '{var}' to {REGISTRY_REL} and regenerate docs/knobs.md "
            "with --gen-knobs (or delete the read)",
            qn))

    # K2/K3 only make sense on a full-tree scan — linting a lone fixture
    # file must not report every registry entry as stale.
    reg_sf = next((sf for sf in files if sf.rel == REGISTRY_REL), None)
    if reg_sf is None:
        return findings

    # K2: dead knobs — registered (and so advertised by docs/knobs.md)
    # but read nowhere in the scanned tree.
    for name, meta in KNOBS.items():
        if name in seen or meta["group"] in EXTERNAL_GROUPS:
            continue
        decl_line = next((i for i, t in enumerate(reg_sf.lines, 1)
                          if f'"{name}"' in t), 1)
        if allowed(reg_sf, RULE_DEAD, decl_line):
            continue
        findings.append(make_finding(
            reg_sf, RULE_DEAD, decl_line,
            f"dead knob: '{name}' is registered (and advertised in "
            "docs/knobs.md) but read by no scanned file",
            "delete the stale entry and regenerate with --gen-knobs, or "
            "move the knob to an EXTERNAL_GROUPS group if a platform "
            "component reads it",
            name))

    # K3: docs/knobs.md freshness
    want = generate_knobs_md(reads)
    doc = ctx.knobs_doc
    have = doc.read_text() if doc.exists() else ""
    if have != want:
        findings.append(make_finding(
            reg_sf, RULE, 1,
            "docs/knobs.md is stale relative to the registry and the "
            "scanned reads",
            "run `python -m tools.graftlint --gen-knobs`",
            "docs/knobs.md"))
    return findings
