"""The env-knob registry: every environment variable the tree may read.

``docs/knobs.md`` is generated from this table plus the read sites the
env-knob pass discovers (``python -m tools.graftlint --gen-knobs``).
Adding an ``os.environ`` read without registering it here fails
``make lint``.

Groups in ``EXTERNAL_GROUPS`` are exempt from the stale-entry check: the
value is owned by the platform (JAX, the kubelet, cloud SDKs) so a knob
may stay registered even when no scanned file currently reads it.

``bench.py`` / ``bench_orchestrator.py`` are part of the linted tree, so
the bench-harness phase knobs (``BENCH_*`` / ``BENCH_ORCH_*``) are
registered here like everything else; the measurement methodology behind
them stays in ``docs/benchmarking.md``.
"""

EXTERNAL_GROUPS = {"platform"}


def _k(group, default, desc):
    return {"group": group, "default": default, "desc": desc}


KNOBS = {
    # --- engine serving (servers/jaxserver.py unit-param fallbacks) -------
    "WEIGHT_DTYPE": _k("engine-serving", "(checkpoint dtype)",
                       "Override weight dtype at load, e.g. `int8` to serve a "
                       "bf16 HF checkpoint quantized."),
    "ACT_DTYPE": _k("engine-serving", "(follows weights)",
                    "W8A8 activation dtype for int8 weights (`int8`/`bf16`)."),
    "PREFIX_CACHE": _k("engine-serving", "0",
                       "Enable prompt-prefix KV reuse (radix trie over "
                       "block-aligned prefixes)."),
    "PREFIX_CACHE_MB": _k("engine-serving", "0 (auto)",
                          "HBM budget for retained prefix KV, in MiB."),
    "CHUNKED_PREFILL": _k("engine-serving", "0",
                          "Interleave prefill chunks with decode steps "
                          "(stall-free scheduling)."),
    "PREFILL_CHUNK": _k("engine-serving", "0 (model block)",
                        "Prefill chunk length in tokens."),
    "DISPATCH_TOKEN_BUDGET": _k("engine-serving", "0 (auto)",
                                "Per-dispatch token budget shared by decode "
                                "and prefill chunks."),
    "PAGED_KV": _k("engine-serving", "0",
                   "Paged KV cache: global block pool + per-slot block "
                   "tables instead of dense per-slot slabs."),
    "KV_BLOCK": _k("engine-serving", "0 (model default)",
                   "KV block size in tokens (paged mode)."),
    "KV_POOL_MB": _k("engine-serving", "0 (dense-equivalent)",
                     "KV pool size in HBM MiB (paged mode)."),
    "RAGGED": _k("engine-serving", "0",
                 "graftragged unified dispatch: pack any mix of prefill "
                 "chunks, continuations and decode steps into ONE "
                 "ragged wave kernel (single compiled variant, no "
                 "bucket/group lattice). Forces paged_kv + "
                 "chunked_prefill."),
    "RAGGED_CHUNK": _k("engine-serving", "0 (prefill_chunk)",
                       "Per-slot token segment per ragged wave; the "
                       "wave's flat token buffer is max_slots * "
                       "ragged_chunk. Power of two, multiple of "
                       "kv_block."),
    "RAGGED_KERNEL": _k("engine-serving", "masked",
                        "graftkern ragged attention leg: `masked` = "
                        "bit-exact full-width baseline; `sparse` = "
                        "block-sparse jnp walker touching only live KV "
                        "blocks (online softmax, int8 dequant fused; "
                        "the CPU perf leg); `pallas` = the Mosaic TPU "
                        "kernel for the same walk (interpret-mode on "
                        "CPU). Greedy outputs token-identical across "
                        "legs; all legs share the ONE (ragged, C) "
                        "compiled variant. Also selects the spec "
                        "verify_wave leg."),
    "SPEC": _k("engine-serving", "0",
               "graftspec speculative decoding: a drafter proposes k "
               "tokens per live decode row and ONE wide ragged verify "
               "wave scores all k + 1 positions against the paged "
               "block tables; exact-match acceptance keeps output "
               "bit-identical to SPEC=0 at any temperature. Requires "
               "paged_kv (forced on); incompatible with RAGGED."),
    "SPEC_K": _k("engine-serving", "0 (engine default 4)",
                 "Draft tokens per verify wave (power of two); the "
                 "compiled pow2 verify ladder spans 1..spec_k and "
                 "PILOT=1 auto-tunes the live rung from the windowed "
                 "acceptance rate."),
    "SPEC_DRAFT": _k("engine-serving", "(empty: host n-gram drafter)",
                     "Draft model preset (e.g. `bench-1b` under an 8B "
                     "target): loads a resident draft model and "
                     "compiles the (\"draft\", k) ladder; empty uses "
                     "the zero-cost host n-gram drafter."),
    "TP": _k("engine-serving", "0 (legacy auto mesh)",
             "graftmesh tensor-parallel group size. 0 keeps the legacy "
             "auto mesh; 1 pins an explicit single-chip ('tp',) mesh — "
             "the bit-exact parity reference every TP gate compares "
             "against; N>1 shards weights and the paged KV pool over N "
             "devices (exact-TP: greedy output stays bit-identical to "
             "tp=1). Requires tp | n_kv_heads, n_heads, d_ff; mutually "
             "exclusive with mesh_sp>1."),
    "MESH_DEVICES": _k("engine-serving", "0 (no cap)",
                       "Caps the devices graftmesh may claim "
                       "(device_budget()); operator guard for sharing "
                       "a host between engines — e.g. MESH_DEVICES=4 "
                       "keeps a tp=2 engine off the back half of a "
                       "v5e-8."),
    "MAX_QUEUE": _k("engine-serving", "0 (unbounded)",
                    "Admission queue bound; past it submit() sheds with "
                    "a retriable 429 EngineOverloaded."),
    "DEFAULT_DEADLINE_MS": _k("engine-serving", "0 (none)",
                              "Default per-request TTL in ms; per-request "
                              "deadline_ms still wins."),
    "HEAL": _k("engine-serving", "0",
               "graftheal supervised fault recovery: a faulted wave "
               "rebuilds device state and RESURRECTS every innocent "
               "in-flight request by replaying its committed tokens "
               "(deterministic per-position sampling keys make the "
               "continued stream bit-identical, greedy or sampled); "
               "repeat faulters are bisected down to a poison "
               "quarantine. Off (the default) leaves the raw "
               "fail-everything path byte-identical to the pre-heal "
               "engine. State machine at /debug/health; gated by "
               "`make heal-audit`."),
    "HEAL_MAX_RETRIES": _k("engine-serving", "4",
                           "Per-request replay budget: how many times one "
                           "request may ride a faulted wave before its "
                           "next fault fails it terminally "
                           "(kind=internal, retriable=false) instead of "
                           "re-entering the backoff pen. Must be >= 1."),
    "HEAL_WATCHDOG_MS": _k("engine-serving", "0 (off)",
                           "Bound every boundary fetch to this wall-clock "
                           "budget: a fetch that exceeds it is declared a "
                           "hung wave and recovered like a dispatch "
                           "fault (the wedged worker thread is "
                           "abandoned, never joined). 0 fetches inline "
                           "with no watchdog thread."),

    # --- chaos fault injection (servers/chaos.py, env-only by design) -----
    "CHAOS": _k("chaos", "0", "Master switch (`1`/`true`/`yes`); never a "
                "unit parameter, so manifests cannot enable it by accident."),
    "CHAOS_SEED": _k("chaos", "0", "Seed for the deterministic fault "
                     "sequence; replays a failure byte-for-byte."),
    "CHAOS_DISPATCH_FAIL": _k("chaos", "0", "Probability a dispatch raises "
                              "(drives _fail_all rebuild)."),
    "CHAOS_ALLOC_FAIL": _k("chaos", "0", "Probability a paged-pool "
                           "allocation is refused."),
    "CHAOS_SLOW_BOUNDARY": _k("chaos", "0", "Probability a boundary fetch "
                              "is artificially delayed."),
    "CHAOS_SLOW_MS": _k("chaos", "5", "Delay for a slow boundary, ms."),
    "CHAOS_DISCONNECT": _k("chaos", "0", "Probability a client disconnect "
                           "is injected (stream close -> cancel)."),
    "CHAOS_NAN_INJECT": _k("chaos", "0", "Probability a fetched boundary's "
                           "token ids are overwritten out-of-vocab (what "
                           "NaN logits / corrupt DMA look like to the "
                           "host; drives the graftheal sentinel)."),
    "CHAOS_HANG": _k("chaos", "0", "Probability a boundary fetch sleeps "
                     "CHAOS_HANG_MS (drives the graftheal watchdog's "
                     "hung-wave declaration)."),
    "CHAOS_HANG_MS": _k("chaos", "200", "Duration of an injected fetch "
                        "hang, ms; set past HEAL_WATCHDOG_MS to trip the "
                        "watchdog."),
    "CHAOS_STICKY_RID": _k("chaos", "-1 (off)", "Request id that faults "
                           "EVERY whole-batch wave it rides — the "
                           "deterministic poison-quarantine bisection "
                           "test vector."),

    # --- runtime concurrency sanitizer (servers/graftsan.py) --------------
    "GRAFTSAN": _k("sanitizer", "0",
                   "Enable the runtime concurrency sanitizer: "
                   "order-asserting lock proxies, boundary refcount "
                   "audits, terminal-item enforcement (`make sanitize`). "
                   "Env-only by design; zero overhead when unset."),
    "GRAFTSAN_SEED": _k("sanitizer", "0",
                        "Seed for the sanitizer's interleaving explorer; "
                        "a fixed seed replays the same perturbation "
                        "sequence."),

    # --- runtime microservice / persistence / tracing ---------------------
    "API_TYPE": _k("runtime", "REST,GRPC", "Transports to serve."),
    "SERVICE_TYPE": _k("runtime", "MODEL",
                       "Role of this unit (MODEL/ROUTER/TRANSFORMER/...)."),
    "PERSISTENCE": _k("runtime", "0", "Enable model-state persistence "
                      "(Redis-backed save/restore)."),
    "PREDICTIVE_UNIT_PARAMETERS": _k("runtime", "[]",
                                     "JSON list of unit parameters injected "
                                     "by the operator."),
    "PREDICTIVE_UNIT_SERVICE_PORT": _k("runtime", "9000",
                                       "Microservice listen port."),
    "PREDICTIVE_UNIT_ID": _k("runtime", "model/unit",
                             "Unit name stamped on responses and state keys."),
    "PREDICTOR_ID": _k("runtime", "predictor", "Predictor name for state "
                       "keys."),
    "SELDON_DEPLOYMENT_ID": _k("runtime", "dep", "Deployment name for state "
                               "keys."),
    "SELDON_TPU_FASTPATH": _k("runtime", "1", "Skip flask/reloader overhead "
                              "on the REST data path (`0` disables)."),
    "SELDON_TPU_STATE_DIR": _k("runtime", "/tmp/seldon-tpu-state",
                               "Local fallback directory for persisted "
                               "state when Redis is absent."),
    "PERSISTENCE_PUSH_FREQUENCY": _k("runtime", "300",
                                     "Seconds between persistence pushes."),
    "REDIS_SERVICE_HOST": _k("runtime", "(unset)", "Redis host; unset "
                             "selects the local-file persistence fallback."),
    "REDIS_SERVICE_PORT": _k("runtime", "6379", "Redis port."),
    "TRACING": _k("runtime", "0", "Enable request tracing."),
    "TRACING_FILE": _k("runtime", "(stdout)", "JSONL trace sink path."),
    "FLIGHT_RECORDER": _k("runtime", "0",
                          "Enable the engine flight recorder: a bounded "
                          "ring of lifecycle/boundary records served at "
                          "/debug/timeline (tools/trace_view.py renders "
                          "Perfetto JSON from it)."),
    "FLIGHT_RECORDER_SIZE": _k("runtime", "4096",
                               "Flight-recorder ring capacity (records); "
                               "older records are overwritten."),
    "COMPILE_LEDGER": _k("runtime", "0",
                         "Enable the compile ledger: every jitted engine "
                         "entry point registers its static-shape variant "
                         "key; post-warmup dispatches on undeclared keys "
                         "are recorded as live-retrace witnesses. Served "
                         "at /debug/compile; gated by `make "
                         "compile-audit`."),
    "HBM_LEDGER": _k("runtime", "0",
                     "Enable the HBM ledger: weights / KV reservation / "
                     "live KV / prefix cache / workspace live-byte "
                     "accounting with high-watermarks, served at "
                     "/debug/hbm and folded into probe_hbm."),
    "SCHED_LEDGER": _k("runtime", "0",
                       "Enable the scheduler waste ledger: per-boundary "
                       "goodput attribution (bucket/group padding, chunk "
                       "fragmentation, idle boundaries, preemption "
                       "churn), queue-wait decomposition, and a "
                       "conservation audit run under the bookkeeping "
                       "lock. Served at /debug/sched; gated by `make "
                       "sched-audit`."),
    "PILOT": _k("runtime", "0",
                "Enable graftpilot, the scheduler's feedback controller: "
                "\"1\" auto-tunes dispatch_token_budget / admission group "
                "size / the adaptive-chunk rung from the sched ledger's "
                "stall-vs-contention split (hysteresis, clamped envelope, "
                "cooldowns) and schedules EDF deadline-first with "
                "starvation-proof aging; \"hold\" keeps EDF + the decision "
                "ledger but freezes every knob (operator pinning). "
                "Implies a sched ledger. Every decision lands in the "
                "/debug/pilot ledger with its signal snapshot, rationale "
                "and counterfactual effect; gated by `make pilot-audit`."),
    "DISPATCH_TIMING": _k("runtime", "0",
                          "Per-variant dispatch duration histograms, "
                          "measured at the scheduler's deliberate sync "
                          "boundary; lands in EngineStats, Prometheus "
                          "(jaxserver_dispatch_ms_*), and the flight "
                          "recorder's dispatch records (per-variant "
                          "Perfetto lanes via tools/trace_view.py)."),
    "ROOF_LEDGER": _k("runtime", "0",
                      "Enable graftroof, the MFU/MBU roofline ledger: "
                      "closed-form FLOPs + HBM-bytes pricing of every "
                      "dispatch key joined with the measured wave timing "
                      "(implies DISPATCH_TIMING) into per-variant "
                      "compute/bandwidth/host-bound classification, plus "
                      "the host-pre / device / host-post boundary "
                      "decomposition with a 1% conservation audit. "
                      "Served at /debug/roof, mirrored as jaxserver_mfu "
                      "/ jaxserver_mbu / jaxserver_host_frac gauges and "
                      "flight-recorder roof records (Perfetto host/"
                      "device lanes); gated by `make roof-audit`."),
    "ROOF_PEAK_TFLOPS": _k("runtime", "(unset)",
                           "Operator override for the roofline's peak "
                           "dense TFLOPS (the MFU denominator). Unset: "
                           "the builtin per-platform table keyed on the "
                           "JAX device_kind, falling back to a one-shot "
                           "numpy microbench on unknown platforms."),
    "ROOF_PEAK_GBS": _k("runtime", "(unset)",
                        "Operator override for the roofline's peak HBM "
                        "GB/s (the MBU denominator). Resolution order "
                        "matches ROOF_PEAK_TFLOPS."),
    "TRACE_PROFILE_N": _k("runtime", "0",
                          "Capture a jax.profiler device trace over the "
                          "first N dispatched scheduler boundaries "
                          "(0 = off); profile-start/-stop markers land "
                          "in the flight recording."),
    "TRACE_PROFILE_DIR": _k("runtime", "/tmp/seldon-tpu-profile",
                            "Output directory for the TRACE_PROFILE_N "
                            "capture."),
    "PODINFO_ANNOTATIONS": _k("runtime", "/etc/podinfo/annotations",
                              "Downward-API annotations file."),
    "PREDICTOR_HOST": _k("runtime", "(unset)",
                         "Predictor endpoint an explainer calls back into."),

    # --- orchestrator -----------------------------------------------------
    "ENGINE_PREDICTOR": _k("orchestrator", "(unset)",
                           "Base64 predictor spec the service orchestrator "
                           "deserializes at boot."),
    "ENGINE_WORKERS": _k("orchestrator", "1",
                         "Orchestrator worker processes."),
    "SELDON_TPU_GRPC_WORKERS": _k("orchestrator", "8",
                                  "gRPC server thread-pool size."),
    "PORT": _k("orchestrator", "8080", "Request-logger listen port."),
    "SELDON_MESSAGE_LOGGING_SERVICE": _k("orchestrator", "(disabled)",
                                         "URL of the request/response "
                                         "logging sink."),

    # --- operator / storage ----------------------------------------------
    "WEBHOOK_CERT_DIR": _k("operator-storage",
                           "/tmp/k8s-webhook-server/serving-certs",
                           "Admission-webhook TLS cert directory."),
    "KUBECONFIG": _k("operator-storage", "~/.kube/config",
                     "Kubeconfig path when running out-of-cluster."),
    "SELDON_TPU_LOCALSTORE_DEBUG": _k("operator-storage", "0",
                                      "Verbose local object-store logging."),
    "SELDON_TPU_MODEL_DIR": _k("operator-storage", "/mnt/models",
                               "Download target for model artifacts."),
    "AZURE_SAS_TOKEN": _k("operator-storage", "(unset)",
                          "SAS token appended to Azure blob downloads."),
    "SAGEMAKER_ENDPOINT_NAME": _k("operator-storage", "(unset)",
                                  "SageMaker endpoint the proxy server "
                                  "invokes."),
    "SAGEMAKER_RUNTIME_URL": _k("operator-storage", "(regional default)",
                                "Override for the SageMaker runtime URL."),

    # --- multi-host TPU slice (parallel/distributed.py) -------------------
    "TPU_WORKER_HOSTNAMES_SVC": _k("distributed", "(unset)",
                                   "Headless-service name enumerating slice "
                                   "workers."),
    "TPU_WORKER_COUNT": _k("distributed", "1",
                           "Expected process count in the slice."),
    "TPU_COORDINATOR_PORT": _k("distributed", "(jax default)",
                               "Coordinator port for "
                               "jax.distributed.initialize."),

    # --- bench & probe tools (tools/*.py, CPU-smoke friendly) -------------
    "MB_PRESET": _k("bench-tools", "bench-1b", "Decode microbench model "
                    "preset (also profile_decode)."),
    "MB_SLOTS": _k("bench-tools", "160", "Microbench batch slots."),
    "MB_WINDOW": _k("bench-tools", "257", "Microbench KV window."),
    "MB_ACT": _k("bench-tools", "(follows weights)", "Microbench activation "
                 "dtype."),
    "MB_DRAFT": _k("bench-tools", "(unset)", "Draft-model preset for the "
                   "`--spec k` microbench mode; adds the draft dispatch "
                   "to the wave cost."),
    "MB_RAGGED_CHUNK": _k("bench-tools", "16", "Per-slot chunk capacity "
                          "C for the `--ragged` kernel microbench wave."),
    "MB_PALLAS": _k("bench-tools", "(unset)", "Non-empty adds the pallas "
                    "leg (interpret-mode off-TPU — slow) to the "
                    "`--ragged` kernel microbench."),
    "TUNE_ACT": _k("bench-tools", "int8", "Activation dtype for the 8b "
                   "tuning sweep."),
    "PROBE_PRESET": _k("bench-tools", "llama3-8b", "Slot-cliff probe preset "
                       "(`tiny` = CPU smoke)."),
    "PROBE_PAGED": _k("bench-tools", "0", "Add the paged-KV sweep to "
                      "probe_hbm / probe_slot_cliff."),
    "PB_PRESET": _k("bench-tools", "tiny", "Prefix-cache probe preset."),
    "PB_PROMPT": _k("bench-tools", "128", "Prefix probe prompt length."),
    "PB_BLOCK": _k("bench-tools", "16", "Prefix probe trie block size."),
    "PB_NREQ": _k("bench-tools", "16", "Prefix probe request count."),
    "PB_KV": _k("bench-tools", "(preset dtype)", "Prefix probe KV dtype."),
    "PB_SHARED_FRAC": _k("bench-tools", "0.5", "Fraction of requests "
                         "sharing the warm prefix."),
    "PC_PRESET": _k("bench-tools", "tiny", "Chunked-prefill probe preset."),
    "PC_PROMPT": _k("bench-tools", "32", "Chunked probe short-prompt "
                    "length."),
    "PC_LONG": _k("bench-tools", "8*PC_PROMPT", "Chunked probe interloper "
                  "prompt length."),
    "PC_CHUNK": _k("bench-tools", "PC_PROMPT", "Prefill chunk length."),
    "PC_BUDGET": _k("bench-tools", "PC_CHUNK", "Dispatch token budget."),
    "PC_STREAMS": _k("bench-tools", "4", "Concurrent decode streams."),
    "PC_NEW": _k("bench-tools", "64", "New tokens per stream."),
    "PC_KV": _k("bench-tools", "(preset dtype)", "Chunked probe KV dtype."),
    "CH_PRESET": _k("bench-tools", "tiny", "Chaos probe preset."),
    "CH_N": _k("bench-tools", "200", "Chaos probe request count."),
    "CH_SEED": _k("bench-tools", "0", "Chaos probe fault seed."),
    "CH_DISPATCH_FAIL": _k("bench-tools", "0.02", "Chaos probe dispatch "
                           "fault rate."),
    "CH_ALLOC_FAIL": _k("bench-tools", "0.02", "Chaos probe alloc fault "
                        "rate."),
    "CH_SLOW": _k("bench-tools", "0.05", "Chaos probe slow-boundary rate."),
    "CH_DISCONNECT": _k("bench-tools", "0.01", "Chaos probe disconnect "
                        "rate."),
    "CH_PAGED": _k("bench-tools", "0", "Chaos probe paged-KV mode."),
    "CH_DEADLINE_FRAC": _k("bench-tools", "0.1", "Fraction of chaos probe "
                           "requests given tight deadlines."),
    "CH_CANCEL_FRAC": _k("bench-tools", "0.1", "Fraction of chaos probe "
                         "requests cancelled mid-flight."),

    # --- bench harness (bench.py / bench_orchestrator.py) -----------------
    "BENCH_PRESET": _k("bench-harness", "llama3-8b",
                       "Model preset for the headline bench run "
                       "(`tiny` = CPU smoke)."),
    "BENCH_SLOTS": _k("bench-harness", "0 (192 for llama3-8b, else 160)",
                      "Decode batch slots; 0 picks the measured per-preset "
                      "knee."),
    "BENCH_NREQ": _k("bench-harness", "0 (2x slots)",
                     "Requests in the throughput phase."),
    "BENCH_ADMIT": _k("bench-harness", "0 (16 for llama3-8b, else 8)",
                      "Max admissions per scheduler step."),
    "BENCH_PROMPT": _k("bench-harness", "128", "Prompt length in tokens."),
    "BENCH_NEW": _k("bench-harness", "128", "New tokens per request."),
    "BENCH_CHUNK": _k("bench-harness", "64", "Decode dispatch chunk."),
    "BENCH_KV": _k("bench-harness", "int8", "KV cache dtype."),
    "BENCH_ATTN": _k("bench-harness", "(model default)",
                     "Attention kernel override."),
    "BENCH_WEIGHTS": _k("bench-harness", "int8",
                        "Weight dtype (`bf16` reverts weight-only int8)."),
    "BENCH_ACT": _k("bench-harness", "int8",
                    "W8A8 matmul activation dtype (`bf16` reverts)."),
    "BENCH_PREFIX": _k("bench-harness", "0",
                       "Run the shared-prefix cache phase."),
    "BENCH_PREFIX_BLOCK": _k("bench-harness", "16",
                             "Prefix phase trie block size."),
    "BENCH_PREFIX_NREQ": _k("bench-harness", "24",
                            "Prefix phase request count."),
    "BENCH_CHUNKED": _k("bench-harness", "0",
                        "Run the chunked-prefill interference phase."),
    "BENCH_CHUNKED_STREAMS": _k("bench-harness", "6",
                                "Chunked phase concurrent decode streams."),
    "BENCH_CHUNKED_LONG_X": _k("bench-harness", "8",
                               "Chunked phase interloper prompt length, as "
                               "a multiple of BENCH_PROMPT."),
    "BENCH_PAGED": _k("bench-harness", "0",
                      "Run the paged-vs-dense fixed-HBM phase."),
    "BENCH_PAGED_DENSE_SLOTS": _k("bench-harness", "4",
                                  "Dense-slab slot count the paged phase "
                                  "compares against."),
    "BENCH_PAGED_KV_BLOCK": _k("bench-harness", "16",
                               "Paged phase KV block size."),
    "BENCH_RAGGED": _k("bench-harness", "0",
                       "Run the ragged-dispatch phase: the same closed "
                       "wave RAGGED=1 vs bucketed at equal hardware, "
                       "reporting req/s, padding_waste_frac, compile "
                       "variant count, and the measured speedup vs the "
                       "waste_roofline prediction."),
    "BENCH_SPEC": _k("bench-harness", "0",
                     "Run the speculative-decoding phase: the same "
                     "greedy closed wave SPEC on vs off at equal "
                     "hardware, asserting bit-identical streams and "
                     "reporting per-leg decode tok/s, dispatches/token "
                     "and the acceptance rate (bench_compare gates "
                     "acceptance_rate higher-is-better and tok_s "
                     "no-regression)."),
    "BENCH_SPEC_K": _k("bench-harness", "4",
                       "Draft tokens per verify wave in the spec "
                       "phase."),
    "BENCH_SPEC_DRAFT": _k("bench-harness", "self",
                           "Spec phase drafter: `self` (target weights "
                           "— the acceptance upper bound), empty for "
                           "the host n-gram drafter, or a preset name "
                           "for a resident draft model."),
    "BENCH_MESH": _k("bench-harness", "0",
                     "Run the graftmesh phase: the same greedy ragged "
                     "closed wave tp=BENCH_MESH_TP vs single-chip at "
                     "EQUAL engine config, asserting bit-identical "
                     "streams and recording per-device HBM "
                     "(bench_compare gates bytes_per_device and "
                     "kv_per_device_frac lower-is-better). On fake "
                     "devices the speedup is not meaningful; the parity "
                     "and sharding-dividend record is."),
    "BENCH_MESH_TP": _k("bench-harness", "2",
                        "TP group size for the mesh phase leg."),
    "BENCH_HEAL": _k("bench-harness", "0",
                     "Run the graftheal phase: the same greedy closed "
                     "wave clean vs under seeded CHAOS dispatch faults "
                     "with HEAL on, asserting resurrected streams "
                     "bit-identical to the clean leg and reporting "
                     "goodput_retained_frac (bench_compare gates it "
                     "higher-is-better) and user_visible_errors "
                     "(lower-is-better, exact)."),
    "BENCH_HEAL_FAULT": _k("bench-harness", "0.05",
                           "Dispatch-fault probability for the heal "
                           "phase's chaos leg."),
    "BENCH_SLO": _k("bench-harness", "1 for bench-1b, else 0",
                    "Run the TTFT SLO search phase."),
    "BENCH_SLO_CHUNK": _k("bench-harness", "0 (adaptive)",
                          "Pin a fixed dispatch chunk for the SLO search "
                          "instead of occupancy-adaptive chunking."),
    "BENCH_PILOT": _k("bench-harness", "0",
                      "Run the pilot phase: a mixed-deadline closed wave "
                      "twice at equal hardware — PILOT=1 vs pilot off — "
                      "reporting slo_goodput, decision count, EDF "
                      "inversions and final knob values for both legs."),
    "BENCH_SECOND_PRESET": _k("bench-harness",
                              "bench-1b for llama3-8b, else (empty)",
                              "Trailing deployment-proxy preset; empty "
                              "disables the second phase."),
    "BENCH_SECOND_SLOTS": _k("bench-harness", "0 (160)",
                             "Slots for the trailing preset run."),
    "BENCH_SECOND_SLO": _k("bench-harness", "1",
                           "Run the SLO search in the trailing phase."),
    "BENCH_BACKEND_WAIT": _k("bench-harness", "900",
                             "Seconds the supervisor polls TPU bring-up "
                             "before giving up (tunneled-rig outage "
                             "proofing)."),
    "BENCH_ATTEMPT_TIMEOUT": _k("bench-harness", "4500",
                                "Per-attempt wall clock for the measurement "
                                "child process."),
    "BENCH_ATTEMPTS": _k("bench-harness", "2",
                         "Measurement child retry budget."),
    "BENCH_REQUIRE_TPU": _k("bench-harness",
                            "0 when JAX_PLATFORMS=cpu, else 1",
                            "Whether a cpu-only backend fails the bring-up "
                            "probe."),
    "_BENCH_CHILD": _k("bench-harness", "(set by the supervisor)",
                       "Internal parent->child marker; `1` makes bench.py "
                       "run the measurement instead of supervising."),
    "BENCH_ORCH_CLIENTS": _k("bench-harness", "32",
                             "Orchestrator bench concurrent clients."),
    "BENCH_ORCH_CLIENT_PROCS": _k("bench-harness", "2",
                                  "Client processes generating load."),
    "BENCH_ORCH_SECONDS": _k("bench-harness", "12",
                             "Measurement window per configuration."),
    "BENCH_ORCH_REPEATS": _k("bench-harness", "3",
                             "Repeats per configuration (best kept)."),
    "BENCH_ORCH_TRANSPORTS": _k("bench-harness", "rest,grpc",
                                "Transports to sweep."),
    "BENCH_ORCH_PAYLOADS": _k("bench-harness", "ndarray,dense",
                              "Payload shapes to sweep."),
    "BENCH_ORCH_GRAPHS": _k("bench-harness", "inproc,netunit",
                            "Graph topologies to sweep (in-process stub vs "
                            "real microservice subprocess)."),
    "BENCH_ORCH_FAST": _k("bench-harness", "1",
                          "Expose the framed-proto fast lane on port+1; "
                          "`0` pins the hop to full gRPC for A/B."),

    # --- platform (owned by JAX / Kubernetes / cloud SDKs) ----------------
    "JAX_PLATFORMS": _k("platform", "(auto)", "JAX backend selection; "
                        "`cpu` pins tests and probes off the TPU."),
    "XLA_FLAGS": _k("platform", "(unset)", "XLA compiler flags; the entry "
                    "shim appends host-platform device-count flags for "
                    "CPU smoke runs."),
    "KUBERNETES_SERVICE_HOST": _k("platform", "kubernetes.default.svc",
                                  "In-cluster API host (set by the "
                                  "kubelet)."),
    "KUBERNETES_SERVICE_PORT": _k("platform", "443", "In-cluster API port."),
    "AWS_ACCESS_KEY_ID": _k("platform", "(unset)", "SageMaker proxy "
                            "credentials."),
    "AWS_SECRET_ACCESS_KEY": _k("platform", "(unset)", "SageMaker proxy "
                                "credentials."),
    "AWS_SESSION_TOKEN": _k("platform", "(unset)", "SageMaker proxy "
                            "credentials."),
    "AWS_REGION": _k("platform", "us-east-1", "SageMaker proxy region."),
    "HOSTNAME": _k("platform", "(pod name)", "Used to derive the process "
                   "index within a TPU slice."),
    "PYTHONPATH": _k("platform", "(inherited)", "Propagated to operator "
                     "local-mode child processes."),
}
