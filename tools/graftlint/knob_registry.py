"""The env-knob registry: every environment variable the tree may read.

``docs/knobs.md`` is generated from this table plus the read sites the
env-knob pass discovers (``python -m tools.graftlint --gen-knobs``).
Adding an ``os.environ`` read without registering it here fails
``make lint``.

Groups in ``EXTERNAL_GROUPS`` are exempt from the stale-entry check: the
value is owned by the platform (JAX, the kubelet, cloud SDKs) so a knob
may stay registered even when no scanned file currently reads it.

Bench-harness phase knobs (``BENCH_*``) are documented in
``docs/benchmarking.md``; ``bench.py`` lives outside the linted tree.
"""

EXTERNAL_GROUPS = {"platform"}


def _k(group, default, desc):
    return {"group": group, "default": default, "desc": desc}


KNOBS = {
    # --- engine serving (servers/jaxserver.py unit-param fallbacks) -------
    "WEIGHT_DTYPE": _k("engine-serving", "(checkpoint dtype)",
                       "Override weight dtype at load, e.g. `int8` to serve a "
                       "bf16 HF checkpoint quantized."),
    "ACT_DTYPE": _k("engine-serving", "(follows weights)",
                    "W8A8 activation dtype for int8 weights (`int8`/`bf16`)."),
    "PREFIX_CACHE": _k("engine-serving", "0",
                       "Enable prompt-prefix KV reuse (radix trie over "
                       "block-aligned prefixes)."),
    "PREFIX_CACHE_MB": _k("engine-serving", "0 (auto)",
                          "HBM budget for retained prefix KV, in MiB."),
    "CHUNKED_PREFILL": _k("engine-serving", "0",
                          "Interleave prefill chunks with decode steps "
                          "(stall-free scheduling)."),
    "PREFILL_CHUNK": _k("engine-serving", "0 (model block)",
                        "Prefill chunk length in tokens."),
    "DISPATCH_TOKEN_BUDGET": _k("engine-serving", "0 (auto)",
                                "Per-dispatch token budget shared by decode "
                                "and prefill chunks."),
    "PAGED_KV": _k("engine-serving", "0",
                   "Paged KV cache: global block pool + per-slot block "
                   "tables instead of dense per-slot slabs."),
    "KV_BLOCK": _k("engine-serving", "0 (model default)",
                   "KV block size in tokens (paged mode)."),
    "KV_POOL_MB": _k("engine-serving", "0 (dense-equivalent)",
                     "KV pool size in HBM MiB (paged mode)."),
    "MAX_QUEUE": _k("engine-serving", "0 (unbounded)",
                    "Admission queue bound; past it submit() sheds with "
                    "a retriable 429 EngineOverloaded."),
    "DEFAULT_DEADLINE_MS": _k("engine-serving", "0 (none)",
                              "Default per-request TTL in ms; per-request "
                              "deadline_ms still wins."),

    # --- chaos fault injection (servers/chaos.py, env-only by design) -----
    "CHAOS": _k("chaos", "0", "Master switch (`1`/`true`/`yes`); never a "
                "unit parameter, so manifests cannot enable it by accident."),
    "CHAOS_SEED": _k("chaos", "0", "Seed for the deterministic fault "
                     "sequence; replays a failure byte-for-byte."),
    "CHAOS_DISPATCH_FAIL": _k("chaos", "0", "Probability a dispatch raises "
                              "(drives _fail_all rebuild)."),
    "CHAOS_ALLOC_FAIL": _k("chaos", "0", "Probability a paged-pool "
                           "allocation is refused."),
    "CHAOS_SLOW_BOUNDARY": _k("chaos", "0", "Probability a boundary fetch "
                              "is artificially delayed."),
    "CHAOS_SLOW_MS": _k("chaos", "5", "Delay for a slow boundary, ms."),
    "CHAOS_DISCONNECT": _k("chaos", "0", "Probability a client disconnect "
                           "is injected (stream close -> cancel)."),

    # --- runtime microservice / persistence / tracing ---------------------
    "API_TYPE": _k("runtime", "REST,GRPC", "Transports to serve."),
    "SERVICE_TYPE": _k("runtime", "MODEL",
                       "Role of this unit (MODEL/ROUTER/TRANSFORMER/...)."),
    "PERSISTENCE": _k("runtime", "0", "Enable model-state persistence "
                      "(Redis-backed save/restore)."),
    "PREDICTIVE_UNIT_PARAMETERS": _k("runtime", "[]",
                                     "JSON list of unit parameters injected "
                                     "by the operator."),
    "PREDICTIVE_UNIT_SERVICE_PORT": _k("runtime", "9000",
                                       "Microservice listen port."),
    "PREDICTIVE_UNIT_ID": _k("runtime", "model/unit",
                             "Unit name stamped on responses and state keys."),
    "PREDICTOR_ID": _k("runtime", "predictor", "Predictor name for state "
                       "keys."),
    "SELDON_DEPLOYMENT_ID": _k("runtime", "dep", "Deployment name for state "
                               "keys."),
    "SELDON_TPU_FASTPATH": _k("runtime", "1", "Skip flask/reloader overhead "
                              "on the REST data path (`0` disables)."),
    "SELDON_TPU_STATE_DIR": _k("runtime", "/tmp/seldon-tpu-state",
                               "Local fallback directory for persisted "
                               "state when Redis is absent."),
    "PERSISTENCE_PUSH_FREQUENCY": _k("runtime", "300",
                                     "Seconds between persistence pushes."),
    "REDIS_SERVICE_HOST": _k("runtime", "(unset)", "Redis host; unset "
                             "selects the local-file persistence fallback."),
    "REDIS_SERVICE_PORT": _k("runtime", "6379", "Redis port."),
    "TRACING": _k("runtime", "0", "Enable request tracing."),
    "TRACING_FILE": _k("runtime", "(stdout)", "JSONL trace sink path."),
    "PODINFO_ANNOTATIONS": _k("runtime", "/etc/podinfo/annotations",
                              "Downward-API annotations file."),
    "PREDICTOR_HOST": _k("runtime", "(unset)",
                         "Predictor endpoint an explainer calls back into."),

    # --- orchestrator -----------------------------------------------------
    "ENGINE_PREDICTOR": _k("orchestrator", "(unset)",
                           "Base64 predictor spec the service orchestrator "
                           "deserializes at boot."),
    "ENGINE_WORKERS": _k("orchestrator", "1",
                         "Orchestrator worker processes."),
    "SELDON_TPU_GRPC_WORKERS": _k("orchestrator", "8",
                                  "gRPC server thread-pool size."),
    "PORT": _k("orchestrator", "8080", "Request-logger listen port."),
    "SELDON_MESSAGE_LOGGING_SERVICE": _k("orchestrator", "(disabled)",
                                         "URL of the request/response "
                                         "logging sink."),

    # --- operator / storage ----------------------------------------------
    "WEBHOOK_CERT_DIR": _k("operator-storage",
                           "/tmp/k8s-webhook-server/serving-certs",
                           "Admission-webhook TLS cert directory."),
    "KUBECONFIG": _k("operator-storage", "~/.kube/config",
                     "Kubeconfig path when running out-of-cluster."),
    "SELDON_TPU_LOCALSTORE_DEBUG": _k("operator-storage", "0",
                                      "Verbose local object-store logging."),
    "SELDON_TPU_MODEL_DIR": _k("operator-storage", "/mnt/models",
                               "Download target for model artifacts."),
    "AZURE_SAS_TOKEN": _k("operator-storage", "(unset)",
                          "SAS token appended to Azure blob downloads."),
    "SAGEMAKER_ENDPOINT_NAME": _k("operator-storage", "(unset)",
                                  "SageMaker endpoint the proxy server "
                                  "invokes."),
    "SAGEMAKER_RUNTIME_URL": _k("operator-storage", "(regional default)",
                                "Override for the SageMaker runtime URL."),

    # --- multi-host TPU slice (parallel/distributed.py) -------------------
    "TPU_WORKER_HOSTNAMES_SVC": _k("distributed", "(unset)",
                                   "Headless-service name enumerating slice "
                                   "workers."),
    "TPU_WORKER_COUNT": _k("distributed", "1",
                           "Expected process count in the slice."),
    "TPU_COORDINATOR_PORT": _k("distributed", "(jax default)",
                               "Coordinator port for "
                               "jax.distributed.initialize."),

    # --- bench & probe tools (tools/*.py, CPU-smoke friendly) -------------
    "MB_PRESET": _k("bench-tools", "bench-1b", "Decode microbench model "
                    "preset (also profile_decode)."),
    "MB_SLOTS": _k("bench-tools", "160", "Microbench batch slots."),
    "MB_WINDOW": _k("bench-tools", "257", "Microbench KV window."),
    "MB_ACT": _k("bench-tools", "(follows weights)", "Microbench activation "
                 "dtype."),
    "TUNE_ACT": _k("bench-tools", "int8", "Activation dtype for the 8b "
                   "tuning sweep."),
    "PROBE_PRESET": _k("bench-tools", "llama3-8b", "Slot-cliff probe preset "
                       "(`tiny` = CPU smoke)."),
    "PROBE_PAGED": _k("bench-tools", "0", "Add the paged-KV sweep to "
                      "probe_hbm / probe_slot_cliff."),
    "PB_PRESET": _k("bench-tools", "tiny", "Prefix-cache probe preset."),
    "PB_PROMPT": _k("bench-tools", "128", "Prefix probe prompt length."),
    "PB_BLOCK": _k("bench-tools", "16", "Prefix probe trie block size."),
    "PB_NREQ": _k("bench-tools", "16", "Prefix probe request count."),
    "PB_KV": _k("bench-tools", "(preset dtype)", "Prefix probe KV dtype."),
    "PB_SHARED_FRAC": _k("bench-tools", "0.5", "Fraction of requests "
                         "sharing the warm prefix."),
    "PC_PRESET": _k("bench-tools", "tiny", "Chunked-prefill probe preset."),
    "PC_PROMPT": _k("bench-tools", "32", "Chunked probe short-prompt "
                    "length."),
    "PC_LONG": _k("bench-tools", "8*PC_PROMPT", "Chunked probe interloper "
                  "prompt length."),
    "PC_CHUNK": _k("bench-tools", "PC_PROMPT", "Prefill chunk length."),
    "PC_BUDGET": _k("bench-tools", "PC_CHUNK", "Dispatch token budget."),
    "PC_STREAMS": _k("bench-tools", "4", "Concurrent decode streams."),
    "PC_NEW": _k("bench-tools", "64", "New tokens per stream."),
    "PC_KV": _k("bench-tools", "(preset dtype)", "Chunked probe KV dtype."),
    "CH_PRESET": _k("bench-tools", "tiny", "Chaos probe preset."),
    "CH_N": _k("bench-tools", "200", "Chaos probe request count."),
    "CH_SEED": _k("bench-tools", "0", "Chaos probe fault seed."),
    "CH_DISPATCH_FAIL": _k("bench-tools", "0.02", "Chaos probe dispatch "
                           "fault rate."),
    "CH_ALLOC_FAIL": _k("bench-tools", "0.02", "Chaos probe alloc fault "
                        "rate."),
    "CH_SLOW": _k("bench-tools", "0.05", "Chaos probe slow-boundary rate."),
    "CH_DISCONNECT": _k("bench-tools", "0.01", "Chaos probe disconnect "
                        "rate."),
    "CH_PAGED": _k("bench-tools", "0", "Chaos probe paged-KV mode."),
    "CH_DEADLINE_FRAC": _k("bench-tools", "0.1", "Fraction of chaos probe "
                           "requests given tight deadlines."),
    "CH_CANCEL_FRAC": _k("bench-tools", "0.1", "Fraction of chaos probe "
                         "requests cancelled mid-flight."),

    # --- platform (owned by JAX / Kubernetes / cloud SDKs) ----------------
    "JAX_PLATFORMS": _k("platform", "(auto)", "JAX backend selection; "
                        "`cpu` pins tests and probes off the TPU."),
    "KUBERNETES_SERVICE_HOST": _k("platform", "kubernetes.default.svc",
                                  "In-cluster API host (set by the "
                                  "kubelet)."),
    "KUBERNETES_SERVICE_PORT": _k("platform", "443", "In-cluster API port."),
    "AWS_ACCESS_KEY_ID": _k("platform", "(unset)", "SageMaker proxy "
                            "credentials."),
    "AWS_SECRET_ACCESS_KEY": _k("platform", "(unset)", "SageMaker proxy "
                                "credentials."),
    "AWS_SESSION_TOKEN": _k("platform", "(unset)", "SageMaker proxy "
                            "credentials."),
    "AWS_REGION": _k("platform", "us-east-1", "SageMaker proxy region."),
    "HOSTNAME": _k("platform", "(pod name)", "Used to derive the process "
                   "index within a TPU slice."),
    "PYTHONPATH": _k("platform", "(inherited)", "Propagated to operator "
                     "local-mode child processes."),
}
