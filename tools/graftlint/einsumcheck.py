"""Einsum silent-broadcast and masked-softmax dtype checks (graftnum).

``jnp.einsum`` follows NumPy broadcasting: a repeated label whose two
bindings have different sizes does NOT raise when one of them is 1 —
it silently broadcasts.  That is exactly the PR 16 bug where a KV-head
dim expanded with ``[:, None]`` (size 1) met the real head dim under
the same label and every KV head summed ALL heads' values, with no
shape error and plausible-looking output.

Rule ``einsum-broadcast``: for every ``jnp.einsum`` / ``lax.dot_general``
whose operand shapes are statically traceable (tuple-unpacked
``.shape``, ``reshape``/``zeros``/``ones``/``full``/``broadcast_to``
literals — the descriptor-driven fixed buffers of the ragged path),
flag a repeated label binding a literal size-1 dimension against a
dimension of literal size > 1 or a named (symbolic) size.  Two
bindings of the SAME symbol (legitimate batch that may be 1 at
runtime) are clean — the trap is a *structural* 1 meeting a real axis.

Rule ``mask-dtype``: the masked-softmax contract — the additive mask
and the scores combine in f32, rounding only at declared boundaries.
``jnp.where(cond, scores, -1e30)`` (or NEG_INF) where the scores
branch is cast to bf16/f16 means the -1e30 fill and any downstream
max/exp run in low precision: bf16 has 8 mantissa bits, so near-tied
logits flip under the mask instead of being suppressed exactly.

Waive with ``# graftlint: allow(einsum-broadcast) why`` /
``# graftlint: allow(mask-dtype) why``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.graftlint import core

RULE_BROADCAST = "einsum-broadcast"
RULE_MASK = "mask-dtype"

# A shape is a tuple of dims; each dim is ("lit", int) | ("sym", str).
Dim = Tuple[str, object]
Shape = Tuple[Dim, ...]

_LOW_FLOATS = {"bfloat16", "float16"}


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _dim_of(node: ast.expr) -> Optional[Dim]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return ("lit", node.value)
    if isinstance(node, ast.Name):
        return ("sym", node.id)
    return None


def _shape_literal(node: ast.expr) -> Optional[Shape]:
    """Parse a (a, b, 1, c) shape expression; None when any dim is
    untraceable (opaque dims would poison size comparisons)."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in node.elts:
        d = _dim_of(e)
        if d is None:
            return None
        dims.append(d)
    return tuple(dims)


def _shape_env(fn: ast.AST) -> Dict[str, Shape]:
    """Function-local symbolic shapes:
      B, T, H, D = x.shape     -> x: (B, T, H, D)
      y = x.reshape(B, 1, D)   -> y: (B, 1, D)
      z = jnp.zeros((B, T))    -> z: (B, T)    (ones/full/empty too)
      w = jnp.broadcast_to(v, (B, T, D)) -> w: (B, T, D)
    Any other assignment to a tracked name drops it."""
    env: Dict[str, Shape] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        value = node.value

        # B, T, H = x.shape  — names the dims of x.
        if (isinstance(target, ast.Tuple)
                and isinstance(value, ast.Attribute)
                and value.attr == "shape"
                and isinstance(value.value, ast.Name)
                and all(isinstance(e, ast.Name) for e in target.elts)):
            env[value.value.id] = tuple(
                ("sym", e.id) for e in target.elts)  # type: ignore
            continue

        shape: Optional[Shape] = None
        if isinstance(value, ast.Call):
            tail = _call_tail(value.func)
            if tail == "reshape" and value.args:
                if len(value.args) == 1:
                    shape = _shape_literal(value.args[0])
                else:
                    dims = [_dim_of(a) for a in value.args]
                    if all(d is not None for d in dims):
                        shape = tuple(dims)  # type: ignore
            elif tail in ("zeros", "ones", "empty", "full") and value.args:
                shape = _shape_literal(value.args[0])
                if shape is None:
                    d = _dim_of(value.args[0])
                    if d is not None:
                        shape = (d,)
            elif tail == "broadcast_to" and len(value.args) >= 2:
                shape = _shape_literal(value.args[1])

        if isinstance(target, ast.Name):
            if shape is not None:
                env[target.id] = shape
            else:
                env.pop(target.id, None)
    return env


def _operand_shape(node: ast.expr, env: Dict[str, Shape]) -> Optional[Shape]:
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _parse_spec(spec: str) -> Optional[List[str]]:
    """Input label groups of an einsum spec; None for forms this pass
    doesn't model (ellipsis, implicit output is fine)."""
    spec = spec.replace(" ", "")
    if "..." in spec:
        return None
    ins = spec.split("->")[0]
    groups = ins.split(",")
    if not all(g.isalpha() for g in groups):
        return None
    return groups


def _broadcast_conflict(a: Dim, b: Dim) -> bool:
    """True when one binding is a structural literal 1 and the other
    is a literal > 1 or a symbol (a real axis).  Same symbol twice, or
    equal literals, is clean."""
    for x, y in ((a, b), (b, a)):
        if x == ("lit", 1):
            if y[0] == "lit" and y[1] != 1:
                return True
            if y[0] == "sym":
                return True
    return False


def _fmt_dim(d: Dim) -> str:
    return str(d[1])


def _check_einsum(sf: core.SourceFile, fn: ast.AST, call: ast.Call,
                  env: Dict[str, Shape],
                  findings: List[core.Finding]) -> bool:
    """Returns True when the site had traceable shapes (for stats)."""
    if not call.args or not isinstance(call.args[0], ast.Constant):
        return False
    spec = call.args[0].value
    if not isinstance(spec, str):
        return False
    groups = _parse_spec(spec)
    if groups is None:
        return False
    operands = call.args[1:1 + len(groups)]
    if len(operands) != len(groups):
        return False

    bindings: Dict[str, List[Tuple[int, Dim]]] = {}
    traced = False
    for oi, (labels, op) in enumerate(zip(groups, operands)):
        shape = _operand_shape(op, env)
        if shape is None or len(shape) != len(labels):
            continue
        traced = True
        for label, dim in zip(labels, shape):
            bindings.setdefault(label, []).append((oi, dim))

    for label, bound in bindings.items():
        for i in range(len(bound)):
            for j in range(i + 1, len(bound)):
                (oi, da), (oj, db) = bound[i], bound[j]
                if not _broadcast_conflict(da, db):
                    continue
                if core.allowed_above(sf, RULE_BROADCAST, call.lineno,
                                      fn.lineno):
                    return traced
                findings.append(core.make_finding(
                    sf, RULE_BROADCAST, call.lineno,
                    f"einsum '{spec}' label '{label}' binds size "
                    f"{_fmt_dim(da)} (operand {oi}) against size "
                    f"{_fmt_dim(db)} (operand {oj}) — a size-1 dim "
                    f"under a repeated label broadcasts silently "
                    f"instead of raising, summing across the real "
                    f"axis (the PR 16 every-KV-head-summed-ALL-heads "
                    f"bug)",
                    hint="squeeze the size-1 axis out of the spec, or "
                         "give it its own output label if the "
                         "broadcast is intended",
                    qualname=core.qualname_of(call)))
                return traced
    return traced


def _literal_int_pairs(node: ast.expr) -> Optional[List[Tuple[int, int]]]:
    """((l0, r0), ...) from a dimension_numbers pair literal like
    ((1,), (0,))."""
    if not isinstance(node, (ast.Tuple, ast.List)) or len(node.elts) != 2:
        return None
    sides = []
    for side in node.elts:
        if not isinstance(side, (ast.Tuple, ast.List)):
            return None
        idxs = []
        for e in side.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            idxs.append(e.value)
        sides.append(idxs)
    if len(sides[0]) != len(sides[1]):
        return None
    return list(zip(sides[0], sides[1]))


def _check_dot_general(sf: core.SourceFile, fn: ast.AST, call: ast.Call,
                       env: Dict[str, Shape],
                       findings: List[core.Finding]) -> bool:
    if len(call.args) < 3:
        return False
    lhs = _operand_shape(call.args[0], env)
    rhs = _operand_shape(call.args[1], env)
    dn = call.args[2]
    if lhs is None or rhs is None:
        return False
    if not isinstance(dn, (ast.Tuple, ast.List)) or len(dn.elts) != 2:
        return False
    contract = _literal_int_pairs(dn.elts[0])
    batch = _literal_int_pairs(dn.elts[1])
    if contract is None or batch is None:
        return False
    for kind, pairs in (("contracting", contract), ("batch", batch)):
        for li, ri in pairs:
            if li >= len(lhs) or ri >= len(rhs):
                continue
            if _broadcast_conflict(lhs[li], rhs[ri]):
                if core.allowed_above(sf, RULE_BROADCAST, call.lineno,
                                      fn.lineno):
                    return True
                findings.append(core.make_finding(
                    sf, RULE_BROADCAST, call.lineno,
                    f"dot_general {kind} dims pair lhs[{li}]="
                    f"{_fmt_dim(lhs[li])} with rhs[{ri}]="
                    f"{_fmt_dim(rhs[ri])} — a structural size-1 axis "
                    f"against a real axis broadcasts or miscontracts "
                    f"silently",
                    hint="squeeze the size-1 axis before the "
                         "contraction",
                    qualname=core.qualname_of(call)))
                return True
    return True


def _is_neg_inf(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)):
        return node.value <= -1e9
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, (int, float))):
        return node.operand.value >= 1e9
    if isinstance(node, ast.Name) and "NEG_INF" in node.id.upper():
        return True
    if isinstance(node, ast.Attribute) and "NEG_INF" in node.attr.upper():
        return True
    return False


def _low_precision_cast(node: ast.expr) -> Optional[int]:
    """Line of a bf16/f16 astype inside the scores branch, if any."""
    for n in ast.walk(node):
        if not (isinstance(n, ast.Call)
                and _call_tail(n.func) == "astype" and n.args):
            continue
        arg = n.args[0]
        if isinstance(arg, ast.Attribute) and arg.attr in _LOW_FLOATS:
            return n.lineno
        if isinstance(arg, ast.Name) and arg.id in _LOW_FLOATS:
            return n.lineno
        if (isinstance(arg, ast.Constant)
                and arg.value in _LOW_FLOATS):
            return n.lineno
    return None


def _check_mask(sf: core.SourceFile, fn: ast.AST, call: ast.Call,
                findings: List[core.Finding]) -> None:
    if _call_tail(call.func) != "where" or len(call.args) != 3:
        return
    _, scores, fill = call.args
    if not _is_neg_inf(fill):
        return
    cast_line = _low_precision_cast(scores)
    if cast_line is None:
        return
    if core.allowed_above(sf, RULE_MASK, call.lineno, fn.lineno):
        return
    findings.append(core.make_finding(
        sf, RULE_MASK, call.lineno,
        "masked softmax combines a -inf fill with scores cast to "
        "bf16/f16 — the mask-add contract is f32 (round only at "
        "declared boundaries); with 8 mantissa bits near-tied logits "
        "flip under the mask instead of being suppressed exactly",
        hint="mask in f32 and cast AFTER the softmax: "
             "jnp.where(m, s, -1e30) with s float32",
        qualname=core.qualname_of(call)))


def run(files: List[core.SourceFile], ctx: core.Context) -> List[core.Finding]:
    findings: List[core.Finding] = []
    einsum_sites = 0
    traced_sites = 0
    for sf in files:
        core.attach_parents(sf.tree)
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            env = _shape_env(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tail = _call_tail(node.func)
                if tail == "einsum":
                    einsum_sites += 1
                    if _check_einsum(sf, fn, node, env, findings):
                        traced_sites += 1
                elif tail == "dot_general":
                    einsum_sites += 1
                    if _check_dot_general(sf, fn, node, env, findings):
                        traced_sites += 1
                elif tail == "where":
                    _check_mask(sf, fn, node, findings)
    stats = getattr(ctx, "stats", None)
    if stats is not None:
        stats["einsumcheck"] = {
            "contraction_sites": einsum_sites,
            "shape_traced": traced_sites,
        }
    return findings
