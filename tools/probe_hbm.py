"""Measure achievable HBM bandwidth on this chip: sum-reduce (pure read)
and scaled copy (read+write) over large arrays, bf16 and int8."""

import time

import jax
import jax.numpy as jnp

N = 1 << 30  # 1Gi elements


def timeit(fn, *args):
    _ = jax.device_get(fn(*args))
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        _ = jax.device_get(fn(*args))
    return (time.perf_counter() - t0) / n


@jax.jit
def red_bf16(x):
    return x.astype(jnp.float32).sum()


@jax.jit
def red_int8(x):
    return x.astype(jnp.int32).sum()


@jax.jit
def mm_bf16(a, w):
    return jnp.einsum("bd,df->bf", a, w)


@jax.jit
def mm_int8w(a, w):
    return jnp.einsum("bd,df->bf", a, w.astype(a.dtype))


def main():
    x = jnp.ones((N,), jnp.bfloat16)
    dt = timeit(red_bf16, x)
    print(f"read bf16  2GiB: {dt*1000:7.2f} ms  {2/dt:7.1f} GB/s", flush=True)
    xi = jnp.ones((N,), jnp.int8)
    dt = timeit(red_int8, xi)
    print(f"read int8  1GiB: {dt*1000:7.2f} ms  {1/dt:7.1f} GB/s", flush=True)
    del x, xi
    # One big matmul at serving batch: [160, 8192] x [8192, 65536]
    B, D, F = 160, 8192, 65536  # 0.5G weights -> 1GiB bf16
    a = jnp.ones((B, D), jnp.bfloat16)
    w = jnp.ones((D, F), jnp.bfloat16)
    dt = timeit(mm_bf16, a, w)
    print(f"mm bf16 [160x8k x 8kx64k] 1GiB w: {dt*1000:7.2f} ms  {1.0/dt:7.1f} GB/s", flush=True)
    wq = jnp.ones((D, F), jnp.int8)
    dt = timeit(mm_int8w, a, wq)
    print(f"mm int8w same shape      0.5GiB w: {dt*1000:7.2f} ms  {0.5/dt:7.1f} GB/s", flush=True)
    # Bigger token batch (1024) to see if MXU grain changes BW
    a = jnp.ones((1024, D), jnp.bfloat16)
    dt = timeit(mm_bf16, a, w)
    print(f"mm bf16 [1024x8k x 8kx64k]: {dt*1000:7.2f} ms  {1.0/dt:7.1f} GB/s", flush=True)
    dt = timeit(mm_int8w, a, wq)
    print(f"mm int8w [1024]:           {dt*1000:7.2f} ms  {0.5/dt:7.1f} GB/s", flush=True)


if __name__ == "__main__":
    main()
