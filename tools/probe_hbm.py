"""Measure achievable HBM bandwidth on this chip: sum-reduce (pure read)
and scaled copy (read+write) over large arrays, bf16 and int8 — plus,
with PROBE_PAGED=1, a paged-KV pool utilization report (blocks
live/free/shared, CoW copies, internal fragmentation) from a tiny engine
held mid-decode on a mixed short/long stream set."""

import os
import time

import jax
import jax.numpy as jnp

N = 1 << 30  # 1Gi elements


def timeit(fn, *args):
    _ = jax.device_get(fn(*args))
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        _ = jax.device_get(fn(*args))
    return (time.perf_counter() - t0) / n


@jax.jit
def red_bf16(x):
    return x.astype(jnp.float32).sum()


@jax.jit
def red_int8(x):
    return x.astype(jnp.int32).sum()


@jax.jit
def mm_bf16(a, w):
    return jnp.einsum("bd,df->bf", a, w)


@jax.jit
def mm_int8w(a, w):
    return jnp.einsum("bd,df->bf", a, w.astype(a.dtype))


def paged_pool_report():
    """Paged-KV pool utilization under a mixed-length workload: admit a
    short/long stream set into a tiny paged engine (prefix cache on, one
    repeated prompt for zero-copy sharing), step it mid-decode by hand,
    and report the allocator gauges plus internal fragmentation — the
    fraction of allocated block tokens no stream has written yet (the
    cost of kv_block granularity; the dense slab's equivalent number is
    1 - written/max_seq_len per slot)."""
    import dataclasses

    from seldon_tpu.models import init_params
    from seldon_tpu.models.config import get_config
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config("tiny")
    eng = InferenceEngine(
        init_params(cfg, jax.random.key(0)), cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(16, 32),
                     paged_kv=True, kv_block=16, prefix_cache=True,
                     prefix_block=8),
    )
    def step():
        with eng._book:
            work = eng._dispatch_once()
        if work is not None:
            eng._process_boundary(*work)

    # 26 tokens -> 3 trie spans (24): the warm stream below matches 24,
    # sharing 1 full kv block zero-copy + 1 partial block via CoW.
    shared = list(range(2, 28))
    sp = SamplingParams(temperature=0.0, max_new_tokens=30)
    eng.submit(shared, sp)
    eng.submit(list(range(40, 45)), sp)
    step()  # cold wave admitted; prompts inserted into the block trie
    step()
    # Warm stream AFTER the donor's insertion: its admission refcounts
    # the shared prompt's blocks (zero-copy) and CoWs the partial tail.
    eng.submit(shared + [30, 31], sp)
    for _ in range(2):
        step()
    snap = eng.stats.snapshot()
    bs = eng._kv_block
    owned = written = 0
    for req in eng.live_requests():
        if req.finished:
            continue
        owned += len(req.block_ids) * bs
        written += len(req.tokens) + req.n_generated
    frag = 1.0 - written / owned if owned else 0.0
    print(
        f"paged pool [kv_block={bs}]: "
        f"{snap['pool_blocks_used']}/{snap['pool_blocks_total']} blocks "
        f"live ({snap['pool_blocks_free']} free, "
        f"{snap['pool_blocks_shared']} shared)",
        flush=True,
    )
    print(
        f"  zero-copy admissions: {snap['zero_copy_admissions']}  "
        f"cow copies: {snap['cow_copies']}  "
        f"pool stalls: {snap['pool_stalls']}  "
        f"preemptions: {snap['preemptions']}",
        flush=True,
    )
    print(
        f"  internal fragmentation: {frag:.1%} "
        f"({owned - written}/{owned} allocated block tokens unwritten; "
        f"dense slab would idle "
        f"{1.0 - written / (3 * 64):.1%} of 3 slots x 64 tokens)",
        flush=True,
    )
    # With HBM_LEDGER=1 the engine carries the byte-level view of the
    # same pool — fold it in so one probe run answers "where does HBM
    # go" end to end (weights / reservation / live / workspace).
    hbm = eng.debug_hbm()
    if hbm is not None:
        cats = hbm["categories"]
        line = "  ".join(
            f"{name}={cat['bytes']}B (hi {cat['high_bytes']}B)"
            for name, cat in sorted(cats.items())
        )
        print(f"  hbm ledger: {line}", flush=True)
        print(
            f"  hbm total: {hbm['total_bytes']}B "
            f"(hi {hbm['total_high_bytes']}B)",
            flush=True,
        )


def main():
    if os.environ.get("PROBE_PAGED", "0") == "1":
        paged_pool_report()
        return
    x = jnp.ones((N,), jnp.bfloat16)
    dt = timeit(red_bf16, x)
    print(f"read bf16  2GiB: {dt*1000:7.2f} ms  {2/dt:7.1f} GB/s", flush=True)
    xi = jnp.ones((N,), jnp.int8)
    dt = timeit(red_int8, xi)
    print(f"read int8  1GiB: {dt*1000:7.2f} ms  {1/dt:7.1f} GB/s", flush=True)
    del x, xi
    # One big matmul at serving batch: [160, 8192] x [8192, 65536]
    B, D, F = 160, 8192, 65536  # 0.5G weights -> 1GiB bf16
    a = jnp.ones((B, D), jnp.bfloat16)
    w = jnp.ones((D, F), jnp.bfloat16)
    dt = timeit(mm_bf16, a, w)
    print(f"mm bf16 [160x8k x 8kx64k] 1GiB w: {dt*1000:7.2f} ms  {1.0/dt:7.1f} GB/s", flush=True)
    wq = jnp.ones((D, F), jnp.int8)
    dt = timeit(mm_int8w, a, wq)
    print(f"mm int8w same shape      0.5GiB w: {dt*1000:7.2f} ms  {0.5/dt:7.1f} GB/s", flush=True)
    # Bigger token batch (1024) to see if MXU grain changes BW
    a = jnp.ones((1024, D), jnp.bfloat16)
    dt = timeit(mm_bf16, a, w)
    print(f"mm bf16 [1024x8k x 8kx64k]: {dt*1000:7.2f} ms  {1.0/dt:7.1f} GB/s", flush=True)
    dt = timeit(mm_int8w, a, wq)
    print(f"mm int8w [1024]:           {dt*1000:7.2f} ms  {0.5/dt:7.1f} GB/s", flush=True)


if __name__ == "__main__":
    main()
