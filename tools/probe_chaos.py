"""Probe: chaos-soak the request lifecycle. Prints ONE JSON line.

Runs a mixed request stream (varied prompt/decode lengths, a slice of
requests carrying tight deadlines, a slice cancelled client-side
mid-stream) against an engine with deterministic fault injection
(servers/chaos.py: dispatch failures, allocator exhaustion, slow
boundaries, forced disconnects). Every request must land in exactly one
outcome bucket and the engine's slot/pool/trie accounting must return
to empty — the number reported is the completed fraction, the detail is
the full outcome ledger plus injected-fault counts and any leaks
(`leaks` non-empty means the lifecycle lost track of state: a bug).

Knobs (env): CH_PRESET (tiny), CH_N (200), CH_SEED (0),
CH_DISPATCH_FAIL (0.02), CH_ALLOC_FAIL (0.02), CH_SLOW (0.05),
CH_DISCONNECT (0.01), CH_PAGED (0 = dense), CH_DEADLINE_FRAC (0.1),
CH_CANCEL_FRAC (0.1).
CPU smoke: JAX_PLATFORMS=cpu CH_N=40 python tools/probe_chaos.py
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PRESET = os.environ.get("CH_PRESET", "tiny")
N_REQ = int(os.environ.get("CH_N", 200))
SEED = int(os.environ.get("CH_SEED", 0))
DISPATCH_FAIL = float(os.environ.get("CH_DISPATCH_FAIL", 0.02))
ALLOC_FAIL = float(os.environ.get("CH_ALLOC_FAIL", 0.02))
SLOW = float(os.environ.get("CH_SLOW", 0.05))
DISCONNECT = float(os.environ.get("CH_DISCONNECT", 0.01))
PAGED = int(os.environ.get("CH_PAGED", 0))
DEADLINE_FRAC = float(os.environ.get("CH_DEADLINE_FRAC", 0.1))
CANCEL_FRAC = float(os.environ.get("CH_CANCEL_FRAC", 0.1))


def main() -> None:
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:  # explicit pin beats the image's sitecustomize (see bench.py)
        jax.config.update("jax_platforms", plat)

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.chaos import ChaosConfig
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config(PRESET)
    params = init_params(cfg, jax.random.key(0))
    ecfg = EngineConfig(
        max_slots=8,
        max_seq_len=64,
        prompt_buckets=(8, 16, 32),
        max_queue=4 * N_REQ,  # bounded but not the thing under test
        paged_kv=bool(PAGED),
        chaos=ChaosConfig(
            seed=SEED,
            dispatch_fail=DISPATCH_FAIL,
            alloc_fail=ALLOC_FAIL if PAGED else 0.0,
            slow_boundary=SLOW,
            slow_ms=2.0,
            disconnect=DISCONNECT,
        ),
    )
    engine = InferenceEngine(params, cfg, ecfg)
    t0 = time.perf_counter()
    engine.warmup()
    warm_s = time.perf_counter() - t0
    engine.start()

    rng = random.Random(SEED)
    nrng = np.random.default_rng(SEED)
    outcomes = {"completed": 0, "shed": 0, "deadline": 0,
                "cancelled": 0, "errored": 0}
    olock = threading.Lock()

    threads = []
    t_run = time.perf_counter()
    submitted = 0
    for i in range(N_REQ):
        plen = rng.choice((5, 8, 13, 21, 30))
        prompt = nrng.integers(3, cfg.vocab_size, size=(plen,)).tolist()
        sp = SamplingParams(
            temperature=0.0,
            max_new_tokens=rng.choice((4, 8, 16)),
            seed=i,
            deadline_ms=(
                rng.choice((30, 80)) if rng.random() < DEADLINE_FRAC else 0
            ),
        )
        try:
            q = engine.submit(prompt, sp)
        except Exception:
            with olock:
                outcomes["shed"] += 1
            continue
        submitted += 1
        cancels = rng.random() < CANCEL_FRAC

        def run(q=q, cancels=cancels):
            done_clean = True
            while True:
                item = q.get(timeout=120)
                if item is None:
                    break
                if "error" in item:
                    done_clean = False
                    kind = item.get("kind", "")
                    with olock:
                        if kind == "deadline":
                            outcomes["deadline"] += 1
                        elif kind == "cancelled":
                            outcomes["cancelled"] += 1
                        elif kind in ("draining", "shutdown"):
                            outcomes["shed"] += 1
                        else:
                            outcomes["errored"] += 1
                    continue
                if cancels and item.get("tokens"):
                    engine.cancel(q.rid)
                    cancels = False
            if done_clean:
                with olock:
                    outcomes["completed"] += 1

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)
        if rng.random() < 0.3:
            time.sleep(0.002)  # mild arrival jitter

    for t in threads:
        t.join(timeout=300)
    hung = sum(1 for t in threads if t.is_alive())
    run_s = time.perf_counter() - t_run
    drained = engine.drain(timeout=60)
    leaks = engine.debug_lifecycle_check()
    chaos = engine.chaos_counts()
    snap = engine.stats.snapshot()
    engine.stop()

    total_outcomes = sum(outcomes.values())
    print(json.dumps({
        "metric": "chaos_soak_completed_frac",
        "value": round(outcomes["completed"] / max(1, N_REQ), 3),
        "unit": (
            f"fraction ({PRESET}, {N_REQ} req, seed {SEED}, "
            f"{'paged' if PAGED else 'dense'})"
        ),
        "detail": {
            "outcomes": outcomes,
            "outcomes_total": total_outcomes,
            "submitted_accepted": submitted,
            "hung_waiters": hung,
            "drained": bool(drained),
            "leaks": leaks,
            "chaos": chaos,
            "shed_total": int(snap["shed_total"]),
            "cancelled_total": int(snap["cancelled_total"]),
            "deadline_expired_total": int(snap["deadline_expired_total"]),
            "run_s": round(run_s, 1),
            "warmup_s": round(warm_s, 1),
            "device": str(jax.devices()[0]),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
