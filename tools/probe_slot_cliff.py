"""Diagnose the 8B decode superlinear step-cost cliff past ~160 slots.

Round-4 measurements (memory: tpu-bench-rig-quirks): decode step ms at
96/160/256/320 slots = 18.7/24.5/44.8/55.5 — linear KV growth predicts
~19/21/25/28, so something structural changes past ~192. Suspects:
  (a) HBM pressure: weights (8 GB int8) + KV (~17 MB/slot int8 at the
      257-token window) + activations crowd the 16 GB chip and XLA
      falls back to a worse layout or spills;
  (b) a batch-dim tiling boundary in the attention/matmul kernels
      (B=256 crossing a lane/sublane multiple changes the MXU tiling);
  (c) the int8 KV dequant scales turning into a separately-materialized
      broadcast at larger B.

Run ALONE on the real chip:  python -m tools.probe_slot_cliff [slots...]
For each slot count: compile the decode step, report (1) per-step wall
via slope timing, (2) the compiled HLO's peak memory + largest
allocations, (3) per-step cost SPLIT into attention-only vs MLP-only
variants to localize the superlinearity.

PROBE_PAGED=1 adds a paged-mode sweep at each slot count with the pool
sized to the SAME token budget as the dense slab, so the concurrent-
streams-vs-pool-size cliff is directly comparable: the paged step adds
the block-table gather on the KV read path, and this probe prices it
against the slab at every batch size.
"""

from __future__ import annotations

import sys

import jax
import numpy as np

from seldon_tpu.models import get_config
from seldon_tpu.models.quantize import init_params_int8
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from tools.timing import slope_time

PROMPT, NEW = 128, 128


def probe(params, cfg, slots: int, paged: bool = False) -> None:
    # Window padded to the kv_block grid under paged mode; the pool gets
    # the dense slab's exact token budget so the sweep compares layouts,
    # not HBM sizes.
    seq = PROMPT + NEW + 1
    pkw = {}
    if paged:
        seq = (seq + 15) & ~15
        pkw = dict(paged_kv=True, kv_block=16)
    ecfg = EngineConfig(
        max_slots=slots,
        max_seq_len=seq,
        prompt_buckets=(PROMPT,),
        max_admit=8,
        decode_chunk=1,  # single steps: isolate per-step cost
        min_chunk=1,  # keep the single-step rung valid (min <= decode)
        **pkw,
    )
    eng = InferenceEngine(params, cfg, ecfg)
    eng.warmup()
    if paged:
        chunk1 = eng._jit_chunks_paged[1]
        import jax.numpy as jnp

        table = jnp.asarray(eng.table_host_snapshot())

        def step(state):
            s2, _, _, _ = chunk1(params, state, table)
            return s2
    else:
        chunk1 = eng._jit_chunks[1]  # decode_chunk=1 -> single-step rung

        def step(state):
            s2, _, _, _ = chunk1(params, state)
            return s2

    # Slope-fit per-step time (the tunneled host<->device RT swamps
    # per-call timing; chained calls cancel it).
    sec, state = slope_time(step, eng._state)
    peak = args = None
    try:
        if paged:
            comp = chunk1.lower(params, state, table).compile()
        else:
            comp = chunk1.lower(params, state).compile()
        mem = comp.memory_analysis()
        peak = getattr(mem, "temp_size_in_bytes", None)
        args = getattr(mem, "argument_size_in_bytes", None)
    except Exception:  # memory_analysis availability varies per backend
        pass
    mode = "paged" if paged else "dense"
    print(
        f"slots={slots:4d} [{mode}]  {sec*1e3:7.2f} ms/step  "
        f"temp={peak/1e9 if peak else float('nan'):6.2f} GB  "
        f"args={args/1e9 if args else float('nan'):6.2f} GB",
        flush=True,
    )


def main() -> None:
    import os

    slots_list = [int(s) for s in sys.argv[1:]] or [96, 160, 192, 224, 256]
    preset = os.environ.get("PROBE_PRESET", "llama3-8b")  # tiny = CPU smoke
    cfg = get_config(preset, kv_cache_dtype="int8", weight_dtype="int8")
    params = init_params_int8(cfg, jax.random.key(0))
    dev = jax.devices()[0]
    print(f"device: {dev}", flush=True)
    paged_too = os.environ.get("PROBE_PAGED", "0") == "1"
    for s in slots_list:
        probe(params, cfg, s)
        if paged_too:
            probe(params, cfg, s, paged=True)


if __name__ == "__main__":
    main()
