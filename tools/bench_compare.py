#!/usr/bin/env python
"""Compare two bench JSON runs and fail on perf regressions.

Input files are either the supervisor wrapper written by the bench
driver (``{"n", "cmd", "rc", "tail", "parsed"}`` — the metric line
lives under ``parsed``), a raw metric line
(``{"metric", "value", "detail": {...}}``), or a JSONL stream of metric
lines (the last complete one wins, matching the supervisor's pick).

Every numeric scalar in the metric line is flattened to a dot path
(``value``, ``detail.p50_ttft_ms``, ``detail.bench_1b.req_per_s``, ...)
and compared base -> candidate with a direction heuristic:

 * lower-is-better:  names containing ``ms``, ``latency``, ``stall``,
   ``frag``, ``dropped``, ``error``, ``bytes_per_device`` (graftmesh:
   per-chip HBM the TP sharding is supposed to save), plus the exact
   waste metrics ``padding_waste_frac`` / ``goodput_gap`` (the sched
   ledger's lost-capacity fractions — checked before the ``goodput``
   substring would claim them as higher-is-better), graftroof's
   ``host_frac`` (scheduler overhead share of the boundary wall), and
   graftmesh's ``kv_per_device_frac`` (TP-leg per-chip KV bytes over
   the single-chip leg's — ~1/tp when the pool shards), and graftheal's
   ``user_visible_errors`` (streams a seeded fault storm still failed
   in front of the user — quarantine + retry exhaustion are the only
   sanctioned sources, so any rise is a recovery regression);
 * higher-is-better: names containing ``req_per_s``, ``req_s``,
   ``tokens_per_s``, ``tok_s``, ``speedup``, ``hit_rate``, ``goodput``,
   ``coverage``, ``acceptance_rate`` (graftspec: a better drafter keeps
   more of every verify wave), plus the headline ``value`` /
   ``vs_baseline``, graftroof's achieved ``mfu`` / ``mbu`` and
   graftheal's ``goodput_retained_frac`` (bit-identical completions
   over offered under the BENCH_HEAL fault storm); the
   exact leaf ``dispatch_per_token`` gates lower-is-better (verify
   waves compress the decode loop), and ``roof_predicted_req_s`` stays
   informational (it moves when the COST MODEL changes, not when the
   served binary regresses);
 * strict:           ``live_retraces`` and ``compile_variants`` — any
   increase over base fails regardless of tolerance (a retrace storm
   is a correctness-of-the-lattice bug, and the variant count is an
   exact closed-form property of the config — graftragged collapses
   it to ≤ 2, so even one stray variant is a real regression);
 * everything else is informational (printed, never gated).

A gated metric regresses when it moves the wrong way by more than the
tolerance (default 10%, ``--tol 0.05`` for 5%). Exit is non-zero iff
at least one gated metric regressed. Usage::

    make bench-compare BASE=BENCH_r05.json CAND=BENCH_r06.json
    python -m tools.bench_compare BENCH_r05.json BENCH_r06.json --tol 0.05

See docs/benchmarking.md ("Comparing runs") for how this slots into
the release flow.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Substring -> direction tables, checked against the LAST path segment
# so "detail.chunked.p50_ttft_ms" gates on "p50_ttft_ms".
_LOWER = ("ms", "latency", "stall", "frag", "dropped", "error",
          "inversions", "bytes_per_device")
_HIGHER = ("req_per_s", "req_s", "tokens_per_s", "tok_s", "speedup",
           "hit_rate", "goodput", "coverage", "acceptance_rate")
# Exact leaf-name matches for the headline numbers. graftroof's
# utilization gauges gate higher-is-better: a PR that drops achieved
# MFU/MBU at the same throughput spent more hardware for the same work.
# "goodput_retained_frac" is graftheal's: the share of a seeded fault
# storm's offered requests that still completed bit-identical to the
# clean leg — resurrection working less well shows up here first.
_HIGHER_EXACT = ("value", "vs_baseline", "mfu", "mbu",
                 "goodput_retained_frac")
# Exact lower-is-better leaves, checked BEFORE the substring tables:
# "goodput_gap" would otherwise match the higher-is-better "goodput"
# substring, and "padding_waste_frac" matches nothing ("frac" != "frag").
# "dispatch_per_token" is graftspec's compression metric — verify waves
# emitting more tokens per dispatch push it DOWN. "host_frac" is
# graftroof's scheduler-overhead share of the boundary wall.
# "kv_per_device_frac" is graftmesh's sharding dividend — the TP leg's
# per-chip KV bytes as a fraction of the single-chip leg's; exact-TP
# splits the head axis, so it should sit at ~1/tp and only rise if a
# regression stops the pool from sharding. "user_visible_errors" is
# graftheal's headline — streams a seeded fault storm still failed in
# front of the user; quarantine and retry exhaustion are its only
# sanctioned sources, so any rise is a recovery regression.
_LOWER_EXACT = ("padding_waste_frac", "goodput_gap", "dispatch_per_token",
                "host_frac", "kv_per_device_frac", "user_visible_errors")
# Model-side constants, never gated: "roof_predicted_req_s" moves when
# the COST MODEL changes, not when the served binary regresses.
_INFO_EXACT = ("roof_predicted_req_s",)
_STRICT = ("live_retraces", "compile_variants")


def load_metric(path: str) -> Dict[str, Any]:
    """Read one bench artifact; return the metric-line dict."""
    with open(path) as f:
        raw = f.read()
    try:
        obj = json.loads(raw)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("parsed"), dict):  # supervisor wrapper
            return obj["parsed"]
        if "metric" in obj:  # raw metric line
            return obj
    # JSONL stream: last parseable metric line wins.
    last: Optional[Dict[str, Any]] = None
    for ln in raw.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            cand = json.loads(ln)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand:
            last = cand
    if last is None:
        raise SystemExit(f"bench-compare: {path} holds no metric line")
    return last


def flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Numeric scalars of a metric line, keyed by dot path."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            path = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, path))
    elif isinstance(obj, bool):
        pass  # True/False are flags, not measurements
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    return out


def direction(path: str) -> str:
    """'lower' | 'higher' | 'strict' | 'info' for a flattened path."""
    leaf = path.rsplit(".", 1)[-1]
    if leaf in _INFO_EXACT:
        return "info"
    if leaf in _STRICT:
        return "strict"
    if leaf in _LOWER_EXACT:
        return "lower"
    if leaf in _HIGHER_EXACT:
        return "higher"
    if any(s in leaf for s in _HIGHER):
        return "higher"
    if any(s in leaf for s in _LOWER):
        return "lower"
    return "info"


def compare(base: Dict[str, float], cand: Dict[str, float],
            tol: float) -> Tuple[List[str], List[str]]:
    """Return (report lines, regression messages)."""
    lines: List[str] = []
    regressions: List[str] = []
    header = (f"{'metric':<44} {'base':>12} {'cand':>12} "
              f"{'delta':>8}  gate")
    lines.append(header)
    lines.append("-" * len(header))
    for path in sorted(set(base) | set(cand)):
        b, c = base.get(path), cand.get(path)
        d = direction(path)
        if b is None or c is None:
            # Say WHICH side is missing: a metric only in cand was added
            # by the candidate run; one only in base was removed by it.
            status = "added" if b is None else "removed"
            lines.append(f"{path:<44} {_fmt(b):>12} {_fmt(c):>12} "
                         f"{'--':>8}  {d} ({status})")
            continue
        delta = (c - b) / abs(b) if b else (0.0 if c == b else float("inf"))
        verdict = d
        if d == "strict" and c > b:
            verdict = "REGRESSION"
            regressions.append(
                f"{path}: {b:g} -> {c:g} (strict: no increase allowed)")
        elif d == "lower" and delta > tol:
            verdict = "REGRESSION"
            regressions.append(
                f"{path}: {b:g} -> {c:g} (+{delta:.1%} > {tol:.0%} tol, "
                f"lower is better)")
        elif d == "higher" and delta < -tol:
            verdict = "REGRESSION"
            regressions.append(
                f"{path}: {b:g} -> {c:g} ({delta:.1%} < -{tol:.0%} tol, "
                f"higher is better)")
        lines.append(f"{path:<44} {_fmt(b):>12} {_fmt(c):>12} "
                     f"{delta:>+7.1%}  {verdict}")
    return lines, regressions


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "--"
    return f"{v:g}" if abs(v) < 1e6 else f"{v:.3e}"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="diff two bench JSON runs; non-zero exit on regression")
    p.add_argument("base", help="baseline bench JSON (e.g. BENCH_r05.json)")
    p.add_argument("cand", help="candidate bench JSON")
    p.add_argument("--tol", type=float, default=0.10,
                   help="relative tolerance for gated metrics "
                        "(default 0.10 = 10%%)")
    args = p.parse_args(argv)

    base_line = load_metric(args.base)
    cand_line = load_metric(args.cand)
    if base_line.get("metric") != cand_line.get("metric"):
        print(f"bench-compare: metric mismatch "
              f"({base_line.get('metric')} vs {cand_line.get('metric')}); "
              f"comparing anyway", file=sys.stderr)

    lines, regressions = compare(flatten(base_line), flatten(cand_line),
                                 args.tol)
    print(f"bench-compare: {args.base} -> {args.cand} "
          f"(tol {args.tol:.0%})")
    for ln in lines:
        print(ln)
    if regressions:
        print(f"\n{len(regressions)} regression(s):", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    print("\nno regressions")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
