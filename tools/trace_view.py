#!/usr/bin/env python
"""Flight-recording -> Chrome/Perfetto trace_event JSON.

Input: a /debug/timeline snapshot (servers/flight_recorder.snapshot()
shape) from a file or stdin; output: trace_event JSON that loads
directly in Perfetto (ui.perfetto.dev) or chrome://tracing.

    curl -s http://host:9000/debug/timeline | python tools/trace_view.py - \
        > timeline.trace.json

Rendering model:

 * one "engine" process; request tracks keyed by rid, one scheduler
   track (tid 0) for engine-wide events;
 * per-request lifecycle becomes "X" duration slices — `queued`
   (submit -> admit) and `running` (admit -> terminal), colored by the
   terminal outcome via the slice name;
 * point records (trie-hit/miss, cow, preempt, pool-stall, chaos,
   drain, fail-all, profile markers) become "i" instants on the
   owning request's track (engine-wide ones on the scheduler track);
 * "boundary" records also emit "C" counter series — `active_slots`
   always, `pool_blocks_free` when the engine is paged (the allocator's
   free count rides every boundary record), and `padding_waste_frac`
   when SCHED_LEDGER=1 (the sched ledger's per-wave pad fraction) — so
   scheduler occupancy, pool headroom and shape waste read as graphs
   above the slices;
 * "dispatch" records (DISPATCH_TIMING=1) become "X" slices on a
   second "variants" process — one lane per compile-ledger variant key
   ("admit/32/4", "decode/8", ...), spanning dispatch -> boundary so
   per-variant device occupancy reads directly off the track; under
   SPEC=1 the draft and verify waves land on their own "draft/k" /
   "verify/k" lanes, so speculation's dispatch structure reads
   directly against the plain decode lane it replaced;
 * spec boundary records (SPEC=1, the ``verify_k``/``emitted``/
   ``accepted``/``rejected`` detail) add a ``spec_accepted_tokens``
   counter series — per-wave acceptance as a graph over the verify
   lanes that earned it;
 * "retrace" records (COMPILE_LEDGER=1) are the live-retrace
   witnesses — rendered as instants on the paying request's track;
 * "pilot" records (PILOT=1) are the controller's decisions — rendered
   as a dedicated decision lane on a third "pilot" process ("budget
   128->256" instants carrying the full rationale in args) plus "C"
   counter series for the live knob values (`pilot_budget`,
   `pilot_max_admit`, `pilot_chunk_bias`), so control actions line up
   against the boundary/waste counters they reacted to;
 * "roof" records (ROOF_LEDGER=1) are graftroof's per-boundary step
   decompositions — rendered on a fourth "roofline" process as a host
   lane ("host-pre" / "host-post" slices) and a device lane ("enqueue"
   / "fetch" slices), laid out backwards from the boundary-done stamp
   with the pipelined in-flight gap left empty between them, so the
   host-vs-device shape of every scheduler step reads directly off
   the track (plus a `roof_host_ms` counter for the host share).

Monotonic record timestamps convert to wall-clock microseconds via the
snapshot's epoch pairing, so the device profile captured by
TRACE_PROFILE_N (jax.profiler, see tools/profile_decode.py for the
trace.json.gz parse) lines up on the same absolute axis.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

# Records that close out a request's `running` slice.
_TERMINAL = "terminal"
# Point-event records rendered as instants (everything not lifecycle).
_INSTANTS = (
    "trie-hit", "trie-miss", "cow", "preempt", "pool-stall", "chaos",
    "drain", "fail-all", "profile-start", "profile-stop", "shed",
    "retrace",
)
# Per-variant dispatch lanes live on their own process row.
_VARIANT_PID = 2
# Pilot decisions get their own process row: a decision lane + knob
# counters, visually separate from both requests and variants.
_PILOT_PID = 3
# graftroof's host/device step decomposition: host lane (tid 0) +
# device lane (tid 1) per boundary.
_ROOF_PID = 4
# graftheal recoveries: one instant marker per wave-fault recovery plus
# a verdict-count counter track (resurrect/pen/poison/exhausted).
_HEAL_PID = 5


def _wall_us(snapshot: Dict[str, Any], ts: float) -> float:
    """Monotonic record ts -> absolute wall-clock microseconds."""
    return (snapshot["epoch_wall"] + (ts - snapshot["epoch_mono"])) * 1e6


def convert(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """Flight-recorder snapshot -> trace_event JSON dict."""
    events: List[Dict[str, Any]] = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "seldon-tpu engine"}},
        {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
         "args": {"name": "scheduler"}},
    ]
    # rid -> (kind, ts, detail) markers collected for slice pairing.
    submit: Dict[int, Any] = {}
    admit: Dict[int, Any] = {}
    named: set = set()
    # variant key -> lane tid on the variants process (pid 2), assigned
    # in first-seen order so lanes are stable within one recording.
    variant_tids: Dict[str, int] = {}
    pilot_named = False

    def pilot_track() -> int:
        nonlocal pilot_named
        if not pilot_named:
            pilot_named = True
            events.append({
                "ph": "M", "pid": _PILOT_PID, "name": "process_name",
                "args": {"name": "seldon-tpu pilot"},
            })
            events.append({
                "ph": "M", "pid": _PILOT_PID, "tid": 0,
                "name": "thread_name", "args": {"name": "decisions"},
            })
        return 0

    heal_named = False

    def heal_track() -> int:
        nonlocal heal_named
        if not heal_named:
            heal_named = True
            events.append({
                "ph": "M", "pid": _HEAL_PID, "name": "process_name",
                "args": {"name": "seldon-tpu heal"},
            })
            events.append({
                "ph": "M", "pid": _HEAL_PID, "tid": 0,
                "name": "thread_name", "args": {"name": "recoveries"},
            })
        return 0

    roof_named = False

    def roof_tracks() -> None:
        nonlocal roof_named
        if not roof_named:
            roof_named = True
            events.append({
                "ph": "M", "pid": _ROOF_PID, "name": "process_name",
                "args": {"name": "seldon-tpu roofline"},
            })
            events.append({
                "ph": "M", "pid": _ROOF_PID, "tid": 0,
                "name": "thread_name", "args": {"name": "host"},
            })
            events.append({
                "ph": "M", "pid": _ROOF_PID, "tid": 1,
                "name": "thread_name", "args": {"name": "device"},
            })

    def variant_track(key: str) -> int:
        tid = variant_tids.get(key)
        if tid is None:
            tid = len(variant_tids)
            variant_tids[key] = tid
            if tid == 0:
                events.append({
                    "ph": "M", "pid": _VARIANT_PID, "name": "process_name",
                    "args": {"name": "seldon-tpu variants"},
                })
            events.append({
                "ph": "M", "pid": _VARIANT_PID, "tid": tid,
                "name": "thread_name", "args": {"name": key},
            })
        return tid

    def track(rid: int) -> int:
        if rid >= 0 and rid not in named:
            named.add(rid)
            events.append({
                "ph": "M", "pid": 1, "tid": rid, "name": "thread_name",
                "args": {"name": f"request {rid}"},
            })
        return max(rid, 0)

    for rec in snapshot.get("records", []):
        kind, rid = rec["kind"], int(rec.get("rid", -1))
        ts = _wall_us(snapshot, rec["ts"])
        detail = rec.get("detail") or {}
        if kind == "submit":
            submit[rid] = (ts, detail)
        elif kind == "admit":
            admit[rid] = (ts, detail)
            if rid in submit:
                t0, d0 = submit[rid]
                events.append({
                    "ph": "X", "pid": 1, "tid": track(rid),
                    "name": "queued", "ts": t0, "dur": max(ts - t0, 0.1),
                    "args": {**d0, **detail},
                })
        elif kind == _TERMINAL:
            start = admit.get(rid) or submit.get(rid)
            outcome = detail.get("outcome", "ok")
            if start is not None:
                t0, d0 = start
                events.append({
                    "ph": "X", "pid": 1, "tid": track(rid),
                    "name": f"running [{outcome}]" if rid in admit
                            else f"unadmitted [{outcome}]",
                    "ts": t0, "dur": max(ts - t0, 0.1),
                    "args": {**d0, **detail},
                })
            else:  # terminal with no earlier record in the window
                events.append({
                    "ph": "i", "pid": 1, "tid": track(rid),
                    "name": f"terminal [{outcome}]", "ts": ts, "s": "t",
                    "args": detail,
                })
            submit.pop(rid, None)
            admit.pop(rid, None)
        elif kind == "dispatch":
            # Recorded at boundary processing; the slice spans the wave
            # backwards from there (ts is the sync point, ms the
            # dispatch -> sync wall time).
            key = str(detail.get("variant", "?"))
            dur = max(float(detail.get("ms", 0.0)) * 1000.0, 0.1)
            events.append({
                "ph": "X", "pid": _VARIANT_PID, "tid": variant_track(key),
                "name": key, "ts": ts - dur, "dur": dur, "args": detail,
            })
        elif kind == "boundary":
            events.append({
                "ph": "i", "pid": 1, "tid": 0, "name": "boundary",
                "ts": ts, "s": "t", "args": detail,
            })
            events.append({
                "ph": "C", "pid": 1, "name": "active_slots", "ts": ts,
                "args": {"active": detail.get("active", 0)},
            })
            if "pool_free" in detail:
                events.append({
                    "ph": "C", "pid": 1, "name": "pool_blocks_free",
                    "ts": ts, "args": {"free": detail["pool_free"]},
                })
            if "waste_frac" in detail:
                events.append({
                    "ph": "C", "pid": 1, "name": "padding_waste_frac",
                    "ts": ts, "args": {"frac": detail["waste_frac"]},
                })
            if "verify_k" in detail:
                events.append({
                    "ph": "C", "pid": 1, "name": "spec_accepted_tokens",
                    "ts": ts,
                    "args": {"accepted": detail.get("accepted", 0),
                             "rejected": detail.get("rejected", 0)},
                })
        elif kind == "pilot":
            knob = detail.get("knob", "?")
            events.append({
                "ph": "i", "pid": _PILOT_PID, "tid": pilot_track(),
                "name": f"{knob} {detail.get('old')}->{detail.get('new')}",
                "ts": ts, "s": "p", "args": detail,
            })
            for name, key in (("pilot_budget", "budget"),
                              ("pilot_max_admit", "max_admit"),
                              ("pilot_chunk_bias", "chunk_bias")):
                if key in detail:
                    events.append({
                        "ph": "C", "pid": _PILOT_PID, "name": name,
                        "ts": ts, "args": {"value": detail[key]},
                    })
        elif kind == "heal":
            events.append({
                "ph": "i", "pid": _HEAL_PID, "tid": heal_track(),
                "name": f"recovery ({detail.get('state', '?')})",
                "ts": ts, "s": "p", "args": detail,
            })
            events.append({
                "ph": "C", "pid": _HEAL_PID, "name": "heal_verdicts",
                "ts": ts,
                "args": {k: detail.get(k, 0) for k in
                         ("resurrect", "pen", "poison", "exhausted")},
            })
        elif kind == "roof":
            # Recorded when boundary processing finishes (ts = done
            # stamp); the step's phases lay out backwards from there:
            # pre, enqueue, [in-flight gap], fetch, post.
            roof_tracks()
            pre = max(float(detail.get("pre_ms", 0.0)), 0.0) * 1000.0
            enq = max(float(detail.get("enq_ms", 0.0)), 0.0) * 1000.0
            gap = max(float(detail.get("gap_ms", 0.0)), 0.0) * 1000.0
            fetch = max(float(detail.get("fetch_ms", 0.0)), 0.0) * 1000.0
            post = max(float(detail.get("post_ms", 0.0)), 0.0) * 1000.0
            t_fetch = ts - post - fetch
            t_enq = t_fetch - gap - enq
            t_pre = t_enq - pre
            events.append({
                "ph": "X", "pid": _ROOF_PID, "tid": 0, "name": "host-pre",
                "ts": t_pre, "dur": max(pre, 0.1), "args": detail,
            })
            events.append({
                "ph": "X", "pid": _ROOF_PID, "tid": 1, "name": "enqueue",
                "ts": t_enq, "dur": max(enq, 0.1), "args": detail,
            })
            events.append({
                "ph": "X", "pid": _ROOF_PID, "tid": 1, "name": "fetch",
                "ts": t_fetch, "dur": max(fetch, 0.1), "args": detail,
            })
            events.append({
                "ph": "X", "pid": _ROOF_PID, "tid": 0, "name": "host-post",
                "ts": ts - post, "dur": max(post, 0.1), "args": detail,
            })
            events.append({
                "ph": "C", "pid": _ROOF_PID, "name": "roof_host_ms",
                "ts": ts,
                "args": {"host_ms": round(
                    (pre + post) / 1000.0, 3)},
            })
        else:
            events.append({
                "ph": "i", "pid": 1, "tid": track(rid), "name": kind,
                "ts": ts, "s": "t" if rid >= 0 else "p", "args": detail,
            })
    # Requests still open at the end of the window: emit what is known
    # so a truncated recording still renders (dur up to the last record).
    if snapshot.get("records"):
        end = _wall_us(snapshot, snapshot["records"][-1]["ts"])
        for rid, (t0, d0) in list(admit.items()) + [
            (r, v) for r, v in submit.items() if r not in admit
        ]:
            events.append({
                "ph": "X", "pid": 1, "tid": track(rid),
                "name": "in-flight (window end)",
                "ts": t0, "dur": max(end - t0, 0.1), "args": d0,
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "total_recorded": snapshot.get("total_recorded", 0),
            "dropped": snapshot.get("dropped", 0),
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="flight-recorder timeline -> Perfetto trace_event JSON"
    )
    p.add_argument("input", help="/debug/timeline snapshot file, or - for "
                                 "stdin")
    p.add_argument("-o", "--output", default="",
                   help="output path (default stdout)")
    args = p.parse_args(argv)
    raw = (sys.stdin.read() if args.input == "-"
           else open(args.input).read())
    snap = json.loads(raw)
    if not isinstance(snap, dict) or "records" not in snap:
        print("input is not a /debug/timeline snapshot "
              "(missing 'records')", file=sys.stderr)
        return 2
    out = json.dumps(convert(snap))
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        print(out)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
