// seldon_tpu native training data loader.
//
// The reference has no training path at all (SURVEY.md §2.9); this build's
// train step (models/train.py) needs token batches faster than Python can
// slice them when the step time is single-digit milliseconds. This is the
// native data-loader counterpart of the reference's native runtime
// components: memory-mapped token shards + a background prefetch thread
// filling a bounded ring of ready batches, exposed over a plain C ABI
// (ctypes — no pybind11 in the image).
//
// Determinism contract shared with the numpy fallback
// (seldon_tpu/data/loader.py): batch i's row r samples window offset
//   splitmix64(seed ^ (i * B + r)) % (n_tokens - (seq_len + 1))
// so native and fallback produce BIT-IDENTICAL streams (tested).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Shard {
  const uint32_t* data = nullptr;
  size_t n_tokens = 0;
  size_t mapped_bytes = 0;
  int fd = -1;
};

struct Loader {
  std::vector<Shard> shards;
  size_t total_tokens = 0;
  int64_t batch = 0;
  int64_t seq_plus1 = 0;  // seq_len + 1 (input + shifted target)
  uint64_t seed = 0;

  // Ring of prefetched batches (each batch*seq_plus1 int32).
  std::vector<std::vector<int32_t>> ring;
  size_t capacity = 0;
  size_t head = 0, tail = 0, count = 0;
  uint64_t next_to_fill = 0;  // batch counter for the producer

  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::thread worker;
  std::atomic<bool> stop{false};

  uint32_t token_at(size_t idx) const {
    for (const auto& s : shards) {
      if (idx < s.n_tokens) return s.data[idx];
      idx -= s.n_tokens;
    }
    return 0;  // unreachable for valid idx
  }

  void fill_batch(uint64_t batch_idx, int32_t* out) const {
    const uint64_t window = total_tokens - (uint64_t)seq_plus1;
    for (int64_t r = 0; r < batch; ++r) {
      uint64_t off =
          splitmix64(seed ^ (batch_idx * (uint64_t)batch + (uint64_t)r)) %
          window;
      // Fast path: window fully inside one shard -> memcpy.
      size_t idx = off;
      bool copied = false;
      for (const auto& s : shards) {
        if (idx + (size_t)seq_plus1 <= s.n_tokens) {
          for (int64_t t = 0; t < seq_plus1; ++t)
            out[r * seq_plus1 + t] = (int32_t)s.data[idx + t];
          copied = true;
          break;
        }
        if (idx < s.n_tokens) break;  // straddles shard boundary
        idx -= s.n_tokens;
      }
      if (!copied) {
        for (int64_t t = 0; t < seq_plus1; ++t)
          out[r * seq_plus1 + t] = (int32_t)token_at(off + (size_t)t);
      }
    }
  }

  void run() {
    while (!stop.load()) {
      std::unique_lock<std::mutex> lk(mu);
      cv_full.wait(lk, [&] { return count < capacity || stop.load(); });
      if (stop.load()) return;
      uint64_t idx = next_to_fill++;
      auto& slot = ring[tail];
      lk.unlock();
      fill_batch(idx, slot.data());  // slow work outside the lock
      lk.lock();
      tail = (tail + 1) % capacity;
      ++count;
      cv_empty.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// paths: NUL-separated, double-NUL-terminated list of shard files
// (raw little-endian uint32 tokens). Returns nullptr on failure.
void* seldon_loader_create(const char* paths, int64_t batch,
                           int64_t seq_len, uint64_t seed,
                           int64_t capacity) {
  auto* L = new Loader();
  L->batch = batch;
  L->seq_plus1 = seq_len + 1;
  L->seed = seed;
  L->capacity = capacity > 0 ? (size_t)capacity : 4;

  // Any failure must unmap/close every shard opened so far — a leaked
  // mapping+fd per retry would exhaust fds under flaky paths.
  auto fail = [L]() -> void* {
    for (auto& s : L->shards) {
      munmap((void*)s.data, s.mapped_bytes);
      close(s.fd);
    }
    delete L;
    return nullptr;
  };

  const char* p = paths;
  while (*p) {
    std::string path(p);
    p += path.size() + 1;
    int fd = open(path.c_str(), O_RDONLY);
    if (fd < 0) return fail();
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < 4) {
      close(fd);
      return fail();
    }
    void* m = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      close(fd);
      return fail();
    }
    Shard s;
    s.data = (const uint32_t*)m;
    s.n_tokens = (size_t)st.st_size / 4;
    s.mapped_bytes = (size_t)st.st_size;
    s.fd = fd;
    L->shards.push_back(s);
    L->total_tokens += s.n_tokens;
  }
  if (L->total_tokens < (size_t)L->seq_plus1 + 1) return fail();
  L->ring.assign(L->capacity,
                 std::vector<int32_t>((size_t)(batch * L->seq_plus1)));
  L->worker = std::thread([L] { L->run(); });
  return L;
}

// Blocks until a prefetched batch is ready; copies [batch, seq_len+1] int32.
void seldon_loader_next(void* handle, int32_t* out) {
  auto* L = (Loader*)handle;
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_empty.wait(lk, [&] { return L->count > 0; });
  auto& slot = L->ring[L->head];
  std::memcpy(out, slot.data(), slot.size() * sizeof(int32_t));
  L->head = (L->head + 1) % L->capacity;
  --L->count;
  L->cv_full.notify_one();
}

int64_t seldon_loader_total_tokens(void* handle) {
  return (int64_t)((Loader*)handle)->total_tokens;
}

void seldon_loader_destroy(void* handle) {
  auto* L = (Loader*)handle;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_full.notify_all();
  L->cv_empty.notify_all();
  if (L->worker.joinable()) L->worker.join();
  for (auto& s : L->shards) {
    munmap((void*)s.data, s.mapped_bytes);
    close(s.fd);
  }
  delete L;
}

}  // extern "C"
