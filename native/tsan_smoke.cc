// ThreadSanitizer smoke harness (SURVEY §5.2 gap-fix — the reference has
// no race-detection tier at all). Compiled wholly under -fsanitize=thread
// together with the library sources, it exercises the two places real
// threads touch shared state:
//   * the dataloader's prefetch thread racing the consumer (create /
//     next / destroy, including immediate destroy while prefetching)
//   * concurrent bf16 codec + batch fuse/split calls from many threads
//     (stateless by contract — TSan proves it)
// Exits non-zero (and TSan prints a report) on any detected race.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void seldon_f32_to_bf16(const float* src, uint16_t* dst, int64_t n);
void seldon_bf16_to_f32(const uint16_t* src, float* dst, int64_t n);
int64_t seldon_batch_fuse(const uint8_t** srcs, const int64_t* sizes,
                          int32_t n, uint8_t* dst);
int64_t seldon_batch_split(const uint8_t* src, const int64_t* sizes,
                           int32_t n, uint8_t** dsts);
void* seldon_loader_create(const char* paths, int64_t batch, int64_t seq_len,
                           uint64_t seed, int64_t capacity);
void seldon_loader_next(void* handle, int32_t* out);
int64_t seldon_loader_total_tokens(void* handle);
void seldon_loader_destroy(void* handle);
}

int main() {
  // --- stateless codecs hammered from 4 threads ---------------------------
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < 4; ++t) {
      ts.emplace_back([t] {
        std::vector<float> f(4096, 1.5f + t);
        std::vector<uint16_t> b(4096);
        std::vector<float> back(4096);
        for (int i = 0; i < 50; ++i) {
          seldon_f32_to_bf16(f.data(), b.data(), 4096);
          seldon_bf16_to_f32(b.data(), back.data(), 4096);
        }
        std::vector<uint8_t> a(128, uint8_t(t)), c(256, uint8_t(t + 1));
        const uint8_t* srcs[2] = {a.data(), c.data()};
        int64_t sizes[2] = {128, 256};
        std::vector<uint8_t> fused(384);
        std::vector<uint8_t> oa(128), oc(256);
        uint8_t* outs[2] = {oa.data(), oc.data()};
        for (int i = 0; i < 50; ++i) {
          seldon_batch_fuse(srcs, sizes, 2, fused.data());
          seldon_batch_split(fused.data(), sizes, 2, outs);
        }
      });
    }
    for (auto& t : ts) t.join();
  }

  // --- dataloader prefetch thread vs consumer -----------------------------
  {
    std::string shard = "/tmp/tsan_smoke_shard.bin";
    {
      std::ofstream f(shard, std::ios::binary);
      std::vector<int32_t> toks(4096);
      for (size_t i = 0; i < toks.size(); ++i) toks[i] = int32_t(i % 97);
      f.write(reinterpret_cast<const char*>(toks.data()),
              toks.size() * sizeof(int32_t));
    }
    std::string paths = shard;
    paths.push_back('\0');
    paths.push_back('\0');

    for (int round = 0; round < 3; ++round) {
      // capacity 4: a real multi-slot ring so producer/consumer head,
      // tail and count transitions actually interleave under TSan.
      void* h = seldon_loader_create(paths.data(), 4, 64,
                                     uint64_t(7 + round), 4);
      if (!h) { std::fprintf(stderr, "loader create failed\n"); return 2; }
      if (seldon_loader_total_tokens(h) != 4096) return 3;
      // next() copies [batch, seq_len + 1] int32 (inputs + shifted
      // targets share the buffer).
      std::vector<int32_t> out(4 * (64 + 1));
      int n_batches = round == 2 ? 0 : 8;  // round 2: destroy mid-prefetch
      for (int i = 0; i < n_batches; ++i) {
        seldon_loader_next(h, out.data());
      }
      seldon_loader_destroy(h);
    }
    std::remove(shard.c_str());
  }

  std::puts("tsan smoke OK");
  return 0;
}
