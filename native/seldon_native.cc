// seldon_tpu native data-plane core.
//
// The reference's request path runs on two native components (Go operator,
// Java engine — SURVEY.md §2). Here the Python asyncio engine delegates its
// per-request CPU hot spots to this library via ctypes (no pybind11 in the
// image):
//   * batch fuse/split — assembling micro-batches from N request payloads
//     and splitting responses back (orchestrator/batcher.py)
//   * f32 <-> bf16 conversion with round-to-nearest-even — the wire codec
//     for DenseTensor payloads when tensors cross the host boundary
//
// Plain C ABI; buffers are caller-owned. Thread-safe (stateless).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------------------
// bf16 <-> f32 (round-to-nearest-even, matching TPU semantics)
// ---------------------------------------------------------------------------

void seldon_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(src);
  for (int64_t i = 0; i < n; ++i) {
    uint32_t x = bits[i];
    // NaN stays NaN (avoid rounding a NaN payload into inf).
    if ((x & 0x7fffffffu) > 0x7f800000u) {
      dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040);
      continue;
    }
    uint32_t lsb = (x >> 16) & 1u;
    uint32_t rounded = x + 0x7fffu + lsb;
    dst[i] = static_cast<uint16_t>(rounded >> 16);
  }
}

void seldon_bf16_to_f32(const uint16_t* src, float* dst, int64_t n) {
  uint32_t* bits = reinterpret_cast<uint32_t*>(dst);
  for (int64_t i = 0; i < n; ++i) {
    bits[i] = static_cast<uint32_t>(src[i]) << 16;
  }
}

// ---------------------------------------------------------------------------
// Batch fuse / split (byte-level; dtype-agnostic)
// ---------------------------------------------------------------------------

// Concatenate n buffers into dst. sizes[i] = byte length of srcs[i].
// Returns total bytes written.
int64_t seldon_batch_fuse(const uint8_t** srcs, const int64_t* sizes,
                          int32_t n, uint8_t* dst) {
  int64_t off = 0;
  for (int32_t i = 0; i < n; ++i) {
    std::memcpy(dst + off, srcs[i], static_cast<size_t>(sizes[i]));
    off += sizes[i];
  }
  return off;
}

// Split src into n buffers (inverse of fuse). Returns bytes consumed.
int64_t seldon_batch_split(const uint8_t* src, const int64_t* sizes,
                           int32_t n, uint8_t** dsts) {
  int64_t off = 0;
  for (int32_t i = 0; i < n; ++i) {
    std::memcpy(dsts[i], src + off, static_cast<size_t>(sizes[i]));
    off += sizes[i];
  }
  return off;
}

// ---------------------------------------------------------------------------
// Version / health probe for the ctypes loader
// ---------------------------------------------------------------------------

int32_t seldon_native_abi_version() { return 1; }

}  // extern "C"
