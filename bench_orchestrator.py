"""Orchestrator-level benchmark: engine + in-process SIMPLE_MODEL graph.

Reference comparison (SURVEY.md §6, doc/source/reference/benchmarking.md):
the Java engine with the hardcoded SIMPLE_MODEL stub (no microservice
hop) sustained 12,089 req/s REST / 28,256 req/s gRPC with p50 4ms/1ms on
one n1-standard-16 (64 locust slaves). This driver measures the same
thing for the asyncio engine: closed-loop concurrent clients hammering
REST and gRPC over REAL localhost sockets against a SIMPLE_MODEL graph
(zero model compute — pure orchestrator overhead).

Prints one JSON line per transport:
  {"metric": "engine_rest_req_per_s", "value": ..., "p50_ms": ..., ...}

Env knobs: BENCH_ORCH_CLIENTS (default 64), BENCH_ORCH_SECONDS (5),
BENCH_ORCH_TRANSPORTS (rest,grpc).
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

CLIENTS = int(os.environ.get("BENCH_ORCH_CLIENTS", "64"))
SECONDS = float(os.environ.get("BENCH_ORCH_SECONDS", "5"))
TRANSPORTS = os.environ.get("BENCH_ORCH_TRANSPORTS", "rest,grpc").split(",")

REF_REST = 12088.95  # benchmarking.md:40-44
REF_GRPC = 28256.39  # benchmarking.md:52-58


def build_server():
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec

    spec = PredictorSpec(
        name="bench",
        graph=PredictiveUnit(
            name="simple", type="MODEL", implementation="SIMPLE_MODEL"
        ),
    )
    # Batching off: SIMPLE_MODEL is hardcoded in-process (no leaf to fuse
    # for) and the reference bench has no batcher either.
    return EngineServer(spec=spec, http_port=0, grpc_port=0,
                        enable_batching=False)


async def bench_rest(es, seconds: float, clients: int):
    import aiohttp

    port = None
    for site in es._runner.sites:
        port = site._server.sockets[0].getsockname()[1]
    url = f"http://127.0.0.1:{port}/api/v0.1/predictions"
    body = json.dumps(
        {"data": {"names": ["a", "b"], "ndarray": [[1.0, 2.0]]}}
    ).encode()
    headers = {"Content-Type": "application/json"}
    stop_at = time.perf_counter() + seconds
    latencies = []

    async def worker(session):
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            async with session.post(url, data=body, headers=headers) as r:
                await r.read()
                assert r.status == 200, r.status
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    conn = aiohttp.TCPConnector(limit=clients)
    async with aiohttp.ClientSession(connector=conn) as session:
        t0 = time.perf_counter()
        counts = await asyncio.gather(*[worker(session) for _ in range(clients)])
        dt = time.perf_counter() - t0
    return sum(counts), dt, latencies


async def bench_grpc(es, seconds: float, clients: int):
    import grpc.aio

    from seldon_tpu.core import payloads
    from seldon_tpu.proto import prediction_grpc

    port = es.grpc_port  # bound port after start()
    channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
    stub = prediction_grpc.SeldonStub(channel)
    req = payloads.build_message(
        np.array([[1.0, 2.0]], np.float32), names=["a", "b"], kind="ndarray"
    )
    stop_at = time.perf_counter() + seconds
    latencies = []

    async def worker():
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            await stub.Predict(req)
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[worker() for _ in range(clients)])
    dt = time.perf_counter() - t0
    await channel.close()
    return sum(counts), dt, latencies


def report(name: str, total: int, dt: float, lats, ref: float):
    lats_ms = np.array(lats) * 1000.0
    print(json.dumps({
        "metric": name,
        "value": round(total / dt, 1),
        "unit": f"req/s ({CLIENTS} clients, SIMPLE_MODEL graph, {SECONDS}s)",
        "vs_baseline": round(total / dt / ref, 3),
        "detail": {
            "requests": total,
            "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
            "reference_req_s": ref,
        },
    }))


async def main():
    es = build_server()
    await es.start(host="127.0.0.1")
    try:
        if "rest" in TRANSPORTS:
            total, dt, lats = await bench_rest(es, SECONDS, CLIENTS)
            report("engine_rest_req_per_s", total, dt, lats, REF_REST)
        if "grpc" in TRANSPORTS:
            total, dt, lats = await bench_grpc(es, SECONDS, CLIENTS)
            report("engine_grpc_req_per_s", total, dt, lats, REF_GRPC)
    finally:
        await es.stop()


if __name__ == "__main__":
    asyncio.run(main())
