"""Orchestrator-level benchmark: engine + in-process SIMPLE_MODEL graph.

Reference comparison (SURVEY.md §6, doc/source/reference/benchmarking.md):
the Java engine with the hardcoded SIMPLE_MODEL stub (no microservice
hop) sustained 12,089 req/s REST / 28,256 req/s gRPC with p50 4ms/1ms on
one n1-standard-16 (64 locust slaves on SEPARATE nodes). Per core that is
756 REST / 1,766 gRPC req/s.

Methodology: the engine runs in its OWN subprocess (`--serve`), the
client loop in this one. On a small box wall-clock req/s measures
client+server CONTENTION, not server capacity — so the headline metric is
requests per SERVER-CPU-second (utime+stime of the server process around
the run), the per-core capacity number that is comparable to the
reference's per-core figures. Wall req/s is reported alongside.

Payloads: `ndarray` (reference-parity ListValue codec) and `dense` (this
framework's native raw-bytes DenseTensor path) — both reported.

Prints one JSON line per (transport, payload). Env knobs:
BENCH_ORCH_CLIENTS (default 64), BENCH_ORCH_SECONDS (5),
BENCH_ORCH_TRANSPORTS (rest,grpc), BENCH_ORCH_PAYLOADS (ndarray,dense).
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np

# 32 clients / 2 procs measured best on the 1-core bench box: more client
# processes steal server time slices and cache (the reference's own rig
# kept load generators on separate NODES); 2 procs already saturate the
# engine (wall req/s and per-core both HIGHER than with 4 procs).
CLIENTS = int(os.environ.get("BENCH_ORCH_CLIENTS", "32"))
CLIENT_PROCS = int(os.environ.get("BENCH_ORCH_CLIENT_PROCS", "2"))
SECONDS = float(os.environ.get("BENCH_ORCH_SECONDS", "12"))  # 5s windows are too noisy on small boxes
REPEATS = max(1, int(os.environ.get("BENCH_ORCH_REPEATS", "3")))
TRANSPORTS = os.environ.get("BENCH_ORCH_TRANSPORTS", "rest,grpc").split(",")
PAYLOADS = os.environ.get("BENCH_ORCH_PAYLOADS", "ndarray,dense").split(",")
# inproc = hardcoded SIMPLE_MODEL (sync gRPC lane, the reference's own
# stub methodology); netunit = one real microservice subprocess (async
# lane — what every deployed graph rides).
GRAPHS = os.environ.get("BENCH_ORCH_GRAPHS", "inproc,netunit").split(",")

REF_PER_CORE = {  # benchmarking.md:40-58 on n1-standard-16
    "rest": 12088.95 / 16.0,
    "grpc": 28256.39 / 16.0,
}


class EchoModel:
    """Network-unit stub: the cheapest possible real microservice, so the
    netunit rows measure ENGINE orchestration cost (async walker +
    internal client), not model compute — the async-path analogue of the
    reference's SIMPLE_MODEL methodology."""

    def predict(self, X, names, meta=None):
        return X


def build_server(unit_addr: str = ""):
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import (
        Endpoint, PredictiveUnit, PredictorSpec,
    )

    if unit_addr:
        # One REAL network unit: the graph walk leaves the process — the
        # path every deployed (non-hardcoded) graph rides. Native units
        # also expose the framed-proto fast lane (runtime/fastpath.py) on
        # port+1; BENCH_ORCH_FAST=0 pins the hop to full gRPC for A/B.
        host, port = unit_addr.rsplit(":", 1)
        fast = os.environ.get("BENCH_ORCH_FAST", "1") != "0"
        graph = PredictiveUnit(
            name="echo", type="MODEL",
            endpoint=Endpoint(service_host=host, service_port=int(port),
                              fast_port=int(port) + 1 if fast else 0),
        )
    else:
        graph = PredictiveUnit(
            name="simple", type="MODEL", implementation="SIMPLE_MODEL"
        )
    spec = PredictorSpec(name="bench", graph=graph)
    # Batching off: SIMPLE_MODEL is hardcoded in-process (no leaf to fuse
    # for) and the reference bench has no batcher either.
    return EngineServer(spec=spec, http_port=0, grpc_port=0,
                        enable_batching=False)


def serve_unit() -> None:
    """gRPC echo microservice subprocess (its CPU is NOT counted in the
    per-engine-core metric — deployed units run in their own pods).
    Serves the fast lane on port+1, like the microservice CLI."""
    from seldon_tpu.runtime.fastpath import start_fast_server
    from seldon_tpu.runtime.wrapper import build_grpc_server

    model = EchoModel()
    srv = build_grpc_server(model)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    if os.environ.get("BENCH_ORCH_FAST", "1") != "0":
        try:
            start_fast_server(model, "127.0.0.1", port + 1)
        except OSError:
            # port+1 taken: the engine's refused-connect fallback rides
            # gRPC; a bind race must not kill the whole bench run.
            pass
    print(json.dumps({"unit_port": port}), flush=True)
    srv.wait_for_termination()


async def serve_forever(unit_addr: str = ""):
    es = build_server(unit_addr)
    await es.start(host="127.0.0.1")
    http_port = None
    for site in es._runner.sites:
        http_port = site._server.sockets[0].getsockname()[1]
    print(json.dumps({"http_port": http_port, "grpc_port": es.grpc_port}),
          flush=True)
    while True:
        await asyncio.sleep(3600)


def server_cpu_seconds(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as f:
        parts = f.read().rsplit(")", 1)[1].split()
    utime, stime = int(parts[11]), int(parts[12])  # fields 14,15 (1-based)
    return (utime + stime) / os.sysconf("SC_CLK_TCK")


def _payload_rest(kind: str):
    if kind == "dense":
        from seldon_tpu.core import payloads
        from seldon_tpu.core.http import PROTO_CONTENT_TYPE

        msg = payloads.build_message(
            np.array([[1.0, 2.0]], np.float32), names=["a", "b"],
            kind="dense",
        )
        return msg.SerializeToString(), {"Content-Type": PROTO_CONTENT_TYPE}
    body = json.dumps(
        {"data": {"names": ["a", "b"], "ndarray": [[1.0, 2.0]]}}
    ).encode()
    return body, {"Content-Type": "application/json"}


async def bench_rest(http_port: int, kind: str, seconds: float, clients: int):
    import aiohttp

    url = f"http://127.0.0.1:{http_port}/api/v0.1/predictions"
    body, headers = _payload_rest(kind)
    stop_at = time.perf_counter() + seconds
    latencies = []

    async def worker(session):
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            async with session.post(url, data=body, headers=headers) as r:
                await r.read()
                assert r.status == 200, r.status
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    conn = aiohttp.TCPConnector(limit=clients)
    async with aiohttp.ClientSession(connector=conn) as session:
        t0 = time.perf_counter()
        counts = await asyncio.gather(*[worker(session) for _ in range(clients)])
        dt = time.perf_counter() - t0
    return sum(counts), dt, latencies


async def bench_grpc(grpc_port: int, kind: str, seconds: float, clients: int):
    import grpc.aio

    from seldon_tpu.core import payloads
    from seldon_tpu.proto import prediction_grpc

    channel = grpc.aio.insecure_channel(f"127.0.0.1:{grpc_port}")
    stub = prediction_grpc.SeldonStub(channel)
    req = payloads.build_message(
        np.array([[1.0, 2.0]], np.float32), names=["a", "b"], kind=kind
    )
    stop_at = time.perf_counter() + seconds
    latencies = []

    async def worker():
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            await stub.Predict(req)
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[worker() for _ in range(clients)])
    dt = time.perf_counter() - t0
    await channel.close()
    return sum(counts), dt, latencies


def report(name: str, kind: str, total: int, dt: float, p50: float,
           p99: float, cpu_s: float, ref_per_core: float):
    per_core = total / cpu_s if cpu_s > 0 else float("nan")
    graph_label = ("echo-unit subprocess graph" if "netunit" in name
                   else "SIMPLE_MODEL graph")
    print(json.dumps({
        "metric": name,
        "value": round(per_core, 1),
        "unit": (
            f"req/s per server core ({kind} payload, {CLIENTS} clients / "
            f"{CLIENT_PROCS} procs, {graph_label}, {SECONDS}s)"
        ),
        "vs_baseline": round(per_core / ref_per_core, 3),
        "detail": {
            "requests": total,
            "wall_req_s": round(total / dt, 1),
            "server_cpu_s": round(cpu_s, 2),
            "p50_ms": round(p50, 2),
            "p99_ms": round(p99, 2),
            "reference_req_s_per_core": round(ref_per_core, 1),
        },
    }), flush=True)


async def _client_main(transport, port, kind, seconds, clients):
    if transport == "rest":
        total, dt, lats = await bench_rest(port, kind, seconds, clients)
    else:
        total, dt, lats = await bench_grpc(port, kind, seconds, clients)
    lats_ms = np.array(lats) * 1000 if lats else np.array([float("nan")])
    print(json.dumps({
        "total": total, "dt": dt,
        "p50": float(np.percentile(lats_ms, 50)),
        "p99": float(np.percentile(lats_ms, 99)),
    }), flush=True)


def run_clients(transport, port, kind, seconds, clients):
    """Drive load from CLIENT_PROCS separate processes (each its own
    event loop + connections). One python client loop saturates its own
    core well before the server does — measuring with a single client
    process understates server capacity and inflates server CPU with
    idle-poll spin (the reference's own rig was 64 locust slaves on
    separate NODES, benchmarking.md:40-58)."""
    per = max(1, clients // CLIENT_PROCS)
    actual = per * CLIENT_PROCS  # report what actually ran
    if clients >= 16:  # don't let the 8-client warm run clobber the label
        global CLIENTS
        CLIENTS = actual
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--client",
             transport, str(port), kind, str(seconds), str(per)],
            stdout=subprocess.PIPE,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            # De-prioritize the load generators: on a 1-core box they
            # otherwise preempt server threads mid-handler and inflate
            # the server's CPU/req with involuntary context switches —
            # the reference's rig had clients on separate NODES; this is
            # the single-box approximation. Closed-loop clients still
            # saturate the server (it runs whenever work is pending).
            preexec_fn=lambda: os.nice(5),
        )
        for _ in range(CLIENT_PROCS)
    ]
    outs = []
    for p in procs:
        raw = p.stdout.read()
        p.wait(timeout=10)
        lines = raw.splitlines()
        if p.returncode != 0 or not lines:
            raise RuntimeError(
                f"client subprocess failed (rc={p.returncode}); "
                f"output: {raw[-500:]!r}"
            )
        outs.append(json.loads(lines[-1]))
    dt = max(o["dt"] for o in outs)
    total = sum(o["total"] for o in outs)
    # Aggregate percentiles across processes by weighted medians —
    # close enough for a latency side-channel (throughput is the metric).
    p50 = float(np.median([o["p50"] for o in outs]))
    p99 = float(max(o["p99"] for o in outs))
    return total, dt, p50, p99


async def run_scenario(graph: str):
    """One engine topology: 'inproc' (hardcoded SIMPLE_MODEL, sync gRPC
    lane) or 'netunit' (one real gRPC microservice subprocess, async
    lane). Metric rows carry the scenario in their name."""
    here = os.path.dirname(os.path.abspath(__file__))
    unit_proc = None
    serve_cmd = [sys.executable, os.path.abspath(__file__), "--serve"]
    if graph == "netunit":
        unit_proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--serve-unit"],
            stdout=subprocess.PIPE, cwd=here,
        )
        unit_port = json.loads(unit_proc.stdout.readline())["unit_port"]
        serve_cmd += ["--unit", f"127.0.0.1:{unit_port}"]
    proc = subprocess.Popen(serve_cmd, stdout=subprocess.PIPE, cwd=here)
    try:
        ports = json.loads(proc.stdout.readline())
        suffix = "_netunit" if graph == "netunit" else ""

        def run(transport, kind, seconds, clients):
            port = (ports["http_port"] if transport == "rest"
                    else ports["grpc_port"])
            return run_clients(transport, port, kind, seconds, clients)

        for transport in TRANSPORTS:
            for kind in PAYLOADS:
                run(transport, kind, 0.5, 8)  # settle + warm
                # Median of REPEATS windows: single windows on a 1-core
                # box swing +/-30% with scheduler luck; the median is the
                # recorded row (all trials ride identical config).
                trials = []
                for _ in range(REPEATS):
                    cpu0 = server_cpu_seconds(proc.pid)
                    total, dt, p50, p99 = run(
                        transport, kind, SECONDS, CLIENTS
                    )
                    cpu1 = server_cpu_seconds(proc.pid)
                    trials.append((total, dt, p50, p99, cpu1 - cpu0))
                trials.sort(key=lambda t: t[0] / t[4] if t[4] else 0)
                total, dt, p50, p99, cpu_s = trials[len(trials) // 2]
                report(
                    f"engine_{transport}{suffix}_req_per_s_per_core", kind,
                    total, dt, p50, p99, cpu_s,
                    REF_PER_CORE[transport],
                )
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        if unit_proc is not None:
            unit_proc.terminate()
            unit_proc.wait(timeout=10)


async def main():
    for graph in GRAPHS:
        await run_scenario(graph)


if __name__ == "__main__":
    if "--serve-unit" in sys.argv:
        serve_unit()
    elif "--serve" in sys.argv:
        unit = (sys.argv[sys.argv.index("--unit") + 1]
                if "--unit" in sys.argv else "")
        asyncio.run(serve_forever(unit))
    elif "--client" in sys.argv:
        i = sys.argv.index("--client")
        transport, port, kind, seconds, clients = sys.argv[i + 1:i + 6]
        asyncio.run(_client_main(
            transport, int(port), kind, float(seconds), int(clients)
        ))
    else:
        asyncio.run(main())
