"""KubeStore + ControllerLoop against a faked k8s API server.

Reference test analogue: the Go operator's envtest (suite_test.go:1-84 —
a local kube-apiserver). No kubernetes binaries ship here, so a small
aiohttp fake implements the REST verbs KubeStore speaks: typed CRUD,
labelSelector lists, status subresource PATCH, chunked watch."""

import json
import threading
import time

import pytest
from aiohttp import web

from seldon_tpu.operator import types as T
from seldon_tpu.operator.controller import (
    ControllerLoop, handle_admission_review,
)
from seldon_tpu.operator.kubestore import KIND_ROUTES, KubeApiError, KubeStore


class FakeKubeApi:
    """Minimal in-memory API server honoring the KubeStore surface."""

    def __init__(self):
        self.objects = {}  # (prefix, plural, ns, name) -> dict
        self.rv = 0
        self.watch_events = []  # events replayed to the next watcher

    def _key(self, prefix, plural, ns, name):
        return (prefix, plural, ns, name)

    def make_app(self):
        app = web.Application()
        app.router.add_route(
            "*", "/{prefix:api(?:s)?/[^/]+(?:/[^/]+)?}/namespaces/{ns}/{rest:.*}",
            self.handle,
        )
        return app

    async def handle(self, request: web.Request) -> web.StreamResponse:
        prefix = request.match_info["prefix"]
        ns = request.match_info["ns"]
        rest = request.match_info["rest"].split("/")
        plural = rest[0]
        name = rest[1] if len(rest) > 1 and rest[1] else ""
        sub = rest[2] if len(rest) > 2 else ""

        if request.method == "GET" and not name:
            if request.query.get("watch") == "true":
                return await self._serve_watch(request)
            sel = request.query.get("labelSelector", "")
            items = []
            for (p, pl, n, _), obj in self.objects.items():
                if (p, pl, n) != (prefix, plural, ns):
                    continue
                if sel and not self._matches(obj, sel):
                    continue
                items.append(obj)
            return web.json_response(
                {"items": items,
                 "metadata": {"resourceVersion": str(self.rv)}}
            )

        key = self._key(prefix, plural, ns, name)
        if request.method == "GET":
            if key not in self.objects:
                return web.json_response({"reason": "NotFound"}, status=404)
            return web.json_response(self.objects[key])
        if request.method == "POST":
            body = await request.json()
            self.rv += 1
            body.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            key = self._key(prefix, plural, ns, body["metadata"]["name"])
            if key in self.objects:
                return web.json_response({"reason": "Conflict"}, status=409)
            self.objects[key] = body
            return web.json_response(body, status=201)
        if request.method == "PUT":
            body = await request.json()
            if key not in self.objects:
                return web.json_response({"reason": "NotFound"}, status=404)
            live_rv = self.objects[key]["metadata"]["resourceVersion"]
            if body["metadata"].get("resourceVersion") != live_rv:
                return web.json_response({"reason": "Conflict"}, status=409)
            self.rv += 1
            body["metadata"]["resourceVersion"] = str(self.rv)
            # Real apiservers keep the status subresource across spec PUTs.
            if "status" not in body and "status" in self.objects[key]:
                body["status"] = self.objects[key]["status"]
            self.objects[key] = body
            return web.json_response(body)
        if request.method == "PATCH":
            target_key = self._key(prefix, plural, ns, name)
            if sub == "status":
                pass  # status subresource patches the same stored object
            if target_key not in self.objects:
                return web.json_response({"reason": "NotFound"}, status=404)
            patch = await request.json()
            obj = self.objects[target_key]
            for k, v in patch.items():
                obj[k] = v
            return web.json_response(obj)
        if request.method == "DELETE":
            if key not in self.objects:
                return web.json_response({"reason": "NotFound"}, status=404)
            del self.objects[key]
            return web.json_response({})
        return web.json_response({"reason": "MethodNotAllowed"}, status=405)

    async def _serve_watch(self, request):
        resp = web.StreamResponse()
        await resp.prepare(request)
        for ev in self.watch_events:
            await resp.write((json.dumps(ev) + "\n").encode())
        await resp.write_eof()  # server closes; client re-lists
        return resp

    @staticmethod
    def _matches(obj, selector: str) -> bool:
        labels = obj.get("metadata", {}).get("labels", {})
        for pair in selector.split(","):
            k, _, v = pair.partition("=")
            if labels.get(k) != v:
                return False
        return True


@pytest.fixture()
def fake_api():
    import asyncio

    api = FakeKubeApi()
    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_holder = {}

    def serve():
        asyncio.set_event_loop(loop)

        async def run():
            runner = web.AppRunner(api.make_app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port_holder["port"] = site._server.sockets[0].getsockname()[1]
            started.set()

        loop.run_until_complete(run())
        loop.run_forever()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    assert started.wait(5)
    yield api, f"http://127.0.0.1:{port_holder['port']}"
    loop.call_soon_threadsafe(loop.stop)


def _sdep_dict(name="mymodel", generation=1):
    return {
        "apiVersion": "machinelearning.seldon.io/v1alpha3",
        "kind": "SeldonDeployment",
        "metadata": {"name": name, "namespace": "default",
                     "generation": generation},
        "spec": {
            "predictors": [
                {
                    "name": "main",
                    "replicas": 1,
                    "graph": {"name": "clf", "type": "MODEL",
                              "implementation": "JAX_SERVER",
                              "modelUri": "file:///m"},
                }
            ]
        },
    }


def test_kubestore_crud_roundtrip(fake_api):
    api, url = fake_api
    store = KubeStore(base_url=url)
    dep = {"apiVersion": "apps/v1", "kind": "Deployment",
           "metadata": {"name": "d1", "namespace": "default",
                        "labels": {"app": "x"}},
           "spec": {"replicas": 2}}
    store.apply(dep)  # create
    dep2 = dict(dep)
    dep2["spec"] = {"replicas": 3}
    store.apply(dep2)  # update (carries live resourceVersion)
    got = store.list("Deployment", "default", {"app": "x"})
    assert len(got) == 1 and got[0]["spec"]["replicas"] == 3
    assert store.list("Deployment", "default", {"app": "other"}) == []
    # readiness: no status -> not ready; patch status -> ready
    assert not store.is_ready("Deployment", "default", "d1")
    key = ("apis/apps/v1", "deployments", "default", "d1")
    api.objects[key]["status"] = {"readyReplicas": 3}
    assert store.is_ready("Deployment", "default", "d1")
    store.delete("Deployment", "default", "d1")
    assert store.list("Deployment", "default") == []
    store.delete("Deployment", "default", "d1")  # 404 tolerated


def test_controller_resync_reconciles_cr(fake_api):
    api, url = fake_api
    store = KubeStore(base_url=url)
    # Seed the CR as if `kubectl apply`d.
    prefix, plural = KIND_ROUTES["SeldonDeployment"]
    api.objects[(prefix, plural, "default", "mymodel")] = _sdep_dict()
    loop = ControllerLoop(store, namespace="default", istio_enabled=True)
    n = loop.resync()
    assert n == 1 and loop.reconcile_count == 1
    deps = store.list("Deployment", "default")
    assert len(deps) == 1
    names = {c["name"] for c in
             deps[0]["spec"]["template"]["spec"]["containers"]}
    assert any("clf" in n for n in names)
    svcs = store.list("Service", "default")
    assert svcs, "predictor service missing"
    vss = store.list("VirtualService", "default")
    assert vss and vss[0]["spec"]["http"]
    # Status written back to the CR (workloads have no readyReplicas yet
    # -> Creating).
    cr = api.objects[(prefix, plural, "default", "mymodel")]
    assert cr["status"]["state"] == "Creating"
    # Mark workloads ready; re-reconcile -> Available.
    for key, obj in list(api.objects.items()):
        if obj.get("kind") == "Deployment":
            obj["status"] = {"readyReplicas": obj["spec"].get("replicas", 1)}
    loop.resync()
    assert cr["status"]["state"] == "Available"


def test_controller_watch_events_drive_reconcile(fake_api):
    api, url = fake_api
    store = KubeStore(base_url=url)
    api.watch_events = [
        {"type": "ADDED", "object": _sdep_dict(name="watched")},
    ]
    loop = ControllerLoop(store, namespace="default", resync_s=0.2,
                          istio_enabled=False)
    t = threading.Thread(target=loop.run, daemon=True)
    t.start()
    deadline = time.time() + 5
    while time.time() < deadline and loop.reconcile_count < 1:
        time.sleep(0.05)
    loop.stop()
    t.join(timeout=5)
    assert loop.reconcile_count >= 1
    assert store.list("Deployment", "default")


# ---------------------------------------------------------------------------
# Admission webhook handlers (AdmissionReview v1)
# ---------------------------------------------------------------------------


def test_mutating_webhook_patches_defaults():
    review = {"request": {"uid": "u1", "object": _sdep_dict()}}
    out = handle_admission_review(review, mutate=True)
    resp = out["response"]
    assert resp["allowed"] and resp["uid"] == "u1"
    import base64
    patch = json.loads(base64.b64decode(resp["patch"]))
    assert patch[0]["op"] == "replace" and patch[0]["path"] == "/spec"
    # Defaulting assigned the unit an endpoint port.
    graph = patch[0]["value"]["predictors"][0]["graph"]
    assert graph.get("endpoint", {}).get("service_port",
                                         graph.get("endpoint", {}).get(
                                             "servicePort", 0))


def test_validating_webhook_rejects_bad_traffic():
    bad = _sdep_dict()
    bad["spec"]["predictors"].append(
        {"name": "canary", "replicas": 1, "traffic": 10,
         "graph": {"name": "clf2", "type": "MODEL",
                   "implementation": "JAX_SERVER", "modelUri": "file:///m"}}
    )
    bad["spec"]["predictors"][0]["traffic"] = 10  # sums to 20, not 100
    out = handle_admission_review(
        {"request": {"uid": "u2", "object": bad}}, mutate=False
    )
    assert out["response"]["allowed"] is False
    assert "traffic" in out["response"]["status"]["message"].lower()


def test_webhook_malformed_object_rejected():
    out = handle_admission_review(
        {"request": {"uid": "u3", "object": {"spec": {"predictors": 3}}}},
        mutate=False,
    )
    assert out["response"]["allowed"] is False
