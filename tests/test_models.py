"""Model-stack tests on the virtual 8-device CPU mesh (conftest.py).

Mirrors the reference's tier-1 strategy (SURVEY.md §4): in-process, no
cluster, deterministic tiny fixtures.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from seldon_tpu.models import (
    ModelConfig,
    get_config,
    init_params,
    forward,
    prefill,
    decode_step,
    init_cache,
)
from seldon_tpu.models.generate import generate
from seldon_tpu.models.sampling import sample
from seldon_tpu.models.train import make_optimizer, make_sharded_train_step
from seldon_tpu.parallel import (
    MeshPlan,
    make_mesh,
    param_pspecs,
    shard_tree,
)

CFG = get_config("tiny")


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_forward_shapes(params):
    tokens = jnp.ones((2, 8), dtype=jnp.int32)
    logits = forward(params, tokens, CFG)
    assert logits.shape == (2, 8, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_forward_causality(params):
    """Changing a future token must not affect earlier logits."""
    key = jax.random.key(1)
    t1 = jax.random.randint(key, (1, 8), 0, CFG.vocab_size)
    t2 = t1.at[0, 7].set((t1[0, 7] + 1) % CFG.vocab_size)
    l1 = forward(params, t1, CFG)
    l2 = forward(params, t2, CFG)
    np.testing.assert_allclose(l1[0, :7], l2[0, :7], rtol=1e-5)


def test_prefill_decode_matches_forward(params):
    """Incremental decoding must reproduce teacher-forced logits."""
    key = jax.random.key(2)
    S = 6
    tokens = jax.random.randint(key, (2, S), 2, CFG.vocab_size)
    full = forward(params, tokens, CFG)  # [B,S,V]

    cache = init_cache(CFG, 2, 16)
    lens = jnp.array([S, S], dtype=jnp.int32)
    pf_logits, cache = prefill(params, tokens, lens, cache, CFG)
    np.testing.assert_allclose(pf_logits, full[:, S - 1], rtol=2e-2, atol=2e-2)

    # Feed the next token through decode_step; compare against forward on
    # the extended sequence.
    nxt = jnp.argmax(pf_logits, axis=-1).astype(jnp.int32)
    step_logits, cache = decode_step(
        params, nxt, jnp.array([S, S], jnp.int32), cache, CFG
    )
    ext = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    full_ext = forward(params, ext, CFG)
    np.testing.assert_allclose(step_logits, full_ext[:, S], rtol=5e-2, atol=5e-2)


def test_prefill_ragged_rows(params):
    """Right-padded rows take logits at their own last real token."""
    t_a = jnp.array([[5, 6, 7, 0, 0, 0]], dtype=jnp.int32)
    lens = jnp.array([3], dtype=jnp.int32)
    cache = init_cache(CFG, 1, 8)
    ragged, _ = prefill(params, t_a, lens, cache, CFG)
    # Same prompt without padding:
    cache2 = init_cache(CFG, 1, 8)
    exact, _ = prefill(
        params, t_a[:, :3], jnp.array([3], jnp.int32), cache2, CFG
    )
    np.testing.assert_allclose(ragged, exact, rtol=2e-2, atol=2e-2)


def test_generate_shapes_and_eos(params):
    tokens = jnp.array([[4, 5, 6, 0], [7, 8, 0, 0]], dtype=jnp.int32)
    lens = jnp.array([3, 2], dtype=jnp.int32)
    B = 2
    out, out_lens = generate(
        params,
        tokens,
        lens,
        jax.random.key(0),
        jnp.zeros((B,)),  # greedy
        jnp.zeros((B,), jnp.int32),
        jnp.ones((B,)),
        CFG,
        8,
    )
    assert out.shape == (2, 8)
    assert out_lens.shape == (2,)
    assert bool(jnp.all(out_lens >= 1)) and bool(jnp.all(out_lens <= 8))
    # Greedy generation is deterministic.
    out2, _ = generate(
        params, tokens, lens, jax.random.key(9),
        jnp.zeros((B,)), jnp.zeros((B,), jnp.int32), jnp.ones((B,)), CFG, 8,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_sampling_topk_topp():
    logits = jnp.array([[10.0, 9.0, 1.0, 0.0]])
    # top_k=1 == greedy regardless of temperature.
    tok = sample(
        logits, jax.random.key(0), jnp.array([5.0]), jnp.array([1]),
        jnp.array([1.0]),
    )
    assert int(tok[0]) == 0
    # top_p tiny keeps only the argmax.
    tok = sample(
        logits, jax.random.key(1), jnp.array([5.0]), jnp.array([0]),
        jnp.array([1e-6]),
    )
    assert int(tok[0]) == 0
    # temperature 0 = greedy.
    tok = sample(
        logits, jax.random.key(2), jnp.array([0.0]), jnp.array([0]),
        jnp.array([1.0]),
    )
    assert int(tok[0]) == 0


def test_moe_forward():
    cfg = get_config("tiny-moe")
    p = init_params(cfg, jax.random.key(0))
    logits = forward(p, jnp.ones((2, 4), jnp.int32), cfg)
    assert logits.shape == (2, 4, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_sharded_forward_matches_single(params):
    """TP+DP sharded forward == unsharded forward (GSPMD correctness)."""
    mesh = make_mesh(MeshPlan(dp=2, tp=2))
    sharded = shard_tree(params, param_pspecs(CFG), mesh)
    tokens = jax.random.randint(jax.random.key(3), (4, 8), 0, CFG.vocab_size)
    ref = forward(params, tokens, CFG)
    tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    out = jax.jit(lambda p, t: forward(p, t, CFG))(sharded, tok_sh)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-2,
                               atol=2e-2)


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=2, tp=2, sp=2),
    MeshPlan(dp=1, tp=2, sp=1, ep=2),
])
def test_train_step_sharded(plan):
    cfg = get_config("tiny-moe" if plan.ep > 1 else "tiny")
    mesh = make_mesh(plan)
    opt = make_optimizer(total_steps=10)
    init_fn, step_fn = make_sharded_train_step(mesh, cfg, opt)
    state = init_fn(jax.random.key(0))
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    mask = jnp.ones((B, S), jnp.float32)
    losses = []
    for _ in range(3):
        state, metrics = step_fn(state, tokens, mask)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    # Overfit signal: loss decreases on a repeated batch.
    assert losses[-1] < losses[0]


def test_int8_kv_cache_matches_bf16_decode():
    """kv_cache_dtype='int8': teacher-forced decode logits must track the
    bf16 cache step-by-step (per-token-head symmetric quantization).

    Teacher forcing (same token sequence through both paths) rather than
    comparing greedy outputs: a random-init tiny model has near-uniform
    logits where argmax gaps (~1e-3) sit below even well-behaved
    quantization error, so exact token equality is tie-breaking luck, not
    a fidelity signal. Per-step relative logit error IS the signal — the
    measured error of the factored-scale decode path is <0.005/step."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from seldon_tpu.models import get_config, init_params, transformer

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 6, 7, 8]], jnp.int32)
    forced = [5, 9, 3, 200, 77, 13, 42, 250]

    def run(c):
        cache = transformer.init_cache(c, 1, 32)
        if c.kv_cache_dtype == "int8":
            assert cache["k"].dtype == jnp.int8
            assert cache["k_scale"].shape == cache["k"].shape[:-1]
        logits, cache = transformer.prefill(
            params, prompt, jnp.array([4]), cache, c
        )
        lgs = [logits]
        pos = jnp.array([4], jnp.int32)
        for t in forced:
            lg, cache = transformer.decode_step(
                params, jnp.array([t], jnp.int32), pos, cache, c
            )
            lgs.append(lg)
            pos = pos + 1
        return lgs

    ref = run(cfg)
    quant = run(dataclasses.replace(cfg, kv_cache_dtype="int8"))
    # Prefill never reads the cache -> exactly equal logits at step 0.
    assert float(jnp.max(jnp.abs(ref[0] - quant[0]))) == 0.0
    for i, (a, b) in enumerate(zip(ref[1:], quant[1:])):
        rel = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
        assert rel < 0.02, (i, rel)


def test_int8_kv_cache_engine_end_to_end():
    """The continuous-batching engine serves with a quantized cache."""
    import dataclasses

    import jax
    import numpy as np

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype="int8")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(16,),
                     max_admit=2, decode_chunk=4),
    )
    eng.start()
    try:
        out = eng.generate_blocking(
            [5, 6, 7], SamplingParams(max_new_tokens=12, seed=0)
        )
        assert len(out["token_ids"]) >= 1
        assert out["ttft_ms"] is not None
    finally:
        eng.stop()


def test_int8_weight_quantization_close_to_bf16():
    """Weight-only int8 (per-output-channel scales): forward logits stay
    close and greedy decode matches on tiny geometry; works for dense
    AND MoE blocks."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from seldon_tpu.models import forward, get_config, init_params
    from seldon_tpu.models.quantize import is_quantized, quantize_params

    for preset in ("tiny", "tiny-moe"):
        cfg = get_config(preset)
        params = init_params(cfg, jax.random.key(0))
        q = quantize_params(params)
        assert is_quantized(q) and not is_quantized(params)
        assert q["blocks"]["wq"].dtype == jnp.int8
        assert q["embed"].dtype == jnp.int8
        tokens = jax.random.randint(jax.random.key(1), (2, 12), 0,
                                    cfg.vocab_size)
        ref = np.asarray(forward(params, tokens, cfg), np.float32)
        out = np.asarray(forward(q, tokens, cfg), np.float32)
        denom = np.abs(ref).max() + 1e-6
        rel = np.abs(ref - out).max() / denom
        assert rel < 0.08, (preset, rel)
        # Rank agreement at the argmax (what greedy decode consumes).
        agree = (ref.argmax(-1) == out.argmax(-1)).mean()
        assert agree > 0.9, (preset, agree)


def test_w8a8_matches_bf16_math():
    """act_dtype='int8' (W8A8: dynamic per-token A8 + s8 x s8 matmuls):
    logits stay close to the int8-weight/bf16-math path and greedy
    argmax mostly agrees. Also: act_dtype is a NO-OP on unquantized
    weights (the _qdot fallback is the same contraction)."""
    import dataclasses

    import jax
    import numpy as np

    from seldon_tpu.models import forward, get_config, init_params
    from seldon_tpu.models.quantize import quantize_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    q = quantize_params(params)
    tokens = jax.random.randint(jax.random.key(1), (2, 12), 0,
                                cfg.vocab_size)
    cfg_a8 = dataclasses.replace(cfg, weight_dtype="int8",
                                 act_dtype="int8")
    ref = np.asarray(forward(q, tokens, cfg), np.float32)
    out = np.asarray(forward(q, tokens, cfg_a8), np.float32)
    denom = np.abs(ref).max() + 1e-6
    rel = np.abs(ref - out).max() / denom
    assert rel < 0.08, rel
    agree = (ref.argmax(-1) == out.argmax(-1)).mean()
    assert agree > 0.9, agree
    # bf16-weight params: act_dtype must be a no-op (falls back).
    plain = np.asarray(
        forward(params, tokens, dataclasses.replace(cfg, act_dtype="int8")),
        np.float32)
    base = np.asarray(forward(params, tokens, cfg), np.float32)
    np.testing.assert_allclose(plain, base, rtol=0, atol=0)


def test_w8a8_matches_bf16_math_decode_stepwise():
    """Teacher-forced decode with W8A8 matmuls tracks the
    int8-weight/bf16-math path step by step (same methodology and bars
    as the int8-KV acceptance test above: per-step relative logit
    error, not greedy-token luck)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from seldon_tpu.models import get_config, init_params, transformer
    from seldon_tpu.models.quantize import quantize_params

    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8")
    params = quantize_params(init_params(get_config("tiny"),
                                         jax.random.key(0)))
    prompt = jnp.array([[5, 6, 7, 8]], jnp.int32)
    forced = [5, 9, 3, 200, 77, 13, 42, 250]

    def run(c):
        cache = transformer.init_cache(c, 1, 32)
        logits, cache = transformer.prefill(
            params, prompt, jnp.array([4]), cache, c
        )
        lgs = [logits]
        pos = jnp.array([4], jnp.int32)
        for t in forced:
            lg, cache = transformer.decode_step(
                params, jnp.array([t], jnp.int32), pos, cache, c
            )
            lgs.append(lg)
            pos = pos + 1
        return lgs

    ref = run(cfg)
    a8 = run(dataclasses.replace(cfg, act_dtype="int8"))
    for i, (a, b) in enumerate(zip(ref, a8)):
        rel = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
        assert rel < 0.05, (i, rel)


def test_w8a8_full_serving_path():
    """Engine decode with W8A8 matmuls + int8 KV end-to-end."""
    import dataclasses

    import jax

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.quantize import quantize_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8",
                              kv_cache_dtype="int8", act_dtype="int8")
    params = quantize_params(init_params(cfg, jax.random.key(0)))
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(16,),
                     max_admit=2, decode_chunk=4),
    )
    eng.start()
    try:
        out = eng.generate_blocking(
            [5, 6, 7], SamplingParams(max_new_tokens=10, seed=0)
        )
        assert len(out["token_ids"]) >= 1
    finally:
        eng.stop()


def test_int8_weights_full_serving_path():
    """Engine decode on quantized weights (+ optionally quantized cache)."""
    import dataclasses

    import jax

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.quantize import quantize_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8",
                              kv_cache_dtype="int8")
    params = quantize_params(init_params(cfg, jax.random.key(0)))
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(16,),
                     max_admit=2, decode_chunk=4),
    )
    eng.start()
    try:
        out = eng.generate_blocking(
            [5, 6, 7], SamplingParams(max_new_tokens=10, seed=0)
        )
        assert len(out["token_ids"]) >= 1
    finally:
        eng.stop()


def test_quantized_checkpoint_roundtrip(tmp_path):
    """save/load of an int8-quantized tree (skeleton must carry the
    *_scale leaves per config.json's weight_dtype)."""
    import dataclasses

    import jax
    import numpy as np

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.quantize import quantize_params
    from seldon_tpu.servers import checkpoint as ckpt

    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8")
    params = quantize_params(init_params(cfg, jax.random.key(0)))
    # Idempotence: re-quantizing must be a no-op, not scale corruption.
    assert quantize_params(params) is params

    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, params, cfg)
    restored, cfg2 = ckpt.load_checkpoint(path)
    assert cfg2.weight_dtype == "int8"
    np.testing.assert_array_equal(
        np.asarray(restored["blocks"]["wq"]),
        np.asarray(params["blocks"]["wq"]),
    )
    np.testing.assert_allclose(
        np.asarray(restored["blocks"]["wq_scale"]),
        np.asarray(params["blocks"]["wq_scale"]),
    )


def test_jaxserver_weight_dtype_override(tmp_path):
    """JAXServer(weight_dtype='int8') quantizes whatever the checkpoint
    loaded (the HF-bf16-on-disk -> int8-serving path)."""
    import jax
    import jax.numpy as jnp

    from seldon_tpu.servers.jaxserver import JAXServer

    srv = JAXServer(preset="tiny", max_slots=2, max_seq_len=48,
                    weight_dtype="int8")
    srv.load()
    try:
        assert srv.cfg.weight_dtype == "int8"
        assert srv.params["blocks"]["wq"].dtype == jnp.int8
        out = srv.generate({"prompt": "ab", "max_new_tokens": 4, "seed": 1})
        assert out["completion_tokens"] >= 1
    finally:
        srv.engine.stop()
