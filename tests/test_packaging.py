"""Packaging layer (s2i-equivalent) + graph templates (chart equivalents).

The strongest check: a packaged model directory's generated entrypoint
contract actually BOOTS the microservice CLI with those env vars, and
every rendered template validates through the real webhook + reconciles."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest
import requests as rq

from seldon_tpu.operator import (
    InMemoryStore, Reconciler, SeldonDeployment,
)
from seldon_tpu.packaging import (
    generate_dockerfile, package_model, render_template,
)


def test_package_model_writes_artifacts(tmp_path):
    (tmp_path / "MyModel.py").write_text(
        "class MyModel:\n"
        "    def predict(self, X, names, meta=None):\n"
        "        return X\n"
    )
    out = package_model(str(tmp_path), "MyModel", service_type="MODEL")
    assert set(out) == {"dockerfile", "run", "environment"}
    run = open(out["run"]).read()
    assert "MODEL_NAME" in run and "SERVICE_TYPE" in run
    assert "seldon_tpu.runtime.microservice" in run
    assert os.access(out["run"], os.X_OK)
    env = dict(
        l.split("=", 1) for l in open(out["environment"]).read().splitlines()
    )
    assert env["MODEL_NAME"] == "MyModel"
    df = open(out["dockerfile"]).read()
    assert "EXPOSE 9000" in df and "CMD" in df


def test_dockerfile_tpu_variant():
    df = generate_dockerfile(tpu=True)
    assert "cloud-tpu-images" in df
    assert "jax[cpu]" not in df


@pytest.mark.parametrize("language,serve_key,serve_name", [
    ("nodejs", "microservice_js", "microservice.js"),
    ("r", "microservice_r", "microservice.R"),
    ("java", "microservice_java", "Microservice.java"),
])
def test_package_model_foreign_language(tmp_path, language, serve_key,
                                        serve_name):
    """R/NodeJS builders render a Dockerfile + protocol shim
    (reference wrappers/s2i/{R,nodejs}); the shim must carry every
    route + env knob the docs/wrappers.md protocol requires."""
    out = package_model(str(tmp_path), "MyModel", language=language)
    assert "dockerfile" in out and serve_key in out
    df = open(out["dockerfile"]).read()
    assert "EXPOSE 9000" in df
    assert "ENV MODEL_NAME=MyModel" in df
    assert "ENV PREDICTIVE_UNIT_SERVICE_PORT=9000" in df
    assert df.rstrip().endswith("]")  # ENV baked BEFORE the CMD line
    shim = open(out[serve_key]).read()
    # The JSON unit protocol surface (docs/wrappers.md).
    for route in ("predict", "transform-input", "transform-output",
                  "route", "aggregate", "send-feedback", "/live", "/ready",
                  "/metrics"):
        assert route in shim, f"{serve_name} missing {route}"
    for env_var in ("PREDICTIVE_UNIT_SERVICE_PORT", "MODEL_NAME",
                    "PREDICTIVE_UNIT_PARAMETERS"):
        assert env_var in shim, f"{serve_name} missing {env_var}"
    # Routers answer [[branch]]; meta echoes through.
    assert "[[branch]]" in shim or "list(list(branch))" in shim
    assert "meta" in shim


def test_package_model_unknown_language(tmp_path):
    with pytest.raises(ValueError, match="unknown language"):
        package_model(str(tmp_path), "M", language="cobol")


def test_node_shim_boots_if_node_available(tmp_path):
    """Full boot test of the node shim when a node interpreter exists
    (skipped in images without one — render+lint is still pinned by
    test_package_model_foreign_language)."""
    import shutil as _sh

    node = _sh.which("node")
    if node is None:
        pytest.skip("node not installed in this image")
    (tmp_path / "MyModel.js").write_text(
        "exports.predict = (x) => x.map(r => r.map(v => v * 2));\n"
    )
    out = package_model(str(tmp_path), "MyModel", language="nodejs")
    # The shim resolves the user module under /microservice; run from a
    # chroot-free test by patching the resolve root via cwd symlink.
    shim = open(out["microservice_js"]).read().replace(
        "'/microservice'", repr(str(tmp_path))
    )
    shim_path = tmp_path / "shim.js"
    shim_path.write_text(shim)
    env = dict(os.environ)
    env.update({"MODEL_NAME": "MyModel",
                "PREDICTIVE_UNIT_SERVICE_PORT": "0"})
    proc = subprocess.Popen([node, str(shim_path)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)
    try:
        line = proc.stdout.readline()
        assert "listening" in line, line
        import re

        port = int(re.search(r"listening on (\d+)", line).group(1))
        r = rq.post(f"http://127.0.0.1:{port}/predict",
                    json={"data": {"ndarray": [[1, 2]]}}, timeout=10)
        assert r.status_code == 200
        assert r.json()["data"]["ndarray"] == [[2, 4]]
    finally:
        proc.kill()


def test_java_shim_hardening_rendered(tmp_path):
    """The generated Java source carries the robustness fixes: request
    concurrency (a slow predict() must not starve /live and /ready),
    tensor shape surfaced like the node/R shims, and malformed
    PREDICTIVE_UNIT_PARAMETERS tolerated at boot."""
    out = package_model(str(tmp_path), "MyModel", language="java")
    src = open(out["microservice_java"]).read()
    assert "Executors.newCachedThreadPool()" in src
    assert "bad PREDICTIVE_UNIT_PARAMETERS" in src
    assert 'get("shape")' in src and 'put("shape"' in src


def test_java_shim_compiles_and_boots_if_jdk_available(tmp_path):
    """Full compile + boot test of the java shim when a JDK exists
    (skipped in images without one — render is still pinned by
    test_package_model_foreign_language)."""
    import shutil as _sh

    javac, java = _sh.which("javac"), _sh.which("java")
    if javac is None or java is None:
        pytest.skip("JDK not installed in this image")
    (tmp_path / "MyModel.java").write_text(
        "import java.util.*;\n"
        "public class MyModel {\n"
        "    public Object predict(Object data, List names, Map meta) {\n"
        "        if (meta != null && meta.containsKey(\"shape\"))\n"
        "            return meta.get(\"shape\");\n"
        "        List<Object> out = new ArrayList<>();\n"
        "        for (Object row : (List<?>) data) {\n"
        "            List<Object> r = new ArrayList<>();\n"
        "            for (Object v : (List<?>) row)\n"
        "                r.add(((Number) v).doubleValue() * 2);\n"
        "            out.add(r);\n"
        "        }\n"
        "        return out;\n"
        "    }\n"
        "}\n"
    )
    out = package_model(str(tmp_path), "MyModel", language="java")
    classes = tmp_path / "classes"
    subprocess.run(
        [javac, "-d", str(classes), out["microservice_java"],
         str(tmp_path / "MyModel.java")],
        check=True, capture_output=True, text=True)
    env = dict(os.environ)
    env.update({"MODEL_NAME": "MyModel",
                "PREDICTIVE_UNIT_SERVICE_PORT": "0",
                # Malformed on purpose: boot must survive it (shim
                # falls back to []).
                "PREDICTIVE_UNIT_PARAMETERS": "{not json"})
    proc = subprocess.Popen([java, "-cp", str(classes), "Microservice"],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        line = proc.stdout.readline()
        assert "listening" in line, line
        import re

        port = int(re.search(r"listening on (\d+)", line).group(1))
        r = rq.post(f"http://127.0.0.1:{port}/predict",
                    json={"data": {"ndarray": [[1, 2]]}}, timeout=10)
        assert r.status_code == 200
        assert r.json()["data"]["ndarray"] == [[2, 4]]
        r = rq.post(f"http://127.0.0.1:{port}/api/v0.1/route",
                    json={"data": {"ndarray": [[1]]}}, timeout=10)
        assert r.json()["data"]["ndarray"] == [[-1]]
        # Tensor shape rides into predict's meta (node/R shim parity);
        # the test model echoes it back when present.
        r = rq.post(f"http://127.0.0.1:{port}/predict",
                    json={"data": {"tensor": {"shape": [2, 2],
                                              "values": [1, 2, 3, 4]}}},
                    timeout=10)
        assert r.status_code == 200
        assert r.json()["data"]["ndarray"] == [2, 2]
    finally:
        proc.kill()


def test_packaged_entrypoint_boots_microservice(tmp_path):
    """The generated env contract really starts a serving process."""
    (tmp_path / "EchoModel.py").write_text(
        "import numpy as np\n"
        "class EchoModel:\n"
        "    def predict(self, X, names, meta=None):\n"
        "        return np.asarray(X) * 3\n"
    )
    package_model(str(tmp_path), "EchoModel")
    env = dict(os.environ)
    env.update({
        "MODEL_NAME": "EchoModel",
        "SERVICE_TYPE": "MODEL",
        "API_TYPE": "REST",
        "PREDICTIVE_UNIT_SERVICE_PORT": "0",  # ephemeral
        "PYTHONPATH": (
            str(tmp_path) + os.pathsep
            + os.path.dirname(os.path.dirname(__file__))
        ),
        "JAX_PLATFORMS": "cpu",
    })
    # Run the entrypoint's exec line directly (sh may not exist in CI
    # containers' PATH the same way; python -m is the contract's core).
    proc = subprocess.Popen(
        [sys.executable, "-m", "seldon_tpu.runtime.microservice",
         "EchoModel", "--api-type", "REST", "--http-port", "0"],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        import re

        port = None
        deadline = time.time() + 60
        while time.time() < deadline and port is None:
            line = proc.stdout.readline().decode()
            m = re.search(r"REST serving on [^:]*:(\d+)", line)
            if m:
                port = int(m.group(1))
            if proc.poll() is not None:
                raise AssertionError(proc.stdout.read().decode()[-2000:])
        assert port, "no 'REST serving on' line printed"
        r = rq.post(
            f"http://127.0.0.1:{port}/predict",
            json={"data": {"ndarray": [[2.0]]}}, timeout=10,
        )
        assert r.status_code == 200, r.text
        assert r.json()["data"]["ndarray"] == [[6.0]]
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Templates
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("template,kw", [
    ("single-model", {"model_uri": "gs://b/m"}),
    ("abtest", {"model_uri_a": "gs://b/a", "model_uri_b": "gs://b/b"}),
    ("mab", {"model_uri_a": "gs://b/a", "model_uri_b": "gs://b/b"}),
    ("outlier-transformer", {"model_uri": "gs://b/m"}),
])
def test_templates_validate_and_reconcile(template, kw):
    cr = render_template(template, name=f"t-{template}", **kw)
    sdep = SeldonDeployment.from_dict(cr)
    store = InMemoryStore()
    status = Reconciler(store, istio_enabled=True).reconcile(sdep)
    assert status.state == "Available", status
    assert store.list("Deployment", "default")


def test_template_unknown_raises():
    with pytest.raises(ValueError):
        render_template("nope", name="x")


def test_mab_template_carries_bandit_parameters():
    cr = render_template("mab", name="m", model_uri_a="a", model_uri_b="b",
                         epsilon=0.2)
    graph = cr["spec"]["predictors"][0]["graph"]
    assert graph["type"] == "ROUTER"
    params = {p["name"]: p["value"] for p in graph["parameters"]}
    assert params["epsilon"] == "0.2" and params["n_branches"] == "2"
    assert len(graph["children"]) == 2
