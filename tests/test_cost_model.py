"""graftroof cost-model tests: closed forms, coverage, purity.

The load-bearing claims, in test form:
 * the closed-form arithmetic is RIGHT — hand-counted totals for the
   tiny config (flops/token, kv bytes/token, weight bytes, a full
   decode-rung dispatch) pinned as literals;
 * every family in ``shape_lattice.FAMILIES`` is priced (the covered
   set is pinned to FAMILIES exactly) and an unknown family raises
   instead of silently pricing zero;
 * env gating follows the None-attribute idiom (ROOF_LEDGER), peak
   resolution honors env > table > microbench, and the conservation
   audit is not vacuous (a ledger fed inconsistent spans breaches);
 * the ledger is pure observation — greedy outputs are BIT-IDENTICAL
   with ROOF_LEDGER on vs off across all five dispatch paths (dense,
   paged-KV, chunked prefill, ragged, spec-decode).
"""

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import cost_model
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from seldon_tpu.servers.shape_lattice import FAMILIES

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=4)
# Mixed lengths so admission groups carry real bucket + group padding.
PROMPTS = [list(range(2, 2 + n)) for n in (5, 12, 16, 7)]

# The five dispatch paths whose outputs the roof must not perturb.
MODES = {
    "dense": {},
    "paged": dict(paged_kv=True, kv_block=16, kv_pool_blocks=12,
                  prompt_buckets=(16, 32)),
    "chunked": dict(chunked_prefill=True, prefill_chunk=8, prefix_block=8),
    "ragged": dict(paged_kv=True, chunked_prefill=True, prefill_chunk=8,
                   prefix_block=8, kv_block=8, ragged=True),
    "spec": dict(spec_decode=True, spec_k=2, paged_kv=True, kv_block=8,
                 prefix_block=8),
}

TINY = get_config("tiny")
GEOM = dict(max_slots=4, max_seq_len=64)


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(eng, prompts):
    qs = [eng.submit(p, GREEDY) for p in prompts]
    outs = []
    for q in qs:
        toks = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            toks.extend(item["tokens"])
        outs.append(toks)
    return outs


# ---------------------------------------------------------------------------
# Closed forms, hand-counted on the tiny config
# ---------------------------------------------------------------------------
# tiny: n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
# vocab=256, bf16 weights + kv. Per layer: qkv 64*(4*16 + 2*2*16) =
# 8192, o 64*64 = 4096, mlp 3*64*128 = 24576 -> 36864 params.


def test_matmul_params_per_layer_hand_counted():
    assert cost_model.matmul_params_per_layer(TINY) == 36864


def test_flops_per_token_hand_counted():
    # 2 * (2 layers * 36864 + lm_head 64*256) = 2*(73728 + 16384)
    assert cost_model.flops_per_token(TINY) == 180224


def test_kv_bytes_per_token_hand_counted():
    # 2 (K+V) * 2 layers * 2 kv_heads * 16 head_dim * 2 bytes
    assert cost_model.kv_bytes_per_token(TINY) == 256


def test_weight_bytes_hand_counted():
    # matmuls 2*36864*2B + embedding 256*64*2B + lm_head 64*256*2B
    assert cost_model.weight_bytes(TINY) == 212992


def test_attn_flops_hand_counted():
    # 4 * d_model * q * kv * layers = 4 * 64 * 1 * 64 * 2
    assert cost_model.attn_flops(TINY, 1, 64) == 32768
    # Causal prefill of 8 fresh tokens: sum 1..8 = 36 kv positions.
    assert cost_model.causal_attn_flops(TINY, 8) == 4 * 64 * 36 * 2
    # With an 8-token prior every row attends 8 more positions.
    assert (cost_model.causal_attn_flops(TINY, 8, prior=8)
            == 4 * 64 * (36 + 64) * 2)


def test_decode_key_hand_counted():
    # ("decode", 8): 8 steps x 4 slots, each step fpt + full-window
    # attention; bytes re-read the weights + window every step.
    flops, bytes_ = cost_model.cost_of_key(("decode", 8), TINY, **GEOM)
    assert flops == 8 * 4 * (180224 + 32768) == 6815744
    assert bytes_ == 8 * (212992 + 4 * 64 * 256 + 4 * 256) == 2236416


def test_admit_key_hand_counted():
    flops, bytes_ = cost_model.cost_of_key(("admit", 8, 2), TINY, **GEOM)
    assert flops == 2 * (8 * 180224 + cost_model.causal_attn_flops(TINY, 8))
    assert bytes_ == 212992 + 2 * 8 * 256


# ---------------------------------------------------------------------------
# graftmesh: per-chip closed forms at tp=2, hand-counted
# ---------------------------------------------------------------------------
# Exact-TP split (models/tp_sharding): qkv + gate/up shard their output
# dim, o / down / embeddings / lm_head replicate. Per layer per chip:
# qkv 8192/2 = 4096, o 4096, gate+up 2*64*128/2 = 8192, down 8192
# -> 24576 params.


def test_tp2_per_layer_hand_counted():
    assert cost_model.matmul_params_per_layer(TINY, 2) == 24576


def test_tp2_flops_per_token_hand_counted():
    # 2 * (2 layers * 24576 + lm_head 64*256 replicated) = 131072
    assert cost_model.flops_per_token(TINY, 2) == 131072


def test_tp2_attn_and_kv_hand_counted():
    # Heads shard on 'tp': per-chip attention and KV both halve.
    assert cost_model.attn_flops(TINY, 1, 64, tp=2) == 16384
    assert cost_model.kv_bytes_per_token(TINY, 2) == 128


def test_tp2_weight_bytes_hand_counted():
    # matmuls 2*24576*2B + embedding 32768 + lm_head 32768 (both full
    # on every chip) = 163840
    assert cost_model.weight_bytes(TINY, 2) == 163840


def test_tp2_decode_key_hand_counted():
    flops, bytes_ = cost_model.cost_of_key(("decode", 8), TINY,
                                           tp=2, **GEOM)
    assert flops == 8 * 4 * (131072 + 16384) == 4718592
    assert bytes_ == 8 * (163840 + 4 * 64 * 128 + 4 * 128) == 1576960


def test_tp1_default_unchanged():
    # The tp kwarg defaults to 1 and must price exactly the seed
    # numbers — the tp=1 path is byte-identical to a build without
    # graftmesh.
    assert cost_model.matmul_params_per_layer(TINY, 1) == 36864
    assert (cost_model.cost_of_key(("decode", 8), TINY, tp=1, **GEOM)
            == cost_model.cost_of_key(("decode", 8), TINY, **GEOM))


def test_tp_moe_shards_attention_only():
    # MoE expert weights replicate (expert_out contracts d_ff — a psum
    # would break exactness), so only the qkv term divides.
    moe = get_config("tiny-moe")
    full = cost_model.matmul_params_per_layer(moe, 1)
    half = cost_model.matmul_params_per_layer(moe, 2)
    assert full - half == 8192 - 4096  # qkv/2 is the only delta


def test_roof_ledger_binds_tp():
    led = cost_model.RoofLedger()
    led.bind(TINY, tp=2, **GEOM)
    snap = led.snapshot()
    assert snap["tp"] == 2
    # The bound geometry threads into every priced key.
    assert led._cost(("decode", 8)) == cost_model.cost_of_key(
        ("decode", 8), TINY, tp=2, **GEOM)
    # Default bind stays tp=1 — the seed schema payload, plus the key.
    led2 = cost_model.RoofLedger()
    led2.bind(TINY, **GEOM)
    assert led2.snapshot()["tp"] == 1


# ---------------------------------------------------------------------------
# Family coverage pinned to the lattice
# ---------------------------------------------------------------------------

# One representative key per family, at the registered arity.
REPRESENTATIVE = {
    "deactivate": ("deactivate",),
    "admit": ("admit", 8, 2),
    "admit-prefix": ("admit-prefix", 8, 8, 2),
    "admit-paged": ("admit-paged", 8, 2, 16),
    "chunk": ("chunk", 8, 2, 16),
    "seed-prefix": ("seed-prefix", 16),
    "cow": ("cow",),
    "decode": ("decode", 8),
    "ragged": ("ragged", 8),
    "draft": ("draft", 4),
    "verify": ("verify", 4),
}


def test_every_family_is_priced():
    assert set(REPRESENTATIVE) == set(FAMILIES), \
        "FAMILIES drifted — add a representative key AND a cost formula"
    for fam, key in REPRESENTATIVE.items():
        flops, bytes_ = cost_model.cost_of_key(key, TINY, kv_block=16,
                                               ragged_chunk=8, **GEOM)
        assert flops >= 0.0 and bytes_ >= 0.0, fam
        # Everything but the host-drafted spec rung moves SOME bytes.
        if fam != "draft":
            assert bytes_ > 0.0, fam


def test_unknown_family_raises():
    with pytest.raises(ValueError, match="unknown dispatch family"):
        cost_model.cost_of_key(("warp", 8), TINY, **GEOM)


def test_draft_prices_zero_without_resident_model():
    # Host n-gram drafting dispatches nothing on the device.
    assert cost_model.cost_of_key(("draft", 4), TINY, **GEOM) == (0.0, 0.0)
    # A resident draft checkpoint prices as its own decode ladder.
    flops, bytes_ = cost_model.cost_of_key(("draft", 4), TINY,
                                           draft_cfg=TINY, **GEOM)
    assert (flops, bytes_) == cost_model.cost_of_key(("decode", 4), TINY,
                                                     **GEOM)


def test_ragged_priced_at_capacity():
    # The static cost_of_key formula stays the capacity bound at
    # max_slots * C regardless of packing (exported as capacity_*
    # since graftkern; the ledger's live fields come from
    # ragged_occupancy_cost when the engine feeds occupancy).
    f8, _ = cost_model.cost_of_key(("ragged", 8), TINY, **GEOM)
    f16, _ = cost_model.cost_of_key(("ragged", 16), TINY, **GEOM)
    assert f16 > f8 > 0.0


def test_ragged_occupancy_cost_hand_counted():
    # graftkern live pricing: q_tokens * fpt + 4 * d_model * attn_qk *
    # layers; bytes = weights + (kv_read + q) positions of KV traffic.
    flops, bytes_ = cost_model.ragged_occupancy_cost(
        TINY, q_tokens=10, kv_read_tokens=20, attn_qk=100)
    assert flops == 10 * 180224 + 4 * 64 * 100 * 2 == 1853440
    assert bytes_ == 212992 + 20 * 256 + 10 * 256 == 220672
    # tp=2: fpt/kv/weights all take their per-chip forms.
    flops2, bytes2 = cost_model.ragged_occupancy_cost(
        TINY, q_tokens=10, kv_read_tokens=20, attn_qk=100, tp=2)
    assert flops2 == 10 * 131072 + 4 * 64 * 100 * 2 // 2 == 1336320
    assert bytes2 == 163840 + 20 * 128 + 10 * 128 == 167680


def test_ragged_occupancy_ledger_live_vs_capacity():
    # The ledger prices a "ragged" key's LIVE fields from the queued
    # occupancy (FIFO, one entry per wave) and always accumulates the
    # static capacity figure alongside; with the queue empty the live
    # fields fall back to capacity, and non-ragged families are always
    # live == capacity.
    led = cost_model.RoofLedger()
    led.bind(TINY, ragged_chunk=8, **GEOM)
    cap_f, cap_b = led._cost(("ragged", 8))
    led.note_ragged_occupancy(10, 20, 100)
    led.note_wave([("ragged", 8)], 2.0)
    (v,) = led.snapshot()["variants"]
    assert v["flops"] == 1853440.0 and v["bytes"] == 220672.0
    assert v["capacity_flops"] == cap_f
    assert v["capacity_bytes"] == cap_b
    assert v["capacity_flops"] > v["flops"]
    # Queue drained: an occupancy-blind wave prices live == capacity.
    led.note_wave([("ragged", 8)], 2.0)
    (v,) = led.snapshot()["variants"]
    assert v["flops"] == 1853440.0 + cap_f
    assert v["capacity_flops"] == 2 * cap_f
    led.note_wave([("decode", 8)], 1.0)
    (d,) = [x for x in led.snapshot()["variants"]
            if x["family"] == "decode"]
    assert d["capacity_flops"] == d["flops"]
    assert d["capacity_bytes"] == d["bytes"]
    assert d["capacity_predicted_ms"] == d["predicted_ms"]


# ---------------------------------------------------------------------------
# Peaks + predict
# ---------------------------------------------------------------------------


def test_peak_resolution_order(monkeypatch):
    monkeypatch.delenv("ROOF_PEAK_TFLOPS", raising=False)
    monkeypatch.delenv("ROOF_PEAK_GBS", raising=False)
    table = cost_model.resolve_peaks("TPU v5e")
    assert table == {"tflops": 197.0, "gbs": 819.0, "source": "table"}
    # Longest-substring wins: v5p must not fall through to "v5 lite".
    assert cost_model.resolve_peaks("TPU v5p")["tflops"] == 459.0
    # Unknown platform: the cached one-shot microbench.
    mb = cost_model.resolve_peaks("cpu")
    assert mb["source"] == "microbench" and mb["tflops"] > 0.0
    # Env overrides everything, each knob individually.
    monkeypatch.setenv("ROOF_PEAK_TFLOPS", "123.5")
    env = cost_model.resolve_peaks("TPU v5e")
    assert env["tflops"] == 123.5 and env["source"] == "env"
    assert env["gbs"] == 819.0  # GBS still from the table
    # A malformed override falls back rather than crashing the engine.
    monkeypatch.setenv("ROOF_PEAK_TFLOPS", "fast")
    assert cost_model.resolve_peaks("TPU v5e")["tflops"] == 197.0


def test_predict_surface_monotone():
    peaks = {"tflops": 1.0, "gbs": 1.0, "source": "env"}
    base = cost_model.predict(16, 8, TINY, peaks=peaks, **GEOM)
    assert set(base) == {"flops", "bytes", "est_ms"}
    assert base["est_ms"] > 0.0
    longer = cost_model.predict(32, 8, TINY, peaks=peaks, **GEOM)
    deeper = cost_model.predict(16, 16, TINY, peaks=peaks, **GEOM)
    assert longer["flops"] > base["flops"]
    assert deeper["flops"] > base["flops"]
    assert longer["est_ms"] > base["est_ms"]
    # Degenerate inputs clamp instead of going negative.
    zero = cost_model.predict(-3, 0, TINY, peaks=peaks, **GEOM)
    assert zero["flops"] >= 0.0 and zero["est_ms"] >= 0.0


def test_predict_request_ms_is_memoized():
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    a = led.predict_request_ms(16, 8)
    assert a > 0.0
    assert led.predict_request_ms(16, 8) == a
    assert (16, 8) in led._predict_cache


# ---------------------------------------------------------------------------
# Ledger unit semantics
# ---------------------------------------------------------------------------


def test_from_env_gating(monkeypatch):
    monkeypatch.delenv("ROOF_LEDGER", raising=False)
    assert cost_model.from_env() is None
    monkeypatch.setenv("ROOF_LEDGER", "0")
    assert cost_model.from_env() is None
    monkeypatch.setenv("ROOF_LEDGER", "1")
    assert cost_model.from_env() is not None


def test_note_wave_conserves_device_time():
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    led.note_wave([("admit", 8, 2), ("decode", 8), ("cow",)],
                  device_ms=30.0)
    snap = led.snapshot()
    assert snap["waves"] == 1
    assert sum(v["device_ms"] for v in snap["variants"]) \
        == pytest.approx(30.0, abs=0.01)
    # The split is est-weighted: decode prices far above cow, so it
    # must carry more of the wave.
    by_fam = {v["family"]: v for v in snap["variants"]}
    assert by_fam["decode"]["device_ms"] > by_fam["cow"]["device_ms"]


def test_note_wave_unpriceable_key_never_raises():
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    led.note_wave([("warp", 3), ("decode", 8)], device_ms=10.0)
    snap = led.snapshot()
    # The foreign key prices zero but still appears, and the priced key
    # absorbs the whole est-weighted wave.
    assert sum(v["device_ms"] for v in snap["variants"]) \
        == pytest.approx(10.0, abs=0.01)


def test_variant_overflow_folds_to_other():
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    for g in range(cost_model._MAX_VARIANTS + 8):
        led.note_wave([("admit", 8, g + 1)], device_ms=1.0)
    snap = led.snapshot()
    assert len(snap["variants"]) <= cost_model._MAX_VARIANTS + 1
    other = [v for v in snap["variants"] if v["key"] == "other"]
    assert len(other) == 1 and other[0]["dispatches"] == 8


def test_audit_clean_on_consistent_feed():
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    for _ in range(5):
        led.note_step(1.0, 10.0, 2.0, 15.0)  # 2ms pipelined gap
        led.audit()
    snap = led.snapshot()
    assert snap["conservation"]["checked"] == 5
    assert snap["conservation"]["breaches"] == 0
    assert snap["step"]["overlap_ms"] == pytest.approx(10.0)
    assert snap["host_frac"] == pytest.approx(3.0 / 15.0, abs=1e-6)


def test_audit_breaches_on_inconsistent_feed():
    # The audit is not vacuous: components exceeding the measured wall
    # (a span clocked shorter than its own parts) must breach.
    led = cost_model.RoofLedger()
    led.bind(TINY, **GEOM)
    led.note_step(100.0, 100.0, 100.0, 5.0)
    led.audit()
    snap = led.snapshot()
    assert snap["conservation"]["breaches"] == 1
    assert "step components" in snap["conservation"]["last_breach"]


# ---------------------------------------------------------------------------
# Purity: greedy outputs bit-identical with the roof on vs off
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", sorted(MODES))
def test_greedy_bit_identical_roof_on_off(mode, monkeypatch):
    monkeypatch.delenv("ROOF_LEDGER", raising=False)
    eng = _engine(**MODES[mode])
    try:
        base = _collect(eng, PROMPTS)
    finally:
        eng.stop()
    monkeypatch.setenv("ROOF_LEDGER", "1")
    eng = _engine(**MODES[mode])
    try:
        roofed = _collect(eng, PROMPTS)
        snap = eng.debug_roof()
    finally:
        eng.stop()
    assert roofed == base, f"ROOF_LEDGER perturbed {mode} greedy output"
    # And the roof actually observed the run it rode along on.
    assert snap is not None and snap["boundaries"] > 0
    assert snap["totals"]["dispatches"] > 0
    assert snap["conservation"]["breaches"] == 0


def test_disabled_engine_keeps_none_attribute(monkeypatch):
    monkeypatch.delenv("ROOF_LEDGER", raising=False)
    eng = _engine(start=False)
    assert eng._roof is None
    assert eng.debug_roof() is None
    assert eng.roof_predict_ms(16, 8) is None


def test_enabled_engine_predicts_and_serves_snapshot(monkeypatch):
    monkeypatch.setenv("ROOF_LEDGER", "1")
    eng = _engine(start=False)
    assert eng._roof is not None
    assert eng._timing_on, "ROOF_LEDGER must imply dispatch timing"
    assert eng.roof_predict_ms(16, 8) > 0.0
    snap = eng.debug_roof()
    assert snap["enabled"] is True and snap["boundaries"] == 0
