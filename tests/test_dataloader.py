"""Native prefetching data loader vs the bit-identical numpy fallback,
and end-to-end into the sharded train step."""

import numpy as np
import pytest

from seldon_tpu.data import TokenDataLoader, write_token_shard


@pytest.fixture()
def shards(tmp_path):
    rng = np.random.default_rng(0)
    p1 = write_token_shard(str(tmp_path / "a.bin"),
                           rng.integers(0, 250, size=5000))
    p2 = write_token_shard(str(tmp_path / "b.bin"),
                           rng.integers(0, 250, size=3000))
    return [p1, p2]


def test_native_lib_loads(shards):
    dl = TokenDataLoader(shards, batch_size=4, seq_len=32, seed=1)
    try:
        assert dl.native, "native dataloader should build in this image"
        assert dl.total_tokens == 8000
        b = next(dl)
        assert b.shape == (4, 33) and b.dtype == np.int32
        assert (b >= 0).all() and (b < 250).all()
    finally:
        dl.close()


def test_native_and_fallback_bit_identical(shards):
    native = TokenDataLoader(shards, batch_size=8, seq_len=64, seed=42)
    fallback = TokenDataLoader(shards, batch_size=8, seq_len=64, seed=42,
                               force_fallback=True)
    try:
        assert native.native and not fallback.native
        for _ in range(10):
            np.testing.assert_array_equal(next(native), next(fallback))
    finally:
        native.close()


def test_deterministic_and_seed_sensitive(shards):
    a = TokenDataLoader(shards, batch_size=4, seq_len=16, seed=7,
                        force_fallback=True)
    b = TokenDataLoader(shards, batch_size=4, seq_len=16, seed=7,
                        force_fallback=True)
    c = TokenDataLoader(shards, batch_size=4, seq_len=16, seed=8,
                        force_fallback=True)
    np.testing.assert_array_equal(next(a), next(b))
    assert not np.array_equal(next(a), next(c))


def test_windows_are_real_corpus_slices(shards):
    """Every emitted window must be a contiguous slice of the concatenated
    corpus (catches off-by-ones and shard-boundary bugs)."""
    corpus = np.concatenate([np.fromfile(p, dtype="<u4") for p in shards])
    dl = TokenDataLoader(shards, batch_size=16, seq_len=48, seed=3)
    try:
        for _ in range(5):
            batch = next(dl)
            for row in batch:
                # locate by first two tokens then verify the whole window
                starts = np.where(
                    (corpus[:-49] == row[0]) & (corpus[1:-48] == row[1])
                )[0]
                assert any(
                    np.array_equal(corpus[s: s + 49], row) for s in starts
                ), "window is not a contiguous corpus slice"
    finally:
        dl.close()


def test_feeds_train_step(shards):
    import jax
    import jax.numpy as jnp

    from seldon_tpu.models import get_config
    from seldon_tpu.models.train import make_optimizer, make_sharded_train_step
    from seldon_tpu.parallel import MeshPlan, make_mesh

    cfg = get_config("tiny")
    mesh = make_mesh(MeshPlan(dp=2))
    init_fn, step_fn = make_sharded_train_step(
        mesh, cfg, make_optimizer(total_steps=10), seq_sharded=False
    )
    state = init_fn(jax.random.key(0))
    dl = TokenDataLoader(shards, batch_size=4, seq_len=31, seed=0)
    try:
        for _ in range(2):
            batch = jnp.asarray(next(dl)[:, :32])  # [B, S]
            state, metrics = step_fn(
                state, batch, jnp.ones_like(batch, jnp.float32)
            )
            assert np.isfinite(float(metrics["loss"]))
    finally:
        dl.close()


def test_too_small_corpus_raises(tmp_path):
    p = write_token_shard(str(tmp_path / "tiny.bin"), [1, 2, 3])
    with pytest.raises(ValueError):
        TokenDataLoader([p], batch_size=1, seq_len=16, force_fallback=True)
