"""Method-dispatch tests with inline user objects, mirroring the reference's
python/tests/test_model_microservice.py / test_router_microservice.py /
test_combiner_microservice.py fixtures."""

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime import seldon_methods, user_model


class UserModel(user_model.SeldonComponent):
    def predict(self, X, names, meta=None):
        return X * 2

    def tags(self):
        return {"model": "double"}

    def metrics(self):
        return [{"key": "calls", "type": "COUNTER", "value": 1}]


class RawModel:
    def predict_raw(self, msg):
        X = payloads.get_data_from_message(msg)
        return payloads.build_message(X + 1)


class Transformer:
    def transform_input(self, X, names, meta=None):
        return X - 1


class OutTransformer:
    def transform_output(self, X, names, meta=None):
        return X * 10


class Router:
    def route(self, X, names):
        return 1


class BadRouter:
    def route(self, X, names):
        return "not an int"


class Combiner:
    def aggregate(self, Xs, names_list):
        return np.mean(np.stack(Xs), axis=0)


class FeedbackRouter:
    def __init__(self):
        self.seen = []

    def route(self, X, names):
        return 0

    def send_feedback(self, X, names, reward, truth, routing=None):
        self.seen.append((reward, routing))


def _req(arr=None, kind="dense"):
    return payloads.build_message(np.ones((2, 3)) if arr is None else arr, kind=kind)


class TestPredict:
    def test_basic(self):
        resp = seldon_methods.predict(UserModel(), _req())
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.full((2, 3), 2.0)
        )

    def test_tags_and_metrics_attached(self):
        resp = seldon_methods.predict(UserModel(), _req())
        assert resp.meta.tags["model"].string_value == "double"
        assert resp.meta.metrics[0].key == "calls"

    def test_raw_hook_wins(self):
        resp = seldon_methods.predict(RawModel(), _req())
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.full((2, 3), 2.0)
        )

    def test_kind_mirrored(self):
        resp = seldon_methods.predict(UserModel(), _req(kind="ndarray"))
        assert payloads.data_kind(resp) == "ndarray"


class TestTransforms:
    def test_input(self):
        resp = seldon_methods.transform_input(Transformer(), _req())
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.zeros((2, 3))
        )

    def test_output(self):
        resp = seldon_methods.transform_output(OutTransformer(), _req())
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.full((2, 3), 10.0)
        )

    def test_identity_fallthrough(self):
        req = _req()
        resp = seldon_methods.transform_input(object(), req)
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.ones((2, 3))
        )


class TestRoute:
    def test_branch_payload(self):
        resp = seldon_methods.route(Router(), _req())
        out = payloads.get_data_from_message(resp)
        assert out.shape == (1, 1)
        assert int(out[0, 0]) == 1

    def test_bad_return_type(self):
        with pytest.raises(TypeError):
            seldon_methods.route(BadRouter(), _req())


class TestAggregate:
    def test_mean(self):
        msgs = pb.SeldonMessageList(
            seldonMessages=[_req(np.zeros((2, 2))), _req(np.full((2, 2), 2.0))]
        )
        resp = seldon_methods.aggregate(Combiner(), msgs)
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.ones((2, 2))
        )


class TestSendFeedback:
    def test_routing_passed(self):
        r = FeedbackRouter()
        fb = pb.Feedback()
        fb.request.CopyFrom(_req())
        fb.request.meta.routing["router"] = 1
        fb.reward = 0.75
        seldon_methods.send_feedback(r, fb, unit_name="router")
        assert r.seen == [(0.75, 1)]

    def test_no_hook_is_noop(self):
        fb = pb.Feedback()
        fb.request.CopyFrom(_req())
        resp = seldon_methods.send_feedback(object(), fb)
        assert isinstance(resp, pb.SeldonMessage)


class TestGenerate:
    def test_dispatch(self):
        class Gen:
            def generate(self, req):
                return {"text": "hi", "token_ids": [1, 2], "ttft_ms": 3.0}

        req = pb.GenerateRequest(prompt="hello", max_new_tokens=2)
        resp = seldon_methods.generate(Gen(), req)
        assert resp.text == "hi"
        assert list(resp.token_ids) == [1, 2]
        assert resp.completion_tokens == 2
