"""Request-lifecycle hardening: deadlines, cancellation, load shedding,
graceful drain (tiny config, CPU mesh).

The load-bearing claims, in test form:
 * stop() NEVER abandons a waiter — queued and in-flight requests all
   receive an error item + None sentinel, so generate_blocking callers
   can't hang across shutdown (the PR-1 regression this PR fixes);
 * submit() validates what can never succeed (decode past max_seq_len,
   paged prompts bigger than the whole pool) instead of failing
   mid-dispatch;
 * a bounded admission queue sheds with typed EngineOverloaded (429,
   retriable) and a draining engine refuses with EngineDraining (503);
 * deadlines expire queued requests without touching the device and
   finalize in-flight requests at the next boundary;
 * cancel(rid) frees the slot — and, paged, the pool blocks — within
   one scheduler boundary: a pool blocked out by a cancelled stream
   admits the next waiter;
 * the REST wrapper maps the typed errors onto 429/503 and readiness
   flips during drain;
 * after any of the above, engine accounting is leak-free
   (debug_lifecycle_check() == {}).
"""

import threading
import time

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import (
    EngineConfig,
    EngineDraining,
    EngineOverloaded,
    InferenceEngine,
)

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _engine(cfg=None, start=True, **ekw):
    cfg = cfg or get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(q, timeout=60):
    """Drain an output queue to its sentinel: (token_count, error|None)."""
    toks, err = 0, None
    while True:
        item = q.get(timeout=timeout)
        if item is None:
            return toks, err
        if "error" in item:
            assert err is None, "request produced TWO error items"
            err = item
        else:
            toks += len(item["tokens"])


# ---------------------------------------------------------------------------
# stop(): no waiter left hanging
# ---------------------------------------------------------------------------


def test_stop_fails_queued_requests():
    """Requests still queued at stop() get a retriable shutdown error +
    sentinel instead of being silently dropped (pre-hardening, stop()
    abandoned _pending and generate_blocking callers hung forever)."""
    eng = _engine(start=False)  # never started: everything stays queued
    q1 = eng.submit([3, 4, 5], GREEDY)
    q2 = eng.submit([6, 7], GREEDY)
    eng.stop()
    for q_ in (q1, q2):
        toks, err = _collect(q_, timeout=10)
        assert toks == 0
        assert err is not None and err["kind"] == "shutdown"
        assert err["retriable"] is True
    assert eng.debug_lifecycle_check() == {}


def test_stop_unblocks_generate_blocking():
    eng = _engine(start=False)
    box = {}

    def call():
        try:
            eng.generate_blocking([3, 4], GREEDY)
        except RuntimeError as e:
            box["err"] = e

    t = threading.Thread(target=call, daemon=True)
    t.start()
    time.sleep(0.05)  # let it enqueue and block on the out queue
    eng.stop()
    t.join(timeout=10)
    assert not t.is_alive(), "generate_blocking hung across stop()"
    assert box["err"].kind == "shutdown"
    assert box["err"].retriable is True


# ---------------------------------------------------------------------------
# submit() validation
# ---------------------------------------------------------------------------


def test_submit_rejects_decode_past_max_seq_len():
    eng = _engine(start=False, max_seq_len=64)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(list(range(2, 26)),
                   SamplingParams(temperature=0.0, max_new_tokens=48))
    eng.stop()


def test_submit_rejects_prompt_that_never_fits_pool():
    """Paged: a prompt needing more blocks than the whole pool holds can
    never be admitted — reject at submit, not mid-dispatch."""
    eng = _engine(start=False, max_seq_len=32, prompt_buckets=(16, 32),
                  paged_kv=True, kv_block=16,
                  kv_pool_blocks=2)  # trash + 1 usable
    with pytest.raises(ValueError, match="kv blocks"):
        eng.submit(list(range(2, 22)),  # 20 tokens -> 2 blocks > 1
                   SamplingParams(temperature=0.0, max_new_tokens=4))
    eng.stop()


# ---------------------------------------------------------------------------
# Bounded admission queue + draining
# ---------------------------------------------------------------------------


def test_bounded_queue_sheds_with_typed_429():
    eng = _engine(start=False, max_queue=1)
    q1 = eng.submit([3, 4], GREEDY)
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([5, 6], GREEDY)
    assert ei.value.http_status == 429
    assert ei.value.retriable is True
    snap = eng.stats.snapshot()
    assert snap["queue_rejects"] == 1
    assert snap["shed_total"] == 1
    eng.stop()
    _, err = _collect(q1, timeout=10)
    assert err["kind"] == "shutdown"


def test_drain_sheds_queued_and_refuses_new():
    eng = _engine(start=False)
    q1 = eng.submit([3, 4], GREEDY)
    assert eng.drain(timeout=5) is True
    toks, err = _collect(q1, timeout=10)
    assert toks == 0
    assert err["kind"] == "draining"
    assert err["retriable"] is True
    with pytest.raises(EngineDraining) as ei:
        eng.submit([5, 6], GREEDY)
    assert ei.value.http_status == 503
    eng.stop()
    assert eng.debug_lifecycle_check() == {}


def test_drain_completes_inflight():
    """drain() lets admitted work finish (only QUEUED work is shed)."""
    eng = _engine()
    try:
        q = eng.submit([3, 4, 5], GREEDY)
        first = q.get(timeout=60)  # admitted and decoding
        assert "error" not in first
        assert eng.drain(timeout=60) is True
        assert eng.draining
        toks, err = _collect(q, timeout=60)
        assert err is None
        assert len(first["tokens"]) + toks <= GREEDY.max_new_tokens
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


def test_deadline_expires_queued_request():
    """A request whose TTL lapses before admission is shed at the first
    boundary without ever touching the device."""
    eng = _engine(start=False)
    q = eng.submit([3, 4], SamplingParams(
        temperature=0.0, max_new_tokens=4, deadline_ms=1))
    time.sleep(0.05)
    eng.start()
    try:
        toks, err = _collect(q, timeout=60)
        assert toks == 0
        assert err["kind"] == "deadline"
        assert eng.stats.snapshot()["deadline_expired_total"] == 1
    finally:
        eng.stop()


def test_deadline_finalizes_mid_decode():
    """An in-flight request past its TTL is finalized at the next
    boundary: tokens already streamed stay streamed, the waiter gets the
    deadline error, and the slot is reclaimed (engine serves on)."""
    # decode_chunk=1 (no adaptive ladder) makes boundaries frequent and
    # the decode long enough that a ~40 ms TTL reliably lapses mid-way.
    eng = _engine(decode_chunk=1, min_chunk=1, adaptive_chunk=False)
    try:
        q = eng.submit([3, 4, 5], SamplingParams(
            temperature=0.0, max_new_tokens=56, deadline_ms=40))
        toks, err = _collect(q, timeout=120)
        assert err is not None and err["kind"] == "deadline"
        assert toks < 56
        assert eng.stats.snapshot()["deadline_expired_total"] == 1
        # The slot came back: a fresh request completes normally.
        res = eng.generate_blocking([7, 8], GREEDY)
        assert 1 <= len(res["token_ids"]) <= 8
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


def test_pilot_sheds_expired_head_at_pop(monkeypatch):
    """EDF pop-time margin re-check (the pilot's expiry-at-pop fix): a
    head request that expired between the boundary reap and its own
    admission is failed at pop time — before it claims a slot or
    displaces the viable request queued behind it."""
    monkeypatch.setenv("PILOT", "1")
    eng = _engine(start=False)  # scheduler idle: we drive the pop by hand
    q_dead = eng.submit([3, 4], SamplingParams(
        temperature=0.0, max_new_tokens=4, deadline_ms=1))
    q_live = eng.submit([5, 6], GREEDY)
    time.sleep(0.01)  # let the 1 ms TTL lapse while both sit queued
    try:
        with eng._book:
            admits = eng._dispatch_admits()
        toks, err = _collect(q_dead, timeout=10)
        assert toks == 0
        assert err["kind"] == "deadline"
        # The viable request behind the expired head was admitted in the
        # same pass — shedding re-examined the new head, it didn't bail.
        assert len(admits) == 1
        (group, *_rest) = admits[0]
        assert [r.out for r in group] == [q_live]
        snap = eng.stats.snapshot()
        assert snap["deadline_expired_total"] == 1
        assert snap["shed_total"] == 1
        assert eng.debug_pilot()["edf"]["expired_at_pop"] == 1
    finally:
        eng.stop()


def test_default_deadline_applies_when_request_sets_none():
    eng = _engine(start=False, default_deadline_ms=1)
    q = eng.submit([3, 4], SamplingParams(temperature=0.0, max_new_tokens=4))
    time.sleep(0.05)
    eng.start()
    try:
        _, err = _collect(q, timeout=60)
        assert err["kind"] == "deadline"
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Cancellation
# ---------------------------------------------------------------------------


def test_cancel_mid_decode_frees_slot():
    eng = _engine(decode_chunk=1, min_chunk=1, adaptive_chunk=False)
    try:
        q = eng.submit([3, 4, 5], SamplingParams(
            temperature=0.0, max_new_tokens=56))
        first = q.get(timeout=60)
        assert "error" not in first
        assert eng.cancel(q.rid) is True
        toks, err = _collect(q, timeout=60)
        assert err["kind"] == "cancelled"
        assert len(first["tokens"]) + toks < 56
        assert eng.stats.snapshot()["cancelled_total"] == 1
        res = eng.generate_blocking([7, 8], GREEDY)
        assert 1 <= len(res["token_ids"]) <= 8
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


def test_cancel_unknown_or_finished_rid_is_noop():
    eng = _engine()
    try:
        assert eng.cancel(999999) is False
        q = eng.submit([3, 4], GREEDY)
        _collect(q, timeout=60)
        assert eng.cancel(q.rid) is False  # already finished
    finally:
        eng.stop()


def test_cancel_releases_blocked_out_pool():
    """Acceptance: a paged pool fully owned by one stream admits the
    NEXT waiter within a boundary of cancelling the owner — cancel
    releases pool blocks, not just the slot."""
    from seldon_tpu.servers.chaos import ChaosConfig

    # decode_chunk=1 + a 30 ms injected boundary delay pin the owner's
    # 15-token decode to >=450 ms of wall-clock, so the waiter's stall
    # and the cancel both demonstrably land while the owner holds the
    # pool (slow_boundary only sleeps the fetcher; no faults injected).
    eng = _engine(max_seq_len=32, prompt_buckets=(16, 32),
                  paged_kv=True, kv_block=16,
                  kv_pool_blocks=3,  # trash + 2 usable
                  decode_chunk=1, min_chunk=1, adaptive_chunk=False,
                  chaos=ChaosConfig(seed=0, slow_boundary=1.0, slow_ms=30))
    try:
        # Owner: 17-token prompt -> bucket 32 -> both usable blocks.
        qa = eng.submit(list(range(2, 19)),
                        SamplingParams(temperature=0.0, max_new_tokens=15))
        sp = SamplingParams(temperature=0.0, max_new_tokens=8)
        first = qa.get(timeout=60)
        assert "error" not in first
        # Waiter: same shape; stalls on pool exhaustion, not slots.
        qb = eng.submit(list(range(30, 47)), sp)
        time.sleep(0.15)  # ~5 of 15 owner tokens elapse
        assert qb.empty(), "waiter admitted while the pool was full"
        assert eng.cancel(qa.rid) is True
        _, err = _collect(qa, timeout=60)
        assert err["kind"] == "cancelled"
        toks_b, err_b = _collect(qb, timeout=120)
        assert err_b is None, f"waiter failed after cancel: {err_b}"
        assert 1 <= toks_b <= 8
        assert eng.stats.snapshot()["pool_stalls"] >= 1
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Serving surface: jaxserver + REST wrapper
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    from seldon_tpu.servers.jaxserver import JAXServer

    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64,
                    default_deadline_ms=0)
    srv.load()
    yield srv
    srv.engine.stop()


def test_jaxserver_deadline_via_request_dict(server):
    # Hold the bookkeeping lock while the 1 ms TTL lapses: the scheduler
    # cannot drain/admit the request until we release, so the queued-
    # deadline path fires deterministically (a free-running scheduler can
    # race the TTL and legitimately finish 4 tokens first).
    result = {}

    def call():
        try:
            server.generate({"prompt": "hi", "max_new_tokens": 4,
                             "temperature": 0.0, "deadline_ms": 1})
            result["ok"] = True
        except RuntimeError as e:
            result["err"] = e

    with server.engine._book:
        th = threading.Thread(target=call)
        th.start()
        time.sleep(0.05)  # TTL lapses while the request sits queued
    th.join(timeout=30)
    assert not th.is_alive()
    assert "deadline" in str(result.get("err")), result


def test_jaxserver_stream_close_cancels_engine_request(server):
    """Closing the streaming generator mid-stream (what the transports
    do on client disconnect) cancels the engine request — decode stops
    well short of max_new_tokens and the slot is freed."""
    before = server.engine.stats.snapshot()["cancelled_total"]
    gen = server.generate_stream(
        {"prompt": "abcd", "max_new_tokens": 48, "temperature": 0.0}
    )
    for chunk in gen:
        if chunk is not None:
            break  # first real tokens arrived; client "disconnects"
    gen.close()
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        if server.engine.stats.snapshot()["cancelled_total"] == before + 1:
            break
        time.sleep(0.01)
    assert server.engine.stats.snapshot()["cancelled_total"] == before + 1
    assert server.engine.debug_lifecycle_check() == {}


def test_jaxserver_lifecycle_metrics_exposed(server):
    keys = {m["key"] for m in server.metrics()}
    assert {"jaxserver_shed_total", "jaxserver_cancelled_total",
            "jaxserver_deadline_expired_total",
            "jaxserver_queue_rejects"} <= keys


def test_jaxserver_drain_flips_readiness():
    """Readiness must go 503 the moment drain starts (load balancers
    stop routing) — on a dedicated server so the module fixture keeps
    serving."""
    from seldon_tpu.servers.jaxserver import JAXServer

    srv = JAXServer(preset="tiny", max_slots=2, max_seq_len=64)
    srv.load()
    try:
        assert srv.health_status()["engine"] is not None
        assert srv.drain(timeout=10) is True
        with pytest.raises(RuntimeError, match="draining"):
            srv.health_status()
    finally:
        srv.engine.stop()


def test_rest_wrapper_maps_429_and_503():
    """Typed lifecycle errors surface as real HTTP statuses (duck-typed
    http_status — the wrapper never imports the engine)."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from seldon_tpu.runtime.wrapper import build_rest_app

    class Shedding:
        def __init__(self, status):
            self._status = status

        def generate(self, req):
            e = RuntimeError("no capacity")
            e.http_status = self._status
            e.retriable = True
            raise e

    async def run(status):
        runner = web.AppRunner(build_rest_app(Shedding(status)))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.post(
                    f"http://127.0.0.1:{port}/generate",
                    json={"prompt": "x"},
                ) as r:
                    return r.status, await r.json()
        finally:
            await runner.cleanup()

    for status in (429, 503):
        got, body = asyncio.run(run(status))
        assert got == status
        assert body["status"]["retriable"] is True
