"""Distributed tracing: one trace id spans engine -> units -> model.

Reference capability: Jaeger tracing gated by TRACING=1 with spans
propagated engine -> every unit (TracingProvider.java:1-37,
python/seldon_core/microservice.py:115-150). Here: W3C traceparent over
gRPC metadata / HTTP headers, asserted over REAL in-process sockets."""

import asyncio
import json

import grpc
import numpy as np
import pytest

from seldon_tpu.core import payloads, tracing
from seldon_tpu.proto import prediction_pb2 as pb


# ---------------------------------------------------------------------------
# Tracer core
# ---------------------------------------------------------------------------


def test_span_nesting_and_export():
    exp = tracing.InMemoryExporter()
    tracer = tracing.Tracer("svc", exporter=exp)
    with tracer.span("root") as root:
        with tracer.span("child") as child:
            child.set_attribute("k", 1)
    assert len(exp.spans) == 2
    c, r = exp.spans  # children finish first
    assert c.name == "child" and r.name == "root"
    assert c.trace_id == r.trace_id
    assert c.parent_id == r.span_id
    assert r.parent_id is None
    assert c.attributes == {"k": 1}
    assert c.end_ns >= c.start_ns


def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
    tp = ctx.to_traceparent()
    back = tracing.SpanContext.from_traceparent(tp)
    assert back == ctx
    assert tracing.SpanContext.from_traceparent("garbage") is None
    # Case-insensitive key + bytes value (gRPC metadata shape).
    got = tracing.Tracer.extract([("TraceParent", tp.encode())])
    assert got == ctx


def test_error_status_recorded():
    exp = tracing.InMemoryExporter()
    tracer = tracing.Tracer("svc", exporter=exp)
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    assert exp.spans[0].status.startswith("ERROR")


def test_disabled_tracer_is_noop():
    t = tracing.get_tracer("svc")  # TRACING unset in tests
    with t.span("x") as s:
        s.set_attribute("a", 1)  # must not raise
    assert tracing.current_span() is None
    assert tracing.inject_current({}) == {}


# ---------------------------------------------------------------------------
# End-to-end: engine -> gRPC units share one trace
# ---------------------------------------------------------------------------


class _Plus:
    def predict(self, X, names, meta=None):
        return np.asarray(X) + 1.0


def _spec_two_hop(port_a, port_b):
    from seldon_tpu.orchestrator.spec import (
        Endpoint, EndpointType, PredictiveUnit, PredictorSpec,
    )

    leaf = PredictiveUnit(
        name="model-b", type="MODEL",
        endpoint=Endpoint("127.0.0.1", port_b, EndpointType.GRPC),
    )
    root = PredictiveUnit(
        name="transformer-a", type="TRANSFORMER",
        endpoint=Endpoint("127.0.0.1", port_a, EndpointType.GRPC),
        children=[leaf],
    )
    return PredictorSpec(name="p", graph=root)


def test_one_trace_spans_engine_and_units(tmp_path, monkeypatch):
    from seldon_tpu.orchestrator.walker import PredictorEngine
    from seldon_tpu.runtime.wrapper import build_grpc_server

    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))

    class _TI:
        def transform_input(self, X, names, meta=None):
            return np.asarray(X) * 2.0

    srv_a = build_grpc_server(_TI())
    port_a = srv_a.add_insecure_port("127.0.0.1:0")
    srv_a.start()
    srv_b = build_grpc_server(_Plus())
    port_b = srv_b.add_insecure_port("127.0.0.1:0")
    srv_b.start()
    try:
        engine = PredictorEngine(_spec_two_hop(port_a, port_b))
        req = payloads.build_message(np.array([[1.0, 2.0]], np.float32))
        out = asyncio.run(engine.predict(req))
        np.testing.assert_allclose(
            payloads.get_data_from_message(out), [[3.0, 5.0]]
        )
    finally:
        srv_a.stop(0)
        srv_b.stop(0)

    spans = [json.loads(l) for l in trace_file.read_text().splitlines()]
    by_name = {s["name"]: s for s in spans}
    # engine root + 2 graph-walk spans + 2 unit-side spans, ONE trace id.
    assert set(by_name) >= {
        "engine.predict", "unit.transformer-a", "unit.model-b",
        "unit.transform-input", "unit.predict",
    }, sorted(by_name)
    trace_ids = {s["trace_id"] for s in spans}
    assert len(trace_ids) == 1, spans
    # Parenting: unit-side span's parent is the engine-side unit span.
    assert (by_name["unit.predict"]["parent_id"]
            == by_name["unit.model-b"]["span_id"])
    assert (by_name["unit.transform-input"]["parent_id"]
            == by_name["unit.transformer-a"]["span_id"])
    assert by_name["engine.predict"]["parent_id"] is None
    # Services attributed correctly across the process boundary.
    assert by_name["engine.predict"]["service"] == "engine"


def test_incoming_traceparent_becomes_root(tmp_path, monkeypatch):
    """A client-supplied traceparent header parents the whole server-side
    trace (the REST engine entry path)."""
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec
    from seldon_tpu.orchestrator.walker import PredictorEngine

    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))

    spec = PredictorSpec(
        name="p",
        graph=PredictiveUnit(name="m", type="MODEL",
                             implementation="SIMPLE_MODEL"),
    )
    engine = PredictorEngine(spec)
    client_ctx = tracing.SpanContext("ee" * 16, "ff" * 8)
    req = payloads.build_message(np.array([[1.0]], np.float32))
    asyncio.run(engine.predict(req, trace_parent=client_ctx))
    spans = [json.loads(l) for l in trace_file.read_text().splitlines()]
    root = next(s for s in spans if s["name"] == "engine.predict")
    assert root["trace_id"] == "ee" * 16
    assert root["parent_id"] == "ff" * 8


# ---------------------------------------------------------------------------
# LLM engine lifecycle: traceparent over generate transports
# ---------------------------------------------------------------------------


def test_grpc_traceparent_metadata_reaches_generate():
    """Satellite contract: gRPC invocation metadata `traceparent` is
    stamped into meta.tags with the same adoption rules as the HTTP
    header — a body-supplied tag wins over transport metadata."""
    from seldon_tpu.proto import prediction_grpc
    from seldon_tpu.runtime.wrapper import build_grpc_server

    seen = []

    class Gen:
        def generate(self, d):
            seen.append(d.get("traceparent", ""))
            return {"text": "ok", "token_ids": [1]}

    server = build_grpc_server(Gen())
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    meta_tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    body_tp = "00-" + "12" * 16 + "-" + "34" * 8 + "-01"
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = prediction_grpc.TextGenStub(ch)
        # Metadata-only: stamped into the request.
        resp = stub.Generate(pb.GenerateRequest(prompt="x"),
                             metadata=[("traceparent", meta_tp)])
        assert resp.text == "ok"
        # Body tag already present: metadata must NOT overwrite it.
        req = pb.GenerateRequest(prompt="x")
        req.meta.tags["traceparent"].string_value = body_tp
        stub.Generate(req, metadata=[("traceparent", meta_tp)])
        # Streaming entry point stamps identically.
        list(stub.GenerateStream(pb.GenerateRequest(prompt="x"),
                                 metadata=[("traceparent", meta_tp)]))
    finally:
        server.stop(0)
    assert seen == [meta_tp, body_tp, meta_tp]


def test_walker_disabled_tracer_takes_zero_alloc_path(monkeypatch):
    """With tracing off, the per-unit walk must not touch any span
    machinery: no span-info lookup, no context-manager entry."""
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec
    from seldon_tpu.orchestrator.walker import PredictorEngine

    spec = PredictorSpec(
        name="p",
        graph=PredictiveUnit(name="m", type="MODEL",
                             implementation="SIMPLE_MODEL"),
    )
    engine = PredictorEngine(spec)
    assert not engine.tracer.enabled  # TRACING unset in tests

    root_spans = []
    real_span = engine.tracer.span

    def counting_span(name, **kw):
        root_spans.append(name)
        return real_span(name, **kw)

    # The disabled tracer is a shared module singleton: patch through
    # monkeypatch so the counting shim cannot leak into other tests.
    monkeypatch.setattr(engine.tracer, "span", counting_span)

    class _NoTouch(dict):
        def __getitem__(self, key):
            raise AssertionError(
                "disabled tracer must not unpack span info")

    engine._span_info = _NoTouch()
    req = payloads.build_message(np.array([[1.0]], np.float32))
    out = asyncio.run(engine.predict(req))
    assert payloads.get_data_from_message(out).shape[0] == 1
    # Only the root predict span wrapper runs (itself a shared noop CM);
    # the per-unit hot path took the early return.
    assert root_spans == ["engine.predict"]


@pytest.mark.e2e
def test_one_trace_spans_transports_and_engine_lifecycle(
    tmp_path, monkeypatch
):
    """Acceptance: one client trace id spans the transport entry -> engine
    lifecycle spans -> terminal outcome, over REST and gRPC, against a
    real tiny JAXServer on real sockets. The flight recorder rides along
    and /debug/timeline serves its window."""
    import threading
    import time as _time
    import urllib.request

    from aiohttp import web

    from seldon_tpu.proto import prediction_grpc
    from seldon_tpu.runtime.wrapper import build_grpc_server, build_rest_app
    from seldon_tpu.servers.jaxserver import JAXServer

    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))
    monkeypatch.setenv("FLIGHT_RECORDER", "1")

    srv = JAXServer(preset="tiny", max_slots=2, max_seq_len=32)
    srv.load()

    holder, started = {}, threading.Event()

    async def amain():
        runner = web.AppRunner(build_rest_app(srv))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    assert started.wait(30)
    rest_tp = "00-" + "aa" * 16 + "-" + "bb" * 8 + "-01"
    grpc_tp = "00-" + "cc" * 16 + "-" + "dd" * 8 + "-01"

    gsrv = build_grpc_server(srv)
    gport = gsrv.add_insecure_port("127.0.0.1:0")
    gsrv.start()
    try:
        url = f"http://127.0.0.1:{holder['port']}"
        body = json.dumps({"prompt": "hi", "max_new_tokens": 3,
                           "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"{url}/generate", data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": rest_tp})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert out["completion_tokens"] >= 1

        ch = grpc.insecure_channel(f"127.0.0.1:{gport}")
        stub = prediction_grpc.TextGenStub(ch)
        gout = stub.Generate(
            pb.GenerateRequest(prompt="hi", max_new_tokens=3,
                               temperature=0.0),
            metadata=[("traceparent", grpc_tp)], timeout=120)
        assert len(gout.token_ids) >= 1

        # Terminal spans are emitted by the scheduler thread; give the
        # export a moment before asserting.
        deadline = _time.monotonic() + 30
        roots = []
        while _time.monotonic() < deadline:
            spans = [json.loads(l)
                     for l in trace_file.read_text().splitlines()]
            roots = [s for s in spans if s["name"] == "engine.request"]
            if len(roots) >= 2:
                break
            _time.sleep(0.1)
        by_trace = {s["trace_id"]: s for s in roots}
        # Each transport's client trace id owns its engine lifecycle.
        assert "aa" * 16 in by_trace and "cc" * 16 in by_trace, (
            sorted(by_trace))
        assert by_trace["aa" * 16]["parent_id"] == "bb" * 8
        assert by_trace["cc" * 16]["parent_id"] == "dd" * 8
        for root in by_trace.values():
            assert root["attributes"]["outcome"] == "ok"
            kids = [s for s in spans
                    if s["parent_id"] == root["span_id"]]
            names = {s["name"] for s in kids}
            assert {"engine.queued", "engine.prefill",
                    "engine.decode"} <= names, names
            assert all(s["trace_id"] == root["trace_id"] for s in kids)

        # Flight recorder rode along: the debug route serves the window.
        with urllib.request.urlopen(f"{url}/debug/timeline",
                                    timeout=30) as resp:
            snap = json.loads(resp.read())
        kinds = {r["kind"] for r in snap["records"]}
        assert {"submit", "terminal"} <= kinds, kinds
    finally:
        gsrv.stop(0)
        holder["stop"] = True
        t.join(timeout=10)
        srv.engine.stop()
