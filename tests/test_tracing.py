"""Distributed tracing: one trace id spans engine -> units -> model.

Reference capability: Jaeger tracing gated by TRACING=1 with spans
propagated engine -> every unit (TracingProvider.java:1-37,
python/seldon_core/microservice.py:115-150). Here: W3C traceparent over
gRPC metadata / HTTP headers, asserted over REAL in-process sockets."""

import asyncio
import json

import grpc
import numpy as np
import pytest

from seldon_tpu.core import payloads, tracing
from seldon_tpu.proto import prediction_pb2 as pb


# ---------------------------------------------------------------------------
# Tracer core
# ---------------------------------------------------------------------------


def test_span_nesting_and_export():
    exp = tracing.InMemoryExporter()
    tracer = tracing.Tracer("svc", exporter=exp)
    with tracer.span("root") as root:
        with tracer.span("child") as child:
            child.set_attribute("k", 1)
    assert len(exp.spans) == 2
    c, r = exp.spans  # children finish first
    assert c.name == "child" and r.name == "root"
    assert c.trace_id == r.trace_id
    assert c.parent_id == r.span_id
    assert r.parent_id is None
    assert c.attributes == {"k": 1}
    assert c.end_ns >= c.start_ns


def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
    tp = ctx.to_traceparent()
    back = tracing.SpanContext.from_traceparent(tp)
    assert back == ctx
    assert tracing.SpanContext.from_traceparent("garbage") is None
    # Case-insensitive key + bytes value (gRPC metadata shape).
    got = tracing.Tracer.extract([("TraceParent", tp.encode())])
    assert got == ctx


def test_error_status_recorded():
    exp = tracing.InMemoryExporter()
    tracer = tracing.Tracer("svc", exporter=exp)
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    assert exp.spans[0].status.startswith("ERROR")


def test_disabled_tracer_is_noop():
    t = tracing.get_tracer("svc")  # TRACING unset in tests
    with t.span("x") as s:
        s.set_attribute("a", 1)  # must not raise
    assert tracing.current_span() is None
    assert tracing.inject_current({}) == {}


# ---------------------------------------------------------------------------
# End-to-end: engine -> gRPC units share one trace
# ---------------------------------------------------------------------------


class _Plus:
    def predict(self, X, names, meta=None):
        return np.asarray(X) + 1.0


def _spec_two_hop(port_a, port_b):
    from seldon_tpu.orchestrator.spec import (
        Endpoint, EndpointType, PredictiveUnit, PredictorSpec,
    )

    leaf = PredictiveUnit(
        name="model-b", type="MODEL",
        endpoint=Endpoint("127.0.0.1", port_b, EndpointType.GRPC),
    )
    root = PredictiveUnit(
        name="transformer-a", type="TRANSFORMER",
        endpoint=Endpoint("127.0.0.1", port_a, EndpointType.GRPC),
        children=[leaf],
    )
    return PredictorSpec(name="p", graph=root)


def test_one_trace_spans_engine_and_units(tmp_path, monkeypatch):
    from seldon_tpu.orchestrator.walker import PredictorEngine
    from seldon_tpu.runtime.wrapper import build_grpc_server

    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))

    class _TI:
        def transform_input(self, X, names, meta=None):
            return np.asarray(X) * 2.0

    srv_a = build_grpc_server(_TI())
    port_a = srv_a.add_insecure_port("127.0.0.1:0")
    srv_a.start()
    srv_b = build_grpc_server(_Plus())
    port_b = srv_b.add_insecure_port("127.0.0.1:0")
    srv_b.start()
    try:
        engine = PredictorEngine(_spec_two_hop(port_a, port_b))
        req = payloads.build_message(np.array([[1.0, 2.0]], np.float32))
        out = asyncio.run(engine.predict(req))
        np.testing.assert_allclose(
            payloads.get_data_from_message(out), [[3.0, 5.0]]
        )
    finally:
        srv_a.stop(0)
        srv_b.stop(0)

    spans = [json.loads(l) for l in trace_file.read_text().splitlines()]
    by_name = {s["name"]: s for s in spans}
    # engine root + 2 graph-walk spans + 2 unit-side spans, ONE trace id.
    assert set(by_name) >= {
        "engine.predict", "unit.transformer-a", "unit.model-b",
        "unit.transform-input", "unit.predict",
    }, sorted(by_name)
    trace_ids = {s["trace_id"] for s in spans}
    assert len(trace_ids) == 1, spans
    # Parenting: unit-side span's parent is the engine-side unit span.
    assert (by_name["unit.predict"]["parent_id"]
            == by_name["unit.model-b"]["span_id"])
    assert (by_name["unit.transform-input"]["parent_id"]
            == by_name["unit.transformer-a"]["span_id"])
    assert by_name["engine.predict"]["parent_id"] is None
    # Services attributed correctly across the process boundary.
    assert by_name["engine.predict"]["service"] == "engine"


def test_incoming_traceparent_becomes_root(tmp_path, monkeypatch):
    """A client-supplied traceparent header parents the whole server-side
    trace (the REST engine entry path)."""
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec
    from seldon_tpu.orchestrator.walker import PredictorEngine

    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))

    spec = PredictorSpec(
        name="p",
        graph=PredictiveUnit(name="m", type="MODEL",
                             implementation="SIMPLE_MODEL"),
    )
    engine = PredictorEngine(spec)
    client_ctx = tracing.SpanContext("ee" * 16, "ff" * 8)
    req = payloads.build_message(np.array([[1.0]], np.float32))
    asyncio.run(engine.predict(req, trace_parent=client_ctx))
    spans = [json.loads(l) for l in trace_file.read_text().splitlines()]
    root = next(s for s in spans if s["name"] == "engine.predict")
    assert root["trace_id"] == "ee" * 16
    assert root["parent_id"] == "ff" * 8
