"""Subprocess worker for the multi-process SERVING proof
(tests/test_distributed.py::test_engine_serves_across_two_processes):
joins a 2-process jax.distributed "slice" (4 virtual CPU devices each),
builds an InferenceEngine whose params/cache shard over a mesh with the
TP axis SPANNING the two processes (attention psums cross the process
boundary — the v5e-16 deployment shape, SURVEY §5.8), generates real
completions, and prints them as one JSON line.

Determinism contract: in multi-process SPMD every process must enqueue
the SAME device programs in the same order, so all requests are
submitted BEFORE the scheduler starts — the first admission drain then
sees an identical FIFO on both processes, and every subsequent scheduler
decision depends only on device results (identical) — never on wall
timing."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    coordinator = sys.argv[1]
    pid = int(sys.argv[2])
    nproc = int(sys.argv[3])

    from seldon_tpu.parallel import distributed

    cfg_slice = distributed.SliceConfig(
        coordinator=coordinator, num_processes=nproc, process_id=pid
    )
    assert distributed.ensure_initialized(cfg_slice)
    assert len(jax.devices()) == 4 * nproc

    from tests.slice_serve_common import run_engine

    toks = run_engine()
    print(json.dumps({"process_id": pid, "completions": toks}), flush=True)


if __name__ == "__main__":
    main()
