"""graftkern: block-sparse ragged paged-attention kernel legs.

The contract under test (ops/ragged_paged_attention module doc):

 * the ops-level walkers (``partials_sparse``, ``partials_pallas``
   interpret-mode) agree with the full-width ``partials_reference``
   oracle on every bound shape — empty, single-block,
   partially-filled-block, multi-block;
 * the masked-MATCHED two-pass walk (``sparse_max_sum`` +
   ``sparse_weighted_value``) reproduces the masked engine kernels'
   attention output BIT-EXACTLY — same term set, softmax weights
   rounded to the activation dtype, dequant pinned at a
   materialization boundary — for bf16 AND int8 pools;
 * ``ragged_wave`` / ``verify_wave`` under ``kernel="sparse"`` emit
   greedy token streams IDENTICAL to ``kernel="masked"`` across
   prefill / chunk-continuation / decode / verify rows, including the
   decode-only skip cond and the block-budget masked fallback;
   ``kernel="pallas"`` (interpret on CPU) matches greedy tokens on the
   same waves and stays within :data:`RAGGED_LOGITS_ATOL` on raw
   logits;
 * the engine end to end: ``ragged_kernel="sparse"`` streams equal
   masked's bit for bit, the static lattice stays
   ``["deactivate", "ragged/C"]`` and nothing retraces live.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_tpu.models import spec_decode, transformer
from seldon_tpu.models import ragged_attention as ra
from seldon_tpu.models.config import PRESETS
from seldon_tpu.ops import ragged_paged_attention as rpa

jax.config.update("jax_platforms", "cpu")

TINY = PRESETS["tiny"]
BLOCK, NBS = 8, 16
SMAX = BLOCK * NBS
B = 4


def _cfg(kv_dtype):
    return dataclasses.replace(TINY, kv_cache_dtype=kv_dtype)


def _pool_and_table(cfg, key, n_rows=B, nbs=NBS):
    """int8/bf16 paged pool with disjoint per-row tables (trash = 0)
    filled with quantized random normals on every block."""
    nb = n_rows * nbs + 1
    pool1 = transformer.init_paged_cache(cfg, nb, BLOCK)
    # init_paged_cache stacks layers; tests walk ONE layer slice.
    layer = {k: v[0] for k, v in pool1.items()}
    hkv, dh = cfg.n_kv_heads, cfg.head_dim
    raw_k = jax.random.normal(jax.random.fold_in(key, 1),
                              (nb, hkv, BLOCK, dh), jnp.float32)
    raw_v = jax.random.normal(jax.random.fold_in(key, 2),
                              (nb, hkv, BLOCK, dh), jnp.float32)
    if cfg.kv_cache_dtype == "int8":
        kq, ks = transformer._quantize_kv(raw_k.astype(jnp.bfloat16))
        vq, vs = transformer._quantize_kv(raw_v.astype(jnp.bfloat16))
        layer = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
    else:
        layer = {"k": raw_k.astype(layer["k"].dtype),
                 "v": raw_v.astype(layer["v"].dtype)}
    table = jnp.asarray(
        np.stack([1 + i * nbs + np.arange(nbs) for i in range(n_rows)])
        .astype(np.int32))
    return layer, table


def _combine(parts):
    """(m, l, acc) -> attention output, the partials' closed form."""
    m, l, acc = parts
    return acc / jnp.maximum(l, 1e-30)


# Empty row, partial block, exact block edge, multi-block: the bound
# shapes the walker's trip count and tail masking must each survive.
BOUNDS = np.array([0, 5, BLOCK, 61], np.int32)


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_partials_sparse_matches_reference(kv_dtype):
    cfg = _cfg(kv_dtype)
    key = jax.random.key(0)
    layer, table = _pool_and_table(cfg, key)
    sq = 2
    q = jax.random.normal(
        jax.random.fold_in(key, 3),
        (B, sq, cfg.n_kv_heads, cfg.n_heads // cfg.n_kv_heads,
         cfg.head_dim), jnp.bfloat16)
    bound = jnp.broadcast_to(jnp.asarray(BOUNDS)[:, None], (B, sq))
    ref = _combine(rpa.partials_reference(q, layer, table, bound))
    got = _combine(rpa.partials_sparse(q, layer, table, bound))
    live = BOUNDS > 0
    np.testing.assert_allclose(
        np.asarray(got, np.float32)[live],
        np.asarray(ref, np.float32)[live], atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_partials_pallas_interpret_matches_reference(kv_dtype):
    cfg = _cfg(kv_dtype)
    key = jax.random.key(1)
    layer, table = _pool_and_table(cfg, key)
    sq = 1
    q = jax.random.normal(
        jax.random.fold_in(key, 3),
        (B, sq, cfg.n_kv_heads, cfg.n_heads // cfg.n_kv_heads,
         cfg.head_dim), jnp.bfloat16)
    bound = jnp.broadcast_to(jnp.asarray(BOUNDS)[:, None], (B, sq))
    ref = _combine(rpa.partials_reference(q, layer, table, bound))
    got = _combine(rpa.ragged_paged_partials(q, layer, table, bound,
                                             mode="pallas"))
    live = BOUNDS > 0
    np.testing.assert_allclose(
        np.asarray(got, np.float32)[live],
        np.asarray(ref, np.float32)[live], atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_matched_two_pass_is_bit_exact_vs_masked_convention(kv_dtype):
    """The greedy-parity core: the two-pass walk folded with a fresh
    causal suffix must reproduce gqa_attention's prefix+suffix output
    to the BIT — this is what makes sparse-vs-masked streams identical
    rather than merely close."""
    cfg = _cfg(kv_dtype)
    key = jax.random.key(2)
    layer, table = _pool_and_table(cfg, key)
    hkv, dh = cfg.n_kv_heads, cfg.head_dim
    g = cfg.n_heads // hkv
    sc = 4
    qr = jax.random.normal(jax.random.fold_in(key, 3),
                           (B, sc, hkv, g, dh), jnp.bfloat16)
    k_f = jax.random.normal(jax.random.fold_in(key, 4),
                            (B, sc, hkv, dh), jnp.bfloat16)
    v_f = jax.random.normal(jax.random.fold_in(key, 5),
                            (B, sc, hkv, dh), jnp.bfloat16)
    bound1 = jnp.asarray(BOUNDS)
    bound2 = jnp.broadcast_to(bound1[:, None], (B, sc)).astype(jnp.int32)
    smask = jnp.broadcast_to(
        jnp.tril(jnp.ones((sc, sc), bool))[None], (B, sc, sc))

    def masked():
        # _run_blocks_prefill_prefix's exact shape: gather the full
        # window, dequantize, CONCAT with the fresh suffix (the
        # materialization boundary that rounds the dequant), one
        # softmax-in-f32 / bf16-weight value einsum.
        view = {kk: jnp.moveaxis(layer[kk][table], 1, 2).reshape(
            (B, hkv, SMAX) + layer[kk].shape[3:]) for kk in layer}
        pk = view["k"].astype(qr.dtype)
        pv = view["v"].astype(qr.dtype)
        if "k_scale" in view:
            pk = pk * view["k_scale"][..., None].astype(qr.dtype)
            pv = pv * view["v_scale"][..., None].astype(qr.dtype)
        k_all = jnp.concatenate([pk.transpose(0, 2, 1, 3), k_f], axis=1)
        v_all = jnp.concatenate([pv.transpose(0, 2, 1, 3), v_f], axis=1)
        pmask = jnp.broadcast_to(
            jnp.arange(SMAX)[None, None, :] < bound1[:, None, None],
            (B, sc, SMAX))
        mask = jnp.concatenate([pmask, smask], axis=2)
        scores = jnp.einsum("bskgd,btkd->bkgst", qr, k_all,
                            preferred_element_type=jnp.float32) / (dh**0.5)
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(qr.dtype)
        return jnp.einsum("bkgst,btkd->bskgd", w, v_all)

    def sparse():
        s_f = jnp.einsum("bskgd,btkd->bkgst", qr, k_f,
                         preferred_element_type=jnp.float32) / (dh**0.5)
        s_f = jnp.where(smask[:, None, None, :, :], s_f, rpa.NEG_INF)
        m_p, l_p = rpa.sparse_max_sum(qr, layer, table, bound2,
                                      dequant=True)
        m_t = jnp.maximum(m_p, jnp.max(s_f, axis=-1, keepdims=True))
        p_f = jnp.exp(s_f - m_t)
        l_t = l_p * jnp.exp(m_p - m_t) + jnp.sum(p_f, axis=-1,
                                                 keepdims=True)
        acc = rpa.sparse_weighted_value(qr, layer, table, bound2,
                                        m_t, l_t, dequant=True)
        acc = acc + jnp.einsum(
            "bkgst,bktd->bkgsd", (p_f / l_t).astype(qr.dtype),
            v_f.transpose(0, 2, 1, 3).astype(qr.dtype),
            preferred_element_type=jnp.float32)
        return acc.astype(qr.dtype).transpose(0, 3, 1, 2, 4)

    want = np.asarray(jax.jit(masked)(), np.float32)
    got = np.asarray(jax.jit(sparse)(), np.float32)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# Wave-level greedy parity (the smoke the bench gate rides on)
# ---------------------------------------------------------------------------


def _seed_row(cfg, params, pool, table, row, n, seed):
    """Prefill n tokens through the DENSE path and scatter the KV into
    the row's pool blocks; returns (pool, greedy next token)."""
    tks = jnp.asarray(
        np.random.default_rng(seed).integers(2, cfg.vocab_size,
                                             size=(1, n)), jnp.int32)
    cache = transformer.init_cache(cfg, 1, SMAX)
    logits, cache = transformer.prefill(
        params, tks, jnp.asarray([n], jnp.int32), cache, cfg)
    wr = {k: cache[k][:, 0:1, :, :n] for k in cache}
    pool = transformer.paged_scatter_tokens(
        pool, wr, table[row:row + 1], jnp.arange(n)[None, :])
    return pool, int(jnp.argmax(logits[0]))


def _wave_fixture(kv_dtype):
    """(cfg, params, table, state, wave-args): row0 cold prefill final,
    row1 chunk continuation, row2 mid-decode, row3 idle."""
    cfg = _cfg(kv_dtype)
    params = transformer.init_params(cfg, jax.random.key(0))
    pool = transformer.init_paged_cache(cfg, B * NBS + 1, BLOCK)
    table = jnp.asarray(
        np.stack([1 + i * NBS + np.arange(NBS) for i in range(B)])
        .astype(np.int32))
    sc = 8
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(2, cfg.vocab_size, size=(B * sc,)),
                       jnp.int32)
    pool, _ = _seed_row(cfg, params, pool, table, 1, 8, 101)
    pool, last2 = _seed_row(cfg, params, pool, table, 2, 37, 202)
    state = {
        "cache": pool,
        "last_tok": jnp.asarray([0, 0, last2, 0], jnp.int32),
        "pos": jnp.asarray([0, 0, 37, 0], jnp.int32),
        "active": jnp.asarray([False, False, True, False]),
        "temp": jnp.zeros((B,), jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.asarray([11, 22, 33, 44], jnp.int32),
        "remaining": jnp.asarray([0, 0, 3, 0], jnp.int32),
    }
    args = dict(
        tokens=toks,
        plens=jnp.asarray([6, 20, 0, 0], jnp.int32),
        starts=jnp.asarray([0, 8, SMAX, SMAX], jnp.int32),
        seeds=state["seeds"],
        temps=state["temp"],
        top_ks=state["top_k"],
        top_ps=state["top_p"],
        max_news=jnp.asarray([5, 5, 5, 5], jnp.int32),
        finals=jnp.asarray([True, False, False, False]),
        is_prefill=jnp.asarray([True, True, False, False]),
    )
    return cfg, params, table, state, args


def _run_wave(cfg, params, table, state, args, kernel, block_budget=0):
    st = jax.tree.map(lambda x: x, state)
    st2, first, fdone, toks, valid = ra.ragged_wave(
        params, st, table, args["tokens"], args["plens"], args["starts"],
        args["seeds"], args["temps"], args["top_ks"], args["top_ps"],
        args["max_news"], args["finals"], args["is_prefill"], cfg,
        kernel=kernel, block_budget=block_budget)
    return dict(first=np.asarray(first), fdone=np.asarray(fdone),
                toks=np.asarray(toks), valid=np.asarray(valid),
                pos=np.asarray(st2["pos"]),
                last=np.asarray(st2["last_tok"]))


def _assert_wave_equal(m, s):
    live_pf = slice(0, 2)  # rows 0-1 are the prefill rows
    np.testing.assert_array_equal(m["first"][live_pf], s["first"][live_pf])
    np.testing.assert_array_equal(m["fdone"][live_pf], s["fdone"][live_pf])
    live = m["valid"][0]
    np.testing.assert_array_equal(m["toks"][0][live], s["toks"][0][live])
    np.testing.assert_array_equal(m["pos"], s["pos"])
    np.testing.assert_array_equal(m["last"], s["last"])


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_wave_sparse_matches_masked(kv_dtype):
    fix = _wave_fixture(kv_dtype)
    m = _run_wave(*fix, kernel="masked")
    s = _run_wave(*fix, kernel="sparse")
    _assert_wave_equal(m, s)


def test_wave_pallas_interpret_matches_masked():
    # int8 only: the fused-dequant leg is the one pallas exists for;
    # interpret-mode is too slow to sweep both dtypes here.
    fix = _wave_fixture("int8")
    m = _run_wave(*fix, kernel="masked")
    p = _run_wave(*fix, kernel="pallas")
    _assert_wave_equal(m, p)


def test_wave_decode_only_skip_cond():
    """Decode-only waves take the lax.cond prefill skip; tokens must
    still match masked (which always runs its dead prefill leg)."""
    cfg, params, table, state, args = _wave_fixture("bf16")
    args = dict(args,
                plens=jnp.zeros((B,), jnp.int32),
                starts=jnp.full((B,), SMAX, jnp.int32),
                finals=jnp.zeros((B,), bool),
                is_prefill=jnp.zeros((B,), bool))
    m = _run_wave(cfg, params, table, state, args, kernel="masked")
    s = _run_wave(cfg, params, table, state, args, kernel="sparse")
    live = m["valid"][0]
    np.testing.assert_array_equal(m["toks"][0][live], s["toks"][0][live])
    np.testing.assert_array_equal(m["pos"], s["pos"])


def test_wave_block_budget_fallback():
    """block_budget=1 < the live walk's 5 blocks: the sparse leg must
    fall back to the masked head in-trace and reproduce it exactly."""
    fix = _wave_fixture("bf16")
    m = _run_wave(*fix, kernel="masked")
    s = _run_wave(*fix, kernel="sparse", block_budget=1)
    _assert_wave_equal(m, s)


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_prefill_logits_within_atol(kv_dtype):
    """Raw-logit pin: sparse stays bit-exact on the prefill leg; pallas
    stays within the documented RAGGED_LOGITS_ATOL envelope."""
    cfg, params, table, state, args = _wave_fixture(kv_dtype)
    bound = jnp.where(args["is_prefill"], args["starts"],
                      0).astype(jnp.int32)
    toks2 = args["tokens"].reshape(B, -1)

    def masked():
        view = transformer.paged_prefix_view(state["cache"], table, NBS)
        return transformer.prefill_with_prefix(
            params, toks2, args["plens"], view, args["starts"], cfg)[0]

    def leg(kern):
        return ra._prefill_logits_sparse(
            params, toks2, args["plens"], args["starts"], bound,
            state["cache"], table, cfg, kern)[0]

    want = np.asarray(jax.jit(masked)(), np.float32)
    got_s = np.asarray(jax.jit(lambda: leg("sparse"))(), np.float32)
    got_p = np.asarray(jax.jit(lambda: leg("pallas"))(), np.float32)
    live = np.asarray(args["is_prefill"])
    np.testing.assert_array_equal(got_s[live], want[live])
    assert np.abs(got_p[live] - want[live]).max() <= rpa.RAGGED_LOGITS_ATOL


# ---------------------------------------------------------------------------
# Verify-wave greedy parity (the spec leg)
# ---------------------------------------------------------------------------


def _verify_fixture(kv_dtype):
    cfg = _cfg(kv_dtype)
    params = transformer.init_params(cfg, jax.random.key(0))
    pool = transformer.init_paged_cache(cfg, B * NBS + 1, BLOCK)
    table = jnp.asarray(
        np.stack([1 + i * NBS + np.arange(NBS) for i in range(B)])
        .astype(np.int32))
    hist = [13, 21, 37, 5]
    last = []
    for i, n in enumerate(hist):
        pool, nxt = _seed_row(cfg, params, pool, table, i, n, 50 + i)
        last.append(nxt)
    state = {
        "cache": pool,
        "last_tok": jnp.asarray(last, jnp.int32),
        "pos": jnp.asarray(hist, jnp.int32),
        "active": jnp.asarray([True, True, True, False]),
        "temp": jnp.zeros((B,), jnp.float32),
        "top_k": jnp.zeros((B,), jnp.int32),
        "top_p": jnp.ones((B,), jnp.float32),
        "seeds": jnp.asarray([7, 8, 9, 10], jnp.int32),
        "remaining": jnp.asarray([10, 10, 10, 0], jnp.int32),
    }
    drafts = jnp.asarray(
        np.random.default_rng(99).integers(2, cfg.vocab_size, size=(B, 3)),
        jnp.int32)
    wave = jnp.asarray([True, True, True, False])
    return cfg, params, table, state, drafts, wave


def _run_verify(cfg, params, table, state, drafts, wave, kernel,
                block_budget=0):
    st = jax.tree.map(lambda x: x, state)
    st2, toks, valid = spec_decode.verify_wave(
        params, st, table, drafts, wave, cfg, kernel=kernel,
        block_budget=block_budget)
    return dict(toks=np.asarray(toks), valid=np.asarray(valid),
                pos=np.asarray(st2["pos"]),
                last=np.asarray(st2["last_tok"]),
                active=np.asarray(st2["active"]))


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_verify_sparse_matches_masked(kv_dtype):
    fix = _verify_fixture(kv_dtype)
    m = _run_verify(*fix, kernel="masked")
    s = _run_verify(*fix, kernel="sparse")
    liv = m["valid"]
    np.testing.assert_array_equal(m["toks"][liv], s["toks"][liv])
    np.testing.assert_array_equal(m["valid"], s["valid"])
    np.testing.assert_array_equal(m["pos"], s["pos"])
    np.testing.assert_array_equal(m["last"], s["last"])
    np.testing.assert_array_equal(m["active"], s["active"])


def test_verify_pallas_interpret_matches_masked():
    fix = _verify_fixture("int8")
    m = _run_verify(*fix, kernel="masked")
    p = _run_verify(*fix, kernel="pallas")
    liv = m["valid"]
    np.testing.assert_array_equal(m["toks"][liv], p["toks"][liv])
    np.testing.assert_array_equal(m["valid"], p["valid"])


def test_verify_block_budget_fallback():
    fix = _verify_fixture("bf16")
    m = _run_verify(*fix, kernel="masked")
    s = _run_verify(*fix, kernel="sparse", block_budget=1)
    liv = m["valid"]
    np.testing.assert_array_equal(m["toks"][liv], s["toks"][liv])


# ---------------------------------------------------------------------------
# Engine end to end: greedy stream parity + the lattice stays collapsed
# ---------------------------------------------------------------------------


def test_engine_sparse_greedy_stream_parity_and_lattice(monkeypatch):
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    monkeypatch.setenv("COMPILE_LEDGER", "1")
    cfg = _cfg("int8")
    params = transformer.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(29)
    lengths = [12, 26, 7]
    prompts = [rng.integers(3, cfg.vocab_size, size=(n,)).tolist()
               for n in lengths * 2]

    def run(kernel):
        ecfg = EngineConfig(
            max_slots=4, max_seq_len=64, prompt_buckets=(16, 32),
            max_admit=2, decode_chunk=4,
            paged_kv=True, kv_block=8, kv_pool_blocks=4 * 8 + 1,
            chunked_prefill=True, prefill_chunk=16, prefix_block=8,
            ragged=True, ragged_kernel=kernel)
        eng = InferenceEngine(params, cfg, ecfg)
        eng.warmup()
        eng.start()
        qs = [eng.submit(p, SamplingParams(
                  temperature=0.0, top_k=0, top_p=1.0,
                  max_new_tokens=6, seed=i))
              for i, p in enumerate(prompts)]
        streams = []
        for q in qs:
            toks = []
            while True:
                item = q.get(timeout=120)
                if item is None:
                    break
                assert "error" not in item, item
                toks.extend(item.get("tokens", []))
            streams.append(toks)
        comp = eng.debug_compile()
        static = eng.static_lattice()
        eng.stop()
        return streams, comp, static

    want, mcomp, mstatic = run("masked")
    got, scomp, sstatic = run("sparse")
    assert got == want, (got, want)
    assert all(s for s in want)  # every request actually streamed
    # the kernel string is closed over at jit time: same 2-key lattice
    # either way, and nothing compiled on the serving path.
    assert sstatic == ["deactivate", "ragged/16"], sstatic
    assert sstatic == mstatic
    assert scomp["live_retrace_count"] == 0, scomp["live_retraces"]
