"""graftspec (models/spec_decode.py + engine._dispatch_spec): draft
k tokens, verify all k+1 positions in one ragged wave, commit the
accepted prefix, roll the rest back — pinned against the plain engine.

The load-bearing claims, in test form:
 * output is BIT-IDENTICAL spec-on vs spec-off — greedy AND sampled,
   across paged / paged+chunked / prefix-warm modes, for bf16 and int8
   KV: verification is exact-match against the target's own
   sequentially-keyed samples, so speculation can never change a
   token, only the number of dispatches it took;
 * speculation genuinely COMPRESSES dispatches: with a perfect drafter
   the engine emits ~(k+1) tokens per verify wave, driving
   dispatches/token well under 1.0;
 * rollback is leak-free at every edge: rejection at position 0,
   full-k acceptance, acceptance crossing a kv_block boundary (the
   host-side block-table tail trim must unref exactly the dead decode
   blocks), and EOS landing mid-accepted-prefix (drafts that matched
   but fell after the terminal token count rejected);
 * the lattice stays CLOSED: static_lattice() grows exactly the
   ("verify", k) pow2 ladder (+ ("draft", k) with a resident draft
   model), warmup compiles it, and live traffic never retraces;
 * the sched ledger's acceptance accounting is conservation-exact:
   accepted + rejected == drafted, and every verify-wave cell is
   attributed useful-or-rejected with zero audit breaches;
 * spec_decode=False leaves the engine byte-identical to the seed
   build, and EngineConfig rejects unusable spec knob combinations.
"""

import dataclasses
import queue

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))  # 24 tokens: 3 kv_blocks exactly
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=12)
SAMPLED = SamplingParams(temperature=0.9, top_k=8, top_p=0.95,
                         max_new_tokens=12, seed=7)

MIXED = [
    list(range(2, 26)),
    list(range(30, 33)),
    list(range(40, 57)),
    [5, 9],
]

# The spec engine rides the paged substrate (rollback is a block-table
# tail trim); kv_block=8 makes block-boundary crossings cheap to hit.
PAGED = dict(paged_kv=True, kv_block=8, prefix_block=8)
SPEC = dict(spec_decode=True, spec_k=4, **PAGED)


def _engine(cfg, start=True, **ekw):
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _want(cfg, prompt=PROMPT, sp=GREEDY, **ekw):
    """Spec-off reference output for one prompt under a given mode."""
    eng = _engine(cfg, **ekw)
    try:
        return eng.generate_blocking(prompt, sp)["token_ids"]
    finally:
        eng.stop()


def _collect(q, timeout=120):
    toks, err = [], None
    while True:
        item = q.get(timeout=timeout)
        if item is None:
            return toks, err
        if "error" in item:
            err = item
        else:
            toks.extend(item.get("tokens", []))


class _Oracle:
    """Perfect drafter: proposes the exact greedy continuation — every
    wave accepts full-k (until the budget/EOS terminal)."""

    uses_model = False

    def __init__(self, want):
        self._want = list(want)

    def draft(self, prompt, gen, k):
        i = len(gen)
        out = list(self._want[i:i + k])
        while len(out) < k:
            out.append(self._want[-1] if self._want else 0)
        return out


class _AntiOracle:
    """Adversarial drafter: always wrong — every wave rejects at
    position 0 and the engine degrades to one token per dispatch."""

    uses_model = False

    def __init__(self, want, vocab):
        self._want = list(want)
        self._vocab = vocab

    def draft(self, prompt, gen, k):
        i = len(gen)
        out = []
        for j in range(k):
            t = self._want[i + j] if i + j < len(self._want) else 0
            out.append((t + 1) % self._vocab)
        return out


# ---------------------------------------------------------------------------
# Bit-exactness: spec-on vs spec-off across modes and dtypes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
@pytest.mark.parametrize("mode", ["paged", "chunked", "prefix"])
def test_spec_bit_identical_across_modes(kv_dtype, mode):
    """The acceptance gate's exactness criterion: greedy output under
    SPEC matches the spec-off engine token-for-token in every paged
    mode x KV dtype."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    extra = {}
    if mode == "chunked":
        extra = dict(chunked_prefill=True, prefill_chunk=8)
    elif mode == "prefix":
        extra = dict(prefix_cache=True)
    want = _want(cfg, **PAGED, **extra)

    eng = _engine(cfg, **SPEC, **extra)
    try:
        if mode == "prefix":
            # Cold admission seeds the trie; the warm resume is the
            # interesting path (spec waves over shared blocks).
            assert eng.generate_blocking(PROMPT, GREEDY)["token_ids"] \
                == want
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        if mode == "prefix":
            assert eng.stats.snapshot()["zero_copy_admissions"] >= 1
    finally:
        eng.stop()
    assert got == want


def test_spec_sampled_bit_identical():
    """Exact-match verification is temperature-blind: per-row keys are
    position-derived, so sampled output is bit-identical too (this is
    what separates graftspec from rejection-sampling schemes)."""
    cfg = get_config("tiny")
    want = _want(cfg, sp=SAMPLED, **PAGED)
    eng = _engine(cfg, **SPEC)
    try:
        got = eng.generate_blocking(PROMPT, SAMPLED)["token_ids"]
    finally:
        eng.stop()
    assert got == want


def test_spec_mixed_burst_bit_identical():
    """A concurrent mixed-length burst: every row's stream matches its
    spec-off reference even as waves carry different per-row rewind
    depths."""
    cfg = get_config("tiny")
    wants = [_want(cfg, p, **PAGED) for p in MIXED]
    eng = _engine(cfg, **SPEC)
    try:
        qs = [eng.submit(p, GREEDY) for p in MIXED]
        gots = []
        for q in qs:
            toks, err = _collect(q)
            assert err is None, err
            gots.append(toks)
    finally:
        eng.stop()
    assert gots == wants


# ---------------------------------------------------------------------------
# Compression: dispatches/token < 1.0 with a good drafter
# ---------------------------------------------------------------------------


def test_spec_oracle_compresses_dispatches():
    """With a perfect drafter the engine emits k+1 tokens per verify
    wave: 12 decode tokens land in ~3 dispatches instead of 11 — the
    CPU-smoke form of the 2x TPU target (docs/benchmarking.md)."""
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    eng = _engine(cfg, start=False, **SPEC)
    eng._drafter = _Oracle(want)
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got == want
    n_decoded = len(want) - 1  # first token comes from the admit
    assert snap["decode_dispatches"] < n_decoded, snap
    # Perfect acceptance: ceil(11 / (k+1)) = 3 waves for k=4.
    assert snap["decode_dispatches"] <= 3
    assert snap["decode_dispatches"] / snap["tokens_out"] < 1.0


# ---------------------------------------------------------------------------
# Rollback edge cases
# ---------------------------------------------------------------------------


def test_spec_rejection_at_position_zero_is_leak_free():
    """An always-wrong drafter rejects at position 0 every wave: the
    engine degrades to one token per dispatch, stays bit-exact, and
    the per-wave block growth + tail trim nets out to zero leaks."""
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    eng = _engine(cfg, start=False, **SPEC)
    eng._drafter = _AntiOracle(want, cfg.vocab_size)
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
        leaks = eng.debug_lifecycle_check()
    finally:
        eng.stop()
    assert got == want
    # Every wave rejected everything: one emitted token per dispatch.
    assert snap["decode_dispatches"] == len(want) - 1
    assert leaks == {}, leaks


def test_spec_full_k_acceptance_crosses_block_boundary():
    """Full-k waves march the write position straight across kv_block
    boundaries (24-token prompt + 12 generated crosses pos 32 with
    kv_block=8): the commit allocates blocks mid-wave and the
    allocator's refcount discipline stays exact."""
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    eng = _engine(cfg, start=False, **SPEC)
    eng._drafter = _Oracle(want)
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        leaks = eng.debug_lifecycle_check()
        pool = eng._allocator.snapshot()
    finally:
        eng.stop()
    assert got == want
    assert leaks == {}, leaks
    # Every block the request grew came back on completion.
    assert pool["free"] == pool["total"], pool


def test_spec_eos_mid_accepted_prefix():
    """EOS landing inside an accepted run terminates the row exactly
    there: drafts that matched but fell after the terminal token count
    rejected, and the stream matches the spec-off engine's EOS stop."""
    cfg = get_config("tiny")
    base = _want(cfg, **PAGED)
    # Re-point EOS at a token the greedy continuation actually emits,
    # mid-stream, so the terminal lands inside a wave.
    eos_cfg = dataclasses.replace(cfg, eos_token_id=int(base[5]))
    want = _want(eos_cfg, **PAGED)
    assert len(want) < len(base), "fixture must terminate early on EOS"
    eng = _engine(eos_cfg, start=False, **SPEC)
    eng._drafter = _Oracle(base)  # drafts continue PAST the terminal
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        leaks = eng.debug_lifecycle_check()
    finally:
        eng.stop()
    assert got == want
    assert leaks == {}, leaks


# ---------------------------------------------------------------------------
# Lattice containment + zero live retraces
# ---------------------------------------------------------------------------


def test_spec_lattice_declares_verify_ladder_and_never_retraces(
    monkeypatch,
):
    """static_lattice() grows exactly the pow2 verify ladder, warmup
    compiles it, and a full generation stays inside it (zero live
    retraces) — the compile-audit SPEC=1 leg's criterion."""
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    cfg = get_config("tiny")
    eng = _engine(cfg, start=False, **SPEC)
    static = set(eng.static_lattice())
    assert {"verify/1", "verify/2", "verify/4"} <= static
    assert not any(k.startswith("decode/") for k in static), (
        "spec replaces the decode family, not adds to it")
    assert not any(k.startswith("draft/") for k in static), (
        "n-gram drafting is host-side: no draft variants")
    eng.warmup()
    eng.start()
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        comp = eng.debug_compile()
    finally:
        eng.stop()
    assert comp["live_retrace_count"] == 0, comp["live_retraces"]
    assert {e["key"] for e in comp["lattice"]} <= static


def test_spec_model_drafter_declares_draft_family():
    """A resident draft model adds the ("draft", k) ladder to the
    lattice and stays bit-exact — even with weights that disagree with
    the target (bad drafts cost acceptance, never output)."""
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    params = init_params(cfg, jax.random.key(0))
    dparams = init_params(cfg, jax.random.key(1))
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(8, 32),
                     spec_draft="tiny", **SPEC),
        draft=(dparams, cfg),
    )
    static = set(eng.static_lattice())
    assert {"draft/1", "draft/2", "draft/4"} <= static
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        eng.stop()
    assert got == want


def test_spec_self_draft_perfect_greedy_acceptance():
    """The same weights as drafter: greedy drafts are the greedy
    continuation, so acceptance is perfect and the wave count collapses
    to ceil(n/(k+1)) — the strongest compression witness."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    want = _want(cfg, **PAGED)
    eng = InferenceEngine(
        params, cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(8, 32),
                     spec_draft="tiny", **SPEC),
        draft=(params, cfg),
    )
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got == want
    assert snap["decode_dispatches"] <= 3


# ---------------------------------------------------------------------------
# Sched-ledger acceptance accounting
# ---------------------------------------------------------------------------


def test_spec_conservation_and_acceptance_identities(monkeypatch):
    """Every verified token-slot is attributed useful-or-rejected, the
    acceptance identity accepted + rejected == drafted re-sums, and the
    ledger's own boundary audits never breach."""
    monkeypatch.setenv("SCHED_LEDGER", "1")
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    eng = _engine(cfg, start=False, **SPEC)
    eng._drafter = _Oracle(want)
    eng.start()
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        eng.drain(timeout=120)
        sched = eng.debug_sched()
    finally:
        eng.stop()
    assert got == want
    assert sched["conservation"]["breaches"] == 0, (
        sched["conservation"]["last_breach"])
    spec = sched["spec"]
    assert spec["verify_waves"] >= 1
    assert spec["drafted_tokens"] > 0
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["drafted_tokens"])
    # Oracle drafts: acceptance is high (only terminal-clipped drafts
    # reject).
    assert spec["acceptance_rate"] >= 0.5, spec
    # The four-way attribution re-sums to the dispatched cells.
    assert (sched["useful_tokens"] + sched["bucket_pad_tokens"]
            + sched["group_pad_tokens"] + sched["spec_rejected_tokens"]
            == sched["dispatch_cells"])
    verify_shapes = [e for e in sched["by_shape"]
                     if str(e["key"]).startswith("verify/")]
    assert verify_shapes, sched["by_shape"]
    assert all(e["bucket_pad_tokens"] == 0 and e["group_pad_tokens"] == 0
               for e in verify_shapes)


def test_spec_pilot_binds_fourth_knob(monkeypatch):
    """PILOT=1 + SPEC: the controller's spec_k knob lives on the rung
    ladder envelope and the spec acceptance signals flow into decision
    windows — output stays bit-identical (pilot-at-defaults)."""
    monkeypatch.setenv("PILOT", "1")
    cfg = get_config("tiny")
    want = _want(cfg, **PAGED)
    eng = _engine(cfg, **SPEC)
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        pilot = eng.debug_pilot()
    finally:
        eng.stop()
    assert got == want
    assert pilot["knobs"]["spec_k"] == 4
    assert pilot["envelope"]["speck_min"] == 1
    assert pilot["envelope"]["speck_max"] == 4


# ---------------------------------------------------------------------------
# Off-mode isolation + config validation
# ---------------------------------------------------------------------------


def test_spec_off_engine_is_untouched():
    cfg = get_config("tiny")
    eng = _engine(cfg, start=False, **PAGED)
    assert not any(k.startswith(("verify/", "draft/"))
                   for k in eng.static_lattice())
    assert eng._spec is False
    assert eng._drafter is None


def test_spec_config_validation():
    base = dict(max_slots=4, max_seq_len=64, prompt_buckets=(8, 32))
    with pytest.raises(ValueError, match="paged_kv"):
        EngineConfig(spec_decode=True, **base)
    with pytest.raises(ValueError, match="ragged"):
        EngineConfig(spec_decode=True, paged_kv=True, kv_block=8,
                     prefix_block=8, chunked_prefill=True,
                     prefill_chunk=8, ragged=True, **base)
    with pytest.raises(ValueError, match="power of two"):
        EngineConfig(spec_decode=True, spec_k=3, paged_kv=True,
                     kv_block=8, prefix_block=8, **base)
