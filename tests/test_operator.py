"""Operator tests — mirror the reference's envtest assertions
(operator/controllers/seldondeployment_controller_test.go:1-138: created
Deployment shape from a CR fixture; webhook tests; ambassador golden)."""

import base64
import json

import pytest

from seldon_tpu.operator import (
    InMemoryStore,
    Reconciler,
    SeldonDeployment,
    default_deployment,
    machine_name,
    validate_deployment,
)
from seldon_tpu.operator import types as T
from seldon_tpu.operator.reconciler import (
    DEPLOYMENT_LABEL,
    ENGINE_LABEL,
    GENERATION_LABEL,
    ambassador_annotations,
    build_istio_manifests,
)


def fixture_cr(name="mymodel", generation=1, tpu=None, predictors=None):
    pred = {
        "name": "main",
        "replicas": 1,
        "graph": {
            "name": "classifier",
            "type": "MODEL",
            "implementation": "JAX_SERVER",
            "modelUri": "file:///models/demo",
        },
    }
    if tpu:
        pred["tpu"] = tpu
    return SeldonDeployment.from_dict(
        {
            "metadata": {"name": name, "namespace": "test",
                         "generation": generation},
            "spec": {"predictors": predictors or [pred]},
        }
    )


def test_machine_name_truncation():
    n = machine_name("a" * 100, "b")
    assert len(n) <= 63
    assert machine_name("MyModel", "p") == "mymodel-p"
    # Deterministic.
    assert machine_name("a" * 100, "b") == machine_name("a" * 100, "b")


def test_defaulting_assigns_ports_and_hosts():
    sdep = fixture_cr()
    default_deployment(sdep)
    unit = sdep.predictors[0].spec.graph
    assert unit.endpoint is not None
    assert unit.endpoint.service_port == 9000
    assert unit.endpoint.service_host == "localhost"
    assert unit.image == T.DEFAULT_SERVER_IMAGE


def test_defaulting_fastpath_ports_and_stride():
    """Native units get fastPort = service_port+1; allocation strides by
    2 so the fast lane never collides with the next unit; foreign images
    stay off the lane unless the annotation opts them in."""
    sdep = fixture_cr(predictors=[{
        "name": "main", "replicas": 1,
        "graph": {
            "name": "t", "type": "TRANSFORMER",
            "image": "seldon-tpu/microservice:0.1.0",
            "children": [{
                "name": "m", "type": "MODEL",
                "image": "other-registry/foreign:1",
            }],
        },
    }])
    default_deployment(sdep)
    units = {u.name: u for u in sdep.predictors[0].spec.graph.walk()}
    assert units["t"].endpoint.service_port == 9000
    assert units["t"].endpoint.fast_port == 9001
    assert units["m"].endpoint.service_port == 9002  # stride 2
    assert units["m"].endpoint.fast_port == 0  # foreign image: no lane

    sdep2 = fixture_cr(predictors=[{
        "name": "main", "replicas": 1,
        "graph": {"name": "m", "type": "MODEL",
                  "image": "other-registry/foreign:1"},
    }])
    sdep2.annotations[T.ANNOTATION_FASTPATH] = "true"
    default_deployment(sdep2)
    assert sdep2.predictors[0].spec.graph.endpoint.fast_port == 9001

    # fastPort survives the round trip into the engine's spec encoding.
    from seldon_tpu.orchestrator.spec import PredictiveUnit as PU

    rt = PU.from_dict(sdep.predictors[0].spec.graph.to_dict())
    assert rt.endpoint.fast_port == 9001


def test_defaulting_separate_engine_uses_svc_dns():
    sdep = fixture_cr()
    sdep.annotations[T.ANNOTATION_SEPARATE_ENGINE] = "true"
    default_deployment(sdep)
    unit = sdep.predictors[0].spec.graph
    assert unit.endpoint.service_host.endswith(".test.svc.cluster.local.")


def test_validation_catches_problems():
    sdep = fixture_cr()
    sdep.predictors[0].spec.graph.model_uri = ""
    default_deployment(sdep)
    problems = validate_deployment(sdep)
    assert any("modelUri" in p for p in problems)

    two = fixture_cr(
        predictors=[
            {"name": "a", "traffic": 50,
             "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}},
            {"name": "b", "traffic": 40,
             "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}},
        ]
    )
    problems = validate_deployment(two)
    assert any("traffic" in p for p in problems)


def test_reconcile_creates_deployment_shape():
    store = InMemoryStore()
    sdep = fixture_cr()
    status = Reconciler(store).reconcile(sdep)
    assert status.state == "Available"

    deps = store.list("Deployment", "test")
    assert len(deps) == 1
    pod = deps[0]["spec"]["template"]["spec"]
    names = [c["name"] for c in pod["containers"]]
    assert "classifier" in names
    assert "seldon-container-engine" in names
    # Engine carries the base64 graph spec.
    engine = next(c for c in pod["containers"]
                  if c["name"] == "seldon-container-engine")
    env = {e["name"]: e["value"] for e in engine["env"]}
    graph = json.loads(base64.b64decode(env[T.ENV_ENGINE_PREDICTOR]))
    assert graph["graph"]["name"] == "classifier"
    # Model initializer + shared volume.
    assert pod["initContainers"][0]["name"] == "classifier-model-initializer"
    assert pod["volumes"][0]["name"] == "model-volume-classifier"
    # Unit container env.
    unit = next(c for c in pod["containers"] if c["name"] == "classifier")
    uenv = {e["name"]: e["value"] for e in unit["env"]}
    assert uenv[T.ENV_PREDICTIVE_UNIT_SERVICE_PORT] == "9000"
    assert uenv[T.ENV_SELDON_DEPLOYMENT_ID] == "mymodel"
    params = json.loads(uenv[T.ENV_PREDICTIVE_UNIT_PARAMETERS])
    assert {"name": "model_uri", "value": "/mnt/models",
            "type": "STRING"} in params
    # Services: predictor svc exists.
    svcs = store.list("Service", "test")
    assert any(s["metadata"]["name"] == "mymodel-main" for s in svcs)


def test_reconcile_tpu_placement():
    store = InMemoryStore()
    sdep = fixture_cr(tpu={"chips": 4, "topology": "2x2",
                           "accelerator": "tpu-v5-lite-podslice"})
    Reconciler(store).reconcile(sdep)
    pod = store.list("Deployment", "test")[0]["spec"]["template"]["spec"]
    sel = pod["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    unit = next(c for c in pod["containers"] if c["name"] == "classifier")
    assert unit["resources"]["limits"]["google.com/tpu"] == 4


def test_reconcile_multihost_statefulset():
    store = InMemoryStore()
    sdep = fixture_cr(tpu={"chips": 4, "topology": "2x4", "hosts": 2})
    status = Reconciler(store).reconcile(sdep)
    assert status.state == "Available"
    sts = store.list("StatefulSet", "test")
    assert len(sts) == 1
    assert sts[0]["spec"]["replicas"] == 2  # hosts x replicas
    assert sts[0]["spec"]["serviceName"].endswith("-hosts")
    headless = [
        s for s in store.list("Service", "test")
        if s["spec"].get("clusterIP") == "None"
    ]
    assert len(headless) == 1


def test_rolling_update_gc_engine_last():
    """Generation bump with a renamed predictor: old resources deleted,
    engine-labeled ones ordered last; nothing deleted while not ready."""
    store = InMemoryStore()
    r = Reconciler(store)
    sdep = fixture_cr(generation=1)
    r.reconcile(sdep)
    old_dep = store.list("Deployment", "test")[0]["metadata"]["name"]

    # New generation renames the predictor -> new resource names.
    sdep2 = fixture_cr(generation=2)
    sdep2.predictors[0].spec.name = "canary"

    # While the new deployment is not ready, stale resources survive.
    new_name = T.predictor_deployment_name(sdep2, sdep2.predictors[0])
    store.not_ready.add(("Deployment", "test", new_name))
    status = r.reconcile(sdep2)
    assert status.state == "Creating"
    names = [d["metadata"]["name"] for d in store.list("Deployment", "test")]
    assert old_dep in names  # old engine still draining

    # Ready -> stale generation GC'd.
    store.not_ready.clear()
    status = r.reconcile(sdep2)
    assert status.state == "Available"
    names = [d["metadata"]["name"] for d in store.list("Deployment", "test")]
    assert old_dep not in names
    assert new_name in names


def test_istio_traffic_weights():
    sdep = fixture_cr(
        predictors=[
            {"name": "a", "traffic": 75,
             "graph": {"name": "m1", "implementation": "SIMPLE_MODEL"}},
            {"name": "b", "traffic": 25,
             "graph": {"name": "m2", "implementation": "SIMPLE_MODEL"}},
        ]
    )
    default_deployment(sdep)
    manifests = build_istio_manifests(sdep)
    vs = [m for m in manifests if m["kind"] == "VirtualService"][0]
    weights = [r["weight"] for r in vs["spec"]["http"][0]["route"]]
    assert weights == [75, 25]
    assert len([m for m in manifests if m["kind"] == "DestinationRule"]) == 2


def test_ambassador_yaml():
    sdep = fixture_cr()
    default_deployment(sdep)
    yaml_block = ambassador_annotations(sdep)
    assert "prefix: /seldon/test/mymodel/" in yaml_block
    assert "grpc: true" in yaml_block
    assert "retry_on: connect-failure" in yaml_block
    assert "shadow" not in yaml_block
    import yaml as pyyaml

    docs = [d for d in pyyaml.safe_load_all(yaml_block) if d]
    assert len(docs) == 2
    # Single predictor always gets full weight (ambassador.go:228-230).
    assert all(d["weight"] == 100 for d in docs)


def test_ambassador_shadow_and_header_routing():
    """Reference ambassador.go:14-17,119-133: shadow mirroring + custom
    exact/regex header routing + service-name/id overrides."""
    import yaml as pyyaml

    sdep = fixture_cr()
    sdep.annotations[T.ANNOTATION_AMBASSADOR_SHADOW] = "true"
    sdep.annotations[T.ANNOTATION_AMBASSADOR_HEADER] = "x-team: ml : x-env:prod"
    sdep.annotations[T.ANNOTATION_AMBASSADOR_REGEX_HEADER] = "x-user: canary-.*"
    sdep.annotations[T.ANNOTATION_AMBASSADOR_SERVICE] = "extname"
    sdep.annotations[T.ANNOTATION_AMBASSADOR_ID] = "amb-a"
    default_deployment(sdep)
    docs = [
        d for d in pyyaml.safe_load_all(ambassador_annotations(sdep)) if d
    ]
    assert len(docs) == 2
    rest = [d for d in docs if not d.get("grpc")][0]
    grpc = [d for d in docs if d.get("grpc")][0]
    assert rest["shadow"] is True and grpc["shadow"] is True
    assert rest["prefix"] == "/seldon/test/extname/"
    assert rest["headers"] == {"x-team": "ml", "x-env": "prod"}
    assert rest["regex_headers"] == {"x-user": "canary-.*"}
    assert rest["ambassador_id"] == "amb-a"
    # gRPC keeps its routing headers AND gains the custom ones; the
    # seldon routing header follows the external service name.
    assert grpc["headers"]["seldon"] == "extname"
    assert grpc["headers"]["x-team"] == "ml"


def test_ambassador_custom_config_override():
    sdep = fixture_cr()
    sdep.annotations[T.ANNOTATION_AMBASSADOR_CUSTOM] = "my: config\n"
    default_deployment(sdep)
    assert ambassador_annotations(sdep) == "my: config\n"


def test_separate_engine_pod():
    store = InMemoryStore()
    sdep = fixture_cr()
    sdep.annotations[T.ANNOTATION_SEPARATE_ENGINE] = "true"
    Reconciler(store).reconcile(sdep)
    deps = store.list("Deployment", "test")
    assert len(deps) == 2
    engine_deps = [
        d for d in deps
        if d["metadata"]["labels"].get(ENGINE_LABEL) == "true"
    ]
    assert len(engine_deps) == 1
    pods = [
        d for d in deps
        if d["metadata"]["labels"].get(ENGINE_LABEL) != "true"
    ]
    unit_pod = pods[0]["spec"]["template"]["spec"]
    assert all(
        c["name"] != "seldon-container-engine" for c in unit_pod["containers"]
    )


def test_traffic_defaulting():
    """Unset traffic distributes: 2 predictors no traffic -> 50/50; canary
    pattern (only canary set) gives main the remainder."""
    sdep = fixture_cr(
        predictors=[
            {"name": "a", "graph": {"name": "m1",
                                    "implementation": "SIMPLE_MODEL"}},
            {"name": "b", "graph": {"name": "m2",
                                    "implementation": "SIMPLE_MODEL"}},
        ]
    )
    default_deployment(sdep)
    assert [p.spec.traffic for p in sdep.predictors] == [50, 50]
    assert validate_deployment(sdep) == []

    canary = fixture_cr(
        predictors=[
            {"name": "main", "graph": {"name": "m1",
                                       "implementation": "SIMPLE_MODEL"}},
            {"name": "canary", "traffic": 10,
             "graph": {"name": "m2", "implementation": "SIMPLE_MODEL"}},
        ]
    )
    default_deployment(canary)
    assert [p.spec.traffic for p in canary.predictors] == [90, 10]


def test_two_prepackaged_units_get_separate_volumes():
    store = InMemoryStore()
    sdep = fixture_cr(
        predictors=[{
            "name": "p",
            "graph": {
                "name": "top", "type": "MODEL",
                "implementation": "SKLEARN_SERVER",
                "modelUri": "file:///models/a",
                "children": [{
                    "name": "leaf", "type": "MODEL",
                    "implementation": "XGBOOST_SERVER",
                    "modelUri": "file:///models/b",
                }],
            },
        }]
    )
    Reconciler(store).reconcile(sdep)
    pod = store.list("Deployment", "test")[0]["spec"]["template"]["spec"]
    vols = {v["name"] for v in pod["volumes"]}
    # one model volume per unit (no clobbering) + the engine's podinfo
    assert vols == {"model-volume-top", "model-volume-leaf", "podinfo"}
    for c in pod["containers"]:
        if c["name"] in ("top", "leaf"):
            assert c["volumeMounts"][0]["name"] == f"model-volume-{c['name']}"


def test_multihost_env_targets_tpu_container():
    store = InMemoryStore()
    sdep = fixture_cr(
        predictors=[{
            "name": "p",
            "graph": {
                "name": "pre", "type": "TRANSFORMER",
                "endpoint": {"service_port": 9500, "type": "GRPC"},
                "image": "user/transformer:1",
                "children": [{
                    "name": "llm", "type": "MODEL",
                    "implementation": "JAX_SERVER",
                    "modelUri": "file:///models/llm",
                }],
            },
            "tpu": {"chips": 4, "topology": "2x4", "hosts": 2},
        }]
    )
    Reconciler(store).reconcile(sdep)
    pod = store.list("StatefulSet", "test")[0]["spec"]["template"]["spec"]
    llm = next(c for c in pod["containers"] if c["name"] == "llm")
    env = {e["name"] for e in llm["env"]}
    assert "TPU_WORKER_HOSTNAMES_SVC" in env
    pre = next(c for c in pod["containers"] if c["name"] == "pre")
    assert "TPU_WORKER_HOSTNAMES_SVC" not in {e["name"] for e in pre["env"]}


# ---------------------------------------------------------------------------
# HPA + explainer (reference createHpa :87-109, explainers.go:33-194)
# ---------------------------------------------------------------------------


def test_hpa_manifest_shape():
    pred = {
        "name": "main",
        "replicas": 1,
        "graph": {"name": "clf", "type": "MODEL",
                  "implementation": "JAX_SERVER",
                  "modelUri": "file:///m"},
        "hpaSpec": {
            "minReplicas": 1,
            "maxReplicas": 5,
            "metrics": [{"type": "Resource", "resource": {
                "name": "cpu",
                "target": {"type": "Utilization",
                           "averageUtilization": 60}}}],
        },
    }
    sdep = fixture_cr(predictors=[pred])
    store = InMemoryStore()
    Reconciler(store, istio_enabled=False).reconcile(sdep)
    hpas = store.list("HorizontalPodAutoscaler", "test")
    assert len(hpas) == 1
    spec = hpas[0]["spec"]
    assert spec["maxReplicas"] == 5 and spec["minReplicas"] == 1
    assert spec["scaleTargetRef"]["kind"] == "Deployment"
    assert spec["scaleTargetRef"]["name"] == T.predictor_deployment_name(
        sdep, sdep.predictors[0]
    )
    target = spec["metrics"][0]["resource"]["target"]
    assert target["averageUtilization"] == 60


def test_hpa_absent_without_spec():
    sdep = fixture_cr()
    store = InMemoryStore()
    Reconciler(store, istio_enabled=False).reconcile(sdep)
    assert store.list("HorizontalPodAutoscaler", "test") == []


def test_explainer_deployment_and_route():
    pred = {
        "name": "main",
        "replicas": 1,
        "graph": {"name": "clf", "type": "MODEL",
                  "implementation": "JAX_SERVER",
                  "modelUri": "file:///m"},
        "explainer": {
            "type": "anchor_tabular",
            "modelUri": "gs://bucket/explainer",
        },
    }
    sdep = fixture_cr(predictors=[pred])
    store = InMemoryStore()
    Reconciler(store, istio_enabled=True).reconcile(sdep)
    exp_name = T.explainer_deployment_name(sdep, sdep.predictors[0])
    deps = {d["metadata"]["name"]: d for d in store.list("Deployment", "test")}
    assert exp_name in deps
    c = deps[exp_name]["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == T.DEFAULT_EXPLAINER_IMAGE
    # Args point the explainer back at the predictor service (ref :110-120).
    pred_svc = T.predictor_service_name(sdep, sdep.predictors[0])
    assert any(pred_svc in a for a in c["args"] if "--predictor-host" in a)
    assert "anchor_tabular" == c["args"][-1]
    assert any("--storage-uri" in a for a in c["args"])  # modelUri given
    # initContainer downloads the explainer model.
    assert deps[exp_name]["spec"]["template"]["spec"]["initContainers"]
    # Own service + istio -explainer route.
    svcs = {s["metadata"]["name"] for s in store.list("Service", "test")}
    assert exp_name in svcs
    vs = store.list("VirtualService", "test")[0]
    prefixes = [m["uri"]["prefix"] for b in vs["spec"]["http"]
                for m in b["match"]]
    assert any("-explainer/" in p for p in prefixes)
    # Explainer probes mirror reference defaults.
    assert c["readinessProbe"]["tcpSocket"]["port"] == "grpc"


def test_explainer_gc_with_generation():
    pred = {
        "name": "main",
        "replicas": 1,
        "graph": {"name": "clf", "type": "MODEL",
                  "implementation": "JAX_SERVER", "modelUri": "file:///m"},
        "explainer": {"type": "anchor_tabular"},
    }
    store = InMemoryStore()
    rec = Reconciler(store, istio_enabled=False)
    rec.reconcile(fixture_cr(predictors=[pred], generation=1))
    # Generation 2 drops the explainer: stale explainer resources must GC.
    pred2 = dict(pred)
    pred2.pop("explainer")
    rec.reconcile(fixture_cr(predictors=[pred2], generation=2))
    names = {d["metadata"]["name"] for d in store.list("Deployment", "test")}
    assert not any("explainer" in n for n in names), names


def test_cr_annotations_reach_pod_template_for_podinfo():
    """CR annotations must land on the pod template: the engine reads them
    back via the downward-API podinfo mount (core/annotations.py)."""
    sdep = fixture_cr()
    sdep.annotations["seldon.io/rest-read-timeout"] = "9000"
    store = InMemoryStore()
    Reconciler(store, istio_enabled=False).reconcile(sdep)
    pod_meta = store.list("Deployment", "test")[0]["spec"]["template"]["metadata"]
    assert pod_meta["annotations"]["seldon.io/rest-read-timeout"] == "9000"
    vols = {v["name"]: v for v in
            store.list("Deployment", "test")[0]["spec"]["template"]["spec"]["volumes"]}
    items = vols["podinfo"]["downwardAPI"]["items"]
    assert items[0]["fieldRef"]["fieldPath"] == "metadata.annotations"
