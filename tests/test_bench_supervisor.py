"""bench.py supervisor logic: phase-scored record selection (the
outage-proofing that keeps the driver's perf record non-null)."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_phase_score_ordering():
    b = _load_bench()

    def line(partial=False, slo=False, b1=False, b1_slo=False):
        d = {"decode_tokens_per_s": 1.0}
        if partial:
            d["partial"] = True
        if slo:
            d["slo_req_s"] = 50.0
        if b1:
            d["bench_1b"] = (
                {"req_per_s": 1.0, "slo_req_s": 90.0} if b1_slo
                else {"req_per_s": 1.0}
            )
        return {"metric": "m", "value": 1.0, "detail": d}

    s = b._phase_score
    assert s(None) < s(line(partial=True))
    # more completed phases beat fewer, among partials
    assert s(line(partial=True)) < s(line(partial=True, slo=True))
    assert (s(line(partial=True, slo=True))
            < s(line(partial=True, slo=True, b1=True)))
    assert (s(line(partial=True, slo=True, b1=True))
            < s(line(partial=True, slo=True, b1=True, b1_slo=True)))
    # ANY final record beats EVERY partial checkpoint
    assert (s(line(partial=False))
            > s(line(partial=True, slo=True, b1=True, b1_slo=True)))
    # and among finals, richer still wins
    assert s(line()) < s(line(slo=True, b1=True, b1_slo=True))


def test_build_act_dtype_gating(monkeypatch):
    """BENCH_ACT (W8A8) only engages when weights are int8; BENCH_ACT
    and BENCH_WEIGHTS env reverts both stay honored."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    monkeypatch.delenv("BENCH_WEIGHTS", raising=False)
    monkeypatch.delenv("BENCH_ACT", raising=False)
    b = _load_bench()
    assert b.ACT == "int8" and b.WEIGHTS == "int8"  # round-5 defaults
    _, cfg = b._build("tiny")
    assert cfg.weight_dtype == "int8" and cfg.act_dtype == "int8"
    monkeypatch.setenv("BENCH_WEIGHTS", "bf16")
    _, cfg2 = _load_bench()._build("tiny")
    # bf16 weights -> W8A8 must stay off regardless of ACT default.
    assert cfg2.weight_dtype == "bf16" and cfg2.act_dtype == "bf16"
    monkeypatch.delenv("BENCH_WEIGHTS")
    monkeypatch.setenv("BENCH_ACT", "bf16")
    _, cfg3 = _load_bench()._build("tiny")
    assert cfg3.weight_dtype == "int8" and cfg3.act_dtype == "bf16"


def test_bench_prefix_env_gating(monkeypatch):
    """BENCH_PREFIX is opt-in (the headline workload is i.i.d. random
    prompts where a prefix cache only adds overhead) and its block/nreq
    knobs flow through."""
    monkeypatch.delenv("BENCH_PREFIX", raising=False)
    monkeypatch.delenv("BENCH_PREFIX_BLOCK", raising=False)
    monkeypatch.delenv("BENCH_PREFIX_NREQ", raising=False)
    b = _load_bench()
    assert b.PREFIX is False
    monkeypatch.setenv("BENCH_PREFIX", "1")
    monkeypatch.setenv("BENCH_PREFIX_BLOCK", "32")
    monkeypatch.setenv("BENCH_PREFIX_NREQ", "8")
    b2 = _load_bench()
    assert b2.PREFIX is True
    assert b2.PREFIX_BLOCK == 32 and b2.PREFIX_NREQ == 8


def test_phase_score_counts_prefix_phase():
    """A checkpoint that captured the prefix phase must outrank one that
    didn't — and a final record still beats any partial."""
    b = _load_bench()
    base = {"metric": "m", "value": 1.0, "detail": {"partial": True}}
    withp = {"metric": "m", "value": 1.0,
             "detail": {"partial": True, "prefix": {"hit_rate": 0.96}}}
    final = {"metric": "m", "value": 1.0, "detail": {}}
    assert b._phase_score(withp) > b._phase_score(base)
    assert b._phase_score(final) > b._phase_score(withp)


def test_phase_score_retry_never_clobbers_richer_partial():
    """The exact review scenario: attempt 1 died after 3 phases, attempt
    2 died after 1 — the supervisor must keep attempt 1's line."""
    b = _load_bench()
    rich = {"metric": "m", "value": 1.0,
            "detail": {"partial": True, "slo_req_s": 50.0,
                       "bench_1b": {"req_per_s": 100.0}}}
    poor = {"metric": "m", "value": 1.2, "detail": {"partial": True}}
    best = None
    for line in (rich, poor):
        if b._phase_score(line) > b._phase_score(best):
            best = line
    assert best is rich
