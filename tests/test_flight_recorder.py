"""Engine flight recorder + tracing parity tests.

The load-bearing claims, in test form:
 * the ring is bounded and lossy-oldest: wrap keeps the most recent
   `size` records, counts the drops, and snapshots oldest-first with an
   epoch pairing;
 * arming is env-gated and fail-safe (`FLIGHT_RECORDER=1`, size knob);
 * a live engine run leaves a readable timeline — submit/admit/boundary/
   terminal per request — that `tools/trace_view.py` converts into valid
   Perfetto trace_event JSON;
 * SLO accounting: deadline-carrying requests land in the margin
   histogram and met/missed counters; goodput is their ratio;
 * observability is free of Heisenberg effects: greedy output is
   bit-identical with tracing + recorder on vs off — dense, paged, AND
   chunked-prefill engines;
 * exactly-one-terminal-span parity: a chaos soak with tracing on emits
   exactly one `engine.request` span per accepted request, whatever the
   outcome (completed / deadline / cancelled / errored).
"""

import json
import random
import threading

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import flight_recorder
from seldon_tpu.servers.chaos import ChaosConfig
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)

PAGED = dict(paged_kv=True, kv_block=16, kv_pool_blocks=9,
             prompt_buckets=(16, 32))
CHUNKED = dict(decode_chunk=4, min_chunk=2, adaptive_chunk=False)


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_wrap_keeps_newest_and_counts_drops():
    rec = flight_recorder.FlightRecorder(size=4)
    for i in range(7):
        rec.record("submit", rid=i, detail={"i": i})
    assert len(rec) == 4
    snap = rec.snapshot()
    assert snap["total_recorded"] == 7
    assert snap["dropped"] == 3
    # Oldest-first, and only the newest `size` survive the wrap.
    assert [r["rid"] for r in snap["records"]] == [3, 4, 5, 6]
    ts = [r["ts"] for r in snap["records"]]
    assert ts == sorted(ts)
    # Epoch pairing present so consumers can map to wall-clock.
    assert snap["epoch_wall"] > 0 and snap["epoch_mono"] > 0


def test_snapshot_is_stable_under_concurrent_append():
    """snapshot() while writers append: every returned record is intact
    (the ring stores immutable tuples; a torn window only affects WHICH
    records appear, never their fields)."""
    rec = flight_recorder.FlightRecorder(size=64)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            rec.record("boundary", rid=-1, detail={"i": i})
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(50):
            snap = rec.snapshot()
            for r in snap["records"]:
                assert r["kind"] == "boundary"
                assert isinstance(r["detail"]["i"], int)
    finally:
        stop.set()
        t.join(timeout=10)


def test_from_env_gating(monkeypatch):
    monkeypatch.delenv("FLIGHT_RECORDER", raising=False)
    assert flight_recorder.from_env() is None
    monkeypatch.setenv("FLIGHT_RECORDER", "0")
    assert flight_recorder.from_env() is None
    monkeypatch.setenv("FLIGHT_RECORDER", "1")
    rec = flight_recorder.from_env()
    assert rec is not None and rec.size == 4096
    monkeypatch.setenv("FLIGHT_RECORDER_SIZE", "128")
    assert flight_recorder.from_env().size == 128


# ---------------------------------------------------------------------------
# trace_view conversion
# ---------------------------------------------------------------------------


def test_trace_view_converts_synthetic_snapshot():
    from tools import trace_view

    rec = flight_recorder.FlightRecorder(size=64)
    rec.record("submit", 1, {"prompt_tokens": 8, "deadline_ms": 0})
    rec.record("trie-miss", 1, {"matched_tokens": 0, "prompt_tokens": 8})
    rec.record("admit", 1, {"queue_wait_ms": 1.5})
    rec.record("boundary", -1, {"admits": 1, "chunk": 4, "active": 1})
    rec.record("terminal", 1, {"outcome": "ok", "n_generated": 4})
    rec.record("submit", 2, {"prompt_tokens": 8, "deadline_ms": 30})
    rec.record("terminal", 2, {"outcome": "deadline", "n_generated": 0})
    rec.record("submit", 3, {"prompt_tokens": 8, "deadline_ms": 0})

    out = json.loads(json.dumps(trace_view.convert(rec.snapshot())))
    events = out["traceEvents"]
    assert events, "conversion produced no events"
    assert {e["ph"] for e in events} <= {"X", "i", "C", "M"}
    names = [e["name"] for e in events]
    # Request 1: queued + running slices; request 2 never admitted.
    assert "queued" in names
    assert "running [ok]" in names
    assert "unadmitted [deadline]" in names
    # Request 3 is still open at the window end.
    assert "in-flight (window end)" in names
    # Boundary renders as instant + occupancy counter.
    assert "boundary" in names and "active_slots" in names
    # Durations are non-negative, timestamps in wall-clock microseconds.
    for e in events:
        if e["ph"] == "X":
            assert e["dur"] > 0
        if "ts" in e:
            assert e["ts"] > 0


def test_trace_view_rejects_non_snapshot(tmp_path, capsys):
    from tools import trace_view

    bad = tmp_path / "not_a_snapshot.json"
    bad.write_text(json.dumps({"foo": 1}))
    assert trace_view.main([str(bad)]) == 2


# ---------------------------------------------------------------------------
# Live engine timeline + SLO accounting
# ---------------------------------------------------------------------------


def test_engine_timeline_and_slo_accounting(monkeypatch):
    monkeypatch.setenv("FLIGHT_RECORDER", "1")
    eng = _engine()
    try:
        assert eng.debug_timeline() is not None
        # One plain request, one with a generous deadline (met), one with
        # an unmeetable deadline (the first dispatch compiles, so 1 ms is
        # always expired by the first boundary check).
        eng.generate_blocking(PROMPT, GREEDY)
        eng.generate_blocking(
            PROMPT, SamplingParams(temperature=0.0, max_new_tokens=4,
                                   deadline_ms=60_000))
        q = eng.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=4, deadline_ms=1))
        saw_deadline = False
        while True:
            item = q.get(timeout=120)
            if item is None:
                break
            if item.get("kind") == "deadline":
                saw_deadline = True
        assert saw_deadline

        snap = eng.debug_timeline()
        kinds = {r["kind"] for r in snap["records"]}
        assert {"submit", "admit", "boundary", "terminal"} <= kinds, kinds
        by_kind = {}
        for r in snap["records"]:
            by_kind.setdefault(r["kind"], []).append(r)
        assert len(by_kind["submit"]) == 3
        assert len(by_kind["terminal"]) == 3
        outcomes = {r["detail"]["outcome"] for r in by_kind["terminal"]}
        assert "ok" in outcomes and "deadline" in outcomes

        st = eng.stats.snapshot()
        assert st["deadline_met_total"] == 1
        assert st["deadline_missed_total"] == 1
        assert st["completed_no_deadline_total"] == 1
        assert st["goodput"] == 0.5
        # Histogram mass equals the deadline-carrying population, with
        # at least one negative-margin bucket filled by the miss.
        edges = st["deadline_margin_edges_ms"]
        counts = st["deadline_margin_counts"]
        assert len(counts) == len(edges) + 1
        assert sum(counts) == 2
        neg_mass = sum(c for e, c in zip(edges, counts) if e <= 0)
        assert neg_mass >= 1

        # The live snapshot converts cleanly.
        from tools import trace_view

        out = json.loads(json.dumps(trace_view.convert(snap)))
        assert out["traceEvents"]
        assert {e["ph"] for e in out["traceEvents"]} <= {"X", "i", "C", "M"}
    finally:
        eng.stop()


def test_recorder_disabled_by_default():
    eng = _engine(start=False)
    assert eng.debug_timeline() is None


# ---------------------------------------------------------------------------
# Heisenberg check: observability must not change outputs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "ekw",
    [dict(), PAGED, CHUNKED],
    ids=["dense", "paged", "chunked"],
)
def test_greedy_output_bit_identical_with_observability_on(
    ekw, tmp_path, monkeypatch
):
    prompts = [PROMPT, [7, 8, 9], list(range(40, 60))]

    def run():
        eng = _engine(**dict(ekw))
        try:
            return [
                eng.generate_blocking(p, GREEDY)["token_ids"]
                for p in prompts
            ]
        finally:
            eng.stop()

    monkeypatch.delenv("TRACING", raising=False)
    monkeypatch.delenv("FLIGHT_RECORDER", raising=False)
    want = run()

    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(tmp_path / "spans.jsonl"))
    monkeypatch.setenv("FLIGHT_RECORDER", "1")
    got = run()
    assert got == want, "tracing/recorder changed greedy output"
    # The traced run actually traced (the parity is not vacuous).
    spans = (tmp_path / "spans.jsonl").read_text().splitlines()
    assert len(spans) >= len(prompts)


# ---------------------------------------------------------------------------
# Exactly-one-terminal-span parity under chaos
# ---------------------------------------------------------------------------


def test_chaos_soak_exactly_one_terminal_span(tmp_path, monkeypatch):
    """60 mixed requests under seeded chaos + deadlines + cancels, tracing
    on: every ACCEPTED request emits exactly one engine.request span, its
    outcome attribute matching the waiter-observed outcome bucket."""
    trace_file = tmp_path / "spans.jsonl"
    monkeypatch.setenv("TRACING", "1")
    monkeypatch.setenv("TRACING_FILE", str(trace_file))
    monkeypatch.setenv("FLIGHT_RECORDER", "1")

    n = 60
    eng = _engine(
        max_slots=8,
        max_queue=4 * n,
        chaos=ChaosConfig(seed=0, dispatch_fail=0.02, slow_boundary=0.05,
                          slow_ms=2.0, disconnect=0.01),
    )
    rng = random.Random(0)
    outcomes = {"completed": 0, "failed": 0}
    lock = threading.Lock()
    threads = []
    accepted = 0

    def consume(q, want_cancel):
        err, sent = None, False
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            if "error" in item:
                err = item
                continue
            if want_cancel and not sent:
                sent = True
                eng.cancel(q.rid)
        with lock:
            outcomes["completed" if err is None else "failed"] += 1

    try:
        for i in range(n):
            plen = rng.choice((5, 8, 13, 21))
            prompt = [2 + (i + j) % 200 for j in range(plen)]
            dl = rng.choice((30, 80)) if rng.random() < 0.15 else 0
            sp = SamplingParams(temperature=0.0,
                                max_new_tokens=rng.choice((4, 8)),
                                deadline_ms=dl)
            try:
                q = eng.submit(prompt, sp)
            except RuntimeError:
                continue
            accepted += 1
            t = threading.Thread(target=consume,
                                 args=(q, rng.random() < 0.15), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "hung waiter"
        assert eng.drain(timeout=120) is True
    finally:
        eng.stop()

    spans = [json.loads(l) for l in trace_file.read_text().splitlines()]
    roots = [s for s in spans if s["name"] == "engine.request"]
    assert len(roots) == accepted, (
        f"{len(roots)} engine.request spans for {accepted} accepted "
        f"requests (outcomes: {outcomes})"
    )
    # One span per rid — no double emission through _fail_all/cancel/
    # deadline races.
    rids = [s["attributes"]["rid"] for s in roots]
    assert len(set(rids)) == len(rids)
    ok_spans = sum(1 for s in roots if s["attributes"]["outcome"] == "ok")
    assert ok_spans == outcomes["completed"], (ok_spans, outcomes)
    # Every non-completed span carries an ERROR status with its kind.
    for s in roots:
        if s["attributes"]["outcome"] != "ok":
            assert s["status"].startswith("ERROR"), s
