"""Prefix-cache KV reuse: trie mechanics + engine bit-exactness.

The load-bearing claims, in test form:
 * warm admissions (prefix hit) produce BIT-IDENTICAL greedy tokens to a
   cold engine — reused KV + suffix-only prefill is exact, not approximate
   (RoPE is position-absolute; the sampling key folds the FULL prompt len);
 * prefix_cache=False leaves behavior untouched (no trie, zero counters);
 * a LIVE slot's prefix path is pinned and can never be evicted, while
   unpinned paths LRU-evict leaf-first under the byte budget;
 * the int8 (quantized) KV cache variant reuses scales alongside k/v and
   stays token-identical too.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from seldon_tpu.servers.prefix_cache import PrefixIndex


# ---------------------------------------------------------------------------
# PrefixIndex (host-side trie) unit tests — no model involved
# ---------------------------------------------------------------------------


def _get_span(s, e, L=2, H=2, D=4):
    return {
        "k": jnp.full((L, H, e - s, D), float(s), jnp.float32),
        "v": jnp.full((L, H, e - s, D), float(s + 100), jnp.float32),
    }


def test_trie_lookup_empty():
    idx = PrefixIndex(block=4)
    h = idx.lookup([1, 2, 3, 4, 5])
    assert h.match_len == 0 and h.nodes == []


def test_trie_insert_then_lookup_block_aligned():
    idx = PrefixIndex(block=4)
    toks = list(range(10))  # 2 full blocks + ragged tail of 2
    idx.insert(toks, _get_span)
    assert idx.n_nodes == 2  # the tail never enters the trie
    h = idx.lookup(toks)
    assert h.match_len == 8 and len(h.nodes) == 2
    # max_len caps the match (engine uses plen-1 so the last prompt
    # token is always prefilled and produces the first logit).
    h2 = idx.lookup(toks, max_len=7)
    assert h2.match_len == 4
    # Diverging block: shares block 0 only.
    h3 = idx.lookup([0, 1, 2, 3, 99, 98, 97, 96])
    assert h3.match_len == 4
    for h_ in (h, h2, h3):
        idx.release(h_)


def test_trie_gather_concat_and_pad():
    idx = PrefixIndex(block=4)
    idx.insert(list(range(8)), _get_span)
    h = idx.lookup(list(range(8)))
    out = idx.gather(h, pad_to=12)
    assert out["k"].shape == (2, 2, 12, 4)
    # Block 0 tokens carry value 0.0, block 1 tokens 4.0, pad zeros.
    assert float(out["k"][0, 0, 0, 0]) == 0.0
    assert float(out["k"][0, 0, 4, 0]) == 4.0
    assert float(out["k"][0, 0, 11, 0]) == 0.0
    assert float(out["v"][0, 0, 5, 0]) == 104.0
    idx.release(h)


def test_trie_pinned_path_survives_eviction():
    idx = PrefixIndex(block=4, byte_budget=0)  # everything over budget
    toks_a = list(range(8))
    h = idx.lookup(toks_a)  # empty match, but a handle to pin into
    evicted = idx.insert(toks_a, _get_span, handle=h)
    # Own path pinned by the handle -> nothing evictable.
    assert evicted == 0 and idx.n_nodes == 2
    # A second, unpinned insert evicts ITS OWN path (budget 0) but never
    # the pinned one.
    evicted2 = idx.insert([50, 51, 52, 53], _get_span)
    assert evicted2 >= 1
    h_mid = idx.lookup(toks_a)  # pinned path intact
    assert h_mid.match_len == 8
    idx.release(h_mid)
    idx.release(h)
    # Released -> next insert can now reclaim the old path too.
    idx.insert([60, 61, 62, 63], _get_span)
    assert idx.lookup(toks_a).match_len == 0
    assert idx.evictions >= 3


def test_trie_eviction_is_leaf_first():
    """Paths must stay rooted: evicting an interior node would let a
    later lookup match through a hole."""
    idx = PrefixIndex(block=2, byte_budget=1 << 60)
    idx.insert([1, 2, 3, 4, 5, 6], _get_span)  # chain of 3 nodes
    idx.byte_budget = idx.bytes - 1  # force exactly one eviction
    idx.insert([9, 9], _get_span)
    # The deepest (leaf) node of the LRU path went first; the root-side
    # blocks of the old chain still match.
    h = idx.lookup([1, 2, 3, 4, 5, 6])
    assert 0 < h.match_len < 6
    assert h.match_len % 2 == 0
    idx.release(h)


def test_trie_release_idempotent():
    idx = PrefixIndex(block=2)
    idx.insert([1, 2, 3, 4], _get_span)
    h = idx.lookup([1, 2, 3, 4])
    assert h.nodes[0].refs == 1
    idx.release(h)
    idx.release(h)  # double release must not underflow refcounts
    assert h.nodes[0].refs == 0


def test_trie_shared_prefix_dedups_nodes():
    idx = PrefixIndex(block=4)
    idx.insert(list(range(8)), _get_span)
    idx.insert([0, 1, 2, 3, 70, 71, 72, 73], _get_span)
    assert idx.n_nodes == 3  # block 0 shared structurally


# ---------------------------------------------------------------------------
# Engine integration: bit-exactness, counters, disable path
# ---------------------------------------------------------------------------

PROMPT = list(range(2, 18))  # 16 tokens; block=8 -> 1 reusable block
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _engine(cfg, **ekw):
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(
        params,
        cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(8, 16),
                     **ekw),
    )
    eng.start()
    return eng


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_warm_admission_bit_identical_to_cold(kv_dtype):
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    cold = _engine(cfg)
    try:
        want = cold.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        cold.stop()

    eng = _engine(cfg, prefix_cache=True, prefix_block=8)
    try:
        first = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        warm = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    # Cold admission through the prefix-enabled engine is unchanged, and
    # the warm (KV-reusing) admission reproduces it bit-for-bit.
    assert first == want
    assert warm == want
    assert snap["prefix_hits"] == 1
    assert snap["prefix_tokens_saved"] == 8  # one 8-token block reused


def test_shared_prefix_across_different_prompts():
    """Two prompts sharing a 8-token system-prompt block: the second
    reuses the first's KV yet matches its own cold tokens."""
    cfg = get_config("tiny")
    other = PROMPT[:8] + [90, 91, 92, 93, 94, 95, 96, 97]
    cold = _engine(cfg)
    try:
        want = cold.generate_blocking(other, GREEDY)["token_ids"]
    finally:
        cold.stop()

    eng = _engine(cfg, prefix_cache=True, prefix_block=8)
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        got = eng.generate_blocking(other, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got == want
    assert snap["prefix_hits"] == 1
    assert snap["prefix_tokens_saved"] == 8


def test_engine_eviction_under_tiny_budget():
    """A 1-byte budget forces eviction of every released path while the
    in-flight request's own (pinned) path survives — outputs stay
    correct and the eviction counter moves."""
    cfg = get_config("tiny")
    cold = _engine(cfg)
    try:
        want_a = cold.generate_blocking(PROMPT, GREEDY)["token_ids"]
        want_b = cold.generate_blocking(
            [40 + t for t in PROMPT], GREEDY)["token_ids"]
    finally:
        cold.stop()

    eng = _engine(cfg, prefix_cache=True, prefix_block=8,
                  prefix_cache_bytes=1)
    try:
        a = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        b = eng.generate_blocking(
            [40 + t for t in PROMPT], GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert a == want_a and b == want_b
    assert snap["prefix_evictions"] >= 1
    assert snap["prefix_hits"] == 0  # everything evicted between requests


def test_prefix_disabled_leaves_engine_untouched():
    cfg = get_config("tiny")
    eng = _engine(cfg)  # default: prefix_cache=False
    try:
        assert eng._prefix is None
        assert eng._jit_admit_prefix is None
        eng.generate_blocking(PROMPT, GREEDY)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert snap["prefix_hits"] == 0
    assert snap["prefix_tokens_saved"] == 0
    assert snap["prefix_evictions"] == 0
