"""Storage credential injection (operator/credentials.py).

Mirrors the reference's credential test semantics
(operator/controllers/resources/credentials/s3/s3_secret_test.go:1-187,
service_account_credentials.go:64-113): S3 secrets become secretKeyRef
envs + annotation-driven endpoint envs; GCS secrets become a mounted
volume + GOOGLE_APPLICATION_CREDENTIALS."""

import base64

from seldon_tpu.operator import types as T
from seldon_tpu.operator.credentials import (
    CONFIGMAP_NAME,
    CredentialBuilder,
    CredentialConfig,
    build_s3_envs,
)
from seldon_tpu.operator.reconciler import (
    InMemoryStore,
    build_predictor_manifests,
)

KF = "serving.kubeflow.org"
SELDON = "machinelearning.seldon.io"


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _secret(name, data, annotations=None):
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": annotations or {}},
        "data": {k: _b64(v) for k, v in data.items()},
    }


def _sa(name, secret_names):
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {"name": name, "namespace": "default"},
        "secrets": [{"name": n} for n in secret_names],
    }


def _env_map(envs):
    return {e["name"]: e for e in envs}


# --- build_s3_envs scenarios (s3_secret_test.go table) ----------------------


def test_s3_secret_envs_endpoint_annotation():
    secret = _secret(
        "s3-secret", {"awsAccessKeyID": "k", "awsSecretAccessKey": "s"},
        annotations={KF + "/s3-endpoint": "s3.aws.com"},
    )
    envs = _env_map(build_s3_envs(secret, CredentialConfig().s3))
    assert envs["AWS_ACCESS_KEY_ID"]["valueFrom"]["secretKeyRef"] == {
        "name": "s3-secret", "key": "awsAccessKeyID"
    }
    assert envs["AWS_SECRET_ACCESS_KEY"]["valueFrom"]["secretKeyRef"] == {
        "name": "s3-secret", "key": "awsSecretAccessKey"
    }
    assert envs["S3_ENDPOINT"]["value"] == "s3.aws.com"
    assert envs["AWS_ENDPOINT_URL"]["value"] == "https://s3.aws.com"
    assert "S3_USE_HTTPS" not in envs


def test_s3_secret_https_and_ssl_override():
    secret = _secret(
        "s3-secret", {},
        annotations={
            KF + "/s3-endpoint": "s3.aws.com",
            KF + "/s3-usehttps": "0",
            KF + "/s3-verifyssl": "0",
        },
    )
    envs = _env_map(build_s3_envs(secret, CredentialConfig().s3))
    assert envs["S3_USE_HTTPS"]["value"] == "0"
    assert envs["AWS_ENDPOINT_URL"]["value"] == "http://s3.aws.com"
    assert envs["S3_VERIFY_SSL"]["value"] == "0"


def test_s3_seldon_group_wins_over_kubeflow():
    secret = _secret(
        "s3-secret", {},
        annotations={
            SELDON + "/s3-endpoint": "minio.svc:9000",
            KF + "/s3-endpoint": "other",
            SELDON + "/s3-region": "eu-west-1",
        },
    )
    envs = _env_map(build_s3_envs(secret, CredentialConfig().s3))
    assert envs["S3_ENDPOINT"]["value"] == "minio.svc:9000"
    assert envs["AWS_REGION"]["value"] == "eu-west-1"


def test_s3_configmap_endpoint_fallback_and_custom_key_names():
    cfg = CredentialConfig.from_configmap({
        "data": {
            "credentials": (
                '{"s3": {"s3AccessKeyIDName": "AKID", '
                '"s3SecretAccessKeyName": "SAK", '
                '"s3Endpoint": "minio:9000", "s3UseHttps": "0"}}'
            )
        }
    })
    secret = _secret("s3-secret", {})
    envs = _env_map(build_s3_envs(secret, cfg.s3))
    assert envs["AWS_ACCESS_KEY_ID"]["valueFrom"]["secretKeyRef"]["key"] == "AKID"
    assert envs["AWS_SECRET_ACCESS_KEY"]["valueFrom"]["secretKeyRef"]["key"] == "SAK"
    assert envs["AWS_ENDPOINT_URL"]["value"] == "http://minio:9000"
    assert envs["S3_USE_HTTPS"]["value"] == "0"


# --- ServiceAccount walk + injection into the initContainer -----------------


def _deploy_with_sa(store, sa_name="model-sa"):
    sdep = T.SeldonDeployment.from_dict({
        "metadata": {"name": "dep", "namespace": "default"},
        "spec": {
            "predictors": [{
                "name": "p",
                "serviceAccountName": sa_name,
                "graph": {
                    "name": "clf",
                    "implementation": "SKLEARN_SERVER",
                    "modelUri": "s3://bucket/model",
                },
            }]
        },
    })
    creds = CredentialBuilder.from_store(store)
    manifests = build_predictor_manifests(sdep, sdep.predictors[0], creds)
    dep = next(m for m in manifests if m["kind"] == "Deployment")
    pod = dep["spec"]["template"]["spec"]
    return pod


def test_s3_secret_injected_into_initcontainer():
    store = InMemoryStore()
    store.apply(_secret(
        "s3-secret", {"awsAccessKeyID": "k", "awsSecretAccessKey": "s"},
        annotations={SELDON + "/s3-endpoint": "minio:9000"},
    ))
    store.apply(_sa("model-sa", ["s3-secret"]))
    pod = _deploy_with_sa(store)
    init = pod["initContainers"][0]
    envs = _env_map(init["env"])
    assert envs["AWS_ACCESS_KEY_ID"]["valueFrom"]["secretKeyRef"]["name"] == "s3-secret"
    assert envs["S3_ENDPOINT"]["value"] == "minio:9000"
    # Secret VALUES never appear in the manifest (only secretKeyRef).
    import json as _json

    assert "awsAccessKeyID" not in _json.dumps(init).replace(
        '"key": "awsAccessKeyID"', "")


def test_gcs_secret_injected_as_volume():
    store = InMemoryStore()
    store.apply(_secret(
        "gcs-secret", {"gcloud-application-credentials.json": "{}"}
    ))
    store.apply(_sa("model-sa", ["gcs-secret"]))
    pod = _deploy_with_sa(store)
    init = pod["initContainers"][0]
    envs = _env_map(init["env"])
    assert envs["GOOGLE_APPLICATION_CREDENTIALS"]["value"] == (
        "/var/secrets/gcloud-application-credentials.json"
    )
    mounts = {m["name"]: m for m in init["volumeMounts"]}
    assert mounts["user-gcp-sa"]["mountPath"] == "/var/secrets/"
    assert mounts["user-gcp-sa"]["readOnly"] is True
    vols = {v["name"]: v for v in pod["volumes"]}
    assert vols["user-gcp-sa"]["secret"]["secretName"] == "gcs-secret"


def test_missing_sa_or_secret_is_not_fatal():
    store = InMemoryStore()
    pod = _deploy_with_sa(store, sa_name="nope")
    init = pod["initContainers"][0]
    assert not init.get("env")
    # SA exists but its secret doesn't: skipped, still builds.
    store.apply(_sa("model-sa", ["ghost-secret"]))
    pod = _deploy_with_sa(store)
    assert not pod["initContainers"][0].get("env")


def test_first_match_wins_no_duplicate_mounts():
    """Two GCS secrets + two S3 secrets on one SA: only the FIRST of each
    family is injected (duplicate env names / identical mountPaths would
    fail apiserver validation)."""
    store = InMemoryStore()
    for n in ("gcs-a", "gcs-b"):
        store.apply(_secret(n, {"gcloud-application-credentials.json": "{}"}))
    for n in ("s3-a", "s3-b"):
        store.apply(_secret(n, {"awsAccessKeyID": "k",
                                "awsSecretAccessKey": "s"}))
    store.apply(_sa("model-sa", ["gcs-a", "gcs-b", "s3-a", "s3-b"]))
    pod = _deploy_with_sa(store)
    init = pod["initContainers"][0]
    names = [e["name"] for e in init["env"]]
    assert names.count("GOOGLE_APPLICATION_CREDENTIALS") == 1
    assert names.count("AWS_ACCESS_KEY_ID") == 1
    assert [m["name"] for m in init["volumeMounts"]].count("user-gcp-sa") == 1
    ref = next(e for e in init["env"] if e["name"] == "AWS_ACCESS_KEY_ID")
    assert ref["valueFrom"]["secretKeyRef"]["name"] == "s3-a"


def test_non_matching_secret_skipped():
    store = InMemoryStore()
    store.apply(_secret("token-secret", {"token": "abc"}))
    store.apply(_sa("model-sa", ["token-secret"]))
    pod = _deploy_with_sa(store)
    assert not pod["initContainers"][0].get("env")


def test_pod_runs_as_predictor_service_account():
    """The pod itself must run AS the CR's serviceAccountName, so
    identity-based (secretless, e.g. Workload Identity) bucket access
    works even when the SA carries no key secrets."""
    store = InMemoryStore()
    pod = _deploy_with_sa(store, sa_name="model-sa")
    assert pod["serviceAccountName"] == "model-sa"


def test_nameless_secret_ref_skipped():
    """ObjectReference.name is optional: a SA with secrets: [{}] must not
    crash the reconcile (a nameless get would hit the collection URL)."""
    store = InMemoryStore()
    sa = _sa("model-sa", [])
    sa["secrets"] = [{}]
    store.apply(sa)
    pod = _deploy_with_sa(store)
    assert not pod["initContainers"][0].get("env")


def test_configmap_discovery_and_custom_gcs_filename():
    store = InMemoryStore()
    store.apply({
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": CONFIGMAP_NAME, "namespace": "seldon-system"},
        "data": {"credentials": '{"gcs": {"gcsCredentialFileName": "sa.json"}}'},
    })
    store.apply(_secret("gcs-secret", {"sa.json": "{}"}))
    store.apply(_sa("model-sa", ["gcs-secret"]))
    pod = _deploy_with_sa(store)
    envs = _env_map(pod["initContainers"][0]["env"])
    assert envs["GOOGLE_APPLICATION_CREDENTIALS"]["value"] == (
        "/var/secrets/sa.json"
    )


# --- storage.py consumes the injected env -----------------------------------


def test_s3_client_kwargs_from_env():
    from seldon_tpu.servers.storage import _s3_client_kwargs

    assert _s3_client_kwargs({}) == {}
    assert _s3_client_kwargs({"AWS_ENDPOINT_URL": "https://x"}) == {
        "endpoint_url": "https://x"
    }
    kw = _s3_client_kwargs({
        "S3_ENDPOINT": "minio:9000", "S3_USE_HTTPS": "0",
        "S3_VERIFY_SSL": "0", "AWS_REGION": "us-east-1",
    })
    assert kw == {
        "endpoint_url": "http://minio:9000",
        "verify": False,
        "region_name": "us-east-1",
    }
    # AWS_ENDPOINT_URL wins over S3_ENDPOINT composition.
    kw = _s3_client_kwargs({
        "AWS_ENDPOINT_URL": "https://real", "S3_ENDPOINT": "other",
    })
    assert kw["endpoint_url"] == "https://real"
