"""Regression tests for the first code-review pass findings."""

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime import seldon_methods
from seldon_tpu.runtime.metrics_server import ServerMetrics


def _metric(key, mtype, value, tags=None):
    m = pb.Metric(key=key, type=mtype, value=value)
    for k, v in (tags or {}).items():
        m.tags[k] = v
    return m


class TestCustomMetricCollisions:
    def test_same_key_different_tags_does_not_raise(self):
        sm = ServerMetrics()
        sm.record_custom([_metric("mymetric", pb.Metric.COUNTER, 1.0)])
        # Previously raised 'Duplicated timeseries'; now dropped with a log.
        sm.record_custom([_metric("mymetric", pb.Metric.COUNTER, 1.0, {"a": "b"})])
        sm.record_custom([_metric("mymetric", pb.Metric.GAUGE, 2.0)])
        body, _ = sm.export()
        assert b"mymetric_total 1.0" in body

    def test_observe_never_raises(self):
        sm = ServerMetrics()
        msg = pb.SeldonMessage()
        msg.meta.metrics.add().key = "seldon_api_executor_server_requests"  # collides
        sm.observe("predict", "rest", 0.01, msg)  # must not raise

    def test_reward_counters(self):
        sm = ServerMetrics()
        sm.record_reward("router", 0.5)
        sm.record_reward("router", -0.25)
        body, _ = sm.export()
        assert b'seldon_api_model_feedback_total{unit="router"} 2.0' in body
        assert b'seldon_api_model_feedback_reward_total{unit="router"} 0.5' in body
        assert b'reward_negative_total{unit="router"} 0.25' in body


class TestRawHookErrors:
    def test_attribute_error_in_raw_hook_surfaces(self):
        calls = []

        class Buggy:
            def predict_raw(self, msg):
                return self.no_such_attr  # genuine bug, must surface

            def predict(self, X, names, meta=None):
                calls.append(1)
                return X

        req = payloads.build_message(np.ones((1, 1)))
        with pytest.raises(AttributeError):
            seldon_methods.predict(Buggy(), req)
        assert calls == []  # high-level path must NOT run as a fallback


class TestNonNumericOutputs:
    def test_string_labels_fall_back_to_ndarray(self):
        class Labeler:
            def predict(self, X, names, meta=None):
                return np.array(["cat", "dog"])

        req = payloads.build_message(np.ones((2, 4)), kind="dense")
        resp = seldon_methods.predict(Labeler(), req)
        assert payloads.data_kind(resp) == "ndarray"
        assert list(payloads.get_data_from_message(resp)) == ["cat", "dog"]

    def test_dict_output_becomes_jsondata(self):
        class Dicty:
            def predict(self, X, names, meta=None):
                return {"label": "cat", "score": 0.9}

        req = payloads.build_message(np.ones((1, 1)), kind="dense")
        resp = seldon_methods.predict(Dicty(), req)
        out = payloads.get_data_from_message(resp)
        assert out == {"label": "cat", "score": 0.9}


class TestInPlaceMutation:
    def test_dense_payload_is_writable(self):
        class Mutator:
            def predict(self, X, names, meta=None):
                X += 1  # in-place, sklearn-scaler style
                return X

        req = payloads.build_message(np.zeros((2, 2), dtype=np.float32), kind="dense")
        resp = seldon_methods.predict(Mutator(), req)
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.ones((2, 2))
        )

    def test_zero_copy_path_available(self):
        dense = payloads.array_to_dense(np.arange(4.0))
        ro = payloads.dense_to_array(dense, writable=False)
        assert not ro.flags.writeable


class TestGenerateStream:
    def test_stream_hook(self):
        class Streamer:
            def generate_stream(self, req):
                for i in range(3):
                    yield {"text": f"t{i}", "token_ids": [i]}

        req = pb.GenerateRequest(prompt="x")
        chunks = list(seldon_methods.generate_stream(Streamer(), req))
        assert [c.text for c in chunks] == ["t0", "t1", "t2"]

    def test_grpc_stream_falls_back_to_unary(self):
        import grpc as grpc_mod

        from seldon_tpu.proto import prediction_grpc
        from seldon_tpu.runtime.wrapper import build_grpc_server

        class UnaryOnly:
            def generate(self, req):
                return {"text": "single", "token_ids": [7]}

        server = build_grpc_server(UnaryOnly())
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            ch = grpc_mod.insecure_channel(f"127.0.0.1:{port}")
            stub = prediction_grpc.TextGenStub(ch)
            chunks = list(stub.GenerateStream(pb.GenerateRequest(prompt="x")))
            assert len(chunks) == 1 and chunks[0].text == "single"
        finally:
            server.stop(0)


class TestRound2ReviewFindings:
    """Round-2 review: parity-server output semantics + batcher splits."""

    def test_sklearn_linear_regressor_predict_returns_values(self, tmp_path):
        from seldon_tpu.servers.sklearnserver import (
            SKLearnServer, export_linear_model,
        )

        export_linear_model(str(tmp_path), np.array([[2.0, 1.0]]),
                            np.array([0.5]), kind="linear")
        srv = SKLearnServer(model_uri=str(tmp_path), method="predict")
        srv.load()
        out = srv.predict(np.array([[1.0, 1.0], [2.0, 0.0]], np.float32), [])
        # Regression values (shape (n,)), NOT argmax indices.
        np.testing.assert_allclose(out, [3.5, 4.5], rtol=1e-6)

    def test_xgboost_reg_logistic_base_score_gate(self, tmp_path):
        import json as _json

        from seldon_tpu.servers.xgboostserver import XGBoostServer

        tree = {"nodeid": 0, "leaf": 1.5}
        (tmp_path / "model.json").write_text(_json.dumps(
            {"trees": [tree], "objective": "reg:logistic", "base_score": 0.5}
        ))
        srv = XGBoostServer(model_uri=str(tmp_path))
        srv.load()
        out = srv.predict(np.array([[0.0]], np.float32), [])
        # logit(0.5)=0 margin; sigmoid(1.5) — the conversion gate must match
        # the sigmoid gate ('logistic', not 'binary').
        np.testing.assert_allclose(out, [1 / (1 + np.exp(-1.5))], rtol=1e-6)

    def test_batcher_string_output_split(self):
        """Co-batched requests to a unit returning string labels must split
        via the ndarray fallback, not crash on dense re-encode."""
        import asyncio

        from seldon_tpu.orchestrator.batcher import MicroBatcher
        from seldon_tpu.orchestrator.spec import PredictiveUnit

        class FakeClient:
            async def call(self, unit, method, msg):
                arr = payloads.get_data_from_message(msg)
                labels = np.array([["x"] if r[0] < 0 else ["y"] for r in arr])
                resp = payloads.build_message(labels, kind="ndarray")
                resp.meta.CopyFrom(msg.meta)
                return resp

        unit = PredictiveUnit(name="m", type="MODEL")
        b = MicroBatcher(max_batch_size=64, window_ms=5.0)

        async def run():
            m1 = payloads.build_message(np.array([[-1.0]], np.float32))
            m2 = payloads.build_message(np.array([[1.0]], np.float32))
            return await asyncio.gather(
                b.call(unit, m1, FakeClient()), b.call(unit, m2, FakeClient())
            )

        r1, r2 = asyncio.run(run())
        assert payloads.get_data_from_message(r1).tolist() == [["x"]]
        assert payloads.get_data_from_message(r2).tolist() == [["y"]]
        assert b.stats["fused_calls"] == 1

    def test_batcher_nested_batch_index_goes_direct(self):
        import asyncio

        from seldon_tpu.orchestrator.batcher import MicroBatcher
        from seldon_tpu.orchestrator.spec import PredictiveUnit

        calls = []

        class FakeClient:
            async def call(self, unit, method, msg):
                calls.append(msg)
                resp = pb.SeldonMessage()
                resp.CopyFrom(msg)
                return resp

        unit = PredictiveUnit(name="m", type="MODEL")
        b = MicroBatcher(max_batch_size=64, window_ms=5.0)
        m = payloads.build_message(np.array([[1.0]], np.float32))
        m.meta.tags["batch_index"].string_value = "deadbeef"
        out = asyncio.run(b.call(unit, m, FakeClient()))
        assert b.stats["direct_calls"] == 1 and b.stats["fused_calls"] == 0
        assert out.meta.tags["batch_index"].string_value == "deadbeef"


class TestSamplerMaskedTail:
    def test_masked_final_tokens_never_sampled(self):
        """Inverse-CDF sampling must not leak residual probability mass to
        masked trailing vocab entries (fp32 cumsum error + clamp bug)."""
        import jax
        import jax.numpy as jnp

        from seldon_tpu.models.sampling import sample_per_row

        B, V = 64, 32000
        # Top-k=2 over a peaked distribution: only tokens {0, 1} legal.
        logits = jnp.tile(
            jnp.concatenate([jnp.array([5.0, 4.0]), jnp.zeros(V - 2)]),
            (B, 1),
        )
        keys = jax.random.split(jax.random.key(0), B)
        for trial in range(20):
            keys = jax.vmap(jax.random.fold_in)(keys, jnp.full(B, trial))
            toks = sample_per_row(
                logits, keys,
                jnp.ones(B), jnp.full(B, 2, jnp.int32), jnp.ones(B),
            )
            assert int(jnp.max(toks)) <= 1, int(jnp.max(toks))
