"""Regression tests for the first code-review pass findings."""

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime import seldon_methods
from seldon_tpu.runtime.metrics_server import ServerMetrics


def _metric(key, mtype, value, tags=None):
    m = pb.Metric(key=key, type=mtype, value=value)
    for k, v in (tags or {}).items():
        m.tags[k] = v
    return m


class TestCustomMetricCollisions:
    def test_same_key_different_tags_does_not_raise(self):
        sm = ServerMetrics()
        sm.record_custom([_metric("mymetric", pb.Metric.COUNTER, 1.0)])
        # Previously raised 'Duplicated timeseries'; now dropped with a log.
        sm.record_custom([_metric("mymetric", pb.Metric.COUNTER, 1.0, {"a": "b"})])
        sm.record_custom([_metric("mymetric", pb.Metric.GAUGE, 2.0)])
        body, _ = sm.export()
        assert b"mymetric_total 1.0" in body

    def test_observe_never_raises(self):
        sm = ServerMetrics()
        msg = pb.SeldonMessage()
        msg.meta.metrics.add().key = "seldon_api_executor_server_requests"  # collides
        sm.observe("predict", "rest", 0.01, msg)  # must not raise

    def test_reward_counters(self):
        sm = ServerMetrics()
        sm.record_reward("router", 0.5)
        sm.record_reward("router", -0.25)
        body, _ = sm.export()
        assert b'seldon_api_model_feedback_total{unit="router"} 2.0' in body
        assert b'seldon_api_model_feedback_reward_total{unit="router"} 0.5' in body
        assert b'reward_negative_total{unit="router"} 0.25' in body


class TestRawHookErrors:
    def test_attribute_error_in_raw_hook_surfaces(self):
        calls = []

        class Buggy:
            def predict_raw(self, msg):
                return self.no_such_attr  # genuine bug, must surface

            def predict(self, X, names, meta=None):
                calls.append(1)
                return X

        req = payloads.build_message(np.ones((1, 1)))
        with pytest.raises(AttributeError):
            seldon_methods.predict(Buggy(), req)
        assert calls == []  # high-level path must NOT run as a fallback


class TestNonNumericOutputs:
    def test_string_labels_fall_back_to_ndarray(self):
        class Labeler:
            def predict(self, X, names, meta=None):
                return np.array(["cat", "dog"])

        req = payloads.build_message(np.ones((2, 4)), kind="dense")
        resp = seldon_methods.predict(Labeler(), req)
        assert payloads.data_kind(resp) == "ndarray"
        assert list(payloads.get_data_from_message(resp)) == ["cat", "dog"]

    def test_dict_output_becomes_jsondata(self):
        class Dicty:
            def predict(self, X, names, meta=None):
                return {"label": "cat", "score": 0.9}

        req = payloads.build_message(np.ones((1, 1)), kind="dense")
        resp = seldon_methods.predict(Dicty(), req)
        out = payloads.get_data_from_message(resp)
        assert out == {"label": "cat", "score": 0.9}


class TestInPlaceMutation:
    def test_dense_payload_is_writable(self):
        class Mutator:
            def predict(self, X, names, meta=None):
                X += 1  # in-place, sklearn-scaler style
                return X

        req = payloads.build_message(np.zeros((2, 2), dtype=np.float32), kind="dense")
        resp = seldon_methods.predict(Mutator(), req)
        np.testing.assert_array_equal(
            payloads.get_data_from_message(resp), np.ones((2, 2))
        )

    def test_zero_copy_path_available(self):
        dense = payloads.array_to_dense(np.arange(4.0))
        ro = payloads.dense_to_array(dense, writable=False)
        assert not ro.flags.writeable


class TestGenerateStream:
    def test_stream_hook(self):
        class Streamer:
            def generate_stream(self, req):
                for i in range(3):
                    yield {"text": f"t{i}", "token_ids": [i]}

        req = pb.GenerateRequest(prompt="x")
        chunks = list(seldon_methods.generate_stream(Streamer(), req))
        assert [c.text for c in chunks] == ["t0", "t1", "t2"]

    def test_grpc_stream_falls_back_to_unary(self):
        import grpc as grpc_mod

        from seldon_tpu.proto import prediction_grpc
        from seldon_tpu.runtime.wrapper import build_grpc_server

        class UnaryOnly:
            def generate(self, req):
                return {"text": "single", "token_ids": [7]}

        server = build_grpc_server(UnaryOnly())
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            ch = grpc_mod.insecure_channel(f"127.0.0.1:{port}")
            stub = prediction_grpc.TextGenStub(ch)
            chunks = list(stub.GenerateStream(pb.GenerateRequest(prompt="x")))
            assert len(chunks) == 1 and chunks[0].text == "single"
        finally:
            server.stop(0)
