"""graftragged (models/ragged_attention.py + engine._dispatch_ragged):
the single-variant unified wave, pinned against the bucketed engine.

The load-bearing claims, in test form:
 * greedy decoding under RAGGED is BIT-IDENTICAL to every ragged-off
   mode — dense slab, paged one-shot, paged+chunked, and a warm
   prefix-trie hit — for bf16 AND int8 KV, including a concurrent
   mixed-length burst;
 * one WAVE really is one DISPATCH: a hand-driven scheduler step packs
   a new admission, a mid-prefill continuation and live decode rows
   into a single ``("ragged", C)`` dispatch, and the compile ledger
   never sees a key outside the static lattice (zero live retraces);
 * the lattice COLLAPSES: ``static_lattice()`` is exactly
   {deactivate, ragged/C} (+cow under prefix_cache) — at most 2 (3)
   variants where the bucketed engine compiles a whole grid;
 * pool exhaustion under ragged PREEMPTS instead of wedging: the
   victim gets the typed retriable "preempted" error, survivors stay
   bit-exact, nothing leaks;
 * the sched ledger prices a wave as useful == packed (capacity is not
   padding — the ragged kernel walks real token counts), so
   padding_waste_frac ~ 0 under mixed traffic;
 * ragged=False leaves the engine byte-identical to the bucketed build,
   and EngineConfig rejects unusable ragged knob combinations.
"""

import dataclasses
import queue

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))  # 24 tokens
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)

# Mixed-length burst: one-chunk shorties, a chunk-aligned prompt, and a
# ragged mid-chunk tail — every packing shape a wave can see.
MIXED = [
    list(range(2, 26)),   # 24 tokens: 3 full chunks
    list(range(30, 33)),  # 3 tokens: single final chunk
    list(range(40, 57)),  # 17 tokens: 2 chunks + ragged tail of 1
    [5, 9],               # 2 tokens
]

# The ragged engine rides the paged + chunked substrate.
RAGGED = dict(paged_kv=True, chunked_prefill=True, prefill_chunk=8,
              prefix_block=8, kv_block=8, ragged=True)


def _engine(cfg, start=True, **ekw):
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _want(cfg, prompt=PROMPT, **ekw):
    """Ragged-off reference output for one prompt under a given mode."""
    eng = _engine(cfg, **ekw)
    try:
        return eng.generate_blocking(prompt, GREEDY)["token_ids"]
    finally:
        eng.stop()


def _collect(q, timeout=120):
    toks, err = [], None
    while True:
        item = q.get(timeout=timeout)
        if item is None:
            return toks, err
        if "error" in item:
            err = item
        else:
            toks.extend(item.get("tokens", []))


def _drain_now(q):
    toks = []
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            return toks, False
        if item is None:
            return toks, True
        assert "error" not in item, item
        toks.extend(item.get("tokens", []))


# ---------------------------------------------------------------------------
# Bit-exactness vs every ragged-off mode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_ragged_bit_identical_to_dense_mixed_burst(kv_dtype):
    """A concurrent mixed-length burst through the ragged engine matches
    the dense slab token-for-token — the acceptance gate's exactness
    criterion, for both KV dtypes."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    wants = [_want(cfg, p) for p in MIXED]

    eng = _engine(cfg, **RAGGED)
    try:
        qs = [eng.submit(p, GREEDY) for p in MIXED]
        gots = []
        for q in qs:
            toks, err = _collect(q)
            assert err is None, err
            gots.append(toks)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert gots == wants
    # The burst really took the ragged path: chunked-prefill accounting
    # ticked (46 prompt tokens packed as exact-length segments).
    assert snap["prefill_chunk_tokens"] == sum(len(p) for p in MIXED)


def test_ragged_bit_identical_to_paged_and_chunked():
    """ragged-on vs the two intermediate ragged-off modes (paged
    one-shot, paged+chunked) — all three agree with each other."""
    cfg = get_config("tiny")
    paged = [_want(cfg, p, paged_kv=True, kv_block=8, prefix_block=8)
             for p in MIXED]
    chunked = [
        _want(cfg, p, paged_kv=True, kv_block=8, chunked_prefill=True,
              prefill_chunk=8, prefix_block=8)
        for p in MIXED
    ]
    eng = _engine(cfg, **RAGGED)
    try:
        ragged = []
        for p in MIXED:
            toks, err = _collect(eng.submit(p, GREEDY))
            assert err is None, err
            ragged.append(toks)
    finally:
        eng.stop()
    assert ragged == paged
    assert ragged == chunked


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_ragged_prefix_warm_bit_identical_and_zero_copy(kv_dtype):
    """A warm prefix-trie resume under ragged: the second admission
    starts mid-prompt (starts > 0 on its FIRST wave), shares blocks
    zero-copy, and still matches the dense slab."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    want = _want(cfg)
    eng = _engine(cfg, **RAGGED, prefix_cache=True)
    try:
        cold = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        warm = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert cold == want
    assert warm == want
    assert snap["zero_copy_admissions"] >= 1
    assert snap["prefix_seed_copies"] == 0


def test_ragged_sync_fetch_loop_bit_identical():
    """async_fetch=False exercises _loop_sync_ragged (the one-wave-
    lookahead pipeline) instead of the fetch-thread path."""
    cfg = get_config("tiny")
    want = _want(cfg)
    eng = _engine(cfg, **RAGGED, async_fetch=False)
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        eng.stop()
    assert got == want


# ---------------------------------------------------------------------------
# Mechanics: mixed prefill + decode + continuation in ONE dispatch
# ---------------------------------------------------------------------------


def test_mixed_wave_is_one_dispatch(monkeypatch):
    """Hand-driven scheduler step (no engine thread): once a stream is
    decoding, submitting a long prompt and a shorty makes the next wave
    carry a NEW admission chunk + a FINAL admission + the live decode
    row in a single ("ragged", C) dispatch — and every key the compile
    ledger ever sees is statically declared (zero live retraces)."""
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    cfg = get_config("tiny")
    eng = _engine(cfg, start=False, **RAGGED)
    eng.warmup()

    def step():
        with eng._book:
            work = eng._dispatch_once()
            if work is None:
                return False
            eng._process_boundary(*work)  # holds(_book), like the loop
        return True

    q_a = eng.submit(PROMPT, SamplingParams(
        temperature=0.0, max_new_tokens=16, seed=0))
    for _ in range(3):  # 24 tokens / chunk 8: wave 3 samples + decodes
        assert step()
    got_a, _ = _drain_now(q_a)
    assert got_a  # A is decoding

    q_b = eng.submit(list(range(40, 57)), GREEDY)  # 17 toks: mid-prefill
    q_c = eng.submit([5, 9], GREEDY)               # 2 toks: final chunk
    before = eng.stats.snapshot()
    assert step()
    snap = eng.stats.snapshot()

    # ONE dispatch carried: B's first (non-final) chunk + C's final
    # chunk + A's decode step.
    assert snap["decode_dispatches"] - before["decode_dispatches"] == 1
    assert snap["prefill_chunks"] - before["prefill_chunks"] == 2
    assert snap["prefill_chunk_tokens"] - before["prefill_chunk_tokens"] \
        == 8 + 2
    got_a, _ = _drain_now(q_a)
    assert got_a, "decode row starved by the admission wave"
    got_c, _ = _drain_now(q_c)
    assert got_c, "final-chunk row got no first token"
    _, b_done = _drain_now(q_b)
    assert not b_done  # B is mid-prefill: the wave was genuinely mixed

    # Drive everything to completion; the ledger must stay inside the
    # static lattice the whole time.
    for _ in range(64):
        if not step():
            break
    comp = eng.debug_compile()
    assert comp["live_retrace_count"] == 0, comp["live_retraces"]
    static = set(eng.static_lattice())
    assert {e["key"] for e in comp["lattice"]} <= static
    assert any(k.startswith("ragged/") for k in static)


# ---------------------------------------------------------------------------
# Lattice collapse + waste accounting
# ---------------------------------------------------------------------------


def test_static_lattice_collapses_to_two_variants():
    from seldon_tpu.servers import compile_ledger, shape_lattice

    def expect(eng):
        # Derived from the same closed form the engine warms up from —
        # PR 13/15 both shipped stale-pin fixes where this list was
        # hand-written; now only the *collapse bound* is asserted as a
        # literal, the key set itself comes from the lattice.
        keys = shape_lattice.dispatch_keys(eng.lattice_spec())
        return [compile_ledger.key_str(k)
                for k in shape_lattice.warmup_order(keys)]

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=64, prompt_buckets=(8, 32), **RAGGED))
    static = eng.static_lattice()
    assert len(static) <= 2
    assert static == expect(eng)
    assert any(k.startswith("ragged/") for k in static)
    # Prefix cache adds only the CoW tail copy — still ≤ 3.
    eng2 = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=64, prompt_buckets=(8, 32),
        prefix_cache=True, **RAGGED))
    static2 = eng2.static_lattice()
    assert len(static2) <= 3
    assert static2 == expect(eng2)
    assert "cow" in static2
    assert {k.split("/")[0] for k in static2} <= set(
        shape_lattice.FAMILY_TAGS)


def test_sched_ledger_prices_waves_as_zero_padding(monkeypatch):
    """Under SCHED_LEDGER=1 mixed traffic, every wave's cells == useful
    tokens (exact-length segments, no bucket rounding, no pow2 group
    replication): padding_waste_frac lands at ~0 — the acceptance
    criterion is ≤ 0.05, construction gives exactly 0."""
    monkeypatch.setenv("SCHED_LEDGER", "1")
    cfg = get_config("tiny")
    eng = _engine(cfg, **RAGGED)
    try:
        qs = [eng.submit(p, GREEDY) for p in MIXED]
        for q in qs:
            toks, err = _collect(q)
            assert err is None, err
        eng.drain(timeout=120)
        sched = eng.debug_sched()
    finally:
        eng.stop()
    assert sched["conservation"]["breaches"] == 0, (
        sched["conservation"]["last_breach"])
    assert sched["useful_tokens"] > 0
    assert sched["bucket_pad_tokens"] == 0
    assert sched["group_pad_tokens"] == 0
    assert sched["padding_waste_frac"] <= 0.05
    ragged_shapes = [e for e in sched["by_shape"]
                     if str(e["key"]).startswith("ragged/")]
    assert ragged_shapes, sched["by_shape"]
    assert all(e["cells"] == e["useful_tokens"] for e in ragged_shapes)


# ---------------------------------------------------------------------------
# Pool exhaustion: preempt, don't wedge
# ---------------------------------------------------------------------------


def test_pool_exhaustion_preempts_and_survivor_is_exact():
    """Two 6-token streams in a pool with 3 usable blocks: both admit
    (1 block each) and both need a second block at the same decode
    boundary. Slot 0 takes the last free block; slot 1's growth finds
    the pool empty and preempts — the victim gets the typed retriable
    error, the survivor finishes bit-exact, nothing leaks."""
    cfg = get_config("tiny")
    p_a = [2, 3, 5, 7, 11, 13]
    p_b = [4, 6, 8, 9, 10, 12]
    want_b = _want(cfg, p_b)

    eng = _engine(cfg, max_seq_len=32, kv_pool_blocks=4, **RAGGED)
    try:
        q_a = eng.submit(p_a, GREEDY)
        q_b = eng.submit(p_b, GREEDY)
        toks_a, err_a = _collect(q_a)
        toks_b, err_b = _collect(q_b)
        snap = eng.stats.snapshot()
        leaks = eng.debug_lifecycle_check()
    finally:
        eng.stop()
    # Exactly one stream lost the race for the second block.
    errs = [e for e in (err_a, err_b) if e is not None]
    assert len(errs) == 1, (err_a, err_b)
    assert errs[0]["kind"] == "preempted", errs[0]
    assert errs[0]["retriable"] is True
    assert snap["preemptions"] >= 1
    # The survivor (deterministically slot order's winner) is bit-exact.
    survivor = toks_b if err_a is not None else toks_a
    want = want_b if err_a is not None else _want(cfg, p_a)
    assert survivor == want
    assert leaks == {}
    assert snap["pool_blocks_used"] == 0


# ---------------------------------------------------------------------------
# Config validation + off-mode isolation
# ---------------------------------------------------------------------------


def test_ragged_config_validation():
    with pytest.raises(ValueError, match="ragged"):
        EngineConfig(ragged=True)  # needs the paged+chunked substrate
    with pytest.raises(ValueError, match="ragged"):
        EngineConfig(ragged=True, paged_kv=True, kv_block=8,
                     prefix_block=8)
    with pytest.raises(ValueError, match="power of two"):
        EngineConfig(ragged=True, paged_kv=True, chunked_prefill=True,
                     kv_block=8, prefill_chunk=8, prefix_block=8,
                     ragged_chunk=24)
    with pytest.raises(ValueError, match="kv_block"):
        EngineConfig(ragged=True, paged_kv=True, chunked_prefill=True,
                     kv_block=16, prefix_block=8, prefill_chunk=16,
                     ragged_chunk=8)
    # The defaults themselves are valid, and ragged_chunk=0 inherits
    # prefill_chunk.
    EngineConfig(ragged=True, paged_kv=True, chunked_prefill=True,
                 kv_block=8, prefill_chunk=8, prefix_block=8)


def test_ragged_off_leaves_engine_untouched():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=64, prompt_buckets=(8, 32)))
    assert not eng._ragged
    assert eng._jit_ragged is None
    assert not any(k.startswith("ragged") for k in eng.static_lattice())
