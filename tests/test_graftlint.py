"""graftlint: one positive + one negative fixture per pass, baseline
round-trip, and a tier-1 gate that the real tree lints clean.

Fixtures are written to tmp_path and run through the pass functions
directly (no subprocess) except the CLI tests, which exercise exit
codes the way CI consumes them.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.graftlint import (core, hotpath, knobs, lockorder, locks, outcome,
                             retrace)
from tools.graftlint.__main__ import default_targets

REPO = Path(__file__).resolve().parents[1]


def lint(tmp_path, src, passes, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    files = core.load_tree([p], tmp_path)
    ctx = core.Context(tmp_path)
    return core.run_passes(files, ctx, passes)


def rules(findings):
    return sorted({f.rule for f in findings})


# --- hot-sync ----------------------------------------------------------------

HOT_BAD = """
    class Engine:
        def _loop(self):
            while True:
                self._step()

        def _step(self):
            x = self._jit_decode(self._state)
            v = float(x)          # blocking transfer in the dispatch loop
            y = x.item()          # same
            return v, y
"""

HOT_OK = """
    class Engine:
        def _loop(self):
            while True:
                self._step()

        def _step(self):
            x = self._jit_decode(self._state)
            x.copy_to_host_async()
            return x

        def offline_tool(self):
            # not reachable from any dispatch root: syncs are fine here
            return float(self._jit_decode(self._state))
"""


def test_hotpath_positive(tmp_path):
    fs = lint(tmp_path, HOT_BAD, [hotpath.run])
    assert rules(fs) == ["hot-sync"]
    assert len(fs) == 2
    assert any(".item()" in f.message for f in fs)
    assert any("float()" in f.message for f in fs)


def test_hotpath_negative(tmp_path):
    assert lint(tmp_path, HOT_OK, [hotpath.run]) == []


def test_hotpath_block_until_ready_flagged_everywhere(tmp_path):
    src = """
        import jax
        def helper(x):
            jax.block_until_ready(x)
    """
    fs = lint(tmp_path, src, [hotpath.run])
    assert len(fs) == 1 and "block_until_ready" in fs[0].message


def test_hotpath_allow_comment_waives(tmp_path):
    src = """
        import jax
        def warmup(x):
            jax.block_until_ready(x)  # graftlint: allow(hot-sync) warmup sync
    """
    assert lint(tmp_path, src, [hotpath.run]) == []


# --- lock-guard --------------------------------------------------------------

LOCK_BAD = """
    import threading

    class Engine:
        def __init__(self):
            self._book = threading.Lock()
            self._slots = []  # graftlint: guarded-by(_book)

        def racy(self):
            return len(self._slots)
"""

LOCK_OK = """
    import threading

    class Engine:
        def __init__(self):
            self._book = threading.Lock()
            self._slots = []  # graftlint: guarded-by(_book)

        def safe(self):
            with self._book:
                return len(self._slots)

        def helper(self):  # graftlint: holds(_book)
            self._slots.append(1)
"""


def test_locks_positive(tmp_path):
    fs = lint(tmp_path, LOCK_BAD, [locks.run])
    assert rules(fs) == ["lock-guard"]
    assert len(fs) == 1
    assert fs[0].qualname == "Engine.racy"
    assert "_book" in fs[0].message


def test_locks_negative(tmp_path):
    # with-block, holds() annotation, and __init__ are all sanctioned
    assert lint(tmp_path, LOCK_OK, [locks.run]) == []


def test_locks_cross_object_access(tmp_path):
    src = LOCK_OK + """
    def exporter(eng):
        return len(eng._slots)  # cannot take eng's lock correctly from here
    """
    fs = lint(tmp_path, src, [locks.run])
    assert len(fs) == 1 and "outside Engine" in fs[0].message


def test_locks_via_role(tmp_path):
    src = """
        import threading

        class Stats:
            def __init__(self):
                self.lock = threading.Lock()
                self.completed = 0  # graftlint: guarded-by(lock) via(stats)

        class Engine:
            def __init__(self):
                self.stats = Stats()

            def racy(self):
                self.stats.completed += 1

            def safe(self):
                with self.stats.lock:
                    self.stats.completed += 1
    """
    fs = lint(tmp_path, src, [locks.run])
    assert len(fs) == 1 and fs[0].qualname == "Engine.racy"


# --- lockorder ---------------------------------------------------------------

LO_INVERSION = """
    import threading

    class InferenceEngine:
        def __init__(self):
            self._book = threading.Lock()
            self._rid_lock = threading.Lock()

        def bad(self):
            with self._rid_lock:
                with self._book:
                    pass
"""

LO_HOLDS = """
    import threading

    class InferenceEngine:
        def __init__(self):
            self._book = threading.Lock()
            self._complete()  # __init__ is pre-publication: sanctioned

        def _complete(self):  # graftlint: holds(_book)
            pass

        def racy(self):
            self._complete()

        def safe(self):
            with self._book:
                self._complete()
"""

LO_BLOCK = """
    import queue
    import threading
    import time

    class InferenceEngine:
        def __init__(self):
            self._book = threading.Lock()
            self._q = queue.Queue(maxsize=4)

        def stall(self):
            with self._book:
                time.sleep(0.1)

        def feed(self, item):
            with self._book:
                self._q.put(item)

        def fine(self, item):
            self._q.put(item)           # no lock held
            with self._book:
                self._q.put(item, block=False)  # non-blocking put
"""

LO_CYCLE = """
    import threading

    class Worker:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""

LO_INTERPROC = """
    import threading

    class BlockAllocator:
        def __init__(self):
            self._lock = threading.Lock()

        def free_count(self):
            with self._lock:
                return 0

    class EngineStats:
        def __init__(self, pool: BlockAllocator):
            self.lock = threading.Lock()
            self.pool = pool

        def snapshot(self):
            with self.lock:
                return self.pool.free_count()
"""

LO_OK = """
    import threading
    import time

    class InferenceEngine:
        def __init__(self):
            self._book = threading.Lock()
            self._rid_lock = threading.Lock()

        def _complete(self):  # graftlint: holds(_book)
            with self._rid_lock:
                pass

        def run(self):
            time.sleep(0.01)  # not under _book: fine
            with self._book:
                self._complete()
"""


def test_lockorder_rank_inversion(tmp_path):
    fs = lint(tmp_path, LO_INVERSION, [lockorder.run])
    assert rules(fs) == ["lock-order"]
    assert len(fs) == 1
    assert "leaf" in fs[0].message and "_rid_lock" in fs[0].message
    assert fs[0].path == "fixture.py" and fs[0].line > 0
    assert "lock_order.py" in fs[0].hint


def test_lockorder_holds_site(tmp_path):
    fs = lint(tmp_path, LO_HOLDS, [lockorder.run])
    assert rules(fs) == ["holds-site"]
    assert len(fs) == 1
    assert fs[0].qualname == "InferenceEngine.racy"
    assert "requires '_book' held" in fs[0].message
    assert "holds(_book)" in fs[0].hint


def test_lockorder_blocking_under_book(tmp_path):
    fs = lint(tmp_path, LO_BLOCK, [lockorder.run])
    assert rules(fs) == ["lock-block"]
    by_qn = {f.qualname: f.message for f in fs}
    assert "time.sleep" in by_qn["InferenceEngine.stall"]
    assert "bounded queue" in by_qn["InferenceEngine.feed"]
    assert len(fs) == 2


def test_lockorder_cycle_between_unranked_locks(tmp_path):
    fs = lint(tmp_path, LO_CYCLE, [lockorder.run])
    assert rules(fs) == ["lock-order"]
    # one finding per edge of the a<->b cycle
    assert len(fs) == 2
    assert all("cycle" in f.message for f in fs)
    assert any("Worker._a" in f.message and "Worker._b" in f.message
               for f in fs)


def test_lockorder_interprocedural_leaf_escape(tmp_path):
    # stats.lock is a leaf; reaching allocator._lock THROUGH a callee
    # (resolved via the annotated ctor-param binding) must be flagged at
    # the call site.
    fs = lint(tmp_path, LO_INTERPROC, [lockorder.run])
    assert rules(fs) == ["lock-order"]
    assert len(fs) == 1
    assert fs[0].qualname == "EngineStats.snapshot"
    assert "allocator._lock" in fs[0].message
    assert "stats.lock" in fs[0].message


def test_lockorder_negative(tmp_path):
    # correct nesting, holds() satisfied lexically, sleep outside the
    # lock, __init__ pre-publication — all clean
    assert lint(tmp_path, LO_OK, [lockorder.run]) == []
    assert lint(tmp_path, LO_HOLDS.replace(
        "def racy(self):\n            self._complete()\n\n        ",
        ""), [lockorder.run]) == []


def test_lockorder_allow_waives_edge(tmp_path):
    src = LO_INVERSION.replace(
        "with self._book:",
        "with self._book:  # graftlint: allow(lock-order) test rig only")
    assert lint(tmp_path, src, [lockorder.run]) == []


# --- retrace -----------------------------------------------------------------

RETRACE_BAD = """
    import jax

    @jax.jit
    def decode(x):
        if x > 0:           # branching on a traced value
            return x
        return -x

    def build(sizes):
        fns = []
        for n in sizes:
            fns.append(jax.jit(lambda s: s[:n]))  # jit inside a loop
        return fns
"""

RETRACE_OK = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def decode(x, n):
        if n > 4:               # static arg: fine
            return x
        if x.shape[0] > 2:      # shape read: static, fine
            return x + 1
        return -x
"""


def test_retrace_positive(tmp_path):
    fs = lint(tmp_path, RETRACE_BAD, [retrace.run])
    assert rules(fs) == ["retrace"]
    msgs = " | ".join(f.message for f in fs)
    assert "branches on a traced value" in msgs
    assert "inside a loop" in msgs


def test_retrace_negative(tmp_path):
    assert lint(tmp_path, RETRACE_OK, [retrace.run]) == []


def test_retrace_unhashable_static_literal(tmp_path):
    src = """
        import jax

        def _impl(x, dims):
            return x

        run = jax.jit(_impl, static_argnums=(1,))

        def call(x):
            return run(x, [1, 2, 3])
    """
    fs = lint(tmp_path, src, [retrace.run])
    assert len(fs) == 1 and "unhashable" in fs[0].message


# --- outcome -----------------------------------------------------------------

OUTCOME_BAD = """
    class Engine:
        def _complete(self, req):
            req.out.put(None)

        def drop_error(self, req):
            # error item but no path to the completer: waiter hangs
            req.out.put({"error": "boom", "kind": "internal"})

        def rogue(self, req):
            req.out.put(None)

        def swallow(self, req):
            try:
                self.dispatch(req)
            except Exception:
                pass
"""

OUTCOME_OK = """
    class Engine:
        def _complete(self, req):
            req.out.put(None)

        def _fail_req(self, req, msg):
            req.out.put({"error": msg, "kind": "internal"})
            self._complete(req)

        def recover(self, req):
            try:
                self.dispatch(req)
            except Exception as e:
                self._fail_req(req, str(e))
"""


def test_outcome_positive(tmp_path):
    fs = lint(tmp_path, OUTCOME_BAD, [outcome.run])
    assert rules(fs) == ["outcome"]
    by_qn = {f.qualname: f.message for f in fs}
    assert "waiter hangs" in by_qn["Engine.drop_error"]          # O2
    assert "outside the designated completer" in by_qn["Engine.rogue"]  # O1
    assert "broad except" in by_qn["Engine.swallow"]             # O3
    assert len(fs) == 3


def test_outcome_negative(tmp_path):
    assert lint(tmp_path, OUTCOME_OK, [outcome.run]) == []


# --- env-knob ----------------------------------------------------------------

def test_knobs_positive(tmp_path):
    src = """
        import os
        FLAG = os.environ.get("GRAFTLINT_TEST_UNREGISTERED_KNOB", "0")
    """
    fs = lint(tmp_path, src, [knobs.run])
    assert rules(fs) == ["env-knob"]
    assert "GRAFTLINT_TEST_UNREGISTERED_KNOB" in fs[0].message


def test_knobs_negative_registered_and_aliased(tmp_path):
    # CHAOS is registered; reads through `import os as _os`, a module
    # constant, and an environ alias must all resolve to it.
    src = """
        import os as _os
        _CHAOS = "CHAOS"
        env = _os.environ
        a = _os.getenv("CHAOS")
        b = _os.environ.get(_CHAOS)
        c = env["CHAOS"] if "CHAOS" in _os.environ else "0"
    """
    assert lint(tmp_path, src, [knobs.run]) == []


def test_knobs_dynamic_read_skipped(tmp_path):
    src = """
        import os
        def read(name):
            return os.environ.get(name)
    """
    assert lint(tmp_path, src, [knobs.run]) == []


# --- env-knob-dead -----------------------------------------------------------

def _lint_tree(tmp_path, sources, passes):
    for rel, src in sources.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    files = core.load_tree([tmp_path], tmp_path)
    return core.run_passes(files, core.Context(tmp_path), passes)


# The K2 check anchors its findings on the registry file; a scan that
# does not include it (every fixture above) must stay silent, so the
# dead-knob tests build a miniature tree that does.
REG_STUB = """
    # fixture registry: the real KNOBS table is imported by the pass;
    # this file only anchors dead-knob findings to a line.
    KNOBS = {
        "CHAOS": {},
    }
"""


def test_knob_dead_positive(tmp_path):
    # A tree that reads nothing: every internally-grouped knob is dead.
    fs = _lint_tree(
        tmp_path, {"tools/graftlint/knob_registry.py": REG_STUB},
        [knobs.run])
    dead = [f for f in fs if f.rule == "env-knob-dead"]
    assert dead, "expected dead-knob findings on a read-free tree"
    chaos = next(f for f in dead if "'CHAOS'" in f.message)
    assert chaos.path == "tools/graftlint/knob_registry.py"
    # anchored to the registry line declaring the knob, not line 1
    assert '"CHAOS"' in (tmp_path / chaos.path).read_text().splitlines()[
        chaos.line - 1]
    assert "--gen-knobs" in chaos.hint
    # external groups (read by JAX/the platform, not this tree) exempt
    assert not any("'JAX_PLATFORMS'" in f.message for f in dead)


def test_knob_dead_negative_when_read(tmp_path):
    fs = _lint_tree(tmp_path, {
        "tools/graftlint/knob_registry.py": REG_STUB,
        "reader.py": """
            import os
            CHAOS = os.environ.get("CHAOS", "0")
        """,
    }, [knobs.run])
    assert not any(f.rule == "env-knob-dead" and "'CHAOS'" in f.message
                   for f in fs)


def test_knob_dead_is_waivable_on_registry_line(tmp_path):
    reg = REG_STUB.replace(
        '"CHAOS": {},',
        '"CHAOS": {},  # graftlint: allow(env-knob-dead) staged rollout')
    fs = _lint_tree(
        tmp_path, {"tools/graftlint/knob_registry.py": reg}, [knobs.run])
    assert not any(f.rule == "env-knob-dead" and "'CHAOS'" in f.message
                   for f in fs)


# --- baseline round-trip -----------------------------------------------------

def test_baseline_round_trip(tmp_path):
    fs = lint(tmp_path, LOCK_BAD, [locks.run])
    assert fs
    bl = tmp_path / "baseline.json"
    core.write_baseline(bl, fs, {})
    loaded = core.load_baseline(bl)
    assert set(loaded) == {f.fingerprint for f in fs}
    data = json.loads(bl.read_text())
    assert data["version"] == 1
    # notes survive a rewrite
    loaded[fs[0].fingerprint]["note"] = "deliberate: single-threaded test rig"
    core.write_baseline(bl, fs, loaded)
    again = core.load_baseline(bl)
    assert again[fs[0].fingerprint]["note"] == \
        "deliberate: single-threaded test rig"


def test_fingerprint_survives_line_drift(tmp_path):
    fs1 = lint(tmp_path, LOCK_BAD, [locks.run], name="a.py")
    fs2 = lint(tmp_path, "\n\n\n" + LOCK_BAD, [locks.run], name="a.py")
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


# --- CLI / real tree ---------------------------------------------------------

def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(REPO)},
    )


@pytest.mark.lint
def test_real_tree_is_clean_vs_baseline():
    r = _cli()
    assert r.returncode == 0, f"graftlint regressions:\n{r.stdout}\n{r.stderr}"


@pytest.mark.lint
def test_cli_fails_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(HOT_BAD))
    r = _cli("--no-baseline", str(bad))
    assert r.returncode == 1
    assert "hot-sync" in r.stdout


@pytest.mark.lint
def test_cli_knobs_doc_is_fresh():
    # docs/knobs.md must match what --gen-knobs would write (K3) over
    # the same target set CI lints (which includes the bench entry
    # points — BENCH_* read sites must show up in the doc).
    files = core.load_tree(default_targets(REPO), REPO)
    want = knobs.generate_knobs_md(knobs.scan_reads(files))
    have = (REPO / "docs" / "knobs.md").read_text()
    assert have == want, "docs/knobs.md is stale: run " \
        "`python -m tools.graftlint --gen-knobs`"


# --- --write-baseline / --note -----------------------------------------------

def test_write_baseline_requires_note(tmp_path, monkeypatch, capsys):
    from tools.graftlint import __main__ as cli
    monkeypatch.setattr(cli, "_repo_root", lambda: tmp_path)
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(HOT_BAD))

    with pytest.raises(SystemExit) as ei:
        cli.main(["--write-baseline", str(bad)])
    assert ei.value.code == 2
    assert "--note" in capsys.readouterr().err

    # a whitespace-only note is no note
    with pytest.raises(SystemExit) as ei:
        cli.main(["--write-baseline", "--note", "   ", str(bad)])
    assert ei.value.code == 2


def test_write_baseline_stamps_note(tmp_path, monkeypatch):
    from tools.graftlint import __main__ as cli
    monkeypatch.setattr(cli, "_repo_root", lambda: tmp_path)
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(HOT_BAD))

    assert cli.main(["--write-baseline", "--note",
                     "offline tool, sync is deliberate", str(bad)]) == 0
    data = json.loads((tmp_path / "graftlint_baseline.json").read_text())
    assert data["suppressions"]
    assert all(e["note"] == "offline tool, sync is deliberate"
               for e in data["suppressions"])
    # the suppressed tree now lints clean...
    assert cli.main([str(bad)]) == 0
    # ...and --no-baseline still reports the findings
    assert cli.main(["--no-baseline", str(bad)]) == 1
