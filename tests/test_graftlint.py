"""graftlint: one positive + one negative fixture per pass, baseline
round-trip, and a tier-1 gate that the real tree lints clean.

Fixtures are written to tmp_path and run through the pass functions
directly (no subprocess) except the CLI tests, which exercise exit
codes the way CI consumes them.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.graftlint import core, hotpath, knobs, locks, outcome, retrace

REPO = Path(__file__).resolve().parents[1]


def lint(tmp_path, src, passes, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    files = core.load_tree([p], tmp_path)
    ctx = core.Context(tmp_path)
    return core.run_passes(files, ctx, passes)


def rules(findings):
    return sorted({f.rule for f in findings})


# --- hot-sync ----------------------------------------------------------------

HOT_BAD = """
    class Engine:
        def _loop(self):
            while True:
                self._step()

        def _step(self):
            x = self._jit_decode(self._state)
            v = float(x)          # blocking transfer in the dispatch loop
            y = x.item()          # same
            return v, y
"""

HOT_OK = """
    class Engine:
        def _loop(self):
            while True:
                self._step()

        def _step(self):
            x = self._jit_decode(self._state)
            x.copy_to_host_async()
            return x

        def offline_tool(self):
            # not reachable from any dispatch root: syncs are fine here
            return float(self._jit_decode(self._state))
"""


def test_hotpath_positive(tmp_path):
    fs = lint(tmp_path, HOT_BAD, [hotpath.run])
    assert rules(fs) == ["hot-sync"]
    assert len(fs) == 2
    assert any(".item()" in f.message for f in fs)
    assert any("float()" in f.message for f in fs)


def test_hotpath_negative(tmp_path):
    assert lint(tmp_path, HOT_OK, [hotpath.run]) == []


def test_hotpath_block_until_ready_flagged_everywhere(tmp_path):
    src = """
        import jax
        def helper(x):
            jax.block_until_ready(x)
    """
    fs = lint(tmp_path, src, [hotpath.run])
    assert len(fs) == 1 and "block_until_ready" in fs[0].message


def test_hotpath_allow_comment_waives(tmp_path):
    src = """
        import jax
        def warmup(x):
            jax.block_until_ready(x)  # graftlint: allow(hot-sync) warmup sync
    """
    assert lint(tmp_path, src, [hotpath.run]) == []


# --- lock-guard --------------------------------------------------------------

LOCK_BAD = """
    import threading

    class Engine:
        def __init__(self):
            self._book = threading.Lock()
            self._slots = []  # graftlint: guarded-by(_book)

        def racy(self):
            return len(self._slots)
"""

LOCK_OK = """
    import threading

    class Engine:
        def __init__(self):
            self._book = threading.Lock()
            self._slots = []  # graftlint: guarded-by(_book)

        def safe(self):
            with self._book:
                return len(self._slots)

        def helper(self):  # graftlint: holds(_book)
            self._slots.append(1)
"""


def test_locks_positive(tmp_path):
    fs = lint(tmp_path, LOCK_BAD, [locks.run])
    assert rules(fs) == ["lock-guard"]
    assert len(fs) == 1
    assert fs[0].qualname == "Engine.racy"
    assert "_book" in fs[0].message


def test_locks_negative(tmp_path):
    # with-block, holds() annotation, and __init__ are all sanctioned
    assert lint(tmp_path, LOCK_OK, [locks.run]) == []


def test_locks_cross_object_access(tmp_path):
    src = LOCK_OK + """
    def exporter(eng):
        return len(eng._slots)  # cannot take eng's lock correctly from here
    """
    fs = lint(tmp_path, src, [locks.run])
    assert len(fs) == 1 and "outside Engine" in fs[0].message


def test_locks_via_role(tmp_path):
    src = """
        import threading

        class Stats:
            def __init__(self):
                self.lock = threading.Lock()
                self.completed = 0  # graftlint: guarded-by(lock) via(stats)

        class Engine:
            def __init__(self):
                self.stats = Stats()

            def racy(self):
                self.stats.completed += 1

            def safe(self):
                with self.stats.lock:
                    self.stats.completed += 1
    """
    fs = lint(tmp_path, src, [locks.run])
    assert len(fs) == 1 and fs[0].qualname == "Engine.racy"


# --- retrace -----------------------------------------------------------------

RETRACE_BAD = """
    import jax

    @jax.jit
    def decode(x):
        if x > 0:           # branching on a traced value
            return x
        return -x

    def build(sizes):
        fns = []
        for n in sizes:
            fns.append(jax.jit(lambda s: s[:n]))  # jit inside a loop
        return fns
"""

RETRACE_OK = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def decode(x, n):
        if n > 4:               # static arg: fine
            return x
        if x.shape[0] > 2:      # shape read: static, fine
            return x + 1
        return -x
"""


def test_retrace_positive(tmp_path):
    fs = lint(tmp_path, RETRACE_BAD, [retrace.run])
    assert rules(fs) == ["retrace"]
    msgs = " | ".join(f.message for f in fs)
    assert "branches on a traced value" in msgs
    assert "inside a loop" in msgs


def test_retrace_negative(tmp_path):
    assert lint(tmp_path, RETRACE_OK, [retrace.run]) == []


def test_retrace_unhashable_static_literal(tmp_path):
    src = """
        import jax

        def _impl(x, dims):
            return x

        run = jax.jit(_impl, static_argnums=(1,))

        def call(x):
            return run(x, [1, 2, 3])
    """
    fs = lint(tmp_path, src, [retrace.run])
    assert len(fs) == 1 and "unhashable" in fs[0].message


# --- outcome -----------------------------------------------------------------

OUTCOME_BAD = """
    class Engine:
        def _complete(self, req):
            req.out.put(None)

        def drop_error(self, req):
            # error item but no path to the completer: waiter hangs
            req.out.put({"error": "boom", "kind": "internal"})

        def rogue(self, req):
            req.out.put(None)

        def swallow(self, req):
            try:
                self.dispatch(req)
            except Exception:
                pass
"""

OUTCOME_OK = """
    class Engine:
        def _complete(self, req):
            req.out.put(None)

        def _fail_req(self, req, msg):
            req.out.put({"error": msg, "kind": "internal"})
            self._complete(req)

        def recover(self, req):
            try:
                self.dispatch(req)
            except Exception as e:
                self._fail_req(req, str(e))
"""


def test_outcome_positive(tmp_path):
    fs = lint(tmp_path, OUTCOME_BAD, [outcome.run])
    assert rules(fs) == ["outcome"]
    by_qn = {f.qualname: f.message for f in fs}
    assert "waiter hangs" in by_qn["Engine.drop_error"]          # O2
    assert "outside the designated completer" in by_qn["Engine.rogue"]  # O1
    assert "broad except" in by_qn["Engine.swallow"]             # O3
    assert len(fs) == 3


def test_outcome_negative(tmp_path):
    assert lint(tmp_path, OUTCOME_OK, [outcome.run]) == []


# --- env-knob ----------------------------------------------------------------

def test_knobs_positive(tmp_path):
    src = """
        import os
        FLAG = os.environ.get("GRAFTLINT_TEST_UNREGISTERED_KNOB", "0")
    """
    fs = lint(tmp_path, src, [knobs.run])
    assert rules(fs) == ["env-knob"]
    assert "GRAFTLINT_TEST_UNREGISTERED_KNOB" in fs[0].message


def test_knobs_negative_registered_and_aliased(tmp_path):
    # CHAOS is registered; reads through `import os as _os`, a module
    # constant, and an environ alias must all resolve to it.
    src = """
        import os as _os
        _CHAOS = "CHAOS"
        env = _os.environ
        a = _os.getenv("CHAOS")
        b = _os.environ.get(_CHAOS)
        c = env["CHAOS"] if "CHAOS" in _os.environ else "0"
    """
    assert lint(tmp_path, src, [knobs.run]) == []


def test_knobs_dynamic_read_skipped(tmp_path):
    src = """
        import os
        def read(name):
            return os.environ.get(name)
    """
    assert lint(tmp_path, src, [knobs.run]) == []


# --- baseline round-trip -----------------------------------------------------

def test_baseline_round_trip(tmp_path):
    fs = lint(tmp_path, LOCK_BAD, [locks.run])
    assert fs
    bl = tmp_path / "baseline.json"
    core.write_baseline(bl, fs, {})
    loaded = core.load_baseline(bl)
    assert set(loaded) == {f.fingerprint for f in fs}
    data = json.loads(bl.read_text())
    assert data["version"] == 1
    # notes survive a rewrite
    loaded[fs[0].fingerprint]["note"] = "deliberate: single-threaded test rig"
    core.write_baseline(bl, fs, loaded)
    again = core.load_baseline(bl)
    assert again[fs[0].fingerprint]["note"] == \
        "deliberate: single-threaded test rig"


def test_fingerprint_survives_line_drift(tmp_path):
    fs1 = lint(tmp_path, LOCK_BAD, [locks.run], name="a.py")
    fs2 = lint(tmp_path, "\n\n\n" + LOCK_BAD, [locks.run], name="a.py")
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


# --- CLI / real tree ---------------------------------------------------------

def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(REPO)},
    )


@pytest.mark.lint
def test_real_tree_is_clean_vs_baseline():
    r = _cli()
    assert r.returncode == 0, f"graftlint regressions:\n{r.stdout}\n{r.stderr}"


@pytest.mark.lint
def test_cli_fails_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(HOT_BAD))
    r = _cli("--no-baseline", str(bad))
    assert r.returncode == 1
    assert "hot-sync" in r.stdout


@pytest.mark.lint
def test_cli_knobs_doc_is_fresh():
    # docs/knobs.md must match what --gen-knobs would write (K3).
    files = core.load_tree([REPO / "seldon_tpu", REPO / "tools"], REPO)
    want = knobs.generate_knobs_md(knobs.scan_reads(files))
    have = (REPO / "docs" / "knobs.md").read_text()
    assert have == want, "docs/knobs.md is stale: run " \
        "`python -m tools.graftlint --gen-knobs`"
