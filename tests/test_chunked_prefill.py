"""Chunked prefill (stall-free scheduling): exactness + mechanics.

The load-bearing claims, in test form:
 * chunked admissions produce BIT-IDENTICAL greedy tokens to a one-shot
   cold engine (bf16 AND int8 KV) — chunk k prefills against chunks
   0..k-1's resident KV and only the FINAL chunk samples, with the same
   length-folded key as the one-shot path;
 * chunking composes with the prefix cache: a warm hit skips straight to
   the first uncached chunk and still matches the cold one-shot tokens;
 * the scheduler actually interleaves: a long prompt's chunks span
   MULTIPLE dispatches, each carrying at most dispatch_token_budget
   prefill tokens, and a concurrently-decoding stream receives tokens
   BETWEEN those chunks (the whole point — no prefill stall);
 * EngineConfig.__post_init__ rejects the configs that would silently
   compile garbage (non-pow2 chunk, chunk splitting a KV block, budget
   smaller than one chunk);
 * EngineStats.snapshot() carries the observability the feature needs
   (queue depth/wait, ITL percentiles, chunk + budget accounting).
"""

import dataclasses
import queue

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))  # 24 tokens -> 3 chunks of 8
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _engine(cfg, start=True, **ekw):
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


# ---------------------------------------------------------------------------
# Bit-exactness vs the one-shot path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_chunked_bit_identical_to_one_shot(kv_dtype):
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    cold = _engine(cfg)
    try:
        want = cold.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        cold.stop()

    eng = _engine(cfg, chunked_prefill=True, prefill_chunk=8,
                  prefix_block=8)
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got == want
    assert snap["prefill_chunks"] == 3  # 24 tokens / chunk 8
    assert snap["prefill_chunk_tokens"] == 24


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_chunked_composes_with_prefix_cache(kv_dtype):
    """Warm admission under chunking: the first chunk starts at the
    first UNCACHED block, later chunks proceed as usual — and the
    output still matches a cold one-shot engine bit-for-bit."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    cold = _engine(cfg)
    try:
        want = cold.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        cold.stop()

    eng = _engine(cfg, chunked_prefill=True, prefill_chunk=8,
                  prefix_cache=True, prefix_block=8)
    try:
        first = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        warm = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert first == want
    assert warm == want
    assert snap["prefix_hits"] == 1
    # 24-token prompt, lookup capped at plen-1=23 -> 2 blocks reused;
    # the warm admission prefilled only chunk 2 (8 tokens).
    assert snap["prefix_tokens_saved"] == 16
    assert snap["prefill_chunk_tokens"] == 24 + 8


def test_chunked_disabled_leaves_engine_untouched():
    cfg = get_config("tiny")
    eng = _engine(cfg)  # default: chunked_prefill=False
    try:
        assert not eng._chunked
        eng.generate_blocking(PROMPT, GREEDY)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert snap["prefill_chunks"] == 0
    assert snap["prefill_chunk_tokens"] == 0


# ---------------------------------------------------------------------------
# Scheduler mechanics: interleave + budget (no engine thread — the test
# drives _dispatch_once/_process_boundary by hand, one wave at a time)
# ---------------------------------------------------------------------------


def _drain(q):
    toks = []
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            return toks, False
        if item is None:
            return toks, True
        assert "error" not in item, item
        toks.extend(item.get("tokens", []))


def test_decode_dispatched_between_prefill_chunks():
    cfg = get_config("tiny")
    eng = _engine(
        cfg, start=False, max_seq_len=128, prompt_buckets=(8, 64),
        decode_chunk=2, min_chunk=2, adaptive_chunk=False,
        chunked_prefill=True, prefill_chunk=8, prefix_block=8,
        dispatch_token_budget=8,
    )

    def step():
        with eng._book:
            work = eng._dispatch_once()
            if work is None:
                return False, False
            mid = bool(eng._prefilling)  # request still has chunks to go
            eng._process_boundary(*work)  # holds(_book), like the loop
        return True, mid

    q_short = eng.submit(
        list(range(2, 10)),
        SamplingParams(temperature=0.0, max_new_tokens=32, seed=0),
    )
    step()  # admits the short stream (single final chunk) + decode
    got, _ = _drain(q_short)
    assert got  # first token out; the stream is now decoding

    q_long = eng.submit(
        list(range(3, 35)),  # 32 tokens -> 4 chunks of 8
        SamplingParams(temperature=0.0, max_new_tokens=2, seed=1),
    )
    chunk_waves = 0  # dispatches that carried one of long's chunks
    short_tokens_mid_prefill = 0
    long_done = short_done = False
    for _ in range(64):
        chunks_before = eng.stats.prefill_chunks
        tokens_before = eng.stats.prefill_chunk_tokens
        ran, mid = step()
        if not ran:
            break
        # Budget invariant: one dispatch never packs more prefill
        # tokens than dispatch_token_budget.
        assert eng.stats.prefill_chunk_tokens - tokens_before <= 8
        got, short_done_now = _drain(q_short)
        short_done = short_done or short_done_now
        if eng.stats.prefill_chunks > chunks_before:
            chunk_waves += 1
            if mid and got:
                # Decode tokens for the SHORT stream landed on a wave
                # that also carried a mid-prefill chunk of the long
                # prompt — the stall-free interleave.
                short_tokens_mid_prefill += len(got)
        _, long_done_now = _drain(q_long)
        long_done = long_done or long_done_now
        if long_done and short_done:
            break
    assert long_done and short_done
    # 32-token prompt / budget 8 -> the prefill spans 4 dispatches...
    assert chunk_waves == 4
    # ...and the short stream kept receiving tokens between them.
    assert short_tokens_mid_prefill > 0


# ---------------------------------------------------------------------------
# Config validation + stats surface
# ---------------------------------------------------------------------------


def test_engine_config_validation():
    with pytest.raises(ValueError, match="min_chunk"):
        EngineConfig(decode_chunk=4, min_chunk=8)
    with pytest.raises(ValueError, match="max_admit"):
        EngineConfig(max_admit=6)
    with pytest.raises(ValueError, match="prompt_buckets"):
        EngineConfig(prompt_buckets=(32, 48))
    with pytest.raises(ValueError, match="prefill_chunk"):
        EngineConfig(chunked_prefill=True, prefill_chunk=48,
                     prefix_block=16)
    with pytest.raises(ValueError, match="prefix_block"):
        EngineConfig(chunked_prefill=True, prefill_chunk=8,
                     prefix_block=16)
    with pytest.raises(ValueError, match="dispatch_token_budget"):
        EngineConfig(chunked_prefill=True, prefill_chunk=64,
                     dispatch_token_budget=32)
    # The knobs are only validated when the feature is on, and the
    # defaults themselves are valid.
    EngineConfig(prefill_chunk=48, dispatch_token_budget=32)
    EngineConfig(chunked_prefill=True)
    EngineConfig(chunked_prefill=True, prefill_chunk=64,
                 dispatch_token_budget=256)


def test_snapshot_reports_queue_wait_and_itl():
    cfg = get_config("tiny")
    eng = _engine(cfg, chunked_prefill=True, prefill_chunk=8,
                  prefix_block=8, decode_chunk=4, min_chunk=4)
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert snap["queue_depth"] == 0  # nothing waiting after completion
    assert eng.stats.queue_wait_count == 1  # submit->first-dispatch taken
    assert snap["mean_queue_wait_ms"] >= 0.0
    # 8 generated tokens at decode_chunk=4 -> at least one post-first
    # burst, so the ITL histogram has samples and percentiles resolve.
    assert snap["itl_count"] >= 1
    assert snap["mean_itl_ms"] > 0.0
    assert (0.0 < snap["itl_p50_ms"] <= snap["itl_p95_ms"]
            <= snap["itl_p99_ms"])
    assert snap["budget_utilization"] > 0.0
