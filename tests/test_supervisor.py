"""graftheal (servers/supervisor.py + engine recovery paths): replay-
based request resurrection, poison quarantine, dispatch watchdog and
the NaN/garbage sentinel.

The load-bearing claims, in test form:
 * HEAL env gating is fail-safe: knobs without the HEAL=1 master
   switch are inert, a heal-off engine keeps `_heal = None` and the
   raw `_fail_all` failure path;
 * resurrection is BIT-IDENTICAL: a mid-stream wave fault resurrects
   every innocent request and the delivered stream matches the
   fault-free reference token-for-token — dense / paged / ragged /
   spec, bf16 AND int8 KV, greedy AND sampled (per-position sampling
   keys make the replayed continuation exact);
 * poison quarantine bisects: a seeded sticky request that
   deterministically wrecks every wave it rides is isolated in log2
   rounds and failed with ``kind="poison"`` (non-retriable) while
   every innocent completes bit-identically;
 * the dispatch watchdog turns a hung boundary fetch into a normal
   wave fault (WatchdogError -> resurrection) instead of a wedged
   scheduler; the sentinel quarantines out-of-vocab token ids before
   any reaches a client;
 * the retry budget is a hard ceiling: a permanently faulting device
   fails requests with retriable=False after `heal_max_retries`
   resurrections — no infinite replay loop;
 * nothing leaks: every scenario ends with an empty
   `debug_lifecycle_check()`, and the chaos+heal soak finishes with
   zero hung waiters, one outcome per request, and user-visible
   errors bounded by quarantined + retry-exhausted.

The long-haul soak (FUZZ_EXAMPLES requests) is marked fuzz+slow:
`make fuzz-chaos` runs it, tier-1 does not.
"""

import dataclasses
import random
import threading
import time
import types

import jax
import numpy as np
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import supervisor
from seldon_tpu.servers.chaos import ChaosConfig, ChaosMonkey
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from seldon_tpu.servers.supervisor import (
    HealSupervisor,
    SentinelError,
    WatchdogError,
)

PROMPT = list(range(2, 26))  # 24 tokens
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=20)
SAMPLED = SamplingParams(temperature=0.9, top_k=8, top_p=0.95,
                         max_new_tokens=20, seed=7)

# The resurrection matrix's serving modes (the migration gate: heal
# must not perturb any substrate it rides).
MODES = {
    "dense": dict(),
    "paged": dict(paged_kv=True, kv_block=16, kv_pool_blocks=9,
                  prompt_buckets=(16, 32)),
    "ragged": dict(paged_kv=True, chunked_prefill=True, prefill_chunk=8,
                   prefix_block=8, kv_block=8, ragged=True),
    "spec": dict(spec_decode=True, spec_k=4, paged_kv=True, kv_block=8,
                 prefix_block=8),
}


def _engine(cfg=None, start=True, **ekw):
    cfg = cfg or get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(q, timeout=120):
    toks, err = [], None
    while True:
        item = q.get(timeout=timeout)
        if item is None:
            return toks, err
        if "error" in item:
            err = item
        else:
            toks.extend(item.get("tokens", []))


def _arm_one_shot_fault(eng, mk):
    """Install `mk` so its NEXT dispatch fault disarms chaos wholesale
    before raising — exactly one injected wave fault, then a clean
    engine (the attribute store is atomic; the scheduler re-reads
    `_chaos` per dispatch)."""
    orig = mk.on_dispatch

    def once(site, rids=()):
        eng._chaos = None
        orig(site, rids)

    mk.on_dispatch = once
    eng._chaos = mk


# ---------------------------------------------------------------------------
# Env gating + construction discipline
# ---------------------------------------------------------------------------


def test_heal_from_env_requires_master_switch(monkeypatch):
    monkeypatch.delenv("HEAL", raising=False)
    monkeypatch.setenv("HEAL_MAX_RETRIES", "7")
    assert supervisor.from_env() is None  # knob without switch: inert

    monkeypatch.setenv("HEAL", "1")
    sup = supervisor.from_env()
    assert sup is not None and sup.max_retries == 7

    monkeypatch.setenv("HEAL_WATCHDOG_MS", "25")
    assert supervisor.from_env().watchdog_ms == 25


def test_heal_build_prefers_config_over_env(monkeypatch):
    monkeypatch.delenv("HEAL", raising=False)
    off = types.SimpleNamespace(heal=False, heal_max_retries=4,
                                heal_watchdog_ms=0)
    assert supervisor.build(off) is None
    on = types.SimpleNamespace(heal=True, heal_max_retries=2,
                               heal_watchdog_ms=30)
    sup = supervisor.build(on)
    assert sup.max_retries == 2 and sup.watchdog_ms == 30


def test_heal_off_engine_has_no_supervisor(monkeypatch):
    monkeypatch.delenv("HEAL", raising=False)
    eng = _engine(start=False)
    assert eng._heal is None
    assert eng.debug_health() is None


def test_engine_config_rejects_unusable_heal_knobs():
    with pytest.raises(ValueError):
        EngineConfig(heal=True, heal_max_retries=0)
    with pytest.raises(ValueError):
        EngineConfig(heal=True, heal_watchdog_ms=-1)


# ---------------------------------------------------------------------------
# Policy unit tests (no engine: the supervisor sees only rids)
# ---------------------------------------------------------------------------


def test_plan_recovery_first_fault_resurrects_everyone():
    sup = HealSupervisor()
    v = sup.plan_recovery([3, 1, 2], now=0.0)
    assert v == {1: "resurrect", 2: "resurrect", 3: "resurrect"}
    assert sup.state == supervisor.RECOVERING


def test_plan_recovery_repeat_replay_is_penned_with_backoff():
    sup = HealSupervisor()
    sup.plan_recovery([1], now=0.0)
    v = sup.plan_recovery([1], now=0.0)
    # A lone recurring rid enters bisection probing itself — either
    # way the verdict must not be an immediate un-delayed resurrect
    # loop; backoff_s grows with the fault streak.
    assert v[1] in ("resurrect", "pen")
    assert sup.backoff_s() > 0.0
    b2 = sup.backoff_s()
    sup.plan_recovery([1], now=0.0)
    assert sup.backoff_s() >= b2  # exponential in the streak


def test_retry_budget_exhaustion_is_terminal():
    sup = HealSupervisor(max_retries=2)
    sup.plan_recovery([5, 6], 0.0)
    sup.plan_recovery([5, 6], 0.0)  # recurs: bisection probes rid 5
    v = sup.plan_recovery([5, 6], 0.0)
    # Rid 5 faulted while probed alone: convicted. Rid 6 charged its
    # third replay against a budget of 2: exhausted, not resurrected.
    assert v[5] == "poison"
    assert v[6] == "exhausted"
    assert sup.retry_exhausted == 1
    assert sup.state == supervisor.DEGRADED
    # Terminal bookkeeping forgets the budget.
    sup.note_done(6)
    assert 6 not in sup.retries


def test_lone_repeat_faulter_is_convicted_not_looped():
    """A single request that faults every wave it rides IS the poison
    case even with no cohort to bisect against: three faults alone
    convict it (probing itself, then recurring) — never an infinite
    resurrect loop."""
    sup = HealSupervisor(max_retries=8)
    sup.plan_recovery([5], 0.0)
    sup.plan_recovery([5], 0.0)
    v = sup.plan_recovery([5], 0.0)
    assert v[5] == "poison"
    assert sup.quarantined == 1 and sup.mode == "normal"


def test_bisection_convicts_the_recurring_faulter():
    sup = HealSupervisor(max_retries=8)
    sup.plan_recovery([1, 2], 0.0)  # fault 1: both resurrect
    v = sup.plan_recovery([1, 2], 0.0)  # fault 2: bisect begins
    assert sup.mode == "bisect"
    assert sorted(v.values()) == ["pen", "resurrect"]
    probe = next(r for r, verdict in v.items() if verdict == "resurrect")
    sup.pen_put(types.SimpleNamespace(
        rid=3 - probe, finished=False), 0.0)
    # Fault 3 recurs with only the probe live: convicted alone.
    v = sup.plan_recovery([probe], 0.0)
    assert v[probe] == "poison"
    assert sup.quarantined == 1 and sup.mode == "normal"
    assert sup.state == supervisor.DEGRADED
    # Conviction flips the penned innocent due for release.
    assert [r.rid for r in sup.pen_take(0.0)] == [3 - probe]


def test_bisection_progress_exonerates_and_advances():
    sup = HealSupervisor(max_retries=8)
    sup.plan_recovery([1, 2, 3, 4], 0.0)
    sup.plan_recovery([1, 2, 3, 4], 0.0)
    assert sup.mode == "bisect" and sup.probing == {1, 2}
    for rid in (3, 4):
        sup.pen_put(types.SimpleNamespace(rid=rid, finished=False), 0.0)
    sup.note_progress(1)
    assert sup.probing == {2}  # half-resolved: still waiting on 2
    sup.note_progress(2)
    # First half exonerated: the next suspects half is probed and its
    # pen entries flip due.
    assert sup.mode == "bisect" and sup.probing == {3}
    assert [r.rid for r in sup.pen_take(0.0)] == [3]
    sup.note_progress(3)
    assert sup.probing == {4}
    sup.note_progress(4)
    # Everyone exonerated: bisection exits, the pen drains.
    assert sup.mode == "normal" and not sup.suspects
    assert [r.rid for r in sup.pen_take(0.0)] == [4]


def test_bisection_note_done_resolves_probe_interest():
    sup = HealSupervisor(max_retries=8)
    sup.plan_recovery([1, 2], 0.0)
    sup.plan_recovery([1, 2], 0.0)
    probe = next(iter(sup.probing))
    sup.note_done(probe)  # probe finished (EOS) while under suspicion
    assert probe not in sup.suspects
    assert sup.probing == {3 - probe}


def test_pen_backoff_release_flush_and_finished_drop():
    sup = HealSupervisor()
    sup.plan_recovery([1], 0.0)
    sup.plan_recovery([1], 0.0)
    sup._exit_bisect_locked()  # force backoff-pen mode for the test
    sup.mode = "normal"
    r1 = types.SimpleNamespace(rid=1, finished=False)
    r2 = types.SimpleNamespace(rid=2, finished=False)
    sup.pen_put(r1, now=10.0)
    assert sup.pen_take(10.0) == []  # backoff not elapsed
    assert sup.pen_take(10.0 + supervisor._BACKOFF_MAX_S) == [r1]
    sup.pen_put(r2, now=10.0)
    assert sup.pen_take(10.0, flush=True) == [r2]  # drain releases all
    r3 = types.SimpleNamespace(rid=3, finished=True)
    sup.pen_put(r3, now=10.0)
    assert sup.pen_take(10.0, flush=True) == []  # reaped while penned
    assert sup.pen_empty()
    assert [r.rid for r in sup.pen_scan()] == []


def test_clean_boundary_streak_walks_back_to_healthy():
    sup = HealSupervisor()
    sup.plan_recovery([1], 0.0)
    assert sup.state == supervisor.RECOVERING and sup.pressure() == 0.5
    for _ in range(supervisor.CLEAN_BOUNDARIES_FOR_HEALTHY):
        sup.note_boundary_ok()
    assert sup.state == supervisor.HEALTHY and sup.pressure() == 0.0
    assert sup.consec_faults == 0


def test_watchdog_bounds_a_hung_fetch_and_recovers():
    sup = HealSupervisor(watchdog_ms=40)
    with pytest.raises(WatchdogError):
        sup.bounded_fetch(lambda: time.sleep(2.0))
    assert sup.watchdog_trips == 1
    # The wedged worker was abandoned wholesale: a fresh call gets a
    # fresh worker and the orphan result can never collide.
    assert sup.bounded_fetch(lambda: 7) == 7

    def boom():
        raise ValueError("from the fetch")

    with pytest.raises(ValueError):  # worker exceptions propagate
        sup.bounded_fetch(boom)
    assert sup.watchdog_trips == 1


def test_watchdog_zero_runs_inline():
    sup = HealSupervisor(watchdog_ms=0)
    assert sup.bounded_fetch(lambda: 11) == 11
    assert sup._wd_thread is None  # no helper thread was ever spawned


def test_sentinel_flags_out_of_vocab_ids():
    sup = HealSupervisor()
    ok_admit = [(np.array([3, 250]), np.array([1.0]))]
    sup.check_tokens(ok_admit, None, vocab_size=256)
    assert sup.sentinel_trips == 0
    with pytest.raises(SentinelError):
        sup.check_tokens(
            [(np.array([3, 1 << 30]), None)], None, vocab_size=256)
    with pytest.raises(SentinelError):
        sup.check_tokens([(np.array([-1]), None)], None, vocab_size=256)
    with pytest.raises(SentinelError):  # chunk-side tokens screened too
        sup.check_tokens([], (np.array([999]),), vocab_size=256)
    assert sup.sentinel_trips == 3


# ---------------------------------------------------------------------------
# Bit-identical resurrection: the migration gate
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
@pytest.mark.parametrize("mode", sorted(MODES))
def test_resurrection_bit_identical_across_modes(mode, kv_dtype):
    """A mid-stream wave fault under HEAL: both live streams (greedy
    AND sampled) are resurrected and their delivered tokens match the
    fault-free reference exactly — per-position sampling keys make the
    replayed continuation bit-identical on every substrate x KV
    dtype."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    ekw = MODES[mode]
    ref = _engine(cfg, **ekw)
    try:
        want_g = ref.generate_blocking(PROMPT, GREEDY)["token_ids"]
        want_s = ref.generate_blocking(PROMPT, SAMPLED)["token_ids"]
    finally:
        ref.stop()

    eng = _engine(cfg, heal=True, **ekw)
    try:
        qg = eng.submit(PROMPT, GREEDY)
        qs = eng.submit(PROMPT, SAMPLED)
        got_g = list(qg.get(timeout=120)["tokens"])
        got_s = list(qs.get(timeout=120)["tokens"])
        _arm_one_shot_fault(
            eng, ChaosMonkey(ChaosConfig(seed=0, dispatch_fail=1.0)))
        tg, eg = _collect(qg)
        ts, es = _collect(qs)
        assert eg is None and es is None, (eg, es)
        got_g += tg
        got_s += ts
        health = eng.debug_health()
        assert health["recoveries"] >= 1, \
            "the one-shot fault never fired — the gate is inert"
        assert health["resurrected"] >= 1
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
    assert got_g == want_g, "greedy resurrection diverged"
    assert got_s == want_s, "sampled resurrection diverged"


# ---------------------------------------------------------------------------
# Poison quarantine: bisection isolates the seeded culprit
# ---------------------------------------------------------------------------


def test_poison_bisection_isolates_sticky_culprit():
    """A sticky chaos fault pins rid 3: every decode wave it rides
    faults, deterministically. The bisection must convict exactly that
    request (kind="poison", non-retriable) while rids 1, 2 and 4 all
    complete bit-identically."""
    ref = _engine()
    try:
        want = ref.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        ref.stop()

    eng = _engine(heal=True, heal_max_retries=8,
                  chaos=ChaosConfig(seed=0, sticky_rid=3))
    try:
        qs = [eng.submit(PROMPT, GREEDY) for _ in range(4)]
        results = [_collect(q, timeout=300) for q in qs]
        for i, (toks, err) in enumerate(results):
            rid = i + 1  # rids are assigned sequentially from 1
            if rid == 3:
                assert err is not None, "the sticky request completed?!"
                assert err["kind"] == "poison", err
                assert err["retriable"] is False
            else:
                assert err is None, (rid, err)
                assert toks == want, f"innocent rid {rid} diverged"
        health = eng.debug_health()
        assert health["quarantined"] == 1
        # The conviction marked the engine degraded; the innocents'
        # clean decode streak afterwards may already have walked the
        # state machine back (note_boundary_ok) — both are legal here,
        # what matters is the quarantine counter above is permanent.
        assert health["state"] in ("degraded", "healthy")
        assert health["mode"] == "normal"  # bisection resolved
        assert eng.chaos_counts()["sticky_faults"] >= 2
        assert eng.debug_lifecycle_check() == {}
        # The engine is fully live post-quarantine (rid 5 > sticky).
        assert eng.generate_blocking(PROMPT, GREEDY)["token_ids"] == want
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Watchdog + sentinel at engine level
# ---------------------------------------------------------------------------


def test_watchdog_turns_hung_fetch_into_recovery():
    """One injected fetch hang, longer than heal_watchdog_ms: the wave
    is declared faulted and resurrected instead of wedging the
    scheduler — the stream still completes bit-identically."""
    ref = _engine()
    try:
        want = ref.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        ref.stop()

    eng = _engine(heal=True, heal_watchdog_ms=60)
    try:
        q = eng.submit(PROMPT, GREEDY)
        got = list(q.get(timeout=120)["tokens"])
        mk = ChaosMonkey(ChaosConfig(seed=0, hang=1.0, hang_ms=1000))
        orig = mk.maybe_hang

        def once():
            eng._chaos = None  # one-shot: disarm before the sleep
            orig()

        mk.maybe_hang = once
        eng._chaos = mk
        toks, err = _collect(q)
        assert err is None, err
        got += toks
        health = eng.debug_health()
        assert health["watchdog_trips"] >= 1
        assert health["recoveries"] >= 1
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
    assert got == want, "post-watchdog resurrection diverged"


def test_sentinel_quarantines_corrupt_tokens_before_delivery():
    """One injected out-of-vocab token id in a fetched boundary: the
    sentinel trips recovery BEFORE the corrupt id reaches the client —
    the delivered stream is still exactly the reference."""
    ref = _engine()
    try:
        want = ref.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        ref.stop()

    eng = _engine(heal=True)
    try:
        q = eng.submit(PROMPT, GREEDY)
        got = list(q.get(timeout=120)["tokens"])
        mk = ChaosMonkey(ChaosConfig(seed=0, nan_inject=1.0))
        orig = mk.poison_fetch

        def once(arrays):
            eng._chaos = None  # one-shot: disarm before poisoning
            orig(arrays)

        mk.poison_fetch = once
        eng._chaos = mk
        toks, err = _collect(q)
        assert err is None, err
        got += toks
        health = eng.debug_health()
        assert health["sentinel_trips"] >= 1
        assert health["recoveries"] >= 1
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
    assert got == want, "post-sentinel resurrection diverged"
    assert all(0 <= t < get_config("tiny").vocab_size for t in got), \
        "a corrupt token id reached the client"


# ---------------------------------------------------------------------------
# Retry budget at engine level
# ---------------------------------------------------------------------------


def test_retry_budget_exhaustion_fails_cleanly():
    """A permanently faulting device (dispatch_fail=1.0, never
    disarmed): resurrection retries up to heal_max_retries, then fails
    the request retriable=False — chaos off again, the engine serves
    bit-identical output and nothing leaked. (Budget 1 so exhaustion
    fires before the lone-faulter bisection can convict it as poison.)"""
    eng = _engine(heal=True, heal_max_retries=1)
    try:
        want = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        q = eng.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=40))
        first = q.get(timeout=120)
        assert "error" not in first
        eng._chaos = ChaosMonkey(ChaosConfig(seed=0, dispatch_fail=1.0))
        toks, err = _collect(q, timeout=300)
        assert err is not None, "exhausted request must error, not hang"
        assert err["kind"] == "internal"
        assert err["retriable"] is False
        assert "exhausted" in err["error"]
        health = eng.debug_health()
        assert health["retry_exhausted"] >= 1
        assert health["state"] == "degraded"

        eng._chaos = None
        assert eng.generate_blocking(PROMPT, GREEDY)["token_ids"] == want
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Chaos + heal soak: the acceptance invariants
# ---------------------------------------------------------------------------


def _run_soak(eng, n, seed, deadline_frac=0.1, cancel_frac=0.1):
    """Submit n requests with injected client behavior (deadlines,
    mid-stream cancels); classify every request into exactly one
    outcome. All randomness is main-thread, drawn before submit, so a
    fixed seed replays the same request stream."""
    rng = random.Random(seed)
    outcomes = {"completed": 0, "shed": 0, "deadline": 0,
                "cancelled": 0, "errored": 0}
    lock = threading.Lock()
    threads = []

    def record(kind):
        with lock:
            outcomes[kind] += 1

    def consume(q, want_cancel):
        err = None
        sent_cancel = False
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            if "error" in item:
                err = item
                continue
            if want_cancel and not sent_cancel:
                sent_cancel = True
                eng.cancel(q.rid)
        if err is None:
            record("completed")
        else:
            kind = err.get("kind", "internal")
            if kind in ("deadline", "cancelled"):
                record(kind)
            elif kind in ("capacity", "draining", "shutdown"):
                record("shed")
            else:
                record("errored")  # internal/poison/preempted: visible

    for i in range(n):
        plen = rng.choice((5, 8, 13, 21))
        prompt = [2 + (i + j) % 200 for j in range(plen)]
        dl = rng.choice((30, 80)) if rng.random() < deadline_frac else 0
        want_cancel = rng.random() < cancel_frac
        sp = SamplingParams(temperature=0.0,
                            max_new_tokens=rng.choice((4, 8)),
                            deadline_ms=dl)
        try:
            q = eng.submit(prompt, sp)
        except RuntimeError:  # EngineOverloaded / EngineDraining
            record("shed")
            continue
        t = threading.Thread(target=consume, args=(q, want_cancel),
                             daemon=True)
        t.start()
        threads.append(t)

    stop_by = time.monotonic() + 300
    hung = 0
    for t in threads:
        t.join(timeout=max(0.0, stop_by - time.monotonic()))
        if t.is_alive():
            hung += 1
    return outcomes, hung


def _heal_soak_engine(n, paged, seed):
    ekw = dict(
        max_slots=8,
        max_queue=4 * n,
        heal=True,
        heal_max_retries=3,
        heal_watchdog_ms=250,
        chaos=ChaosConfig(
            seed=seed,
            dispatch_fail=0.02,
            alloc_fail=0.05 if paged else 0.0,
            slow_boundary=0.05,
            slow_ms=2.0,
            disconnect=0.01,
            nan_inject=0.01,
            hang=0.01,
            hang_ms=400.0,
        ),
    )
    if paged:
        ekw.update(paged_kv=True, kv_block=16, kv_pool_blocks=24,
                   prompt_buckets=(16, 32))
    return _engine(**ekw)


def _assert_soak_invariants(eng, outcomes, hung, n):
    assert hung == 0, f"{hung} waiters never saw a sentinel"
    assert sum(outcomes.values()) == n, outcomes
    assert outcomes["completed"] > 0, outcomes
    health = eng.debug_health()
    # The heal contract: a wave fault is not a user-visible error.
    # The only requests a healing engine may fail for engine-side
    # reasons are quarantined poisons, exhausted retries, and paged
    # preemptions (retriable capacity pushback, not a fault).
    preempted = eng.stats.snapshot().get("preemptions", 0)
    budget = (health["quarantined"] + health["retry_exhausted"]
              + preempted)
    assert outcomes["errored"] <= budget, (outcomes, health)
    assert eng.drain(timeout=120) is True
    assert eng.debug_lifecycle_check() == {}
    faults = eng.chaos_counts()
    assert sum(faults.values()) > 0, "chaos never fired — soak is inert"


def test_heal_soak_80_requests_bounded_visible_errors():
    """Tier-1 soak: 80 mixed requests under seeded chaos WITH heal —
    zero hung waiters, one outcome each, user-visible errors bounded
    by quarantine + budget exhaustion (+ preemption), empty accounting
    after drain."""
    n = 80
    eng = _heal_soak_engine(n, paged=False, seed=0)
    try:
        outcomes, hung = _run_soak(eng, n, seed=0)
        _assert_soak_invariants(eng, outcomes, hung, n)
    finally:
        eng.stop()


@pytest.mark.fuzz
@pytest.mark.slow
@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_heal_soak_long_haul(paged):
    """FUZZ_EXAMPLES-scaled heal soak (make fuzz-chaos); CHAOS_SEED
    replays a fault sequence exactly."""
    import os

    n = int(os.environ.get("FUZZ_EXAMPLES", "300"))
    seed = int(os.environ.get("CHAOS_SEED", "0"))
    eng = _heal_soak_engine(n, paged=paged, seed=seed)
    try:
        outcomes, hung = _run_soak(eng, n, seed=seed,
                                   deadline_frac=0.15, cancel_frac=0.15)
        _assert_soak_invariants(eng, outcomes, hung, n)
    finally:
        eng.stop()
