"""Every example CR is valid; the locally-runnable ones serve for real.

Tier 1 (fast): parse -> default -> validate -> build manifests for every
yaml under examples/. Tier 2 (e2e marker): apply the iris-sklearn,
mlflow and A/B-bandit examples through LocalProcessStore with modelUri
rewritten to generated local artifacts, then predict over live HTTP and
fuzz with the shipped contract fixture."""

import copy
import glob
import json
import os
import pickle
import urllib.request

import numpy as np
import pytest
import yaml

from seldon_tpu.operator import Reconciler, SeldonDeployment
from seldon_tpu.operator.reconciler import InMemoryStore
from seldon_tpu.operator.webhook import default_deployment, validate_deployment

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    glob.glob(os.path.join(REPO, "examples", "**", "*.yaml"), recursive=True)
)


def _load(path):
    with open(path) as f:
        return yaml.safe_load(f)


def test_examples_exist():
    names = {os.path.basename(p) for p in EXAMPLES}
    assert {"iris-sklearn.yaml", "iris-xgboost.yaml", "mlflow-elasticnet.yaml",
            "llama3-8b-jaxserver.yaml", "abtest-mab.yaml",
            "shadow-canary.yaml", "outlier-transformer.yaml"} <= names


@pytest.mark.parametrize("path", EXAMPLES, ids=os.path.basename)
def test_example_cr_valid_and_buildable(path):
    doc = _load(path)
    assert doc["apiVersion"].startswith("machinelearning.seldon.io/")
    sdep = SeldonDeployment.from_dict(doc)
    default_deployment(sdep)
    problems = validate_deployment(sdep)
    assert problems == [], f"{path}: {problems}"
    manifests = Reconciler(InMemoryStore()).desired_manifests(sdep)
    kinds = {m["kind"] for m in manifests}
    assert "Deployment" in kinds
    assert "Service" in kinds
    # TPU block materializes as google.com/tpu resources.
    if "llama3" in path:
        dep = next(m for m in manifests if m["kind"] == "Deployment")
        containers = dep["spec"]["template"]["spec"]["containers"]
        tpu = [c for c in containers
               if c.get("resources", {}).get("limits", {}).get("google.com/tpu")]
        assert tpu, "jaxserver unit should request google.com/tpu"


def test_contract_fixtures_generate():
    from seldon_tpu.runtime.tester import generate_batch

    for path in glob.glob(os.path.join(REPO, "examples", "contracts", "*.json")):
        with open(path) as f:
            contract = json.load(f)
        batch, names = generate_batch(contract, 4)
        assert batch.shape[0] == 4
        assert len(names) == batch.shape[1]


# --- tier 2: really serve them ---------------------------------------------

pytest_e2e = pytest.mark.e2e


def _post(port, path, body, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _iris_sklearn_artifact(dirpath):
    """Logistic-ish 3-class linear model in portable npz form."""
    from seldon_tpu.servers.sklearnserver import export_linear_model

    coef = np.array([[1.0, 0.2, -0.5, -1.0], [-0.5, 0.1, 0.8, 0.2],
                     [-0.5, -0.3, -0.3, 0.8]])
    export_linear_model(dirpath, coef, np.zeros(3),
                        classes=["setosa", "versicolor", "virginica"])


def _iris_xgb_artifact(dirpath):
    os.makedirs(dirpath, exist_ok=True)
    trees = [json.dumps({
        "nodeid": 0, "split": "f2", "split_condition": 2.5,
        "yes": 1, "no": 2, "missing": 1,
        "children": [
            {"nodeid": 1, "leaf": 0.5},
            {"nodeid": 2, "leaf": -0.5},
        ],
    })]
    with open(os.path.join(dirpath, "model.json"), "w") as f:
        json.dump({"trees": trees, "objective": "binary:logistic",
                   "base_score": 0.5}, f)


def _mlflow_artifact(dirpath):
    from sklearn.linear_model import Ridge

    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 11))
    y = X @ rng.normal(size=11) + 5.0
    reg = Ridge().fit(X, y)
    with open(os.path.join(dirpath, "model.pkl"), "wb") as f:
        pickle.dump(reg, f)
    with open(os.path.join(dirpath, "MLmodel"), "w") as f:
        f.write("flavors:\n  sklearn:\n    pickled_model: model.pkl\n")


def _apply_and_serve(doc, tmp_path, rewrites):
    """Rewrite modelUris to local artifacts, reconcile via
    LocalProcessStore, return (store, engine_port)."""
    from seldon_tpu.operator.localstore import LocalProcessStore

    doc = copy.deepcopy(doc)

    def rewrite(unit):
        if unit.get("modelUri") and unit["name"] in rewrites:
            unit["modelUri"] = "file://" + rewrites[unit["name"]]
        for ch in unit.get("children") or []:
            rewrite(ch)

    for pred in doc["spec"]["predictors"]:
        rewrite(pred["graph"])
        pred["replicas"] = 1
    sdep = SeldonDeployment.from_dict(doc)
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    import time

    deadline = time.time() + 120
    while time.time() < deadline:
        status = rec.reconcile(sdep)
        if status.state == "Available":
            break
        if status.state == "Failed":
            store.close()
            raise AssertionError(f"reconcile failed: {status}")
        store.wait_ready(30)
    else:
        store.close()
        raise AssertionError(f"never Available: {status}")
    dep_name = next(
        m["metadata"]["name"] for m in store.list("Deployment", "default")
    )
    return store, store.engine_port(dep_name)


@pytest.mark.e2e
def test_iris_sklearn_example_serves(tmp_path):
    art = str(tmp_path / "iris")
    _iris_sklearn_artifact(art)
    doc = _load(os.path.join(REPO, "examples", "models", "iris-sklearn.yaml"))
    store, port = _apply_and_serve(doc, tmp_path, {"classifier": art})
    try:
        out = _post(port, "/api/v0.1/predictions",
                    {"data": {"ndarray": [[6.0, 3.0, 1.4, 0.2]]}})
        probs = out["data"]["ndarray"][0]
        assert len(probs) == 3
        assert abs(sum(probs) - 1.0) < 1e-4
        # Contract fuzz through the live engine (the shipped fixture).
        from seldon_tpu.runtime.tester import generate_batch

        with open(os.path.join(REPO, "examples", "contracts",
                               "iris_contract.json")) as f:
            contract = json.load(f)
        for i in range(5):
            batch, _ = generate_batch(contract, 3)
            out = _post(port, "/api/v0.1/predictions",
                        {"data": {"ndarray": batch.tolist()}})
            arr = np.asarray(out["data"]["ndarray"], dtype=float)
            assert arr.shape == (3, 3)
            assert ((arr >= 0) & (arr <= 1)).all()
    finally:
        store.close()


@pytest.mark.e2e
def test_mlflow_example_serves(tmp_path):
    art = str(tmp_path / "wine")
    _mlflow_artifact(art)
    doc = _load(os.path.join(REPO, "examples", "models",
                             "mlflow-elasticnet.yaml"))
    store, port = _apply_and_serve(doc, tmp_path, {"regressor": art})
    try:
        # First request triggers the unit's lazy load (unpickle sklearn +
        # jit the linear path) — generous timeout.
        out = _post(port, "/api/v0.1/predictions",
                    {"data": {"ndarray": [[0.0] * 11]}}, timeout=90)
        assert len(out["data"]["ndarray"]) == 1
    finally:
        store.close()


@pytest.mark.e2e
def test_abtest_mab_example_routes_and_learns(tmp_path):
    iris = str(tmp_path / "iris")
    _iris_sklearn_artifact(iris)
    xgb = str(tmp_path / "xgb")
    _iris_xgb_artifact(xgb)
    doc = _load(os.path.join(REPO, "examples", "graphs", "abtest-mab.yaml"))
    store, port = _apply_and_serve(
        doc, tmp_path, {"model-a": iris, "model-b": xgb}
    )
    try:
        routed = set()
        for i in range(12):
            # Generous timeout: the first hit on each branch pays that
            # unit's lazy model load + jit.
            out = _post(port, "/api/v0.1/predictions",
                        {"data": {"ndarray": [[5.0, 3.0, 1.5, 0.2]]}},
                        timeout=90)
            path = out["meta"]["requestPath"]
            assert "eg-router" in path
            routed.update(n for n in path if n.startswith("model-"))
            # Reward the served branch so the bandit keeps learning.
            _post(port, "/api/v0.1/feedback",
                  {"request": {"data": {"ndarray": [[5.0, 3.0, 1.5, 0.2]]}},
                   "response": out, "reward": 1.0})
        assert routed, "router never routed to a model"
    finally:
        store.close()


@pytest.mark.e2e
def test_case_study_mab_converges(tmp_path):
    """The runnable MAB case study (examples/case_study_mab.py — the
    reference's credit_card_default notebook counterpart): bandit must
    route the majority of traffic to the measurably better arm."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "case_study_mab",
        os.path.join(REPO, "examples", "case_study_mab.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    good_dir, weak_dir, acc_good = mod.train_arms(str(tmp_path))
    assert acc_good > 0.8
    store, port = mod.deploy(good_dir, weak_dir)
    try:
        served, acc = mod.run_stream(port, n=200)
        share = served["model-good"] / sum(served.values())
        assert share > 0.5, served
    finally:
        store.close()
