"""Parity servers: sklearn (jax linear), xgboost (jax tree traversal),
tfproxy (REST bridge against a fake TF-Serving endpoint)."""

import json
import threading

import numpy as np
import pytest

from seldon_tpu.ops import trees
from seldon_tpu.servers.sklearnserver import SKLearnServer, export_linear_model
from seldon_tpu.servers.xgboostserver import XGBoostServer


# ---------------------------------------------------------------------------
# Tree ensemble evaluator
# ---------------------------------------------------------------------------

# A 2-tree ensemble in xgboost dump format:
# tree0: f0 < 0.5 ? leaf 1.0 : (f1 < 2.0 ? leaf -1.0 : leaf 3.0)
# tree1: f1 < 1.0 ? leaf 0.5 : leaf -0.5
TREE0 = {
    "nodeid": 0, "split": "f0", "split_condition": 0.5, "yes": 1, "no": 2,
    "children": [
        {"nodeid": 1, "leaf": 1.0},
        {"nodeid": 2, "split": "f1", "split_condition": 2.0, "yes": 3,
         "no": 4, "children": [
             {"nodeid": 3, "leaf": -1.0},
             {"nodeid": 4, "leaf": 3.0},
         ]},
    ],
}
TREE1 = {
    "nodeid": 0, "split": "f1", "split_condition": 1.0, "yes": 1, "no": 2,
    "children": [{"nodeid": 1, "leaf": 0.5}, {"nodeid": 2, "leaf": -0.5}],
}


def manual_predict(x):
    t0 = 1.0 if x[0] < 0.5 else (-1.0 if x[1] < 2.0 else 3.0)
    t1 = 0.5 if x[1] < 1.0 else -0.5
    return t0 + t1


def test_tree_ensemble_matches_manual():
    ens = trees.from_xgboost_json([json.dumps(TREE0), json.dumps(TREE1)])
    X = np.array(
        [[0.0, 0.0], [1.0, 0.0], [1.0, 2.5], [0.4, 5.0], [0.6, 1.5]],
        np.float32,
    )
    out = np.asarray(trees.predict(ens, X))
    expected = np.array([manual_predict(x) for x in X])
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_tree_ensemble_binary_objective():
    ens = trees.from_xgboost_json([json.dumps(TREE1)])
    out = np.asarray(trees.predict(ens, np.array([[0.0, 0.0]]), "binary"))
    assert 0.0 < out[0] < 1.0


# ---------------------------------------------------------------------------
# XGBoostServer on the jax path
# ---------------------------------------------------------------------------


def test_xgboost_server_json(tmp_path):
    model_dir = tmp_path / "xgb"
    model_dir.mkdir()
    (model_dir / "model.json").write_text(
        json.dumps({"trees": [TREE0, TREE1], "objective": "reg",
                    "base_score": 0.0})
    )
    srv = XGBoostServer(model_uri=str(model_dir))
    srv.load()
    out = srv.predict(np.array([[0.0, 0.0]], np.float32), [])
    np.testing.assert_allclose(out, [1.5], rtol=1e-6)
    assert srv.tags()["backend"] == "jax-trees"


def test_xgboost_server_logistic_base_score(tmp_path):
    # xgboost's stored base_score for binary:logistic is in PROBABILITY
    # space: 0.5 must contribute margin logit(0.5)=0, not +0.5.
    model_dir = tmp_path / "xgb"
    model_dir.mkdir()
    (model_dir / "model.json").write_text(
        json.dumps({"trees": [TREE0, TREE1], "objective": "binary:logistic",
                    "base_score": 0.5})
    )
    srv = XGBoostServer(model_uri=str(model_dir))
    srv.load()
    out = srv.predict(np.array([[0.0, 0.0]], np.float32), [])
    # margins sum to 1.5; sigmoid(1.5 + logit(0.5)) == sigmoid(1.5)
    np.testing.assert_allclose(out, [1.0 / (1.0 + np.exp(-1.5))], rtol=1e-6)


# ---------------------------------------------------------------------------
# SKLearnServer on the jax path
# ---------------------------------------------------------------------------


def test_sklearn_server_npz_logistic(tmp_path):
    # 3-class logistic: coef [3, 2].
    coef = np.array([[1.0, 0.0], [0.0, 1.0], [-1.0, -1.0]])
    intercept = np.array([0.0, 0.0, 0.0])
    export_linear_model(str(tmp_path), coef, intercept,
                        classes=["a", "b", "c"])
    srv = SKLearnServer(model_uri=str(tmp_path))
    srv.load()
    probs = srv.predict(np.array([[5.0, 0.0]], np.float32), [])
    assert probs.shape == (1, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)
    assert np.argmax(probs[0]) == 0  # feature favors class a
    assert srv.class_names() == ["a", "b", "c"]

    srv2 = SKLearnServer(model_uri=str(tmp_path), method="predict")
    srv2.load()
    labels = srv2.predict(np.array([[0.0, 5.0]], np.float32), [])
    # sklearn's model.predict() returns class LABELS, not argmax indices.
    assert labels[0] == "b"


def test_sklearn_server_binary_sigmoid(tmp_path):
    export_linear_model(str(tmp_path), np.array([[2.0, -1.0]]),
                        np.array([0.5]))
    srv = SKLearnServer(model_uri=str(tmp_path))
    srv.load()
    probs = srv.predict(np.array([[1.0, 1.0]], np.float32), [])
    assert probs.shape == (1, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


# ---------------------------------------------------------------------------
# MLFlowServer: native MLmodel parsing, no mlflow installed
# (reference servers/mlflowserver/mlflowserver/MLFlowServer.py:12-49)
# ---------------------------------------------------------------------------


def _write_mlflow_dir(tmp_path, model, flavor_yaml: str,
                      pkl_name="model.pkl"):
    import pickle

    (tmp_path / pkl_name).write_bytes(pickle.dumps(model))
    (tmp_path / "MLmodel").write_text(flavor_yaml)


def test_mlflow_sklearn_flavor_without_mlflow(tmp_path):
    """sklearn-flavor mlflow dir serves natively (mlflow absent in this
    image by design); logistic models ride the jitted linear path and
    match sklearn's own predict_proba."""
    import sys

    assert "mlflow" not in sys.modules
    from sklearn.linear_model import LogisticRegression

    from seldon_tpu.servers.mlflowserver import MLFlowServer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(80, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    clf = LogisticRegression().fit(X, y)
    _write_mlflow_dir(
        tmp_path, clf,
        "flavors:\n"
        "  python_function:\n"
        "    loader_module: mlflow.sklearn\n"
        "    model_path: model.pkl\n"
        "  sklearn:\n"
        "    pickled_model: model.pkl\n"
        "    serialization_format: cloudpickle\n"
        "    sklearn_version: 1.9.0\n",
    )
    srv = MLFlowServer(model_uri=str(tmp_path), method="predict_proba")
    srv.load()
    assert srv._predict_jit is not None  # linear fast path engaged
    Xt = rng.normal(size=(5, 3)).astype(np.float32)
    np.testing.assert_allclose(
        srv.predict(Xt, []), clf.predict_proba(Xt), rtol=2e-3, atol=2e-4
    )
    labels = MLFlowServer(model_uri=str(tmp_path), method="predict")
    np.testing.assert_array_equal(labels.predict(Xt, []), clf.predict(Xt))


def test_mlflow_pyfunc_descriptor_only(tmp_path):
    """python_function-only descriptor (loader_module mlflow.sklearn)
    resolves to the same native loader; regressors return 1-D output."""
    from sklearn.linear_model import Ridge

    from seldon_tpu.servers.mlflowserver import MLFlowServer

    rng = np.random.default_rng(1)
    X = rng.normal(size=(60, 4))
    y = X @ np.array([1.0, -2.0, 0.5, 0.0]) + 3.0
    reg = Ridge().fit(X, y)
    _write_mlflow_dir(
        tmp_path, reg,
        "flavors:\n"
        "  python_function:\n"
        "    loader_module: mlflow.sklearn\n"
        "    model_path: model.pkl\n",
    )
    srv = MLFlowServer(model_uri=str(tmp_path))
    Xt = rng.normal(size=(7, 4)).astype(np.float32)
    out = srv.predict(Xt, [])
    assert out.shape == (7,)
    np.testing.assert_allclose(out, reg.predict(Xt), rtol=1e-3, atol=1e-3)


def test_mlflow_nonlinear_estimator_falls_back_to_sklearn(tmp_path):
    """Tree models (no coef_) predict through the unpickled estimator."""
    from sklearn.ensemble import RandomForestClassifier

    from seldon_tpu.servers.mlflowserver import MLFlowServer

    rng = np.random.default_rng(2)
    X = rng.normal(size=(50, 3))
    y = (X[:, 0] > 0).astype(int)
    clf = RandomForestClassifier(n_estimators=5, random_state=0).fit(X, y)
    _write_mlflow_dir(
        tmp_path, clf,
        "flavors:\n  sklearn:\n    pickled_model: model.pkl\n",
    )
    srv = MLFlowServer(model_uri=str(tmp_path), method="predict_proba")
    Xt = rng.normal(size=(4, 3))
    np.testing.assert_allclose(srv.predict(Xt, []), clf.predict_proba(Xt))


def test_mlflow_margin_classifier_no_jit_path(tmp_path):
    """LinearSVC has coef_/classes_ but no predict_proba: the jitted
    softmax path must NOT engage (it would argmax a [B,1] margin column
    to constant class 0); predictions route through the estimator."""
    from sklearn.svm import LinearSVC

    from seldon_tpu.servers.mlflowserver import MLFlowServer

    rng = np.random.default_rng(3)
    X = rng.normal(size=(60, 3))
    y = (X[:, 0] - X[:, 2] > 0).astype(int)
    clf = LinearSVC().fit(X, y)
    _write_mlflow_dir(
        tmp_path, clf,
        "flavors:\n  sklearn:\n    pickled_model: model.pkl\n",
    )
    srv = MLFlowServer(model_uri=str(tmp_path), method="predict")
    Xt = rng.normal(size=(8, 3))
    srv.predict(Xt, [])
    assert srv._predict_jit is None
    np.testing.assert_array_equal(srv.predict(Xt, []), clf.predict(Xt))


def test_mlflow_glm_keeps_inverse_link(tmp_path):
    """PoissonRegressor exposes coef_/intercept_ but predict() applies
    exp(link): the raw-matmul fast path must NOT engage, or the server
    would silently return log-space values."""
    from sklearn.linear_model import PoissonRegressor

    from seldon_tpu.servers.mlflowserver import MLFlowServer

    rng = np.random.default_rng(4)
    X = rng.normal(size=(80, 3))
    y = rng.poisson(np.exp(0.3 * X[:, 0] + 1.0))
    reg = PoissonRegressor().fit(X, y)
    _write_mlflow_dir(
        tmp_path, reg,
        "flavors:\n  sklearn:\n    pickled_model: model.pkl\n",
    )
    srv = MLFlowServer(model_uri=str(tmp_path))
    Xt = rng.normal(size=(6, 3))
    out = srv.predict(Xt, [])
    assert srv._predict_jit is None
    np.testing.assert_allclose(out, reg.predict(Xt))
    assert (out > 0).all()  # rate space, not log space


def test_mlflow_exotic_flavor_clear_error(tmp_path):
    from seldon_tpu.servers.mlflowserver import MLFlowServer

    (tmp_path / "MLmodel").write_text(
        "flavors:\n  pytorch:\n    model_data: data\n"
    )
    srv = MLFlowServer(model_uri=str(tmp_path))
    with pytest.raises(RuntimeError, match="pytorch"):
        srv.load()


# ---------------------------------------------------------------------------
# TFServingProxy against a fake TF-Serving REST endpoint
# ---------------------------------------------------------------------------


def test_tfproxy_rest_roundtrip():
    import http.server

    class FakeTFS(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(
                self.rfile.read(int(self.headers["Content-Length"]))
            )
            instances = body["instances"]
            out = {"predictions": (np.asarray(instances) * 3.0).tolist()}
            data = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), FakeTFS)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        from seldon_tpu.servers.tfproxy import TFServingProxy

        proxy = TFServingProxy(
            rest_endpoint=f"http://127.0.0.1:{port}", model_name="m"
        )
        out = proxy.predict(np.array([[1.0, 2.0]]), [])
        np.testing.assert_allclose(out, [[3.0, 6.0]])
    finally:
        httpd.shutdown()
