"""Microservice CLI, persistence, SeldonClient, contract tester.

Mirrors reference python/tests/test_microservice.py (spawns a real
subprocess and hits it with the contract tester)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from seldon_tpu.client import SeldonClient
from seldon_tpu.runtime.microservice import parse_parameters
from seldon_tpu.runtime import persistence
from seldon_tpu.runtime.tester import (
    generate_batch,
    run_contract_test,
    validate_response,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL_APP = """
import numpy as np

class EchoScaler:
    def __init__(self, factor=2.0):
        self.factor = float(factor)

    def predict(self, X, names, meta=None):
        return np.asarray(X) * self.factor

    def tags(self):
        return {"m": "echo"}
"""

CONTRACT = {
    "features": [
        {"name": "f1", "dtype": "FLOAT", "ftype": "continuous", "range": [0, 1]},
        {"name": "f2", "dtype": "FLOAT", "ftype": "continuous", "range": [0, 1]},
    ],
    "targets": [
        {"name": "o1", "dtype": "FLOAT", "ftype": "continuous",
         "range": [0, 3], "repeat": 2},
    ],
}


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_parse_parameters():
    raw = json.dumps(
        [
            {"name": "a", "value": "3", "type": "INT"},
            {"name": "b", "value": "0.5", "type": "FLOAT"},
            {"name": "c", "value": "true", "type": "BOOL"},
            {"name": "d", "value": "x", "type": "STRING"},
        ]
    )
    assert parse_parameters(raw) == {"a": 3, "b": 0.5, "c": True, "d": "x"}


@pytest.fixture(scope="module")
def microservice(tmp_path_factory):
    """Real subprocess running the CLI on a user model file."""
    workdir = tmp_path_factory.mktemp("app")
    (workdir / "EchoScaler.py").write_text(MODEL_APP)
    http_port, grpc_port = _free_port(), _free_port()
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        PREDICTIVE_UNIT_PARAMETERS=json.dumps(
            [{"name": "factor", "value": "2.0", "type": "FLOAT"}]
        ),
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "seldon_tpu.runtime.microservice",
            "EchoScaler", "--api-type", "REST,GRPC",
            "--http-port", str(http_port), "--grpc-port", str(grpc_port),
            "--host", "127.0.0.1",
        ],
        cwd=workdir,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    # Wait for readiness.
    deadline = time.time() + 30
    ready = False
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", http_port), 0.2):
                ready = True
                break
        except OSError:
            if proc.poll() is not None:
                out = proc.stdout.read().decode()
                raise RuntimeError(f"microservice died:\n{out}")
            time.sleep(0.1)
    assert ready, "microservice never came up"
    yield http_port, grpc_port
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_cli_rest_predict(microservice):
    http_port, _ = microservice
    client = SeldonClient(host="127.0.0.1", port=http_port, transport="rest")
    r = client.microservice(data=np.array([[1.0, 2.0]]), method="predict")
    assert r.success, r.error
    np.testing.assert_allclose(r.data, [[2.0, 4.0]])


def test_cli_grpc_predict(microservice):
    _, grpc_port = microservice
    client = SeldonClient(
        host="127.0.0.1", grpc_port=grpc_port, transport="grpc"
    )
    r = client.microservice(data=np.array([[3.0, 4.0]]), method="predict")
    assert r.success, r.error
    np.testing.assert_allclose(r.data, [[6.0, 8.0]])
    client.close()


def test_cli_rest_proto_fast_path(microservice):
    http_port, _ = microservice
    client = SeldonClient(
        host="127.0.0.1", port=http_port, transport="rest-proto"
    )
    r = client.microservice(data=np.array([[5.0, 6.0]], dtype=np.float32))
    assert r.success, r.error
    out = r.data
    assert out.dtype == np.float32  # dense fast path preserves dtype
    np.testing.assert_allclose(out, [[10.0, 12.0]])


def test_contract_tester_against_cli(microservice, tmp_path):
    http_port, _ = microservice
    cpath = tmp_path / "contract.json"
    cpath.write_text(json.dumps(CONTRACT))
    result = run_contract_test(
        str(cpath), host="127.0.0.1", port=http_port, transport="rest",
        n_requests=5, batch_size=3,
    )
    assert result["ok"], result["failures"]


def test_contract_generator_shapes():
    X, names = generate_batch(CONTRACT, 4)
    assert X.shape == (4, 2)
    assert names == ["f1", "f2"]
    problems = validate_response(CONTRACT, X * 2.0)
    assert problems == []
    problems = validate_response(CONTRACT, X * 100.0)
    assert problems  # out of target range


class _Bandit:
    def __init__(self):
        self.counts = [0, 0]


def test_persistence_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setattr(persistence, "_STATE_DIR", str(tmp_path))
    monkeypatch.setenv("PREDICTIVE_UNIT_ID", "u1")
    obj = _Bandit()
    obj.counts = [5, 9]
    persistence.persist(obj)
    restored = persistence.restore(_Bandit())
    assert restored is not None
    assert restored.counts == [5, 9]


def test_persistence_none_when_empty(tmp_path, monkeypatch):
    monkeypatch.setattr(persistence, "_STATE_DIR", str(tmp_path))
    monkeypatch.setenv("PREDICTIVE_UNIT_ID", "nothing-here")
    assert persistence.restore(_Bandit()) is None


def test_openapi_served_at_seldon_json():
    """Reference parity: /seldon.json on both unit wrapper and engine."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec
    from seldon_tpu.runtime.wrapper import build_rest_app

    class M:
        def predict(self, X, names, meta=None):
            return X

    async def run():
        runner = web.AppRunner(build_rest_app(M()))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        es = EngineServer(
            spec=PredictorSpec(name="p", graph=PredictiveUnit(
                name="m", type="MODEL", implementation="SIMPLE_MODEL")),
            http_port=0, grpc_port=0,
        )
        await es.start(host="127.0.0.1")
        eport = None
        for s in es._runner.sites:
            eport = s._server.sockets[0].getsockname()[1]
        async with aiohttp.ClientSession() as sess:
            async with sess.get(
                f"http://127.0.0.1:{port}/seldon.json"
            ) as r:
                unit_spec = await r.json()
            async with sess.get(
                f"http://127.0.0.1:{eport}/seldon.json"
            ) as r:
                engine_spec = await r.json()
        await runner.cleanup()
        await es.stop()
        return unit_spec, engine_spec

    unit_spec, engine_spec = asyncio.run(run())
    assert unit_spec["openapi"].startswith("3.")
    assert "/predict" in unit_spec["paths"]
    assert "/send-feedback" in unit_spec["paths"]
    assert "/api/v0.1/predictions" in engine_spec["paths"]
    # Schema shape: SeldonMessage body documented for JSON + proto.
    op = engine_spec["paths"]["/api/v0.1/predictions"]["post"]
    assert "application/x-protobuf" in op["requestBody"]["content"]


def test_wrapper_accepts_multipart_predict():
    """The unit wrapper shares parse_message, so multipart/form-data
    predictions (file part -> strData) work at /predict too."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from seldon_tpu.runtime.wrapper import build_rest_app

    class EchoStr:
        def predict_raw(self, msg):
            from seldon_tpu.proto import prediction_pb2 as pb

            out = pb.SeldonMessage()
            out.strData = msg.strData.upper()
            return out

    async def run():
        runner = web.AppRunner(build_rest_app(EchoStr()))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        form = aiohttp.FormData()
        form.add_field("strData", b"shout this",
                       filename="doc.txt", content_type="text/plain")
        async with aiohttp.ClientSession() as sess:
            async with sess.post(f"http://127.0.0.1:{port}/predict",
                                 data=form) as r:
                status, body = r.status, await r.json()
        await runner.cleanup()
        return status, body

    status, body = asyncio.run(run())
    assert status == 200, body
    assert body["strData"] == "SHOUT THIS"


def test_openapi_paths_exist_in_routers():
    """Anti-drift: every path the schema documents must be mounted by the
    actual server (spec subset-of routes, checked against the routers)."""
    from seldon_tpu.core.openapi import engine_openapi, unit_openapi
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec
    from seldon_tpu.runtime.wrapper import build_rest_app

    class M:
        def predict(self, X, names, meta=None):
            return X

    unit_routes = {
        r.resource.canonical
        for r in build_rest_app(M()).router.routes()
        if r.resource is not None
    }
    for path in unit_openapi()["paths"]:
        assert path in unit_routes, f"unit spec documents unmounted {path}"

    es = EngineServer(spec=PredictorSpec(
        name="p", graph=PredictiveUnit(name="m", type="MODEL",
                                       implementation="SIMPLE_MODEL")))
    engine_routes = {
        r.resource.canonical
        for r in es.build_app().router.routes()
        if r.resource is not None
    }
    for path in engine_openapi()["paths"]:
        assert path in engine_routes, f"engine spec documents unmounted {path}"
