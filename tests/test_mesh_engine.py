"""graftmesh (servers/mesh_engine.py + models/tp_sharding.py +
engine tp threading): tensor-parallel serving on the fake 8-device CPU
mesh, pinned bit-exact against tp=1.

The load-bearing claims, in test form:
 * greedy output is BIT-IDENTICAL tp=2 vs tp=1 across every dispatch
   family the engine ships — dense, paged, chunked, ragged, spec —
   and for bf16, int8-KV and W8A8 weights: the exact-TP scheme shards
   only output dims (models/tp_sharding docstring), so per-element
   reduction order never changes;
 * sampled output is identical too (logits are replicated, so the
   seeded sampler sees the same distribution);
 * the sharding tables are enforced: validate() rejects indivisible
   configs, hints() rejects a mesh whose 'tp' axis disagrees with the
   config, EngineConfig rejects tp < 1, and the engine rejects
   flash/ring attention under tp;
 * one sealed lattice serves the whole TP group: with COMPILE_LEDGER=1
   a warmed tp=2 engine reports its geometry and ZERO live retraces
   under traffic (donated-state sharding is pinned, so jit cache keys
   cannot drift);
 * /debug/hbm grows honest per-device accounting: weights commit
   sharded (per-device < full), the KV reservation halves per chip;
 * MESH_DEVICES caps the devices build_tp_mesh may claim.

CPU CI serves real 2-device meshes via
XLA_FLAGS=--xla_force_host_platform_device_count=8 (conftest.py).
"""

import dataclasses

import jax
import pytest

from seldon_tpu.models import init_params, tp_sharding
from seldon_tpu.models.config import get_config
from seldon_tpu.models.quantize import quantize_params
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import mesh_engine
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)
SAMPLED = SamplingParams(temperature=0.9, top_k=8, top_p=0.95,
                         max_new_tokens=8, seed=7)
# Mixed lengths: admission groups carry real bucket + group padding.
PROMPTS = [list(range(2, 2 + n)) for n in (5, 12, 24, 7)]

GEOM = dict(max_slots=4, max_seq_len=64)
MODES = {
    "dense": {},
    "paged": dict(paged_kv=True, kv_block=16, kv_pool_blocks=12,
                  prompt_buckets=(16, 32)),
    "chunked": dict(chunked_prefill=True, prefill_chunk=8, prefix_block=8),
    "ragged": dict(paged_kv=True, chunked_prefill=True, prefill_chunk=8,
                   prefix_block=8, kv_block=8, ragged=True),
    "spec": dict(spec_decode=True, spec_k=2, paged_kv=True, kv_block=8,
                 prefix_block=8),
}


def _params(cfg):
    params = init_params(cfg, jax.random.key(0))
    if cfg.weight_dtype == "int8":
        params = quantize_params(params)
    return params


def _run(cfg, params, tp, sp=GREEDY, **ekw):
    ekw = dict(GEOM, **ekw)
    ekw.setdefault("prompt_buckets", (8, 32))
    if tp > 1:
        eng = mesh_engine.MeshEngine(params, cfg, EngineConfig(**ekw),
                                     tp=tp)
    else:
        eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    eng.start()
    try:
        qs = [eng.submit(p, sp) for p in PROMPTS]
        outs = []
        for q in qs:
            toks = []
            while True:
                item = q.get(timeout=300)
                if item is None:
                    break
                assert "error" not in item, item
                toks.extend(item["tokens"])
            outs.append(toks)
        return outs
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Bit-exact parity: tp=2 vs tp=1, every dispatch family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", sorted(MODES))
def test_greedy_bit_identical_tp2_vs_tp1(mode):
    cfg = get_config("tiny")
    params = _params(cfg)
    want = _run(cfg, params, 1, **MODES[mode])
    got = _run(cfg, params, 2, **MODES[mode])
    assert got == want, f"tp=2 diverged from tp=1 under {mode}"
    assert all(len(t) > 0 for t in want)


def test_greedy_bit_identical_int8_kv_ragged():
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype="int8")
    params = _params(cfg)
    want = _run(cfg, params, 1, **MODES["ragged"])
    got = _run(cfg, params, 2, **MODES["ragged"])
    assert got == want, "tp=2 diverged from tp=1 with int8 KV"


def test_greedy_bit_identical_w8a8_dense():
    # Sharded int8 weights carry per-output-channel scales that ride
    # their output slice; the per-token activation scale is a max over
    # the unsharded feature axis — both exact under the split.
    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8",
                              act_dtype="int8")
    params = _params(cfg)
    want = _run(cfg, params, 1)
    got = _run(cfg, params, 2)
    assert got == want, "tp=2 diverged from tp=1 under W8A8"


def test_greedy_bit_identical_w8a8_big_bucket():
    # Regression: at the 128 bucket the W8A8 activation-quantization max
    # used to fuse into its producer and read unrounded f32
    # intermediates, so the int8 scale depended on fusion choices —
    # which differ between the single-chip and SPMD-partitioned
    # compilations — and tp=2 greedy drifted from tp=1 on near-ties
    # mid-stream. _quantize_act/_quantize_kv now pin their input with an
    # optimization_barrier; this is the geometry that caught it.
    cfg = dataclasses.replace(get_config("tiny"), weight_dtype="int8",
                              act_dtype="int8", kv_cache_dtype="int8")
    params = _params(cfg)
    big = dict(max_slots=4, max_seq_len=128, prompt_buckets=(32, 128),
               paged_kv=True, kv_block=16, kv_pool_blocks=33,
               chunked_prefill=True, prefill_chunk=32, prefix_block=16,
               ragged=True)
    prompts = [list(range(2, 2 + n)) for n in (24, 48, 96, 16)]
    sp = SamplingParams(temperature=0.0, max_new_tokens=16)

    def leg(tp):
        ekw = dict(big)
        if tp > 1:
            eng = mesh_engine.MeshEngine(params, cfg, EngineConfig(**ekw),
                                         tp=tp)
        else:
            eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
        eng.start()
        try:
            qs = [eng.submit(p, sp) for p in prompts]
            outs = []
            for q in qs:
                toks = []
                while True:
                    item = q.get(timeout=300)
                    if item is None:
                        break
                    assert "error" not in item, item
                    toks.extend(item["tokens"])
                outs.append(toks)
            return outs
        finally:
            eng.stop()

    want = leg(1)
    got = leg(2)
    assert got == want, "tp=2 diverged from tp=1 under W8A8 at the 128 bucket"


def test_sampled_bit_identical_tp2_vs_tp1():
    # Logits replicate across the group, so the seeded sampler draws
    # the same tokens — not just argmax parity.
    cfg = get_config("tiny")
    params = _params(cfg)
    want = _run(cfg, params, 1, sp=SAMPLED)
    got = _run(cfg, params, 2, sp=SAMPLED)
    assert got == want, "tp=2 diverged from tp=1 under seeded sampling"


# ---------------------------------------------------------------------------
# Sharding-table enforcement
# ---------------------------------------------------------------------------


def test_validate_rejects_indivisible_configs():
    cfg = get_config("tiny")  # n_kv_heads=2, n_heads=4, d_ff=128
    with pytest.raises(ValueError, match="n_kv_heads"):
        tp_sharding.validate(cfg, 3)
    with pytest.raises(ValueError, match="n_kv_heads"):
        tp_sharding.validate(cfg, 4)
    tp_sharding.validate(cfg, 2)  # divides everything
    tp_sharding.validate(cfg, 1)  # tp=1 is always fine


def test_hints_rejects_mesh_mismatch():
    assert tp_sharding.hints(None, 1) is None
    with pytest.raises(ValueError, match="requires a mesh"):
        tp_sharding.hints(None, 2)
    mesh = mesh_engine.build_tp_mesh(2)
    with pytest.raises(ValueError, match="2-way"):
        tp_sharding.hints(mesh, 4)
    h = tp_sharding.hints(mesh, 2)
    assert h is not None and h.tp == 2


def test_engine_config_rejects_bad_tp():
    with pytest.raises(ValueError):
        EngineConfig(max_slots=4, max_seq_len=64, tp=0)
    with pytest.raises(ValueError):
        EngineConfig(max_slots=4, max_seq_len=64, tp=-2)


def test_engine_rejects_untheaded_attention_kernels():
    cfg = dataclasses.replace(get_config("tiny"), attn_impl="flash")
    params = init_params(get_config("tiny"), jax.random.key(0))
    with pytest.raises(ValueError, match="not supported"):
        mesh_engine.MeshEngine(params, cfg,
                               EngineConfig(tp=2, **GEOM), tp=2)


def test_mesh_engine_rejects_tp_disagreement():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="disagrees"):
        mesh_engine.MeshEngine(params, cfg,
                               EngineConfig(tp=2, **GEOM), tp=4)


def test_mesh_devices_env_caps_budget(monkeypatch):
    monkeypatch.setenv("MESH_DEVICES", "1")
    assert mesh_engine.device_budget() == 1
    with pytest.raises(ValueError, match="MESH_DEVICES"):
        mesh_engine.build_tp_mesh(2)
    monkeypatch.setenv("MESH_DEVICES", "0")
    assert mesh_engine.device_budget() == len(jax.devices())


# ---------------------------------------------------------------------------
# One sealed lattice, per-device HBM
# ---------------------------------------------------------------------------


def test_tp_group_seals_one_lattice_zero_retraces(monkeypatch):
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    cfg = get_config("tiny")
    eng = mesh_engine.MeshEngine(_params(cfg), cfg,
                                 EngineConfig(prompt_buckets=(8, 32),
                                              **GEOM),
                                 tp=2)
    eng.warmup()
    eng.start()
    try:
        qs = [eng.submit(p, GREEDY) for p in PROMPTS]
        for q in qs:
            while q.get(timeout=300) is not None:
                pass
        snap = eng.debug_compile()
    finally:
        eng.stop()
    assert snap["tp"] == 2 and snap["mesh_devices"] == 2
    assert snap["warmup_complete"] is True
    assert snap["live_retrace_count"] == 0, snap["live_retraces"]
    assert snap["declared_variants"] >= snap["dispatched_variants"]


def test_hbm_reports_per_device_bytes(monkeypatch):
    monkeypatch.setenv("HBM_LEDGER", "1")
    cfg = get_config("tiny")
    params = _params(cfg)
    ref = InferenceEngine(params, cfg,
                          EngineConfig(prompt_buckets=(8, 32), **GEOM))
    try:
        ref_w = ref.debug_hbm()["categories"]["weights"]["bytes"]
    finally:
        ref.stop()
    eng = mesh_engine.MeshEngine(params, cfg,
                                 EngineConfig(prompt_buckets=(8, 32),
                                              **GEOM),
                                 tp=2)
    try:
        snap = eng.debug_hbm()
        assert snap["devices"] == 2
        cats = snap["categories"]
        w = cats["weights"]
        # Mesh-wide weight bytes are per-device x devices (replicated
        # leaves genuinely live on every chip).
        assert w["bytes"] == 2 * w["bytes_per_device"]
        # Sharding actually saves per-chip memory vs single-chip, but
        # less than half of it (wo / w_down / embeddings / norms
        # replicate).
        assert ref_w // 2 < w["bytes_per_device"] < ref_w
        # KV reservation shards exactly on the head axis.
        kv = cats["kv_cache"]
        assert kv["bytes_per_device"] == kv["bytes"] // 2
        assert snap["total_bytes_per_device"] < snap["total_bytes"]
    finally:
        eng.stop()


def test_mesh_info_surface():
    cfg = get_config("tiny")
    eng = mesh_engine.MeshEngine(_params(cfg), cfg,
                                 EngineConfig(prompt_buckets=(8, 32),
                                              **GEOM),
                                 tp=2)
    try:
        info = eng.mesh_info()
        assert info["tp"] == 2
        assert info["axis"] == tp_sharding.TP_AXIS
        assert len(info["devices"]) == 2
        assert info["weight_bytes_per_device"] > 0
    finally:
        eng.stop()


def test_roof_prices_per_chip_under_tp(monkeypatch):
    monkeypatch.setenv("ROOF_LEDGER", "1")
    cfg = get_config("tiny")
    eng = mesh_engine.MeshEngine(_params(cfg), cfg,
                                 EngineConfig(prompt_buckets=(8, 32),
                                              **GEOM),
                                 tp=2)
    eng.start()
    try:
        qs = [eng.submit(p, GREEDY) for p in PROMPTS]
        for q in qs:
            while q.get(timeout=300) is not None:
                pass
        snap = eng.debug_roof()
    finally:
        eng.stop()
    assert snap["tp"] == 2
    assert snap["boundaries"] > 0
    assert snap["conservation"]["breaches"] == 0
