"""Explainers: IG on jax models, occlusion on remote predictors, and the
deployed ExplainerServer explaining a LIVE engine over real sockets."""

import asyncio
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_tpu.components.explainers import (
    ExplainerServer, IntegratedGradients, OcclusionExplainer,
)


# ---------------------------------------------------------------------------
# Integrated gradients
# ---------------------------------------------------------------------------


def test_ig_linear_model_recovers_weights():
    w = jnp.array([2.0, -1.0, 0.5])

    def model(X):
        return X @ w  # scalar output per row

    ig = IntegratedGradients(model, steps=32)
    X = np.array([[1.0, 1.0, 1.0], [2.0, 0.0, -2.0]], np.float32)
    attrs = ig.explain(X)
    # For a linear model IG is exactly w * (x - b).
    np.testing.assert_allclose(attrs, X * np.asarray(w), rtol=1e-4)


def test_ig_completeness_nonlinear():
    def model(X):
        h = jnp.tanh(X @ jnp.array([[1.0, -2.0], [0.5, 1.0]]))
        return h @ jnp.array([1.0, 2.0])

    ig = IntegratedGradients(model, steps=256)
    X = np.array([[0.7, -1.3]], np.float32)
    attrs = ig.explain(X)
    fx = float(model(jnp.asarray(X))[0])
    f0 = float(model(jnp.zeros_like(jnp.asarray(X)))[0])
    # Completeness axiom: attributions sum to f(x) - f(baseline).
    np.testing.assert_allclose(attrs.sum(), fx - f0, rtol=1e-2)


def test_ig_class_output_index():
    W = jnp.array([[3.0, 0.0], [0.0, 5.0]])

    def model(X):
        return X @ W  # [B, 2] class scores

    attrs0 = IntegratedGradients(model, steps=16, output_index=0).explain(
        np.array([[1.0, 1.0]], np.float32)
    )
    np.testing.assert_allclose(attrs0, [[3.0, 0.0]], atol=1e-4)


# ---------------------------------------------------------------------------
# Occlusion
# ---------------------------------------------------------------------------


def test_occlusion_matches_linear_effect():
    calls = []

    def predict_fn(X):
        calls.append(np.asarray(X).shape)
        return np.asarray(X) @ np.array([1.0, 10.0, -5.0])

    occ = OcclusionExplainer(predict_fn)
    attrs = occ.explain(np.array([[2.0, 1.0, 1.0]], np.float32))
    np.testing.assert_allclose(attrs, [[2.0, 10.0, -5.0]], rtol=1e-6)
    # One BATCHED call per row (f+1 rows), not per feature.
    assert calls == [(4, 3)]


# ---------------------------------------------------------------------------
# ExplainerServer against a live engine
# ---------------------------------------------------------------------------


def test_explainer_server_explains_live_engine():
    from aiohttp import web

    from seldon_tpu.client import SeldonClient
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import (
        Endpoint, EndpointType, PredictiveUnit, PredictorSpec,
    )
    from seldon_tpu.runtime.wrapper import build_grpc_server, build_rest_app

    class Linear:
        def predict(self, X, names, meta=None):
            return np.asarray(X) @ np.array([[4.0], [-2.0]])

    results = {}

    async def run():
        # model unit (gRPC)
        gsrv = build_grpc_server(Linear())
        uport = gsrv.add_insecure_port("127.0.0.1:0")
        gsrv.start()
        # engine fronting it
        es = EngineServer(
            spec=PredictorSpec(
                name="p",
                graph=PredictiveUnit(
                    name="lin", type="MODEL",
                    endpoint=Endpoint("127.0.0.1", uport, EndpointType.GRPC),
                ),
            ),
            http_port=0, grpc_port=0, enable_batching=False,
        )
        await es.start(host="127.0.0.1")
        eport = None
        for site in es._runner.sites:
            eport = site._server.sockets[0].getsockname()[1]
        # explainer unit (REST), pointed at the engine like the deployed pod
        explainer = ExplainerServer(predictor_host=f"127.0.0.1:{eport}")
        xrunner = web.AppRunner(build_rest_app(explainer))
        await xrunner.setup()
        xsite = web.TCPSite(xrunner, "127.0.0.1", 0)
        await xsite.start()
        xport = xsite._server.sockets[0].getsockname()[1]

        def client_calls():
            c = SeldonClient(host="127.0.0.1", port=eport)
            results["explain"] = c.explain(
                data=np.array([[3.0, 1.0]]), payload_kind="ndarray",
                explainer_host=f"127.0.0.1:{xport}",
            )

        # requests is sync: keep the loop free for the three servers.
        await asyncio.get_running_loop().run_in_executor(None, client_calls)
        await xrunner.cleanup()
        await es.stop()
        gsrv.stop(0)

    asyncio.run(run())
    resp = results["explain"]
    assert resp.success
    from seldon_tpu.core import payloads

    attrs = payloads.get_data_from_message(resp.msg)
    # Linear single-output model: occlusion == weight * x exactly.
    np.testing.assert_allclose(np.asarray(attrs), [[12.0, -2.0]], rtol=1e-5)
    assert resp.msg.meta.tags["explainer"].string_value == "occlusion"
