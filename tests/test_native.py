"""C++ native core bindings (graceful numpy fallback when unbuilt)."""

import numpy as np
import pytest

from seldon_tpu import native


def test_bf16_roundtrip_matches_mldtypes():
    import ml_dtypes

    x = (np.random.default_rng(0).standard_normal(4096) * 50).astype(
        np.float32
    )
    ours = native.f32_to_bf16(x)
    ref = x.astype(ml_dtypes.bfloat16).view(np.uint16)
    np.testing.assert_array_equal(ours, ref)
    np.testing.assert_array_equal(
        native.bf16_to_f32(ours), x.astype(ml_dtypes.bfloat16).astype(np.float32)
    )


def test_bf16_specials():
    out = native.bf16_to_f32(
        native.f32_to_bf16(np.array([np.nan, np.inf, -np.inf, 0.0], np.float32))
    )
    assert np.isnan(out[0])
    assert out[1] == np.inf and out[2] == -np.inf and out[3] == 0.0


def test_fuse_split_roundtrip():
    rng = np.random.default_rng(1)
    parts = [rng.standard_normal((i + 1, 3)).astype(np.float32)
             for i in range(4)]
    fused = native.fuse_rows(parts)
    np.testing.assert_array_equal(fused, np.concatenate(parts))
    back = native.split_rows(fused, [p.shape[0] for p in parts])
    for a, b in zip(back, parts):
        np.testing.assert_array_equal(a, b)


def test_split_rejects_bad_counts():
    with pytest.raises(ValueError):
        native.split_rows(np.zeros((4, 2)), [1, 1])


def test_fuse_mixed_dtype_falls_back():
    a = np.zeros((1, 2), np.float32)
    b = np.zeros((1, 2), np.float64)
    out = native.fuse_rows([a, b])  # numpy fallback promotes
    assert out.shape == (2, 2)
