"""Paged KV cache (block pool + ragged block-table attention): exactness,
zero-copy sharing, and allocator mechanics.

The load-bearing claims, in test form:
 * paged greedy decoding is BIT-IDENTICAL to the dense slab (bf16 AND
   int8 KV), one-shot and chunked, cold and through a warm prefix hit —
   the pool gather reads exactly the tokens the slab would;
 * warm admissions are ZERO-COPY: the prefix trie refcounts retained
   pool blocks instead of seeding a KV copy (prefix_seed_copies stays
   0), and a partially-filled shared block is copied ONCE (CoW) so the
   sharer never scribbles on the donor's tail;
 * paged_kv=False leaves the engine byte-identical to the dense build —
   no allocator, no pool gauges;
 * admission blocks on POOL exhaustion (pool_stalls), not slot
   exhaustion, and every stream still completes once blocks free up;
 * the pool's accounting invariant (used + free == total) holds through
   a full admit/decode/complete cycle, and the allocator's misuse
   guards + bookkeeping survive a randomized op fuzz (`fuzz` marker;
   FUZZ_EXAMPLES scales it up — see `make fuzz-alloc`).
"""

import dataclasses
import os
import random

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.block_pool import BlockAllocator
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))  # 24 tokens
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _engine(cfg, start=True, **ekw):
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _dense_want(cfg, prompt=PROMPT):
    cold = _engine(cfg)
    try:
        return cold.generate_blocking(prompt, GREEDY)["token_ids"]
    finally:
        cold.stop()


# ---------------------------------------------------------------------------
# Bit-exactness vs the dense slab
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_paged_bit_identical_one_shot_cold_and_warm(kv_dtype):
    """One-shot paged admission (cold AND through a warm prefix hit)
    matches the dense slab token-for-token; the warm hit shares blocks
    zero-copy instead of seeding a KV copy."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    want = _dense_want(cfg)

    eng = _engine(cfg, prompt_buckets=(16, 32), paged_kv=True, kv_block=16,
                  prefix_cache=True, prefix_block=8)
    try:
        cold = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        warm = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert cold == want
    assert warm == want
    assert snap["prefix_hits"] == 1
    assert snap["zero_copy_admissions"] == 1
    # The dense prefix cache pays a KV copy to seed the warm slot; the
    # paged trie only bumps refcounts.
    assert snap["prefix_seed_copies"] == 0


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_paged_bit_identical_chunked(kv_dtype):
    """Chunked prefill appends pool blocks as chunks land — cold and
    warm outputs still match the dense one-shot engine bit-for-bit."""
    cfg = dataclasses.replace(get_config("tiny"), kv_cache_dtype=kv_dtype)
    want = _dense_want(cfg)

    eng = _engine(cfg, paged_kv=True, kv_block=8, prefix_cache=True,
                  prefix_block=8, chunked_prefill=True, prefill_chunk=8)
    try:
        cold = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        warm = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert cold == want
    assert warm == want
    assert snap["prefill_chunks"] == 4  # cold 3 (24/8) + warm suffix 1
    assert snap["prefix_hits"] == 1
    assert snap["prefix_seed_copies"] == 0


def test_paged_cow_on_partially_shared_block():
    """A warm hit whose match ends MID-block shares the full blocks
    zero-copy and copies the partial tail once (copy-on-write), so the
    sharer's suffix prefill never corrupts the donor's retained KV."""
    cfg = get_config("tiny")
    # 26-token shared prompt -> 3 prefix_block=8 trie spans (24 tokens);
    # the warm prompt matches all 24: one full kv_block=16 shared
    # zero-copy, tokens 16..23 live in a partially-filled block -> CoW.
    shared = list(range(2, 28))
    warm_prompt = shared + [30, 31]
    want_shared = _dense_want(cfg, shared)
    want_warm = _dense_want(cfg, warm_prompt)

    eng = _engine(cfg, prompt_buckets=(16, 32), paged_kv=True, kv_block=16,
                  prefix_cache=True, prefix_block=8)
    try:
        got_shared = eng.generate_blocking(shared, GREEDY)["token_ids"]
        got_warm = eng.generate_blocking(warm_prompt, GREEDY)["token_ids"]
        mid = eng.stats.snapshot()
        # The donor runs again AFTER the share: another warm hit (its
        # own partial tail CoWs too) whose continuation must be
        # unaffected by the first sharer's CoW'd writes.
        again = eng.generate_blocking(shared, GREEDY)["token_ids"]
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got_shared == want_shared
    assert got_warm == want_warm
    assert again == want_shared
    assert mid["cow_copies"] == 1
    assert snap["cow_copies"] == 2
    assert snap["zero_copy_admissions"] >= 2
    assert snap["prefix_seed_copies"] == 0


# ---------------------------------------------------------------------------
# Off-switch, pool accounting, exhaustion
# ---------------------------------------------------------------------------


def test_paged_off_leaves_engine_untouched():
    cfg = get_config("tiny")
    eng = _engine(cfg)  # default: paged_kv=False
    try:
        assert not eng._paged
        eng.generate_blocking(PROMPT, GREEDY)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert snap["pool_blocks_total"] == 0
    assert snap["zero_copy_admissions"] == 0
    assert snap["cow_copies"] == 0
    assert snap["pool_stalls"] == 0


def test_pool_accounting_returns_to_empty():
    """used + free == total at every observation point, and with no
    prefix cache every block returns to the free list at completion."""
    cfg = get_config("tiny")
    eng = _engine(cfg, prompt_buckets=(16, 32), paged_kv=True, kv_block=16)
    try:
        s0 = eng.stats.snapshot()
        assert s0["pool_blocks_used"] + s0["pool_blocks_free"] \
            == s0["pool_blocks_total"]
        eng.generate_blocking(PROMPT, GREEDY)
        s1 = eng.stats.snapshot()
    finally:
        eng.stop()
    assert s1["pool_blocks_used"] == 0
    assert s1["pool_blocks_free"] == s1["pool_blocks_total"]


def test_admission_stalls_on_pool_exhaustion_then_completes():
    """A pool sized for ONE stream forces the second submission to wait
    for the first to release its blocks: pool_stalls ticks, both
    streams still finish, and the outputs match the dense engine."""
    cfg = get_config("tiny")
    # 24-token prompts + 8 decode in a 32 window: exactly 2 blocks of 16
    # cover a stream's whole life, so admission's prompt reservation IS
    # the total need (no mid-decode growth -> no preemption pressure).
    p_a = list(range(2, 26))
    p_b = list(range(40, 64))
    want_a = _dense_want(cfg, p_a)
    want_b = _dense_want(cfg, p_b)

    eng = _engine(cfg, max_seq_len=32, prompt_buckets=(32,), paged_kv=True,
                  kv_block=16, kv_pool_blocks=3)  # trash + 2 usable
    try:
        qa = eng.submit(p_a, GREEDY)
        qb = eng.submit(p_b, GREEDY)

        def collect(q):
            toks = []
            while True:
                item = q.get(timeout=120)
                if item is None:
                    return toks
                assert "error" not in item, item
                toks.extend(item.get("tokens", []))

        got_a = collect(qa)
        got_b = collect(qb)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert got_a == want_a
    assert got_b == want_b
    assert snap["pool_stalls"] >= 1


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------


def test_paged_config_validation():
    base = dict(paged_kv=True, kv_block=16, prefix_block=8,
                max_seq_len=64, prompt_buckets=(16, 32))
    with pytest.raises(ValueError, match="kv_block.*power of two"):
        EngineConfig(**{**base, "kv_block": 12, "prefix_block": 4})
    with pytest.raises(ValueError, match="multiple of.*prefix_block"):
        EngineConfig(**{**base, "kv_block": 8, "prefix_block": 16,
                        "prompt_buckets": (8, 32)})
    with pytest.raises(ValueError, match="max_seq_len.*multiple of"):
        EngineConfig(**{**base, "max_seq_len": 40})
    with pytest.raises(ValueError, match="prompt_buckets entry"):
        EngineConfig(**{**base, "prompt_buckets": (8, 32)})
    with pytest.raises(ValueError, match="prefill_chunk.*multiple of"):
        EngineConfig(**base, chunked_prefill=True, prefill_chunk=8)
    with pytest.raises(ValueError, match="kv_pool_blocks"):
        EngineConfig(**base, kv_pool_blocks=1)
    # The knobs only bite when paged_kv is on, and valid configs build.
    EngineConfig(kv_block=12)
    EngineConfig(**base)
    EngineConfig(**base, kv_pool_blocks=9)


# ---------------------------------------------------------------------------
# Randomized allocator property test (scaled up by `make fuzz-alloc`)
# ---------------------------------------------------------------------------


@pytest.mark.fuzz
def test_block_allocator_fuzz():
    """Shadow-model fuzz of BlockAllocator: random alloc / alloc_many /
    ref / unref interleavings (plus deliberate misuse) must keep the
    allocator's accounting identical to a plain dict model, and every
    misuse must raise instead of corrupting state."""
    n_examples = int(os.environ.get("FUZZ_EXAMPLES", "300"))
    rng = random.Random(0xB10C)

    for case in range(n_examples):
        num_blocks = rng.randint(2, 24)
        alloc = BlockAllocator(num_blocks)
        model = {}  # bid -> refcount (live blocks only)
        for _ in range(rng.randint(1, 60)):
            op = rng.random()
            if op < 0.35:
                bid = alloc.alloc()
                if len(model) == num_blocks - 1:
                    assert bid is None  # exhausted: no block invented
                else:
                    assert bid is not None and bid not in model
                    assert bid != BlockAllocator.TRASH
                    model[bid] = 1
            elif op < 0.50:
                n = rng.randint(0, num_blocks)
                got = alloc.alloc_many(n)
                if n > num_blocks - 1 - len(model):
                    assert got is None  # all-or-nothing: no partial grab
                else:
                    assert got is not None and len(set(got)) == n
                    for bid in got:
                        assert bid not in model
                        model[bid] = 1
            elif op < 0.70 and model:
                bid = rng.choice(list(model))
                alloc.ref(bid)
                model[bid] += 1
            elif op < 0.90 and model:
                bid = rng.choice(list(model))
                alloc.unref(bid)
                if model[bid] == 1:
                    del model[bid]
                else:
                    model[bid] -= 1
            else:  # misuse must raise and must not disturb accounting
                with pytest.raises(RuntimeError):
                    rng.choice([alloc.ref, alloc.unref])(
                        BlockAllocator.TRASH
                    )
                free = [b for b in range(1, num_blocks) if b not in model]
                if free:
                    with pytest.raises(RuntimeError):
                        rng.choice([alloc.ref, alloc.unref])(
                            rng.choice(free)
                        )
            # Invariants after EVERY op, checked against the model.
            snap = alloc.snapshot()
            assert snap["total"] == num_blocks - 1
            assert snap["used"] == len(model)
            assert snap["free"] == num_blocks - 1 - len(model)
            assert snap["used"] + snap["free"] == snap["total"]
            assert snap["shared"] == sum(1 for c in model.values() if c > 1)
            for bid, c in model.items():
                assert alloc.refcount(bid) == c
        # Drain: unref everything back; the free list must be whole.
        for bid, c in list(model.items()):
            for _ in range(c):
                alloc.unref(bid)
        assert alloc.free_count == num_blocks - 1
        assert alloc.live_count == 0
