"""Bandit routers + outlier detectors (reference components/, SURVEY §2.7)."""

import pickle

import numpy as np
import pytest

from seldon_tpu.components import (
    EpsilonGreedy,
    MahalanobisDetector,
    ThompsonSampling,
    ZScoreDetector,
)


def test_epsilon_greedy_learns_best_branch():
    r = EpsilonGreedy(n_branches=3, epsilon=0.1, seed=0)
    # Branch 2 pays best.
    rng = np.random.default_rng(0)
    for _ in range(300):
        branch = r.route(np.array([[1.0]]), [])
        reward = {0: 0.1, 1: 0.4, 2: 0.9}[branch] + rng.normal(0, 0.01)
        r.send_feedback(np.array([[1.0]]), [], reward, None, routing=branch)
    assert r.best_branch == 2
    choices = [r.route(np.array([[1.0]]), []) for _ in range(100)]
    assert np.mean(np.array(choices) == 2) > 0.8  # mostly exploits


def test_epsilon_greedy_explores():
    r = EpsilonGreedy(n_branches=2, epsilon=1.0, seed=0)  # pure exploration
    choices = {r.route(np.array([[1.0]]), []) for _ in range(50)}
    assert choices == {0, 1}


def test_thompson_sampling_converges():
    r = ThompsonSampling(n_branches=2, seed=0)
    rng = np.random.default_rng(1)
    for _ in range(400):
        b = r.route(np.array([[1.0]]), [])
        reward = float(rng.random() < (0.8 if b == 1 else 0.2))
        r.send_feedback(np.array([[1.0]]), [], reward, None, routing=b)
    choices = [r.route(np.array([[1.0]]), []) for _ in range(100)]
    assert np.mean(np.array(choices) == 1) > 0.8


def test_routers_pickle_roundtrip():
    r = EpsilonGreedy(n_branches=2, seed=0)
    r.send_feedback(None, [], 1.0, None, routing=1)
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.branch_count == r.branch_count
    assert r2.route(np.array([[1.0]]), []) in (0, 1)

    t = ThompsonSampling(n_branches=2, seed=0)
    t.send_feedback(None, [], 1.0, None, routing=0)
    t2 = pickle.loads(pickle.dumps(t))
    assert t2.successes == t.successes


def test_router_ignores_invalid_routing():
    r = EpsilonGreedy(n_branches=2, seed=0)
    r.send_feedback(None, [], 5.0, None, routing=None)
    r.send_feedback(None, [], 5.0, None, routing=7)
    assert r.branch_count == [0, 0]


def test_mahalanobis_flags_outliers():
    det = MahalanobisDetector(threshold=3.0, start_clip=20)
    rng = np.random.default_rng(0)
    inliers = rng.normal(0, 1, (200, 4))
    det.predict(inliers, [])
    scores_in = det.predict(rng.normal(0, 1, (20, 4)), [])
    scores_out = det.predict(np.full((5, 4), 25.0), [])
    assert scores_out.min() > scores_in.max()
    assert det.tags()["outlier"] is True
    assert det.tags()["outlier_count"] == 5
    m = {d["key"]: d["value"] for d in det.metrics()}
    assert m["outlier_score_max"] > 3.0


def test_mahalanobis_warmup_silent():
    det = MahalanobisDetector(start_clip=50)
    scores = det.predict(np.random.default_rng(0).normal(0, 1, (10, 3)), [])
    np.testing.assert_array_equal(scores, 0.0)
    assert det.tags() == {"outlier": False, "outlier_count": 0}


def test_zscore_detector():
    det = ZScoreDetector(threshold=4.0, start_clip=10)
    rng = np.random.default_rng(0)
    det.predict(rng.normal(0, 1, (100, 3)), [])
    out = det.predict(np.array([[50.0, 0.0, 0.0]]), [])
    assert out[0] > 4.0
    assert det.tags()["outlier"] is True


def test_detector_transform_mode_passthrough():
    det = ZScoreDetector(start_clip=1)
    X = np.array([[1.0, 2.0]])
    out = det.transform_input(X, [])
    np.testing.assert_array_equal(out, X)


def test_detector_pickle_roundtrip():
    det = MahalanobisDetector(start_clip=5)
    det.predict(np.random.default_rng(0).normal(0, 1, (30, 3)), [])
    det2 = pickle.loads(pickle.dumps(det))
    assert det2.n == det.n
    s = det2.predict(np.full((1, 3), 10.0), [])
    assert s[0] > 0


def test_client_aggregate_and_unknown_method():
    """SeldonClient returns error responses, never raw KeyError."""
    from seldon_tpu.client import SeldonClient

    c = SeldonClient(transport="grpc", grpc_port=1)  # nothing listening
    r = c.microservice(method="nope")
    assert not r.success and "unknown method" in r.error
    r = c.microservice(method="send_feedback", msg=None)
    assert not r.success and "Feedback" in r.error
    c.close()


def test_tester_string_categorical_batch():
    from seldon_tpu.runtime.tester import generate_batch

    contract = {
        "features": [
            {"name": "s", "dtype": "STRING", "ftype": "categorical",
             "values": ["a", "b"]},
            {"name": "x", "dtype": "FLOAT", "range": [0, 1]},
        ]
    }
    X, names = generate_batch(contract, 3)
    assert X.shape == (3, 2)
    assert X.dtype == object
    assert set(np.unique(X[:, 0])) <= {"a", "b"}
