"""Compile observatory tests: variant ledger, retrace witness, timing.

The load-bearing claims, in test form:
 * the ledger's state machine is right: pre-warmup dispatches implicitly
   declare their keys, ``warmup_done()`` seals the lattice, and only a
   FIRST post-warmup dispatch on an undeclared key yields a witness
   (cached re-dispatches never do); the witness list is capped but the
   count keeps going;
 * everything is env-gated with the None-attribute idiom: off by
   default, the engine carries no ledger, no timing list, and the raw
   dispatch path (``_observe`` False) — ``debug_compile()`` /
   ``debug_hbm()`` return None;
 * a warmed engine under traffic finishes with ``warmup_complete`` and
   ZERO live retraces — the compile-audit contract at unit scale;
 * skipping warmup and sealing an empty lattice makes the very first
   request pay visible retraces: witnesses carry the paying rid and a
   real compile_ms, and ``retrace`` records land in the flight
   recording;
 * ``DISPATCH_TIMING=1`` populates per-variant histograms in EngineStats
   and ``dispatch`` records that trace_view renders as variant lanes;
 * the Heisenberg check: greedy output is bit-identical with the FULL
   observatory on vs off — dense, paged, and chunked-prefill engines.
"""

import json

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import compile_ledger, flight_recorder
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)

PAGED = dict(paged_kv=True, kv_block=16, kv_pool_blocks=9,
             prompt_buckets=(16, 32))
CHUNKED = dict(decode_chunk=4, min_chunk=2, adaptive_chunk=False)

OBS_KNOBS = ("COMPILE_LEDGER", "HBM_LEDGER", "DISPATCH_TIMING",
             "FLIGHT_RECORDER")


def _engine(start=True, warmup=False, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if warmup:
        eng.warmup()
    if start:
        eng.start()
    return eng


# ---------------------------------------------------------------------------
# Ledger state machine (no engine)
# ---------------------------------------------------------------------------


def test_ledger_pre_warmup_dispatches_declare():
    led = compile_ledger.CompileLedger()
    assert led.dispatch(("admit", 32, 4), -1, 0.5) is None
    assert led.dispatch(("decode", 8), -1, 0.3) is None
    led.warmup_done()
    snap = led.snapshot()
    assert snap["warmup_complete"] is True
    assert snap["declared_variants"] == 2
    assert snap["live_retrace_count"] == 0
    # Warmup paid the first dispatch; nothing re-used yet.
    assert snap["warmup_coverage"] == 0.0
    assert snap["compile_s_total"] == pytest.approx(0.8)


def test_ledger_witness_only_on_first_undeclared_post_warmup():
    led = compile_ledger.CompileLedger()
    led.dispatch(("decode", 8), -1, 0.2)
    led.warmup_done()
    # Declared key: cached re-dispatch, never a witness.
    assert led.dispatch(("decode", 8), 3, 0.001) is None
    # Undeclared key: first dispatch is THE witness...
    w = led.dispatch(("admit", 32, 4), 7, 0.4)
    assert w is not None
    assert w["key"] == "admit/32/4"
    assert w["rid"] == 7
    assert w["compile_ms"] == pytest.approx(400.0)
    # ...and the now-cached variant stops witnessing.
    assert led.dispatch(("admit", 32, 4), 8, 0.001) is None
    snap = led.snapshot()
    assert snap["live_retrace_count"] == 1
    assert snap["live_retraces"][0]["key"] == "admit/32/4"
    # Coverage counts declared keys live traffic re-used.
    assert snap["warmup_coverage"] == 1.0
    lattice = {e["key"]: e for e in snap["lattice"]}
    assert lattice["decode/8"]["declared"] is True
    assert lattice["decode/8"]["dispatches"] == 2
    assert lattice["admit/32/4"]["declared"] is False
    assert lattice["admit/32/4"]["first_dispatch_ms"] == pytest.approx(400.0)


def test_ledger_witness_list_capped_count_not():
    led = compile_ledger.CompileLedger()
    led.warmup_done()
    for i in range(compile_ledger._MAX_WITNESSES + 10):
        assert led.dispatch(("k", i), i, 0.01) is not None
    snap = led.snapshot()
    assert snap["live_retrace_count"] == compile_ledger._MAX_WITNESSES + 10
    assert len(snap["live_retraces"]) == compile_ledger._MAX_WITNESSES


def test_explicit_declare_suppresses_witness():
    led = compile_ledger.CompileLedger()
    led.declare(("chunk", 128, 2, 16))
    led.warmup_done()
    assert led.dispatch(("chunk", 128, 2, 16), 1, 0.2) is None
    assert led.snapshot()["live_retrace_count"] == 0


def test_from_env_gating(monkeypatch):
    for var, mod in (("COMPILE_LEDGER", compile_ledger),):
        monkeypatch.delenv(var, raising=False)
        assert mod.from_env() is None
        monkeypatch.setenv(var, "0")
        assert mod.from_env() is None
        monkeypatch.setenv(var, "1")
        assert mod.from_env() is not None


def test_key_str():
    assert compile_ledger.key_str(("admit-prefix", 16, 32, 4)) == \
        "admit-prefix/16/32/4"
    assert compile_ledger.key_str(("cow",)) == "cow"


# ---------------------------------------------------------------------------
# Engine integration: off by default, warmed contract, retrace witness
# ---------------------------------------------------------------------------


def test_observatory_off_by_default(monkeypatch):
    for var in OBS_KNOBS:
        monkeypatch.delenv(var, raising=False)
    eng = _engine(start=False)
    assert eng._cledger is None
    assert eng._hbm is None
    assert eng._timing_on is False
    assert eng._observe is False
    assert eng.debug_compile() is None
    assert eng.debug_hbm() is None


def test_warmed_engine_serves_with_zero_retraces(monkeypatch):
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    monkeypatch.setenv("DISPATCH_TIMING", "1")
    monkeypatch.setenv("FLIGHT_RECORDER", "1")
    eng = _engine(warmup=True)
    try:
        comp = eng.debug_compile()
        assert comp["warmup_complete"] is True
        assert comp["declared_variants"] >= 3  # admits + decode + deactivate
        assert comp["compile_s_total"] > 0.0
        for p in (PROMPT, [7, 8, 9], list(range(40, 60))):
            eng.generate_blocking(p, GREEDY)
        comp = eng.debug_compile()
        assert comp["live_retrace_count"] == 0, comp["live_retraces"]
        assert not [e for e in comp["lattice"] if not e["declared"]]
        assert comp["warmup_coverage"] > 0.0

        # Per-variant timing reached EngineStats with histogram mass.
        st = eng.stats.snapshot()
        timing = st["variant_timing"]
        assert timing, "DISPATCH_TIMING=1 populated no histograms"
        assert any(k.startswith("decode/") for k in timing), sorted(timing)
        for h in timing.values():
            assert h["count"] >= 1
            assert h["sum_ms"] > 0.0
            assert len(h["counts"]) == len(st["dispatch_edges_ms"]) + 1
            assert sum(h["counts"]) == h["count"]

        # ...and the flight recording carries dispatch records that
        # trace_view renders as lanes on the variants process.
        from tools import trace_view

        snap = eng.debug_timeline()
        kinds = {r["kind"] for r in snap["records"]}
        assert "dispatch" in kinds, kinds
        out = json.loads(json.dumps(trace_view.convert(snap)))
        lanes = [e for e in out["traceEvents"]
                 if e.get("pid") == trace_view._VARIANT_PID]
        assert any(e["ph"] == "X" for e in lanes)
        lane_names = {e["args"]["name"] for e in lanes
                      if e["ph"] == "M" and e["name"] == "thread_name"}
        assert lane_names, "no variant lane metadata"
    finally:
        eng.stop()


def test_unwarmed_shape_fires_retrace_witness(monkeypatch):
    """Skip warmup, seal the (empty) lattice by hand: the first request's
    dispatches are all live retraces — each witness carries the paying
    rid and the real compile wall time, and lands in the recording."""
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    monkeypatch.setenv("FLIGHT_RECORDER", "1")
    eng = _engine(start=False)
    eng._cledger.warmup_done()  # nothing declared: everything retraces
    eng.start()
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        comp = eng.debug_compile()
        assert comp["live_retrace_count"] >= 2  # admit + decode at least
        keys = {w["key"] for w in comp["live_retraces"]}
        assert any(k.startswith("admit") for k in keys), keys
        assert any(k.startswith("decode/") for k in keys), keys
        for w in comp["live_retraces"]:
            assert w["compile_ms"] > 0.0
        # The admission retrace names the request that paid for it.
        admits = [w for w in comp["live_retraces"]
                  if w["key"].startswith("admit")]
        assert any(w["rid"] >= 0 for w in admits), admits
        # Witnesses mirror into the flight recording.
        recs = [r for r in eng.debug_timeline()["records"]
                if r["kind"] == "retrace"]
        assert len(recs) == comp["live_retrace_count"]
        assert {r["detail"]["key"] for r in recs} == keys
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# trace_view: retrace instants + dispatch lanes from a synthetic ring
# ---------------------------------------------------------------------------


def test_trace_view_variant_lanes_and_retrace_instants():
    from tools import trace_view

    rec = flight_recorder.FlightRecorder(size=64)
    rec.record("submit", 1, {"prompt_tokens": 8})
    rec.record("admit", 1, {})
    rec.record("retrace", 1, {"key": "admit/32/4", "rid": 1,
                              "compile_ms": 812.0, "ts": 1.0})
    rec.record("dispatch", -1, {"variant": "admit/32/4", "ms": 812.0})
    rec.record("dispatch", -1, {"variant": "decode/8", "ms": 2.5})
    rec.record("dispatch", -1, {"variant": "decode/8", "ms": 2.4})
    # The graftragged wave key uses the same stable slash rendering —
    # repeated waves share ONE lane named "ragged/8".
    rec.record("dispatch", -1, {"variant": "ragged/8", "ms": 3.0})
    rec.record("dispatch", -1, {"variant": "ragged/8", "ms": 2.9})
    rec.record("terminal", 1, {"outcome": "ok"})

    out = json.loads(json.dumps(trace_view.convert(rec.snapshot())))
    events = out["traceEvents"]
    # Retrace: an instant on the paying request's track (engine process).
    retr = [e for e in events if e["name"] == "retrace"]
    assert len(retr) == 1 and retr[0]["ph"] == "i" and retr[0]["pid"] == 1

    lanes = [e for e in events if e.get("pid") == trace_view._VARIANT_PID]
    slices = [e for e in lanes if e["ph"] == "X"]
    assert len(slices) == 5
    # One lane (tid) per variant key, stable across repeats.
    by_name = {}
    for e in slices:
        by_name.setdefault(e["name"], set()).add(e["tid"])
    assert set(by_name) == {"admit/32/4", "decode/8", "ragged/8"}
    assert all(len(tids) == 1 for tids in by_name.values())
    # Slices back-span from the sync point with the recorded duration.
    admit = next(e for e in slices if e["name"] == "admit/32/4")
    assert admit["dur"] == pytest.approx(812.0 * 1000.0)
    # Lane + process metadata present so Perfetto names the tracks.
    metas = [e for e in lanes if e["ph"] == "M"]
    assert {"seldon-tpu variants"} == {
        e["args"]["name"] for e in metas if e["name"] == "process_name"}
    assert {"admit/32/4", "decode/8", "ragged/8"} == {
        e["args"]["name"] for e in metas if e["name"] == "thread_name"}


# ---------------------------------------------------------------------------
# Heisenberg check: full observatory must not change outputs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "ekw",
    [dict(), PAGED, CHUNKED],
    ids=["dense", "paged", "chunked"],
)
def test_greedy_output_bit_identical_with_observatory_on(ekw, monkeypatch):
    prompts = [PROMPT, [7, 8, 9], list(range(40, 60))]

    def run():
        eng = _engine(**dict(ekw))
        try:
            return [
                eng.generate_blocking(p, GREEDY)["token_ids"]
                for p in prompts
            ]
        finally:
            eng.stop()

    for var in OBS_KNOBS:
        monkeypatch.delenv(var, raising=False)
    want = run()

    for var in OBS_KNOBS:
        monkeypatch.setenv(var, "1")
    got = run()
    assert got == want, "compile/HBM/timing observatory changed output"
