"""Test config: force JAX onto a virtual 8-device CPU mesh so sharding
tests run without TPU hardware (mirrors how the driver dry-runs multichip)."""

import os
import sys

# Hard-set (not setdefault): the runtime image presets JAX_PLATFORMS=axon,
# which would make every test wait on the single real TPU chip's tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize imports jax and calls jax.config.update(
# "jax_platforms", "axon,cpu") at interpreter start, which overrides the env
# var above. Re-point the config at cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
