"""Test config: force JAX onto a virtual 8-device CPU mesh so sharding
tests run without TPU hardware (mirrors how the driver dry-runs multichip)."""

import os
import sys

# Hard-set (not setdefault): the runtime image presets JAX_PLATFORMS=axon,
# which would make every test wait on the single real TPU chip's tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize imports jax and calls jax.config.update(
# "jax_platforms", "axon,cpu") at interpreter start, which overrides the env
# var above. Re-point the config at cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: dozens of engine tests compile the
# SAME tiny-config kernel lattice from scratch (each bit-identical
# on/off pair boots two engines). Keyed by HLO + compile options, so
# hits return byte-identical executables — it changes wall time only.
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("SELDON_TEST_JAX_CACHE",
                                 "/tmp/seldon-jax-test-cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
