"""Aux parity: annotation config, azure/http storage, sagemaker proxy,
load tester — each driven against real local sockets or files."""

import datetime
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from seldon_tpu.core import annotations as A


# ---------------------------------------------------------------------------
# Downward-API annotations
# ---------------------------------------------------------------------------


def test_parse_downward_api_format():
    text = (
        'seldon.io/rest-read-timeout="10000"\n'
        'seldon.io/rest-connect-retries="5"\n'
        'kubernetes.io/config.seen="2026-01-01T00:00:00"\n'
        'weird="va\\"lue"\n'
    )
    out = A.parse_downward_api(text)
    assert out["seldon.io/rest-read-timeout"] == "10000"
    assert out["weird"] == 'va"lue'


def test_annotations_config_typed_accessors(tmp_path):
    p = tmp_path / "annotations"
    p.write_text(
        'seldon.io/rest-read-timeout="2500"\n'
        'seldon.io/grpc-max-message-size="1048576"\n'
        'seldon.io/rest-connect-retries="notanint"\n'
    )
    cfg = A.AnnotationsConfig(path=str(p))
    assert cfg.rest_timeout_s() == 2.5
    assert cfg.grpc_max_msg_bytes() == 1048576
    assert cfg.connect_retries(7) == 7  # bad int -> default
    missing = A.AnnotationsConfig(path=str(tmp_path / "nope"))
    assert missing.rest_timeout_s(3000) == 3.0


def test_engine_server_picks_up_annotations(tmp_path, monkeypatch):
    p = tmp_path / "annotations"
    p.write_text('seldon.io/grpc-max-message-size="7777777"\n'
                 'seldon.io/rest-connect-retries="9"\n')
    monkeypatch.setenv("PODINFO_ANNOTATIONS", str(p))
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec

    es = EngineServer(spec=PredictorSpec(
        name="p", graph=PredictiveUnit(name="m", type="MODEL",
                                       implementation="SIMPLE_MODEL")))
    assert es.grpc_max_msg == 7777777
    assert es.engine.client.retries == 9


# ---------------------------------------------------------------------------
# Storage: http + azure blob over a local fake
# ---------------------------------------------------------------------------


class _FakeBlobHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):
        if "comp=list" in self.path:
            body = (
                "<?xml version='1.0'?><EnumerationResults><Blobs>"
                "<Blob><Name>models/demo/model.json</Name></Blob>"
                "<Blob><Name>models/demo/weights.bin</Name></Blob>"
                "</Blobs></EnumerationResults>"
            ).encode()
        elif self.path.endswith("model.json"):
            body = b'{"kind": "demo"}'
        elif self.path.endswith("weights.bin"):
            body = b"\x00\x01\x02"
        elif self.path.endswith("single.txt"):
            body = b"plain http file"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def fake_http():
    srv = HTTPServer(("127.0.0.1", 0), _FakeBlobHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_http_download(fake_http, tmp_path):
    from seldon_tpu.servers.storage import download

    local = download(f"{fake_http}/files/single.txt", out_dir=str(tmp_path))
    assert open(f"{local}/single.txt").read() == "plain http file"


def test_azure_blob_prefix_download(fake_http, tmp_path):
    from seldon_tpu.servers import storage

    # https:// form exercises the same List Blobs + GET path as azure://
    # (azure:// only differs in deriving the account host).
    local = storage._download_azure_blob(
        f"{fake_http}/container/models/demo", str(tmp_path / "az")
    )
    assert json.load(open(f"{local}/model.json"))["kind"] == "demo"
    assert open(f"{local}/weights.bin", "rb").read() == b"\x00\x01\x02"


def test_relative_key_rejects_traversal():
    """Listing-supplied object keys are remote input: keys that would
    escape the download dir (.. segments, absolute paths, backslashes)
    must be skipped across all backends (gs/s3/azure all route here)."""
    from seldon_tpu.servers.storage import _relative_key

    assert _relative_key("models/demo/a/b.bin", "models/demo") == "a/b.bin"
    assert _relative_key("models/demo/../../etc/passwd", "models/demo") is None
    assert _relative_key("../evil", "") is None
    assert _relative_key("/etc/passwd", "") is None
    assert _relative_key("models/demo/..", "models/demo") is None
    assert _relative_key(r"models/demo/a\..\..\x", "models/demo") is None
    # Directory-marker placeholders (console-created 'folders') skip.
    assert _relative_key("models/demo/sub/", "models/demo") is None
    # Prefix mismatch still guarded.
    assert _relative_key("models/demo2/a", "models/demo") is None


# ---------------------------------------------------------------------------
# SageMaker proxy
# ---------------------------------------------------------------------------


def test_sigv4_matches_known_vector():
    """AWS's documented test vector (GET iam, 2015-08-30)."""
    from seldon_tpu.servers.sagemakerproxy import sigv4_headers

    h = sigv4_headers(
        "GET", "iam.amazonaws.com", "/", b"",
        region="us-east-1", service="iam",
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        now=datetime.datetime(2015, 8, 30, 12, 36, 0,
                              tzinfo=datetime.timezone.utc),
    )
    # Signature differs from the doc vector (we sign x-amz-content-sha256
    # too), but structure + determinism must hold.
    assert h["authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20150830/us-east-1/iam/"
        "aws4_request"
    )
    h2 = sigv4_headers(
        "GET", "iam.amazonaws.com", "/", b"",
        region="us-east-1", service="iam",
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        now=datetime.datetime(2015, 8, 30, 12, 36, 0,
                              tzinfo=datetime.timezone.utc),
    )
    assert h == h2


class _FakeSagemaker(BaseHTTPRequestHandler):
    seen = {}

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers["Content-Length"])
        body = self.rfile.read(n)
        _FakeSagemaker.seen = {
            "path": self.path,
            "auth": self.headers.get("authorization", ""),
            "body": body,
        }
        out = json.dumps({"predictions": [[0.1, 0.9]]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


def test_sagemaker_proxy_invokes_endpoint(monkeypatch):
    from seldon_tpu.servers.sagemakerproxy import SagemakerProxy

    srv = HTTPServer(("127.0.0.1", 0), _FakeSagemaker)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secret")
        proxy = SagemakerProxy(
            endpoint_name="my-model", region="us-west-2",
            endpoint_url=f"http://127.0.0.1:{srv.server_port}",
        )
        out = proxy.predict(np.array([[1.0, 2.0]]), [])
        np.testing.assert_allclose(out, [[0.1, 0.9]])
        assert _FakeSagemaker.seen["path"] == "/endpoints/my-model/invocations"
        assert "AWS4-HMAC-SHA256" in _FakeSagemaker.seen["auth"]
        assert json.loads(_FakeSagemaker.seen["body"]) == {
            "instances": [[1.0, 2.0]]
        }
        assert proxy.tags()["proxy"] == "sagemaker"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Load tester against a live engine
# ---------------------------------------------------------------------------


def test_loadtester_rest_against_engine():
    import asyncio

    from seldon_tpu.loadtester import report, run_rest
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import PredictiveUnit, PredictorSpec

    async def run():
        es = EngineServer(
            spec=PredictorSpec(
                name="lt",
                graph=PredictiveUnit(name="m", type="MODEL",
                                     implementation="SIMPLE_MODEL"),
            ),
            http_port=0, grpc_port=0, enable_batching=False,
        )
        await es.start(host="127.0.0.1")
        port = None
        for site in es._runner.sites:
            port = site._server.sockets[0].getsockname()[1]
        try:
            return await run_rest(
                f"http://127.0.0.1:{port}",
                b'{"data": {"ndarray": [[1.0, 2.0]]}}',
                clients=8, seconds=1.0,
            )
        finally:
            await es.stop()

    total, dt, lats, errors = asyncio.run(run())
    assert errors == 0 and total > 10
    out = report("rest", total, dt, lats, errors, 8)
    assert out["detail"]["p50_ms"] > 0
