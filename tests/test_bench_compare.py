"""tools/bench_compare.py tests: loading, flattening, gating.

Claims under test:
 * all three artifact shapes load — the supervisor wrapper (metric line
   under ``parsed``), a raw metric line, and a JSONL stream where the
   last complete metric line wins;
 * numeric scalars flatten to dot paths; bools and strings are skipped;
 * the direction heuristic gates latencies lower-is-better and
   throughput higher-is-better, leaves unknown names informational, and
   treats ``live_retraces`` strictly (ANY increase fails, tolerance
   ignored — a retrace storm is a bug, not noise);
 * end to end: a regressed candidate exits non-zero, an improved or
   within-tolerance one exits zero.
"""

import json

import pytest

from tools import bench_compare


def _metric(value, detail):
    return {"metric": "engine_req_per_s_per_chip", "value": value,
            "unit": "req/s", "vs_baseline": value / 125.0,
            "detail": detail}


BASE = _metric(100.0, {
    "decode_tokens_per_s": 10000.0, "p50_ttft_ms": 200.0,
    "p99_ttft_ms": 400.0, "total_tokens": 40000,
    "live_retraces": 0, "compile_variants": 9,
    "device": "TPU v5 lite0", "partial": False,
    "bench_1b": {"req_per_s": 140.0, "p50_ttft_ms": 900.0},
})


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def test_load_supervisor_wrapper(tmp_path):
    p = tmp_path / "wrapped.json"
    p.write_text(json.dumps({"n": 5, "cmd": "python bench.py", "rc": 0,
                             "tail": "...", "parsed": BASE}))
    assert bench_compare.load_metric(str(p)) == BASE


def test_load_raw_metric_line(tmp_path):
    p = tmp_path / "raw.json"
    p.write_text(json.dumps(BASE))
    assert bench_compare.load_metric(str(p)) == BASE


def test_load_jsonl_last_metric_wins(tmp_path):
    p = tmp_path / "stream.jsonl"
    partial = _metric(90.0, {"partial": True})
    p.write_text("noise\n" + json.dumps(partial) + "\n"
                 + json.dumps(BASE) + "\n{broken\n")
    assert bench_compare.load_metric(str(p))["value"] == 100.0


def test_load_no_metric_exits(tmp_path):
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"n": 4, "rc": 1, "parsed": None}))
    with pytest.raises(SystemExit):
        bench_compare.load_metric(str(p))


# ---------------------------------------------------------------------------
# Flattening + direction heuristic
# ---------------------------------------------------------------------------


def test_flatten_numeric_scalars_only():
    flat = bench_compare.flatten(BASE)
    assert flat["value"] == 100.0
    assert flat["detail.p50_ttft_ms"] == 200.0
    assert flat["detail.bench_1b.req_per_s"] == 140.0
    assert "detail.device" not in flat   # string
    assert "detail.partial" not in flat  # bool


def test_direction_heuristic():
    d = bench_compare.direction
    assert d("detail.p50_ttft_ms") == "lower"
    assert d("detail.bench_1b.p99_ttft_ms") == "lower"
    assert d("detail.pool_stalls") == "lower"
    assert d("detail.decode_tokens_per_s") == "higher"
    assert d("detail.bench_1b.req_per_s") == "higher"
    assert d("detail.prefix.hit_rate") == "higher"
    assert d("value") == "higher"
    assert d("detail.bench_1b.vs_baseline") == "higher"
    assert d("detail.live_retraces") == "strict"
    assert d("detail.total_tokens") == "info"
    # Exact variant counts gate strictly: the static lattice is closed
    # form, so any growth is a real regression, not noise.
    assert d("detail.compile_variants") == "strict"
    # graftroof: achieved utilization gates higher, scheduler-overhead
    # share lower, and the model-side prediction stays informational.
    assert d("detail.bench_1b.mfu") == "higher"
    assert d("detail.bench_1b.mbu") == "higher"
    assert d("detail.bench_1b.host_frac") == "lower"
    assert d("detail.bench_1b.roof_predicted_req_s") == "info"
    # predicted_vs_measured_req_s rides the req_s substring: a run that
    # lands closer to its roofline prediction gates higher-is-better.
    assert d("detail.predicted_vs_measured_req_s") == "higher"
    # graftmesh: per-chip HBM gates lower (sharding is supposed to save
    # it), the sharding-dividend fraction gates lower, the TP-leg
    # throughput rides the req_per_s/tok_s substrings, and the mesh
    # size itself is a config constant — informational.
    assert d("detail.mesh.mesh.kv_bytes_per_device") == "lower"
    assert d("detail.mesh.mesh.weights_bytes_per_device") == "lower"
    assert d("detail.mesh.kv_per_device_frac") == "lower"
    assert d("detail.mesh.mesh.req_per_s") == "higher"
    assert d("detail.mesh.hbm_devices") == "info"


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------


def test_within_tolerance_passes():
    base = bench_compare.flatten(BASE)
    cand = dict(base)
    cand["value"] *= 0.95             # -5% on a 10% gate
    cand["detail.p50_ttft_ms"] *= 1.08
    _, regressions = bench_compare.compare(base, cand, tol=0.10)
    assert regressions == []


def test_throughput_drop_regresses():
    base = bench_compare.flatten(BASE)
    cand = dict(base)
    cand["value"] *= 0.8
    _, regressions = bench_compare.compare(base, cand, tol=0.10)
    assert any(r.startswith("value:") for r in regressions)


def test_latency_rise_regresses_and_fall_does_not():
    base = bench_compare.flatten(BASE)
    cand = dict(base)
    cand["detail.p99_ttft_ms"] *= 1.5
    cand["detail.p50_ttft_ms"] *= 0.5  # improvement, never gated
    _, regressions = bench_compare.compare(base, cand, tol=0.10)
    assert len(regressions) == 1
    assert regressions[0].startswith("detail.p99_ttft_ms:")


def test_live_retraces_strict_no_tolerance():
    base = bench_compare.flatten(BASE)
    cand = dict(base)
    cand["detail.live_retraces"] = 1.0
    _, regressions = bench_compare.compare(base, cand, tol=10.0)
    assert any("live_retraces" in r for r in regressions)
    # Equal or fewer retraces is fine.
    cand["detail.live_retraces"] = 0.0
    _, regressions = bench_compare.compare(base, cand, tol=10.0)
    assert regressions == []


def test_one_sided_metrics_are_informational():
    base = bench_compare.flatten(BASE)
    cand = {"value": 100.0}  # candidate lost every detail metric
    lines, regressions = bench_compare.compare(base, cand, tol=0.10)
    assert regressions == []
    # Metrics only in base were removed by the candidate run.
    assert any("(removed)" in ln for ln in lines)
    assert not any("(added)" in ln for ln in lines)


def test_one_sided_reports_which_side():
    base = bench_compare.flatten(BASE)
    cand = dict(base)
    del cand["detail.p99_ttft_ms"]         # dropped by the candidate
    cand["detail.new_counter"] = 7.0       # introduced by the candidate
    lines, regressions = bench_compare.compare(base, cand, tol=0.10)
    assert regressions == []
    assert any("p99_ttft_ms" in ln and "(removed)" in ln for ln in lines)
    assert any("new_counter" in ln and "(added)" in ln for ln in lines)


# ---------------------------------------------------------------------------
# End to end
# ---------------------------------------------------------------------------


def test_main_exit_codes(tmp_path, capsys):
    base_p = tmp_path / "base.json"
    base_p.write_text(json.dumps(BASE))
    good = _metric(101.0, dict(BASE["detail"]))
    good_p = tmp_path / "good.json"
    good_p.write_text(json.dumps(good))
    assert bench_compare.main([str(base_p), str(good_p)]) == 0
    assert "no regressions" in capsys.readouterr().out

    bad_detail = dict(BASE["detail"])
    bad_detail["live_retraces"] = 3
    bad = _metric(100.0, bad_detail)
    bad_p = tmp_path / "bad.json"
    bad_p.write_text(json.dumps(bad))
    assert bench_compare.main([str(base_p), str(bad_p)]) == 1
    assert "strict" in capsys.readouterr().err
