"""Learned outlier detectors: VAE, IsolationForest, Seq2Seq-LSTM.

Each must (a) separate planted anomalies from inliers after fit(),
(b) work in both MODEL (predict) and TRANSFORMER (transform_input +
tags/metrics) roles, (c) survive pickling (persistence layer)."""

import pickle

import numpy as np
import pytest

from seldon_tpu.components import (
    IsolationForestDetector, Seq2SeqLSTMDetector, VAEDetector,
)


@pytest.fixture(scope="module")
def tabular_data():
    rng = np.random.default_rng(0)
    inliers = rng.normal(0.0, 1.0, size=(512, 8)).astype(np.float32)
    outliers = rng.normal(6.0, 1.0, size=(16, 8)).astype(np.float32)
    return inliers, outliers


# ---------------------------------------------------------------------------
# VAE
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fitted_vae(tabular_data):
    inliers, _ = tabular_data
    return VAEDetector(latent_dim=2, seed=0).fit(
        inliers, epochs=30, batch_size=128
    )


def test_vae_separates_outliers(tabular_data, fitted_vae):
    inliers, outliers = tabular_data
    s_in = fitted_vae.predict(inliers[:64], [])
    s_out = fitted_vae.predict(outliers, [])
    # Clean separation: every planted outlier scores above every inlier mean.
    assert s_out.min() > s_in.mean() * 2, (s_in.mean(), s_out.min())


def test_vae_transformer_dual(tabular_data, fitted_vae):
    inliers, outliers = tabular_data
    det = fitted_vae
    det.threshold = float(det.predict(inliers[:64], []).max() * 1.5)
    out = det.transform_input(outliers[:4], [])
    np.testing.assert_array_equal(out, outliers[:4])  # pass-through
    assert det.tags()["outlier"] is True
    assert det.tags()["outlier_count"] == 4
    keys = {m["key"] for m in det.metrics()}
    assert "outlier_score_max" in keys
    det.transform_input(inliers[:4], [])
    assert det.tags()["outlier"] is False


def test_vae_pickle_roundtrip(tabular_data, fitted_vae):
    inliers, outliers = tabular_data
    restored = pickle.loads(pickle.dumps(fitted_vae))
    np.testing.assert_allclose(
        restored.predict(outliers, []), fitted_vae.predict(outliers, []),
        rtol=1e-5,
    )


# ---------------------------------------------------------------------------
# Isolation forest
# ---------------------------------------------------------------------------


def test_iforest_separates_outliers(tabular_data):
    inliers, outliers = tabular_data
    det = IsolationForestDetector(n_trees=100, seed=0).fit(inliers)
    s_in = det.predict(inliers[:64], [])
    s_out = det.predict(outliers, [])
    assert s_out.mean() > s_in.mean() + 0.1, (s_in.mean(), s_out.mean())
    # Canonical iforest property: scores in (0, 1], anomalies near ~>0.6.
    assert 0.0 < s_in.min() and s_out.max() <= 1.0
    assert np.median(s_out) > 0.55


def test_iforest_pickle_and_dual(tabular_data):
    inliers, outliers = tabular_data
    det = IsolationForestDetector(n_trees=50, seed=1, threshold=0.55)
    det.fit(inliers)
    restored = pickle.loads(pickle.dumps(det))
    np.testing.assert_allclose(
        restored.predict(outliers, []), det.predict(outliers, []), rtol=1e-6
    )
    restored.transform_input(outliers[:3], [])
    assert restored.tags()["outlier"] is True


# ---------------------------------------------------------------------------
# Seq2Seq LSTM
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sequence_data():
    rng = np.random.default_rng(0)
    t = np.linspace(0, 4 * np.pi, 32)
    # Inliers: noisy sinusoids with random phase.
    phases = rng.uniform(0, 2 * np.pi, size=(256, 1))
    inliers = np.sin(t[None, :] + phases) + rng.normal(
        0, 0.05, size=(256, 32)
    )
    # Anomalies: white noise bursts.
    outliers = rng.normal(0, 1.2, size=(8, 32))
    return inliers.astype(np.float32), outliers.astype(np.float32)


def test_seq2seq_separates_anomalous_sequences(sequence_data):
    inliers, outliers = sequence_data
    det = Seq2SeqLSTMDetector(hidden_dim=24, seed=0)
    det.fit(inliers, epochs=40, batch_size=64)
    s_in = det.predict(inliers[:32], [])
    s_out = det.predict(outliers, [])
    assert s_out.mean() > 2 * s_in.mean(), (s_in.mean(), s_out.mean())
    # Dual + pickle
    det.threshold = float(s_in.max() * 1.5)
    restored = pickle.loads(pickle.dumps(det))
    restored.transform_input(outliers[:2], [])
    assert restored.tags()["outlier"] is True
    np.testing.assert_allclose(
        restored.predict(outliers, []), s_out, rtol=1e-5
    )


def test_seq2seq_multivariate_shape():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(32, 10, 3)).astype(np.float32)
    det = Seq2SeqLSTMDetector(hidden_dim=8, seed=0)
    det.fit(X, epochs=2, batch_size=16)
    assert det.predict(X[:5], []).shape == (5,)
    with pytest.raises(ValueError):
        det.predict(np.zeros((2, 2, 2, 2), np.float32), [])
