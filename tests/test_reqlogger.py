"""Request/response logging: CloudEvents pairs engine -> sink.

Reference: PredictionService.java:169-203 (CE POST to
SELDON_MESSAGE_LOGGING_SERVICE) + seldon-request-logger/app/app.py
(flattening sink). Tested over a REAL aiohttp sink socket."""

import asyncio
import json

import numpy as np
import pytest
from aiohttp import web

from seldon_tpu.core import payloads
from seldon_tpu.orchestrator.reqlogger import (
    CE_TYPE_REQUEST, CE_TYPE_RESPONSE, RequestLogger, build_sink_app, _flatten,
)


# ---------------------------------------------------------------------------
# Flattener (sink side)
# ---------------------------------------------------------------------------


def test_flatten_ndarray_rows_with_names():
    body = {"data": {"names": ["a", "b"], "ndarray": [[1, 2], [3, 4]]}}
    docs = _flatten(body, CE_TYPE_REQUEST, "p1", {"Ce-Deploymentname": "dep"})
    assert len(docs) == 2
    assert docs[0] == {
        "ce_type": CE_TYPE_REQUEST, "request_id": "p1", "deployment": "dep",
        "predictor": "", "kind": "request", "batch_index": 0, "a": 1, "b": 2,
    }
    assert docs[1]["a"] == 3 and docs[1]["batch_index"] == 1


def test_flatten_tensor_and_fallbacks():
    body = {"data": {"tensor": {"shape": [2, 2], "values": [1, 2, 3, 4]}}}
    docs = _flatten(body, CE_TYPE_RESPONSE, "p", {})
    assert [d["row"] for d in docs] == [[1, 2], [3, 4]]
    assert docs[0]["kind"] == "response"
    # strData passthrough
    docs = _flatten({"strData": "hello"}, CE_TYPE_REQUEST, "p", {})
    assert docs[0]["payload"] == {"strData": "hello"}


# ---------------------------------------------------------------------------
# Shipper -> sink over a real socket
# ---------------------------------------------------------------------------


async def _start_sink(store):
    app = build_sink_app(store=store)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}/"


def test_engine_pair_reaches_sink_and_flattens():
    async def run():
        store = []
        runner, url = await _start_sink(store)
        rl = RequestLogger(sink_url=url, deployment="dep", predictor="pred")
        req = payloads.build_message(
            np.array([[1.0, 2.0]], np.float32), names=["x", "y"],
            kind="ndarray",
        )
        resp = payloads.build_message(
            np.array([[0.9]], np.float32), names=["p"], kind="ndarray",
        )
        resp.meta.puid = "puid-1"
        rl.log_pair(req, resp, "puid-1")
        for _ in range(100):
            if rl.sent >= 2:
                break
            await asyncio.sleep(0.02)
        await rl.close()
        await runner.cleanup()
        return store, rl

    store, rl = asyncio.run(run())
    assert rl.sent == 2 and rl.dropped == 0
    kinds = sorted(d["kind"] for d in store)
    assert kinds == ["request", "response"]
    req_doc = next(d for d in store if d["kind"] == "request")
    assert req_doc["x"] == 1.0 and req_doc["y"] == 2.0
    assert req_doc["request_id"] == "puid-1"
    assert req_doc["deployment"] == "dep" and req_doc["predictor"] == "pred"


def test_ce_ids_unique_per_event():
    """CloudEvents ids must differ between the request and response of one
    prediction (dedup-capable sinks drop same-id pairs); correlation rides
    Ce-Requestid instead."""

    async def run():
        seen = []

        async def handle(request):
            seen.append(dict(request.headers))
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_post("/", handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        rl = RequestLogger(sink_url=f"http://127.0.0.1:{port}/")
        msg = payloads.build_message(np.ones((1, 1), np.float32))
        rl.log_pair(msg, msg, "puid-7")
        for _ in range(100):
            if rl.sent >= 2:
                break
            await asyncio.sleep(0.02)
        await rl.close()
        await runner.cleanup()
        return seen

    seen = asyncio.run(run())
    ids = sorted(h["CE-Id"] for h in seen)
    assert ids == ["puid-7-request", "puid-7-response"]
    assert all(h["Ce-Requestid"] == "puid-7" for h in seen)
    types = {h["CE-Id"]: h["CE-Type"] for h in seen}
    assert types["puid-7-request"] == CE_TYPE_REQUEST
    assert types["puid-7-response"] == CE_TYPE_RESPONSE


def test_disabled_logger_is_free():
    rl = RequestLogger(sink_url="", log_requests=False, log_responses=False)
    assert not rl.enabled
    # No loop running; must not touch asyncio at all.
    rl.log_pair(payloads.build_message(np.zeros((1, 1))),
                payloads.build_message(np.zeros((1, 1))), "p")
    assert rl.sent == 0 and rl._queue is None


def test_unreachable_sink_drops_not_blocks():
    async def run():
        rl = RequestLogger(sink_url="http://127.0.0.1:9/", max_queue=4)
        msg = payloads.build_message(np.zeros((1, 1), np.float32))
        import time
        t0 = time.perf_counter()
        for i in range(10):
            rl.log_pair(msg, msg, f"p{i}")
        hot_path_s = time.perf_counter() - t0
        await asyncio.sleep(0.3)
        await rl.close()
        return hot_path_s, rl

    hot_path_s, rl = asyncio.run(run())
    assert hot_path_s < 0.2  # enqueue-only; never awaits the sink
    assert rl.sent == 0
    assert rl.dropped >= 6  # 20 events, queue of 4: most drop


def test_stdout_raw_logging(capsys):
    async def run():
        rl = RequestLogger(sink_url="", log_requests=True, log_responses=True)
        msg = payloads.build_message(np.ones((1, 1), np.float32), kind="ndarray")
        rl.log_pair(msg, msg, "p")
        await rl.close()

    asyncio.run(run())
    out = capsys.readouterr().out
    assert out.count("Request: ") == 1 and out.count("Response: ") == 1
    json.loads(out.splitlines()[0].split("Request: ", 1)[1])  # valid JSON
