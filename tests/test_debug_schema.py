"""Frozen-schema golden tests for the debug observatory snapshots.

``/debug/compile``, ``/debug/hbm``, ``/debug/sched``, ``/debug/pilot``,
``/debug/roof`` and ``/debug/health`` are consumed by parties that
never import this repo's dataclasses: the loadtester's ledger polls,
``tools/compile_audit.py`` / ``tools/sched_audit.py`` /
``tools/pilot_audit.py`` / ``tools/roof_audit.py`` /
``tools/heal_audit.py``, ``tools/probe_hbm``, and whatever dashboards
operators curl together.
Their schemas are frozen here as literal key sets.  If one of these
tests fails, you changed the wire contract: update the module
docstrings in ``seldon_tpu/servers/compile_ledger.py`` /
``hbm_ledger.py`` / ``sched_ledger.py`` / ``controller.py`` /
``cost_model.py``, the consumers above, AND these goldens in the same
PR — never just the golden.
"""

import json
import time

from seldon_tpu.models.config import get_config
from seldon_tpu.servers.compile_ledger import CompileLedger
from seldon_tpu.servers.controller import PilotController
from seldon_tpu.servers.cost_model import RoofLedger
from seldon_tpu.servers.hbm_ledger import HbmLedger
from seldon_tpu.servers.sched_ledger import SchedLedger
from seldon_tpu.servers.supervisor import HealSupervisor

# The documented /debug/compile schema, frozen.
COMPILE_TOP_KEYS = frozenset({
    "warmup_complete",
    "tp",
    "mesh_devices",
    "declared_variants",
    "dispatched_variants",
    "warmup_coverage",
    "compile_s_total",
    "live_retrace_count",
    "live_retraces",
    "lattice",
})
COMPILE_WITNESS_KEYS = frozenset({"key", "rid", "compile_ms", "ts"})
COMPILE_LATTICE_KEYS = frozenset({
    "key", "dispatches", "first_dispatch_ms", "declared",
})

# The documented /debug/hbm schema, frozen.
HBM_TOP_KEYS = frozenset({
    "categories", "devices", "total_bytes", "total_bytes_per_device",
    "total_high_bytes",
})
HBM_CATEGORY_KEYS = frozenset({
    "bytes", "bytes_per_device", "high_bytes", "static",
})

# The documented /debug/sched schema, frozen (tools/sched_audit.py
# carries the same top-level golden).
SCHED_TOP_KEYS = frozenset({
    "boundaries",
    "dispatch_boundaries",
    "idle_boundaries",
    "dispatch_cells",
    "useful_tokens",
    "bucket_pad_tokens",
    "group_pad_tokens",
    "spec_rejected_tokens",
    "frag_tokens",
    "budget_offered_tokens",
    "budget_used_tokens",
    "budget_starved_passes",
    "padding_waste_frac",
    "budget_utilization",
    "goodput_gap",
    "pool_stall_events",
    "pool_stall_requests",
    "preemptions",
    "preempted_tokens",
    "spec",
    "wait",
    "conservation",
    "by_shape",
})
SCHED_GAP_KEYS = frozenset({
    "bucket_pad_frac", "group_pad_frac", "spec_rejected_frac",
    "frag_frac", "idle_frac",
})
SCHED_SPEC_KEYS = frozenset({
    "drafted_tokens", "accepted_tokens", "rejected_tokens",
    "verify_waves", "acceptance_rate",
})
SCHED_WAIT_KEYS = frozenset({
    "requests", "total_ms", "pool_ms", "bucket_ms", "budget_ms",
    "sched_ms", "predicted_ms",
})
SCHED_CONSERVATION_KEYS = frozenset({"checked", "breaches", "last_breach"})
SCHED_SHAPE_KEYS = frozenset({
    "key", "dispatches", "cells", "useful_tokens", "bucket_pad_tokens",
    "group_pad_tokens", "spec_rejected_tokens",
})

# The documented /debug/pilot schema, frozen (tools/pilot_audit.py
# carries the same top-level + ledger-entry goldens).
PILOT_TOP_KEYS = frozenset({
    "enabled",
    "mode",
    "boundaries",
    "windows",
    "period_boundaries",
    "decisions_total",
    "decisions_by_knob",
    "knobs",
    "envelope",
    "edf",
    "counterfactual",
    "ledger",
})
PILOT_KNOB_KEYS = frozenset({
    "dispatch_token_budget", "max_admit", "chunk_bias", "spec_k",
})
PILOT_ENVELOPE_KEYS = frozenset({
    "budget_min", "budget_max", "admit_min", "admit_max", "bias_min",
    "bias_max", "speck_min", "speck_max",
})
PILOT_EDF_KEYS = frozenset({"inversions", "reorders", "expired_at_pop"})
PILOT_CF_KEYS = frozenset({"windows", "goodput_delta", "waste_frac_delta"})
PILOT_LEDGER_KEYS = frozenset({
    "ts", "knob", "old", "new", "rationale", "expected_effect",
    "signal_snapshot", "effect",
})
PILOT_EFFECT_KEYS = frozenset({"goodput_delta", "waste_frac_delta"})
PILOT_SIGNAL_KEYS = frozenset({
    "boundaries", "dispatch_cells", "useful_tokens", "frag_tokens",
    "budget_dispatches", "budget_starved_passes",
    "budget_offered_tokens", "budget_used_tokens", "pool_stall_events",
    "preemptions", "deadline_expired", "spec_drafted", "spec_accepted",
    "goodput", "queue_depth", "free_slots", "roof_backlog_ms",
    "heal_pressure",
})

# The documented /debug/health schema, frozen (graftheal's
# HealSupervisor.snapshot(); tools/heal_audit.py polls it).
HEALTH_TOP_KEYS = frozenset({
    "enabled",
    "state",
    "mode",
    "max_retries",
    "watchdog_ms",
    "resurrected",
    "quarantined",
    "watchdog_trips",
    "retry_exhausted",
    "sentinel_trips",
    "recoveries",
    "consecutive_faults",
    "clean_boundaries",
    "pen",
    "suspects",
    "probing",
    "pressure",
})

# The documented /debug/roof schema, frozen (tools/roof_audit.py
# carries the same top-level + variant goldens).
ROOF_TOP_KEYS = frozenset({
    "enabled",
    "platform",
    "peaks",
    "tp",
    "boundaries",
    "waves",
    "step",
    "host_frac",
    "device_frac",
    "conservation",
    "variants",
    "totals",
})
ROOF_PEAKS_KEYS = frozenset({"tflops", "gbs", "source"})
ROOF_STEP_KEYS = frozenset({
    "wall_ms", "host_pre_ms", "device_ms", "host_post_ms", "overlap_ms",
})
ROOF_CONSERVATION_KEYS = frozenset({"checked", "breaches", "last_breach"})
ROOF_VARIANT_KEYS = frozenset({
    "key", "family", "dispatches", "flops", "bytes", "device_ms",
    "predicted_ms", "capacity_flops", "capacity_bytes",
    "capacity_predicted_ms", "mfu", "mbu", "bound",
})
ROOF_TOTALS_KEYS = frozenset({
    "dispatches", "flops", "bytes", "device_ms", "predicted_ms",
    "mfu", "mbu",
})


def _populated_compile_ledger() -> CompileLedger:
    """A ledger exercising every snapshot branch: declared + dispatched
    keys, a sealed lattice, and one live-retrace witness."""
    led = CompileLedger()
    led.declare(("admit", 64, 4, 1))
    led.declare(("ragged", 8))  # graftragged's one wave-kernel variant
    led.dispatch(("admit", 64, 4, 1), rid=-1, seconds=0.5)
    led.dispatch(("decode", 8), rid=-1, seconds=0.2)
    led.dispatch(("ragged", 8), rid=-1, seconds=0.4)
    led.warmup_done()
    led.dispatch(("admit", 64, 4, 1), rid=1, seconds=0.001)  # cache hit
    led.dispatch(("ragged", 8), rid=3, seconds=0.0)          # cache hit
    witness = led.dispatch(("admit", 128, 8, 1), rid=2, seconds=0.7)
    assert witness is not None  # undeclared post-seal => live retrace
    return led


def _populated_hbm_ledger() -> HbmLedger:
    led = HbmLedger()
    led.set_static("weights", 1 << 20)
    led.set_static("kv_cache", 1 << 18)
    led.gauge("kv_live", lambda: 4096)
    led.note_workspace(2048)
    return led


def _populated_sched_ledger() -> SchedLedger:
    """A ledger exercising every snapshot branch: admission + chunk
    groups, a starved budget pass, stalls/preempts, idle and dispatch
    boundaries, a decomposed queue wait, and a clean audit pass."""
    led = SchedLedger()
    led.note_group(("admit", 64, 4), 256, 100, 92, 64)
    led.note_group(("chunk", 128, 2, 0), 256, 200, 56, 0)
    # A graftragged wave: cells == useful by construction (exact-length
    # segments, no bucket rounding, no group replication).
    led.note_group(("ragged", 8), 46, 46, 0, 0)
    # A graftspec verify wave: 2 rows x (k=4 drafts + 1) = 10 cells, 7
    # emitted tokens -> 5 accepted drafts, 3 rejected positions.
    led.note_group(("verify", 4), 10, 7, 0, 0, spec_rejected=3)
    led.note_spec(8, 5, 3)
    led.note_budget(512, 400, starved=True)
    led.note_pool_stall(7)
    led.note_bucket_defer(7)
    led.note_preempt(9, tokens=48)
    led.note_boundary()
    led.note_idle()
    now = time.perf_counter()
    led.note_first_dispatch(7, submitted_at=now - 0.05, now=now)
    led.audit()
    return led


def _populated_pilot() -> PilotController:
    """A controller exercising every snapshot branch: a bound envelope,
    an EDF reorder + expired pop, one budget decision with its effect
    window already measured (counterfactual filled)."""
    import collections as _c
    import types as _t

    pilot = PilotController()
    pilot.bind(chunked=True, prefill_chunk=8, max_slots=4, max_admit=4,
               dispatch_token_budget=8, spec=True, spec_rungs=(1, 2, 4))
    now = time.perf_counter()
    pilot.order_queue(_c.deque([
        _t.SimpleNamespace(deadline=now + 9.0, submitted_at=now),
        _t.SimpleNamespace(deadline=now + 1.0, submitted_at=now),
    ]))
    pilot.note_expired_pop()

    def _windows(sig):
        for _ in range(pilot.period):
            pilot.on_boundary(lambda: dict(sig))

    base = {
        "boundaries": 0, "dispatch_cells": 0, "useful_tokens": 0,
        "frag_tokens": 0, "budget_dispatches": 0,
        "budget_starved_passes": 0, "budget_offered_tokens": 0,
        "budget_used_tokens": 0, "pool_stall_events": 0,
        "preemptions": 0, "deadline_expired": 0, "spec_drafted": 0,
        "spec_accepted": 0, "goodput": 1.0,
        "queue_depth": 0, "free_slots": 4, "roof_backlog_ms": 0.0,
        "heal_pressure": 0.0,
    }
    _windows(base)  # window 1 only baselines
    starved = dict(base, budget_dispatches=4, budget_starved_passes=4,
                   budget_offered_tokens=32, budget_used_tokens=32,
                   queue_depth=6)
    _windows(starved)  # window 2: budget raise decision
    _windows(dict(starved, goodput=0.75))  # window 3: effect measured
    return pilot


def _populated_roof_ledger() -> RoofLedger:
    """A ledger exercising every snapshot branch: bound geometry with
    resolved peaks, priced waves across three families (one zero-flop
    family so the host/bandwidth bound split is exercised), a decomposed
    boundary, and a clean audit pass."""
    led = RoofLedger()
    led.bind(get_config("tiny"), max_slots=4, max_seq_len=64,
             kv_block=16, platform="cpu-golden")
    led.note_wave([("admit", 8, 2), ("cow",)], device_ms=5.0)
    led.note_wave([("decode", 8)], device_ms=20.0)
    led.note_step(host_pre_ms=1.0, device_ms=25.0, host_post_ms=2.0,
                  span_ms=30.0)
    led.audit()
    return led


def test_compile_snapshot_key_set_is_frozen():
    snap = _populated_compile_ledger().snapshot()
    assert set(snap) == COMPILE_TOP_KEYS
    assert snap["live_retraces"], "fixture must produce a witness"
    for w in snap["live_retraces"]:
        assert set(w) == COMPILE_WITNESS_KEYS
    assert snap["lattice"], "fixture must produce lattice entries"
    for entry in snap["lattice"]:
        assert set(entry) == COMPILE_LATTICE_KEYS


def test_compile_snapshot_value_kinds():
    snap = _populated_compile_ledger().snapshot()
    assert isinstance(snap["warmup_complete"], bool)
    assert isinstance(snap["declared_variants"], int)
    assert isinstance(snap["dispatched_variants"], int)
    assert isinstance(snap["warmup_coverage"], float)
    assert isinstance(snap["compile_s_total"], float)
    assert isinstance(snap["live_retrace_count"], int)
    for entry in snap["lattice"]:
        # Keys render as the canonical slash-joined string, not tuples.
        assert isinstance(entry["key"], str) and "/" in entry["key"]
        assert isinstance(entry["declared"], bool)
    # The ragged family key renders with the same stable slash form as
    # every other family — consumers key lanes/gates on the string.
    ragged = [e for e in snap["lattice"] if e["key"] == "ragged/8"]
    assert len(ragged) == 1 and ragged[0]["declared"] is True
    assert ragged[0]["dispatches"] == 2


def test_compile_snapshot_empty_ledger_same_keys():
    # A never-touched ledger serves the SAME key set (consumers need no
    # existence checks), just with empty/zero values.
    snap = CompileLedger().snapshot()
    assert set(snap) == COMPILE_TOP_KEYS
    assert snap["lattice"] == [] and snap["live_retraces"] == []


def test_hbm_snapshot_key_set_is_frozen():
    snap = _populated_hbm_ledger().snapshot()
    assert set(snap) == HBM_TOP_KEYS
    assert snap["categories"], "fixture must produce categories"
    for cat in snap["categories"].values():
        assert set(cat) == HBM_CATEGORY_KEYS


def test_hbm_snapshot_value_kinds():
    snap = _populated_hbm_ledger().snapshot()
    cats = snap["categories"]
    assert cats["weights"]["static"] is True
    assert cats["kv_live"]["static"] is False
    assert cats["workspace"]["static"] is False
    assert isinstance(snap["total_bytes"], int)
    assert isinstance(snap["total_high_bytes"], int)
    assert snap["total_bytes"] == sum(c["bytes"] for c in cats.values())


def test_sched_snapshot_key_set_is_frozen():
    snap = _populated_sched_ledger().snapshot()
    assert set(snap) == SCHED_TOP_KEYS
    assert set(snap["goodput_gap"]) == SCHED_GAP_KEYS
    assert set(snap["spec"]) == SCHED_SPEC_KEYS
    assert set(snap["wait"]) == SCHED_WAIT_KEYS
    assert set(snap["conservation"]) == SCHED_CONSERVATION_KEYS
    assert snap["by_shape"], "fixture must produce shape entries"
    for entry in snap["by_shape"]:
        assert set(entry) == SCHED_SHAPE_KEYS


def test_sched_snapshot_value_kinds():
    snap = _populated_sched_ledger().snapshot()
    assert isinstance(snap["boundaries"], int)
    assert snap["boundaries"] == (snap["dispatch_boundaries"]
                                  + snap["idle_boundaries"])
    assert isinstance(snap["padding_waste_frac"], float)
    assert isinstance(snap["budget_utilization"], float)
    for frac in snap["goodput_gap"].values():
        assert isinstance(frac, float) and 0.0 <= frac <= 1.0
    for comp in snap["wait"].values():
        assert isinstance(comp, (int, float)) and comp >= 0
    # The fixture's audit() pass must have run clean.
    assert snap["conservation"]["checked"] == 1
    assert snap["conservation"]["breaches"] == 0
    assert snap["conservation"]["last_breach"] is None
    # Conservation restated from the snapshot itself — the four-way
    # split (graftspec adds rejected draft positions).
    assert (snap["useful_tokens"] + snap["bucket_pad_tokens"]
            + snap["group_pad_tokens"]
            + snap["spec_rejected_tokens"]) == snap["dispatch_cells"]
    # graftspec acceptance identity restated from the snapshot.
    spec = snap["spec"]
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["drafted_tokens"])
    assert spec["verify_waves"] == 1
    assert isinstance(spec["acceptance_rate"], float)
    for entry in snap["by_shape"]:
        # Keys render as the canonical slash-joined string, not tuples.
        assert isinstance(entry["key"], str) and "/" in entry["key"]
    # The ragged family's by_shape entry: stable "ragged/C" key, and
    # its waste attribution is zero-pad by construction.
    ragged = [e for e in snap["by_shape"] if e["key"] == "ragged/8"]
    assert len(ragged) == 1
    assert ragged[0]["cells"] == ragged[0]["useful_tokens"] == 46
    assert ragged[0]["bucket_pad_tokens"] == 0
    assert ragged[0]["group_pad_tokens"] == 0


def test_sched_snapshot_empty_ledger_same_keys():
    # A never-touched ledger serves the SAME key set (consumers need no
    # existence checks), just with empty/zero values.
    snap = SchedLedger().snapshot()
    assert set(snap) == SCHED_TOP_KEYS
    assert set(snap["goodput_gap"]) == SCHED_GAP_KEYS
    assert set(snap["spec"]) == SCHED_SPEC_KEYS
    assert set(snap["wait"]) == SCHED_WAIT_KEYS
    assert snap["by_shape"] == []
    assert snap["spec"]["drafted_tokens"] == 0
    assert snap["spec"]["acceptance_rate"] == 1.0
    assert snap["dispatch_cells"] == 0
    assert snap["padding_waste_frac"] == 0.0
    assert snap["budget_utilization"] == 1.0


def test_pilot_snapshot_key_set_is_frozen():
    snap = _populated_pilot().snapshot()
    assert set(snap) == PILOT_TOP_KEYS
    assert set(snap["decisions_by_knob"]) == PILOT_KNOB_KEYS
    assert set(snap["knobs"]) == PILOT_KNOB_KEYS
    assert set(snap["envelope"]) == PILOT_ENVELOPE_KEYS
    assert set(snap["edf"]) == PILOT_EDF_KEYS
    assert set(snap["counterfactual"]) == PILOT_CF_KEYS
    assert snap["ledger"], "fixture must produce a decision"
    for entry in snap["ledger"]:
        assert set(entry) == PILOT_LEDGER_KEYS
        assert set(entry["signal_snapshot"]) == PILOT_SIGNAL_KEYS
        # The fixture closed the effect window: the counterfactual half
        # of every entry is filled, with exactly the documented keys.
        assert set(entry["effect"]) == PILOT_EFFECT_KEYS


def test_pilot_snapshot_value_kinds():
    snap = _populated_pilot().snapshot()
    assert snap["enabled"] is True
    assert snap["mode"] == "auto"
    assert isinstance(snap["boundaries"], int)
    assert isinstance(snap["windows"], int)
    assert isinstance(snap["period_boundaries"], int)
    assert snap["decisions_total"] == sum(
        snap["decisions_by_knob"].values())
    for v in snap["knobs"].values():
        assert isinstance(v, int)
    for v in snap["envelope"].values():
        assert isinstance(v, int)
    for v in snap["edf"].values():
        assert isinstance(v, int)
    assert isinstance(snap["counterfactual"]["goodput_delta"], float)
    for entry in snap["ledger"]:
        assert isinstance(entry["ts"], float)
        assert isinstance(entry["old"], int)
        assert isinstance(entry["new"], int)
        assert entry["rationale"] and isinstance(entry["rationale"], str)
        assert entry["expected_effect"]
        for v in entry["signal_snapshot"].values():
            assert isinstance(v, (int, float))
    # Live knobs stay inside the envelope — restated from the snapshot.
    env, knobs = snap["envelope"], snap["knobs"]
    assert env["budget_min"] <= knobs["dispatch_token_budget"] \
        <= env["budget_max"]
    assert env["admit_min"] <= knobs["max_admit"] <= env["admit_max"]
    assert env["bias_min"] <= knobs["chunk_bias"] <= env["bias_max"]
    assert env["speck_min"] <= knobs["spec_k"] <= env["speck_max"]


def test_pilot_snapshot_empty_controller_same_keys():
    # A never-flown controller serves the SAME key set (consumers need
    # no existence checks), just with empty/zero values.
    pilot = PilotController()
    pilot.bind(chunked=True, prefill_chunk=8, max_slots=4, max_admit=4,
               dispatch_token_budget=8)
    snap = pilot.snapshot()
    assert set(snap) == PILOT_TOP_KEYS
    assert snap["boundaries"] == 0
    assert snap["decisions_total"] == 0
    assert snap["ledger"] == []


def test_roof_snapshot_key_set_is_frozen():
    snap = _populated_roof_ledger().snapshot()
    assert set(snap) == ROOF_TOP_KEYS
    assert set(snap["peaks"]) == ROOF_PEAKS_KEYS
    assert set(snap["step"]) == ROOF_STEP_KEYS
    assert set(snap["conservation"]) == ROOF_CONSERVATION_KEYS
    assert set(snap["totals"]) == ROOF_TOTALS_KEYS
    assert snap["variants"], "fixture must produce variant entries"
    for entry in snap["variants"]:
        assert set(entry) == ROOF_VARIANT_KEYS


def test_roof_snapshot_value_kinds():
    snap = _populated_roof_ledger().snapshot()
    assert snap["enabled"] is True
    assert snap["platform"] == "cpu-golden"
    assert snap["peaks"]["source"] in ("env", "table", "microbench")
    assert isinstance(snap["peaks"]["tflops"], float)
    assert snap["peaks"]["tflops"] > 0.0
    assert isinstance(snap["boundaries"], int) and snap["boundaries"] == 1
    assert isinstance(snap["waves"], int) and snap["waves"] == 2
    for v in snap["step"].values():
        assert isinstance(v, float) and v >= 0.0
    # Decomposition restated from the snapshot itself: the components
    # re-sum to the measured boundary wall (overlap absorbs the gap).
    step = snap["step"]
    parts = (step["host_pre_ms"] + step["device_ms"]
             + step["host_post_ms"] + step["overlap_ms"])
    assert abs(parts - step["wall_ms"]) <= max(1.0, 0.01 * step["wall_ms"])
    assert 0.0 <= snap["host_frac"] <= 1.0
    assert 0.0 <= snap["device_frac"] <= 1.0
    # The fixture's audit() pass must have run clean.
    assert snap["conservation"]["checked"] == 1
    assert snap["conservation"]["breaches"] == 0
    assert snap["conservation"]["last_breach"] is None
    seen_bounds = set()
    for entry in snap["variants"]:
        # Keys render as the canonical slash-joined string, not tuples.
        assert isinstance(entry["key"], str)
        assert entry["family"] == entry["key"].split("/")[0]
        assert 0.0 <= entry["mfu"] <= 1.0
        assert 0.0 <= entry["mbu"] <= 1.0
        assert entry["bound"] in ("compute", "bandwidth", "host")
        seen_bounds.add(entry["bound"])
        assert entry["dispatches"] >= 1
        assert entry["device_ms"] >= 0.0
    # The cow wave prices zero flops: it can never read compute-bound.
    cow = [e for e in snap["variants"] if e["family"] == "cow"]
    assert len(cow) == 1 and cow[0]["flops"] == 0.0
    assert cow[0]["bound"] in ("bandwidth", "host")
    tot = snap["totals"]
    assert tot["dispatches"] == sum(
        e["dispatches"] for e in snap["variants"])
    # Wave device time is conserved across the per-variant split.
    assert abs(tot["device_ms"] - sum(
        e["device_ms"] for e in snap["variants"])) < 0.01
    assert 0.0 <= tot["mfu"] <= 1.0
    assert 0.0 <= tot["mbu"] <= 1.0


def test_roof_snapshot_empty_ledger_same_keys():
    # A never-touched ledger serves the SAME key set (consumers need no
    # existence checks), just with empty/zero values.
    snap = RoofLedger().snapshot()
    assert set(snap) == ROOF_TOP_KEYS
    assert set(snap["peaks"]) == ROOF_PEAKS_KEYS
    assert set(snap["step"]) == ROOF_STEP_KEYS
    assert set(snap["totals"]) == ROOF_TOTALS_KEYS
    assert snap["variants"] == []
    assert snap["boundaries"] == 0 and snap["waves"] == 0
    assert snap["host_frac"] == 0.0 and snap["device_frac"] == 0.0
    assert snap["totals"]["mfu"] == 0.0


def _populated_supervisor() -> HealSupervisor:
    """A supervisor exercising every snapshot branch: one recovery
    (state leaves healthy), a resurrection counted, a penned repeat
    replay, and a bisection round in flight (suspects + probing
    non-empty)."""
    import types as _t

    sup = HealSupervisor(max_retries=4, watchdog_ms=50)
    now = time.perf_counter()
    # First fault over rids 1..3: everyone resurrects.
    v1 = sup.plan_recovery([1, 2, 3], now)
    assert set(v1.values()) == {"resurrect"}
    for _ in v1:
        sup.note_resurrected()
    # Second fault over the same cohort: bisection starts; the
    # non-probing half lands in the pen.
    v2 = sup.plan_recovery([1, 2, 3], now)
    assert "pen" in v2.values()
    for rid, verdict in sorted(v2.items()):
        if verdict == "pen":
            sup.pen_put(_t.SimpleNamespace(rid=rid, finished=False), now)
    return sup


def test_health_snapshot_key_set_is_frozen():
    snap = _populated_supervisor().snapshot()
    assert set(snap) == HEALTH_TOP_KEYS


def test_health_snapshot_value_kinds():
    snap = _populated_supervisor().snapshot()
    assert snap["enabled"] is True
    assert snap["state"] in ("healthy", "recovering", "degraded")
    assert snap["mode"] in ("normal", "bisect")
    assert isinstance(snap["max_retries"], int)
    assert isinstance(snap["watchdog_ms"], int)
    for k in ("resurrected", "quarantined", "watchdog_trips",
              "retry_exhausted", "sentinel_trips", "recoveries",
              "consecutive_faults", "clean_boundaries", "pen"):
        assert isinstance(snap[k], int) and snap[k] >= 0
    assert isinstance(snap["suspects"], list)
    assert isinstance(snap["probing"], list)
    # The fixture left a bisection in flight with a populated pen.
    assert snap["mode"] == "bisect"
    assert snap["suspects"] and snap["probing"]
    assert snap["pen"] >= 1
    assert snap["resurrected"] == 3 and snap["recoveries"] == 2
    # Pressure restates the state machine: recovering (no quarantine or
    # exhaustion happened) reads 0.5.
    assert snap["state"] == "recovering" and snap["pressure"] == 0.5


def test_health_snapshot_fresh_supervisor_same_keys():
    # A never-faulted supervisor serves the SAME key set (consumers
    # need no existence checks), just with empty/zero values.
    snap = HealSupervisor().snapshot()
    assert set(snap) == HEALTH_TOP_KEYS
    assert snap["state"] == "healthy" and snap["pressure"] == 0.0
    assert snap["mode"] == "normal"
    assert snap["suspects"] == [] and snap["probing"] == []
    assert snap["pen"] == 0 and snap["recoveries"] == 0


def test_snapshots_are_json_clean():
    # All snapshots must survive json.dumps untouched — they go over
    # the wire verbatim from the debug routes.
    comp = json.loads(json.dumps(_populated_compile_ledger().snapshot()))
    assert set(comp) == COMPILE_TOP_KEYS
    hbm = json.loads(json.dumps(_populated_hbm_ledger().snapshot()))
    assert set(hbm) == HBM_TOP_KEYS
    sched = json.loads(json.dumps(_populated_sched_ledger().snapshot()))
    assert set(sched) == SCHED_TOP_KEYS
    pilot = json.loads(json.dumps(_populated_pilot().snapshot()))
    assert set(pilot) == PILOT_TOP_KEYS
    roof = json.loads(json.dumps(_populated_roof_ledger().snapshot()))
    assert set(roof) == ROOF_TOP_KEYS
    heal = json.loads(json.dumps(_populated_supervisor().snapshot()))
    assert set(heal) == HEALTH_TOP_KEYS
