"""Fast-path unit transport (runtime/fastpath.py) + the sync-lane engine
that rides it: framing, error paths, reconnects, and meta parity between
the solo fast walk and the generic async walk."""

import threading

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime.fastpath import FastClient, start_fast_server


class EchoTags:
    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2

    def tags(self):
        return {"arm": "echo"}

    def metrics(self):
        return [{"type": "COUNTER", "key": "echo_calls", "value": 1}]


class Boom:
    def predict(self, X, names, meta=None):
        raise ValueError("boom payload")


@pytest.fixture(scope="module")
def fast_server():
    srv, port = start_fast_server(EchoTags(), "127.0.0.1", 0)
    yield port
    srv.shutdown()


def _req(rows):
    return payloads.build_message(np.asarray(rows, np.float64),
                                  names=["a", "b"], kind="ndarray")


def test_fastpath_roundtrip(fast_server):
    c = FastClient()
    out = c.call("127.0.0.1", fast_server, "predict", _req([[1.0, 2.0]]))
    arr, _, _, _ = payloads.extract_request_parts(out)
    np.testing.assert_allclose(np.asarray(arr), [[2.0, 4.0]])
    # User tags/metrics ride meta like every other transport.
    assert out.meta.tags["arm"].string_value == "echo"
    assert out.meta.metrics[0].key == "echo_calls"
    c.close()


def test_fastpath_persistent_socket_many_calls(fast_server):
    c = FastClient()
    for i in range(20):
        out = c.call("127.0.0.1", fast_server, "predict",
                     _req([[float(i), 1.0]]))
        arr, _, _, _ = payloads.extract_request_parts(out)
        assert np.asarray(arr)[0][0] == 2.0 * i
    c.close()


def test_fastpath_unit_error_is_framed():
    srv, port = start_fast_server(Boom(), "127.0.0.1", 0)
    try:
        c = FastClient()
        with pytest.raises(RuntimeError, match="boom payload"):
            c.call("127.0.0.1", port, "predict", _req([[1.0, 2.0]]))
        # The connection survives a unit error (framed, not fatal).
        with pytest.raises(RuntimeError, match="boom payload"):
            c.call("127.0.0.1", port, "predict", _req([[1.0, 2.0]]))
        c.close()
    finally:
        srv.shutdown()


def test_fastpath_reconnect_after_server_restart():
    srv, port = start_fast_server(EchoTags(), "127.0.0.1", 0)
    c = FastClient()
    c.call("127.0.0.1", port, "predict", _req([[1.0, 2.0]]))
    srv.shutdown()
    srv.server_close()
    srv2, port2 = start_fast_server(EchoTags(), "127.0.0.1", port)
    try:
        # The stale persistent socket raises ConnectionError (the engine
        # client retries and reconnects); a fresh call then succeeds.
        try:
            c.call("127.0.0.1", port, "predict", _req([[1.0, 2.0]]))
        except (ConnectionError, OSError):
            pass
        out = c.call("127.0.0.1", port2, "predict", _req([[1.0, 2.0]]))
        arr, _, _, _ = payloads.extract_request_parts(out)
        np.testing.assert_allclose(np.asarray(arr), [[2.0, 4.0]])
    finally:
        c.close()
        srv2.shutdown()
        srv2.server_close()


def test_async_fast_client_stale_pool_survives_restart():
    """A pooled async connection dying (unit restart) surfaces as
    StaleConnection — retryable, but never counted toward the lane
    write-off — and the next call reconnects."""
    import asyncio

    from seldon_tpu.runtime.fastpath import AsyncFastClient, StaleConnection

    srv, port = start_fast_server(EchoTags(), "127.0.0.1", 0)

    async def go():
        c = AsyncFastClient()
        out = await c.call("127.0.0.1", port, "predict", _req([[1.0, 2.0]]))
        arr, _, _, _ = payloads.extract_request_parts(out)
        np.testing.assert_allclose(np.asarray(arr), [[2.0, 4.0]])
        srv.shutdown()
        srv.server_close()
        srv2, _ = start_fast_server(EchoTags(), "127.0.0.1", port)
        try:
            try:
                await c.call("127.0.0.1", port, "predict",
                             _req([[1.0, 2.0]]))
                stale = None  # at_eof skim may already have dropped it
            except ConnectionError as e:
                stale = e
                # retry reconnects fresh
                await c.call("127.0.0.1", port, "predict",
                             _req([[1.0, 2.0]]))
            if stale is not None:
                assert isinstance(stale, StaleConnection), stale
        finally:
            await c.close()
            srv2.shutdown()
            srv2.server_close()

    asyncio.run(go())


def test_fastpath_threaded_clients(fast_server):
    """Per-thread sockets: concurrent callers never share a connection."""
    c = FastClient()
    errs = []

    def worker(i):
        try:
            for _ in range(10):
                out = c.call("127.0.0.1", fast_server, "predict",
                             _req([[float(i), 0.0]]))
                arr, _, _, _ = payloads.extract_request_parts(out)
                assert np.asarray(arr)[0][0] == 2.0 * i
        except Exception as e:  # surfaced below
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    [t.start() for t in ts]
    [t.join(30) for t in ts]
    assert not errs, errs


# ---------------------------------------------------------------------------
# Sync-lane engine over a network unit
# ---------------------------------------------------------------------------


def _engine_server_with_unit(fast: bool):
    from seldon_tpu.orchestrator.server import EngineServer
    from seldon_tpu.orchestrator.spec import (
        Endpoint, PredictiveUnit, PredictorSpec,
    )
    from seldon_tpu.runtime.wrapper import build_grpc_server

    model = EchoTags()
    gsrv = build_grpc_server(model)
    gport = gsrv.add_insecure_port("127.0.0.1:0")
    gsrv.start()
    fsrv, fport = start_fast_server(model, "127.0.0.1", 0)
    spec = PredictorSpec(
        name="p",
        graph=PredictiveUnit(
            name="echo", type="MODEL",
            endpoint=Endpoint(service_host="127.0.0.1", service_port=gport,
                              fast_port=fport if fast else 0),
        ),
    )
    es = EngineServer(spec=spec, http_port=0, grpc_port=0,
                      enable_batching=False)
    return es, (gsrv, fsrv)


@pytest.mark.parametrize("fast", [True, False])
def test_sync_lane_serves_network_unit(fast):
    """The sync thread-pool gRPC lane now covers network-unit graphs
    (round-5: SyncInternalClient); response meta matches the contract
    (puid, requestPath, unit tags)."""
    import asyncio

    import grpc

    from seldon_tpu.proto import prediction_grpc

    es, servers = _engine_server_with_unit(fast)
    assert es.engine_sync is not None, "graph should be sync-drivable"

    holder, started = {}, threading.Event()

    async def amain():
        await es.start(host="127.0.0.1")
        holder["grpc"] = es.grpc_port
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await es.stop()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    assert started.wait(30)
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{holder['grpc']}")
        stub = prediction_grpc.SeldonStub(ch)
        out = stub.Predict(_req([[1.0, 2.0]]), timeout=30)
        arr, _, _, _ = payloads.extract_request_parts(out)
        np.testing.assert_allclose(np.asarray(arr), [[2.0, 4.0]])
        assert out.meta.puid
        assert out.meta.requestPath["echo"] == "echo"
        assert out.meta.tags["arm"].string_value == "echo"
        ch.close()
    finally:
        holder["stop"] = True
        t.join(timeout=15)
        for s in servers:
            try:
                s.stop(grace=0.2)
            except (AttributeError, TypeError):
                s.shutdown()


def test_fast_lane_falls_back_when_port_refused():
    """A declared fastPort nobody serves (unit image without the lane)
    must not fail the graph: the sync client falls back to gRPC for
    good after the first refused connect."""
    from seldon_tpu.orchestrator.client import SyncInternalClient
    from seldon_tpu.orchestrator.spec import Endpoint, PredictiveUnit
    from seldon_tpu.runtime.wrapper import build_grpc_server

    gsrv = build_grpc_server(EchoTags())
    gport = gsrv.add_insecure_port("127.0.0.1:0")
    gsrv.start()
    # Claim a port and close it: connects there are REFUSED.
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    unit = PredictiveUnit(
        name="echo", type="MODEL",
        endpoint=Endpoint(service_host="127.0.0.1", service_port=gport,
                          fast_port=dead_port),
    )
    c = SyncInternalClient(retries=1)
    try:
        coro = c.call(unit, "predict", _req([[1.0, 2.0]]))
        # drive the never-suspending coroutine without a loop
        try:
            coro.send(None)
            raise AssertionError("sync client call suspended")
        except StopIteration as e:
            out = e.value
        arr, _, _, _ = payloads.extract_request_parts(out)
        np.testing.assert_allclose(np.asarray(arr), [[2.0, 4.0]])
        assert ("127.0.0.1", dead_port) in c._fast_dead
    finally:
        gsrv.stop(grace=0.2)


def test_sync_drivable_classification():
    """Router fan-outs ride the sync lane (one branch per request);
    COMBINER fan-outs over network children need the async gather."""
    from seldon_tpu.orchestrator.spec import (
        Endpoint, PredictiveUnit, PredictorSpec,
    )
    from seldon_tpu.orchestrator.walker import PredictorEngine

    def net(name):
        return PredictiveUnit(name=name, type="MODEL",
                              endpoint=Endpoint(service_port=9000))

    router = PredictorSpec(name="p", graph=PredictiveUnit(
        name="r", type="ROUTER", endpoint=Endpoint(service_port=9004),
        children=[net("a"), net("b")],
    ))
    assert PredictorEngine.sync_drivable(router)

    combiner = PredictorSpec(name="p", graph=PredictiveUnit(
        name="c", type="COMBINER", endpoint=Endpoint(service_port=9004),
        children=[net("a"), net("b")],
    ))
    assert not PredictorEngine.sync_drivable(combiner)

    hardcoded_combiner = PredictorSpec(name="p", graph=PredictiveUnit(
        name="c", type="COMBINER", implementation="AVERAGE_COMBINER",
        children=[
            PredictiveUnit(name="a", type="MODEL",
                           implementation="SIMPLE_MODEL"),
            PredictiveUnit(name="b", type="MODEL",
                           implementation="SIMPLE_MODEL"),
        ],
    ))
    assert PredictorEngine.sync_drivable(hardcoded_combiner)


def test_solo_fast_walk_meta_parity():
    """predict_sync's solo fast walk returns the same meta as the generic
    async walk for the same graph + request."""
    import asyncio

    es, servers = _engine_server_with_unit(True)
    try:
        eng_async, eng_sync = es.engine, es.engine_sync
        assert eng_sync._solo_unit is not None

        req1 = _req([[1.0, 2.0]])
        req1.meta.puid = "fixed-puid"
        req2 = pb.SeldonMessage()
        req2.CopyFrom(req1)

        out_async = asyncio.run(eng_async.predict(req1))
        # The async lane rode the fast transport too (AsyncFastClient
        # is built lazily on first fast-lane use).
        assert eng_async.client._afast is not None
        out_sync = eng_sync.predict_sync(req2)
        assert out_sync.meta.puid == out_async.meta.puid == "fixed-puid"
        assert dict(out_sync.meta.requestPath) == dict(
            out_async.meta.requestPath)
        assert (out_sync.meta.tags["arm"].string_value
                == out_async.meta.tags["arm"].string_value)
        assert ([m.key for m in out_sync.meta.metrics]
                == [m.key for m in out_async.meta.metrics])
        asyncio.run(eng_async.close())
        asyncio.run(eng_sync.close())
    finally:
        for s in servers:
            try:
                s.stop(grace=0.2)
            except (AttributeError, TypeError):
                s.shutdown()
