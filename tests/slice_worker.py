"""Subprocess worker for test_distributed.py: joins a 2-process CPU
"slice" via seldon_tpu.parallel.distributed and proves cross-host
collectives work. Prints one JSON line the test asserts on."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from seldon_tpu.parallel import distributed


def main():
    coordinator = sys.argv[1]
    pid = int(sys.argv[2])
    nproc = int(sys.argv[3])

    cfg = distributed.SliceConfig(
        coordinator=coordinator, num_processes=nproc, process_id=pid
    )
    assert distributed.ensure_initialized(cfg)
    assert distributed.ensure_initialized(cfg)  # idempotent

    # Slice-aware readiness: all hosts joined -> check passes.
    distributed.SliceReadiness(expected_hosts=nproc).check()

    # Cross-host collective: allgather each process's id.
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.array([pid], np.int32))

    # Global mesh spanning both processes; one sharded computation.
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()).reshape(-1)
    mesh = Mesh(devs, ("dp",))
    n = len(devs)
    y = jax.jit(
        lambda: jnp.sum(jnp.arange(n * 4, dtype=jnp.float32)),
        out_shardings=NamedSharding(mesh, P()),
    )()

    print(json.dumps({
        "process_id": pid,
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "allgather": np.asarray(gathered).ravel().tolist(),
        "sharded_sum": float(y),
    }), flush=True)


if __name__ == "__main__":
    main()
