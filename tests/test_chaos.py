"""Deterministic fault injection (servers/chaos.py) against the engine.

The load-bearing claims, in test form:
 * CHAOS env gating is fail-safe: probabilities without the CHAOS=1
   master switch are inert, and the switch alone (all probs zero) is
   inert too;
 * an injected dispatch failure drives `_fail_all`: the waiter gets a
   typed internal error + sentinel (never a hang), the device/slot
   state is rebuilt, and the very next greedy request is bit-identical
   to pre-fault output — dense AND paged;
 * injected allocator exhaustion only delays paged admission (stall /
   preempt path) — requests still complete and nothing leaks;
 * the acceptance soak: a 200-request mixed run under seeded chaos +
   client deadlines + client cancels finishes with ZERO hung waiters,
   every request in exactly one outcome bucket, and an empty
   `debug_lifecycle_check()` after drain.

The long-haul version of the soak (FUZZ_EXAMPLES requests, paged too)
is marked fuzz+slow: `make fuzz-chaos` runs it, tier-1 does not.
"""

import os
import random
import threading
import time

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.chaos import ChaosConfig, ChaosMonkey
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)

PAGED = dict(paged_kv=True, kv_block=16, kv_pool_blocks=9,
             prompt_buckets=(16, 32))


def _engine(cfg=None, start=True, **ekw):
    cfg = cfg or get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(q, timeout=120):
    toks, err = 0, None
    while True:
        item = q.get(timeout=timeout)
        if item is None:
            return toks, err
        if "error" in item:
            err = item
        else:
            toks += len(item["tokens"])


# ---------------------------------------------------------------------------
# Env gating
# ---------------------------------------------------------------------------


def test_chaos_from_env_requires_master_switch(monkeypatch):
    monkeypatch.delenv("CHAOS", raising=False)
    monkeypatch.setenv("CHAOS_DISPATCH_FAIL", "0.5")
    assert ChaosConfig.from_env() is None  # knob without switch: inert

    monkeypatch.setenv("CHAOS", "1")
    cfg = ChaosConfig.from_env()
    assert cfg is not None and cfg.dispatch_fail == 0.5

    monkeypatch.setenv("CHAOS_DISPATCH_FAIL", "0")
    assert ChaosConfig.from_env() is None  # switch without knobs: inert


# ---------------------------------------------------------------------------
# _fail_all coverage via injected dispatch failure
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_dispatch_fault_fails_waiter_and_engine_recovers(paged):
    """Chaos certainty (dispatch_fail=1.0) mid-decode: the waiter gets
    a typed error, never hangs; chaos off again, the rebuilt device
    state serves bit-identical greedy output and nothing leaked."""
    ekw = dict(decode_chunk=1, min_chunk=1, adaptive_chunk=False)
    if paged:
        ekw.update(PAGED)
    eng = _engine(**ekw)
    try:
        want = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]

        q = eng.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=40))
        first = q.get(timeout=120)
        assert "error" not in first
        # Attribute store is atomic; the scheduler reads it per dispatch.
        eng._chaos = ChaosMonkey(ChaosConfig(seed=0, dispatch_fail=1.0))
        toks, err = _collect(q)
        assert err is not None, "faulted request must error, not complete"
        assert err["kind"] == "internal"
        assert eng._chaos.snapshot()["dispatch_faults"] >= 1
        assert len(first["tokens"]) + toks < 40

        eng._chaos = None
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        assert got == want, "post-_fail_all rebuild diverged from pre-fault"
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


def test_alloc_fault_stalls_or_preempts_never_wedges():
    """Injected pool exhaustion hits `_pool_reserve`: requests either
    complete (admission stalled, then retried) or are preempted with
    the typed retriable error — never hang, never leak."""
    eng = _engine(chaos=ChaosConfig(seed=0, alloc_fail=0.5), **PAGED)
    try:
        qs = [eng.submit([2 + i, 3 + i, 5 + i, 7 + i, 11 + i], GREEDY)
              for i in range(6)]
        done = 0
        for q in qs:
            toks, err = _collect(q)
            if err is None:
                assert 1 <= toks <= 8
                done += 1
            else:
                assert err["kind"] == "preempted", err
                assert err["retriable"] is True
        assert done >= 1, "alloc chaos starved every request"
        assert eng.chaos_counts()["alloc_faults"] >= 1
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Mixed soak: the acceptance run
# ---------------------------------------------------------------------------


def _run_soak(eng, n, seed, deadline_frac=0.1, cancel_frac=0.1):
    """Submit n requests with injected client behavior (deadlines,
    mid-stream cancels); classify every request into exactly one
    outcome. All randomness is main-thread, drawn before submit, so a
    fixed seed replays the same request stream."""
    rng = random.Random(seed)
    outcomes = {"completed": 0, "shed": 0, "deadline": 0,
                "cancelled": 0, "errored": 0}
    lock = threading.Lock()
    threads = []

    def record(kind):
        with lock:
            outcomes[kind] += 1

    def consume(q, want_cancel):
        err = None
        sent_cancel = False
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            if "error" in item:
                err = item
                continue
            if want_cancel and not sent_cancel:
                sent_cancel = True
                eng.cancel(q.rid)
        if err is None:
            record("completed")
        else:
            kind = err.get("kind", "internal")
            if kind in ("deadline", "cancelled"):
                record(kind)
            elif kind in ("capacity", "draining", "shutdown"):
                record("shed")
            else:
                record("errored")

    for i in range(n):
        plen = rng.choice((5, 8, 13, 21))
        prompt = [2 + (i + j) % 200 for j in range(plen)]
        dl = rng.choice((30, 80)) if rng.random() < deadline_frac else 0
        want_cancel = rng.random() < cancel_frac
        sp = SamplingParams(temperature=0.0,
                            max_new_tokens=rng.choice((4, 8)),
                            deadline_ms=dl)
        try:
            q = eng.submit(prompt, sp)
        except RuntimeError:  # EngineOverloaded / EngineDraining
            record("shed")
            continue
        t = threading.Thread(target=consume, args=(q, want_cancel),
                             daemon=True)
        t.start()
        threads.append(t)

    stop_by = time.monotonic() + 300
    hung = 0
    for t in threads:
        t.join(timeout=max(0.0, stop_by - time.monotonic()))
        if t.is_alive():
            hung += 1
    return outcomes, hung


def _soak_engine(n, paged, seed):
    ekw = dict(
        max_slots=8,
        max_queue=4 * n,
        chaos=ChaosConfig(
            seed=seed,
            dispatch_fail=0.02,
            alloc_fail=0.05 if paged else 0.0,
            slow_boundary=0.05,
            slow_ms=2.0,
            disconnect=0.01,
        ),
    )
    if paged:
        ekw.update(PAGED)
    return _engine(**ekw)


def test_chaos_soak_200_requests_exactly_one_outcome():
    """Acceptance: 200 mixed requests under seeded chaos — zero hung
    waiters, one outcome each, accounting empty after drain."""
    n = 200
    eng = _soak_engine(n, paged=False, seed=0)
    try:
        outcomes, hung = _run_soak(eng, n, seed=0)
        assert hung == 0, f"{hung} waiters never saw a sentinel"
        assert sum(outcomes.values()) == n, outcomes
        assert outcomes["completed"] > 0, outcomes
        assert eng.drain(timeout=120) is True
        assert eng.debug_lifecycle_check() == {}
        faults = eng.chaos_counts()
        assert sum(faults.values()) > 0, "chaos never fired — soak is inert"
    finally:
        eng.stop()


@pytest.mark.fuzz
@pytest.mark.slow
@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_chaos_soak_long_haul(paged):
    """FUZZ_EXAMPLES-scaled soak (make fuzz-chaos); CHAOS_SEED replays
    a fault sequence exactly."""
    n = int(os.environ.get("FUZZ_EXAMPLES", "500"))
    seed = int(os.environ.get("CHAOS_SEED", "0"))
    eng = _soak_engine(n, paged=paged, seed=seed)
    try:
        outcomes, hung = _run_soak(eng, n, seed=seed,
                                   deadline_frac=0.15, cancel_frac=0.15)
        assert hung == 0, f"{hung} waiters never saw a sentinel"
        assert sum(outcomes.values()) == n, outcomes
        assert eng.drain(timeout=300) is True
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
