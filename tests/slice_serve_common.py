"""Shared engine-under-mesh driver for the multi-process serving proof.

Used by BOTH the 2-process workers (tests/slice_serve_worker.py) and the
single-process reference run in test_distributed.py — identical logical
program, so the sharded-across-processes tokens must match the
single-process-mesh tokens exactly."""

from typing import Dict, List

import numpy as np


def run_engine() -> Dict[str, List[int]]:
    import jax
    from jax.sharding import Mesh

    from seldon_tpu.models import get_config, transformer
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.parallel import sharding as shd
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    # TP as the SLOWEST axis: on the 2-process slice (4 local devices
    # each) the tp=2 groups pair device i of process 0 with device i of
    # process 1 — attention/MLP psums cross the process boundary.
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("tp", "dp"))

    cfg = get_config("tiny")
    with mesh:
        params = jax.jit(
            lambda k: transformer.init_params(cfg, k),
            out_shardings=shd.named_shardings(
                mesh, shd.param_pspecs(cfg)
            ),
        )(jax.random.key(0))

    ecfg = EngineConfig(
        max_slots=8,  # divides dp=4
        max_seq_len=48,
        prompt_buckets=(8,),
        max_admit=4,
        decode_chunk=4,
    )
    engine = InferenceEngine(params, cfg, ecfg, mesh=mesh)
    engine.warmup()

    # Deterministic request set, all queued BEFORE the scheduler runs.
    prompts = [[3 + (i * 7) % 40] * (2 + i % 6) for i in range(6)]
    queues = [
        engine.submit(
            p,
            SamplingParams(
                temperature=0.8, top_k=0, top_p=1.0,
                max_new_tokens=6 + i, seed=100 + i,
            ),
        )
        for i, p in enumerate(prompts)
    ]
    engine.start()
    out: Dict[str, List[int]] = {}
    for i, q in enumerate(queues):
        toks: List[int] = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            assert "error" not in item, item
            toks.extend(item["tokens"])
        out[str(i)] = toks
    engine.stop()
    return out
