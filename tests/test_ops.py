"""Kernel tests: flash attention (pallas vs reference) and ring attention
(shard_map vs single-device reference) on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from seldon_tpu.ops.flash_attention import attention_reference, flash_attention
from seldon_tpu.parallel import MeshPlan, make_mesh
from seldon_tpu.parallel.ring_attention import ring_attention


def _qkv(key, BH=4, Sq=64, Skv=64, Dh=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (BH, Sq, Dh), dtype),
        jax.random.normal(kk, (BH, Skv, Dh), dtype),
        jax.random.normal(kv, (BH, Skv, Dh), dtype),
    )


def test_reference_attention_causality():
    q, k, v = _qkv(jax.random.key(0))
    out = attention_reference(q, k, v, causal=True)
    # Changing a future key must not affect past outputs.
    k2 = k.at[:, -1].add(10.0)
    out2 = attention_reference(q, k2, v, causal=True)
    np.testing.assert_allclose(out[:, :-1], out2[:, :-1], rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_pallas_interpret_matches_reference(causal):
    """Run the pallas kernel in interpret mode (CPU) vs the reference."""
    import importlib

    fa = importlib.import_module("seldon_tpu.ops.flash_attention")

    q, k, v = _qkv(jax.random.key(1), BH=2, Sq=32, Skv=32, Dh=8)
    ref = attention_reference(q, k, v, causal=causal)

    import functools
    from unittest import mock

    from jax.experimental import pallas as pl

    # interpret=True makes pallas_call run on CPU.
    orig = pl.pallas_call

    def interp(*a, **kw):
        kw["interpret"] = True
        return orig(*a, **kw)

    with mock.patch.object(pl, "pallas_call", interp):
        out = fa._flash_pallas(q, k, v, causal, 0, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


def test_flash_q_offset_decode_window():
    """q_offset masks correctly for a decode-style query suffix."""
    q, k, v = _qkv(jax.random.key(2), BH=2, Sq=8, Skv=32, Dh=8)
    # Queries are positions 24..31 of a 32-token sequence.
    out = attention_reference(q, k, v, causal=True, q_offset=24)
    full_q = jnp.concatenate(
        [jnp.zeros((2, 24, 8), q.dtype), q], axis=1
    )
    full = attention_reference(full_q, k, v, causal=True)
    np.testing.assert_allclose(out, full[:, 24:], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(MeshPlan(sp=4, dp=2))
    B, S, H, Dh = 2, 32, 4, 16
    key = jax.random.key(3)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, Dh))
    k = jax.random.normal(kk, (B, S, H, Dh))
    v = jax.random.normal(kv, (B, S, H, Dh))

    # Reference: fold heads, run full attention.
    def ref_fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, Dh)

    ref = attention_reference(ref_fold(q), ref_fold(k), ref_fold(v),
                              causal=causal)
    ref = ref.reshape(B, H, S, Dh).transpose(0, 2, 1, 3)

    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=causal)
    )(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gqa_matches_expanded(causal):
    """GQA ring (Hkv-head k/v rotate) == pre-expanded full-head ring."""
    mesh = make_mesh(MeshPlan(sp=4, dp=2))
    B, S, H, Hkv, Dh = 2, 32, 8, 2, 16
    G = H // Hkv
    key = jax.random.key(9)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, Dh))
    k = jax.random.normal(kk, (B, S, Hkv, Dh))
    v = jax.random.normal(kv, (B, S, Hkv, Dh))

    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs = jax.device_put(q, spec)
    ks, vs = (jax.device_put(x, spec) for x in (k, v))
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=causal)
    )(qs, ks, vs)

    # Head h must attend kv head h // G — same convention as
    # gqa_attention's reshape(B, S, Hkv, G, Dh).
    k_exp = jax.device_put(jnp.repeat(k, G, axis=2), spec)
    v_exp = jax.device_put(jnp.repeat(v, G, axis=2), spec)
    ref = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=causal)
    )(qs, k_exp, v_exp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


def test_ring_attention_grad_flows():
    mesh = make_mesh(MeshPlan(sp=2))
    B, S, H, Dh = 1, 16, 2, 8
    key = jax.random.key(4)
    q = jax.random.normal(key, (B, S, H, Dh))

    def loss(q):
        out = ring_attention(q, q, q, mesh, causal=True)
        return jnp.sum(out**2)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()


def test_forward_flash_flag_matches_xla():
    """cfg.attn_impl='flash' (reference fallback on CPU) == default path."""
    from seldon_tpu.models import forward, get_config, init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    base = forward(params, tokens, cfg)
    flash_cfg = get_config("tiny", attn_impl="flash")
    out = forward(params, tokens, flash_cfg)
    np.testing.assert_allclose(np.asarray(base), np.asarray(out), rtol=2e-2,
                               atol=2e-2)


def test_flash_gqa_native_interpret():
    """GQA via kv index_map == expanded-kv reference (interpret mode)."""
    import importlib
    from unittest import mock

    from jax.experimental import pallas as pl

    fa = importlib.import_module("seldon_tpu.ops.flash_attention")
    B, H, Hkv, S, Dh = 2, 4, 2, 32, 8
    G = H // Hkv
    key = jax.random.key(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B * H, S, Dh))
    k = jax.random.normal(kk, (B * Hkv, S, Dh))
    v = jax.random.normal(kv, (B * Hkv, S, Dh))
    ref = attention_reference(
        q, jnp.repeat(k, G, axis=0), jnp.repeat(v, G, axis=0), causal=True
    )

    orig = pl.pallas_call

    def interp(*a, **kw):
        kw["interpret"] = True
        return orig(*a, **kw)

    with mock.patch.object(pl, "pallas_call", interp):
        out = fa._flash_pallas(q, k, v, True, 0, 16, 16, q_per_kv=G)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


def test_config_rejects_bad_attn_impl():
    from seldon_tpu.models import get_config

    with pytest.raises(AssertionError):
        get_config("tiny", attn_impl="Flash")


def test_ring_attn_impl_forward_matches_xla():
    """cfg.attn_impl='ring' + a sequence-sharded mesh: full forward equals
    the plain xla-attention forward (long-context scoring path)."""
    import dataclasses

    import jax
    import numpy as np

    from seldon_tpu.models import get_config, init_params, forward
    from seldon_tpu.parallel import MeshPlan, make_mesh

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    ref = forward(params, tokens, cfg)

    ring_cfg = dataclasses.replace(cfg, attn_impl="ring")
    mesh = make_mesh(MeshPlan(sp=4, tp=2))
    out = jax.jit(
        lambda p, t: forward(p, t, ring_cfg, ring_mesh=mesh)
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2
    )


def test_ring_attn_train_step():
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from seldon_tpu.models import get_config
    from seldon_tpu.models.train import make_optimizer, make_sharded_train_step
    from seldon_tpu.parallel import MeshPlan, make_mesh

    cfg = dataclasses.replace(get_config("tiny"), attn_impl="ring")
    mesh = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
    init_fn, step_fn = make_sharded_train_step(
        mesh, cfg, make_optimizer(total_steps=10), seq_sharded=True
    )
    state = init_fn(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab_size)
    state, metrics = step_fn(state, toks, jnp.ones((4, 32), jnp.float32))
    assert np.isfinite(float(metrics["loss"]))


def test_decode_step_head_major_cache_layout():
    """decode_step writes the head-major [L, B, Hkv, T, Dh] cache at each
    row's position in one batched scatter — the written slots must hold
    exactly the rope'd fresh k/v and no other slot may change."""
    import numpy as np

    from seldon_tpu.models import get_config, init_params, transformer

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    cache = transformer.init_cache(cfg, 2, 16)
    assert cache["k"].shape == (cfg.n_layers, 2, cfg.n_kv_heads, 16,
                                cfg.head_dim)
    before = np.asarray(cache["k"])
    tok = jnp.array([3, 4], jnp.int32)
    pos = jnp.array([2, 5], jnp.int32)
    _, cache = transformer.decode_step(params, tok, pos, cache, cfg)
    after = np.asarray(cache["k"])
    changed = np.any(after != before, axis=(0, 2, 4))  # [B, T]
    for b, p in enumerate([2, 5]):
        assert changed[b, p], "fresh k must land at the row's position"
        changed[b, p] = False
    assert not changed.any(), "no other slot may be touched"
