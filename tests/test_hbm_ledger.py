"""HBM ledger tests: per-category byte accounting with high-watermarks.

Claims under test:
 * unit semantics — statics are fixed, gauges are evaluated only at
   snapshot and ratchet their high-watermark, a gauge that blows up
   mid-teardown degrades to 0 without losing its watermark, workspace
   tracks the latest dispatch footprint plus its own high;
 * env gating follows the None-attribute idiom (HBM_LEDGER);
 * a live engine accounts the real trees: weights and the KV
   reservation are non-zero at init, kv_live rises with an occupied
   slot and returns to 0 after the stream finishes, the workspace
   watermark moves once a dispatch runs;
 * the paged engine prorates kv_live over allocator used-blocks.
"""

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import hbm_ledger
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

PROMPT = list(range(2, 26))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


# ---------------------------------------------------------------------------
# Unit semantics
# ---------------------------------------------------------------------------


def test_static_gauge_and_workspace_accounting():
    led = hbm_ledger.HbmLedger()
    led.set_static("weights", 1000)
    live = {"n": 0}
    led.gauge("kv_live", lambda: live["n"])

    snap = led.snapshot()
    cats = snap["categories"]
    assert cats["weights"] == {"bytes": 1000, "bytes_per_device": 1000,
                               "high_bytes": 1000, "static": True}
    assert cats["kv_live"] == {"bytes": 0, "bytes_per_device": 0,
                               "high_bytes": 0, "static": False}
    assert "workspace" in cats
    # Single-chip ledger: per-device == full for every category.
    assert snap["devices"] == 1
    assert snap["total_bytes_per_device"] == snap["total_bytes"]

    # Gauge rises: bytes track it, high ratchets.
    live["n"] = 700
    assert led.snapshot()["categories"]["kv_live"]["bytes"] == 700
    live["n"] = 300
    kv = led.snapshot()["categories"]["kv_live"]
    assert kv["bytes"] == 300 and kv["high_bytes"] == 700

    # Workspace: latest footprint + its own watermark.
    led.note_workspace(5000)
    led.note_workspace(2000)
    ws = led.snapshot()["categories"]["workspace"]
    assert ws["bytes"] == 2000 and ws["high_bytes"] == 5000

    snap = led.snapshot()
    assert snap["total_bytes"] == 1000 + 300 + 2000
    assert snap["total_high_bytes"] == 1000 + 700 + 5000


def test_broken_gauge_degrades_to_zero_keeps_watermark():
    led = hbm_ledger.HbmLedger()
    state = {"obj": type("S", (), {"n": 400})()}
    led.gauge("kv_live", lambda: state["obj"].n)
    assert led.snapshot()["categories"]["kv_live"]["bytes"] == 400
    state["obj"] = None  # mid-teardown: attribute access raises
    kv = led.snapshot()["categories"]["kv_live"]
    assert kv["bytes"] == 0 and kv["high_bytes"] == 400


def test_from_env_gating(monkeypatch):
    monkeypatch.delenv("HBM_LEDGER", raising=False)
    assert hbm_ledger.from_env() is None
    monkeypatch.setenv("HBM_LEDGER", "0")
    assert hbm_ledger.from_env() is None
    monkeypatch.setenv("HBM_LEDGER", "1")
    assert hbm_ledger.from_env() is not None


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


def test_engine_accounts_real_trees(monkeypatch):
    monkeypatch.setenv("HBM_LEDGER", "1")
    eng = _engine()
    try:
        hbm = eng.debug_hbm()
        cats = hbm["categories"]
        for name in ("weights", "kv_cache", "kv_live", "prefix_cache",
                     "workspace"):
            assert name in cats, name
        assert cats["weights"]["static"] is True
        assert cats["weights"]["bytes"] > 0
        assert cats["kv_cache"]["bytes"] > 0
        # Nothing admitted yet: no live KV, no dispatch footprint.
        assert cats["kv_live"]["bytes"] == 0
        assert cats["workspace"]["high_bytes"] == 0
        assert hbm["total_bytes"] == sum(
            c["bytes"] for c in cats.values())

        # Gauges are evaluated only at snapshot, so observe mid-stream:
        # after the first token the slot is still occupied.
        q = eng.submit(PROMPT, GREEDY)
        assert q.get(timeout=300) is not None
        cats = eng.debug_hbm()["categories"]
        assert cats["kv_live"]["bytes"] > 0
        while q.get(timeout=300) is not None:
            pass
        eng.drain(timeout=120)
        cats = eng.debug_hbm()["categories"]
        # The stream finished, so live KV is back to 0 — but its
        # watermark and the dispatch workspace recorded the traffic.
        assert cats["kv_live"]["bytes"] == 0
        assert cats["kv_live"]["high_bytes"] > 0
        assert cats["workspace"]["high_bytes"] > 0
        # Live fraction never exceeds the reservation.
        assert cats["kv_live"]["high_bytes"] <= cats["kv_cache"]["bytes"]
    finally:
        eng.stop()


def test_paged_engine_prorates_live_over_blocks(monkeypatch):
    monkeypatch.setenv("HBM_LEDGER", "1")
    eng = _engine(paged_kv=True, kv_block=16, kv_pool_blocks=9,
                  prompt_buckets=(16, 32))
    try:
        q = eng.submit(PROMPT, GREEDY)
        assert q.get(timeout=300) is not None  # admitted: blocks held
        live = eng.debug_hbm()["categories"]["kv_live"]["bytes"]
        while q.get(timeout=300) is not None:
            pass
        cats = eng.debug_hbm()["categories"]
        assert cats["kv_cache"]["bytes"] > 0
        assert 0 < live <= cats["kv_cache"]["bytes"]
        assert cats["kv_live"]["high_bytes"] >= live
    finally:
        eng.stop()


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("HBM_LEDGER", raising=False)
    eng = _engine(start=False)
    assert eng.debug_hbm() is None
