"""Multi-host slice lifecycle: env-driven config, readiness gating, and a
REAL 2-process CPU slice (subprocesses form one jax.distributed job and
run a cross-host collective)."""

import json
import os
import socket
import subprocess
import sys

import pytest

from seldon_tpu.parallel import distributed as D


# ---------------------------------------------------------------------------
# Pure config derivation (the env the reconciler injects)
# ---------------------------------------------------------------------------


def test_slice_config_from_statefulset_env():
    env = {
        "HOSTNAME": "mymodel-main-0-2",
        D.ENV_HOSTNAMES_SVC: "mymodel-main-0-hosts",
        D.ENV_WORKER_COUNT: "4",
    }
    cfg = D.slice_config_from_env(env)
    assert cfg.num_processes == 4
    assert cfg.process_id == 2
    # Coordinator = pod 0's stable DNS name under the headless service.
    assert cfg.coordinator == (
        f"mymodel-main-0-0.mymodel-main-0-hosts:{D.DEFAULT_COORDINATOR_PORT}"
    )


def test_slice_config_single_host_is_none():
    assert D.slice_config_from_env({}) is None
    assert D.slice_config_from_env(
        {D.ENV_HOSTNAMES_SVC: "svc", D.ENV_WORKER_COUNT: "1"}
    ) is None


def test_slice_config_bad_hostname_raises():
    with pytest.raises(RuntimeError):
        D.slice_config_from_env(
            {"HOSTNAME": "nopodordinal",
             D.ENV_HOSTNAMES_SVC: "svc", D.ENV_WORKER_COUNT: "2"}
        )


def test_pod_ordinal():
    assert D.pod_ordinal("x-main-0-3") == 3
    assert D.pod_ordinal("plainhost") is None


def test_readiness_single_host_passes():
    D.SliceReadiness(expected_hosts=1).check()  # devices exist (CPU mesh)


# ---------------------------------------------------------------------------
# Real slice formation: 2 subprocesses, one jax.distributed job
# ---------------------------------------------------------------------------


def test_two_processes_form_one_slice(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    worker = os.path.join(os.path.dirname(__file__), "slice_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))

    for report in outs:
        assert report["process_count"] == 2
        assert report["local_devices"] == 2
        assert report["global_devices"] == 4  # both hosts' devices visible
        assert report["allgather"] == [0, 1]  # cross-host collective worked
        assert report["sharded_sum"] == sum(range(16))


# ---------------------------------------------------------------------------
# Multi-process SERVING proof (VERDICT r2 item 4): an InferenceEngine
# sharded across a real 2-process jax.distributed mesh — TP axis spanning
# the processes — generates the same tokens as a single-process mesh run.
# ---------------------------------------------------------------------------


def test_engine_serves_across_two_processes(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    worker = os.path.join(os.path.dirname(__file__), "slice_serve_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, err.decode()[-3000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))

    # Both processes executed the same SPMD programs -> identical output.
    assert outs[0]["completions"] == outs[1]["completions"]
    completions = outs[0]["completions"]
    assert len(completions) == 6
    assert all(1 <= len(t) <= 6 + i for i, t in
               ((int(k), v) for k, v in completions.items()))

    # And they match the SAME logical program on a single-process
    # 8-device mesh (this pytest process: conftest's virtual CPU mesh).
    from tests.slice_serve_common import run_engine

    reference = run_engine()
    assert completions == reference
