"""graftsan (runtime concurrency sanitizer): witness + audit semantics.

The load-bearing claims, in test form:
 * env gating is fail-safe AND overhead-free: without GRAFTSAN=1 the
   engine keeps raw threading primitives, `_san is None`, and response
   queues are plain `queue.Queue` — nothing to pay on any hot path;
 * the lock-order witness raises on an injected inversion with a
   TWO-stack report (where the held lock was taken, where the violating
   acquisition happened), enforces the re-acquisition self-deadlock
   rule, and still allows legal RLock re-entry;
 * `assert_holds` is the runtime half of `# graftlint: holds(<lock>)`;
 * the boundary audit catches injected refcount drift in BOTH
   directions (phantom allocator ref = leak, phantom table ref = double
   free) and slot/free-list corruption — and the engine stays healthy
   once the injected damage is reverted;
 * TerminalQueue rejects anything put after the terminal sentinel;
 * greedy token output is BIT-IDENTICAL with the sanitizer on or off
   (the seeded perturbation is timing-only), and the perturbation
   streams are deterministic per seed with the same scheduler/fetcher
   RNG split as chaos;
 * the fuzz soak: >=200 mixed dense/paged/chunked requests under
   GRAFTSAN=1 finish with zero hung waiters, zero recorded violations,
   and a clean `debug_lifecycle_check()` (make fuzz-graftsan).
"""

import os
import queue
import random
import threading
import time

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import graftsan
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from seldon_tpu.servers.graftsan import (GraftsanViolation, Sanitizer,
                                         TerminalQueue)

PROMPT = list(range(2, 26))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)

PAGED = dict(paged_kv=True, kv_block=16, kv_pool_blocks=12,
             prompt_buckets=(16, 32))
CHUNKED = dict(chunked_prefill=True, prefill_chunk=8, prefix_block=8)


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


@pytest.fixture
def san_env(monkeypatch):
    monkeypatch.setenv("GRAFTSAN", "1")
    monkeypatch.setenv("GRAFTSAN_SEED", "0")


# ---------------------------------------------------------------------------
# Gating + zero overhead when off
# ---------------------------------------------------------------------------


def test_from_env_gate(monkeypatch):
    monkeypatch.delenv("GRAFTSAN", raising=False)
    assert Sanitizer.from_env() is None
    monkeypatch.setenv("GRAFTSAN", "0")
    assert Sanitizer.from_env() is None
    monkeypatch.setenv("GRAFTSAN", "1")
    monkeypatch.setenv("GRAFTSAN_SEED", "7")
    san = Sanitizer.from_env()
    assert san is not None and san.seed == 7


def test_zero_overhead_when_unset(monkeypatch):
    monkeypatch.delenv("GRAFTSAN", raising=False)
    eng = _engine(start=False)
    assert eng._san is None
    assert not isinstance(eng._book, graftsan._OrderedLock)
    assert not isinstance(eng._rid_lock, graftsan._OrderedLock)
    assert not isinstance(eng.stats.lock, graftsan._OrderedLock)
    q = eng.submit(PROMPT, GREEDY)
    assert type(q) is queue.Queue  # not TerminalQueue


def test_instrumented_engine_structures(san_env):
    eng = _engine(start=False, **PAGED)
    assert isinstance(eng._san, Sanitizer)
    assert isinstance(eng._book, graftsan._OrderedLock)
    assert isinstance(eng._rid_lock, graftsan._OrderedLock)
    assert isinstance(eng.stats.lock, graftsan._OrderedLock)
    assert isinstance(eng._allocator._lock, graftsan._OrderedLock)
    q = eng.submit(PROMPT, GREEDY)
    assert isinstance(q, TerminalQueue)


# ---------------------------------------------------------------------------
# Lock-order witness
# ---------------------------------------------------------------------------


def test_documented_order_is_silent():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    rid = san.wrap_lock(threading.Lock(), "_rid_lock")
    trie = san.wrap_lock(threading.Lock(), "trie._lock")
    alloc = san.wrap_lock(threading.Lock(), "allocator._lock")
    with book:
        with rid:
            pass
        with trie:
            with alloc:
                pass
    assert san.violations == []


def test_order_witness_two_stack_report():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    stats = san.wrap_lock(threading.Lock(), "stats.lock")
    with stats:  # leaf held: acquiring ANYTHING under it is a violation
        with pytest.raises(GraftsanViolation) as ei:
            with book:
                pass
    v = ei.value.violation
    assert v.kind == "lock-order"
    assert "'_book'" in v.message and "'stats.lock'" in v.message
    assert "leaf" in v.message
    assert v.stack and v.other_stack  # both participating sites captured
    assert san.violations == [v]
    rendered = ei.value.args[0]
    assert "detected at" in rendered and "conflicting event" in rendered


def test_order_witness_rank_inversion():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    trie = san.wrap_lock(threading.Lock(), "trie._lock")
    with trie:
        with pytest.raises(GraftsanViolation, match="inverts"):
            with book:
                pass


def test_reacquisition_self_deadlock():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    with book:
        with pytest.raises(GraftsanViolation, match="self-deadlock"):
            book.acquire()


def test_rlock_reentry_is_legal():
    san = Sanitizer()
    lk = san.wrap_lock(threading.RLock(), "Engine._jit_lock")
    with lk:
        with lk:
            pass
    assert san.violations == []


def test_wrap_lock_is_idempotent():
    san = Sanitizer()
    lk = san.wrap_lock(threading.Lock(), "_book")
    assert san.wrap_lock(lk, "_book") is lk


def test_assert_holds():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    with book:
        san.assert_holds("_book")  # satisfied, silent
    with pytest.raises(GraftsanViolation) as ei:
        san.assert_holds("_book")
    assert ei.value.violation.kind == "holds"
    assert "holds(_book)" in ei.value.args[0] or "_book" in ei.value.args[0]


def test_held_stacks_are_per_thread():
    san = Sanitizer()
    book = san.wrap_lock(threading.Lock(), "_book")
    stats = san.wrap_lock(threading.Lock(), "stats.lock")
    errs = []

    def other():
        # This thread holds nothing: taking _book here is clean even
        # while the main thread holds the leaf.
        try:
            with book:
                pass
        except GraftsanViolation as e:  # pragma: no cover
            errs.append(e)

    with stats:
        t = threading.Thread(target=other)
        t.start()
        t.join(timeout=10)
    assert not t.is_alive() and errs == []
    assert san.violations == []


# ---------------------------------------------------------------------------
# Terminal-item protocol
# ---------------------------------------------------------------------------


def test_terminal_queue_rejects_items_after_sentinel():
    san = Sanitizer()
    q = TerminalQueue(san)
    q.put({"tokens": [1]})
    q.put(None)
    with pytest.raises(GraftsanViolation) as ei:
        q.put({"tokens": [2]})
    v = ei.value.violation
    assert v.kind == "terminal"
    assert v.other_stack  # where the original sentinel was put
    with pytest.raises(GraftsanViolation, match="second terminal"):
        q.put(None)
    assert len(san.violations) == 2


# ---------------------------------------------------------------------------
# Boundary audits with injected damage
# ---------------------------------------------------------------------------


def test_slot_audit_catches_free_list_corruption(san_env):
    eng = _engine()
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        with eng._book:
            eng._san.audit(eng)  # quiescent engine: clean
            eng._free.append(eng._free[0])  # inject a duplicate entry
            with pytest.raises(GraftsanViolation) as ei:
                eng._san.audit(eng)
            assert ei.value.violation.kind == "slot-audit"
            eng._free.pop()
            eng._san.violations.clear()
        eng.generate_blocking(PROMPT, GREEDY)  # engine still healthy
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


def test_refcount_audit_catches_injected_leak(san_env):
    eng = _engine(**PAGED)
    try:
        eng.generate_blocking(PROMPT, GREEDY)
        with eng._book:
            eng._san.audit(eng)
            # A ref the live tables know nothing about = leaked block.
            eng._allocator._refs[9999] = 1
            with pytest.raises(GraftsanViolation) as ei:
                eng._san.audit(eng)
            v = ei.value.violation
            assert v.kind == "refcount" and "leak" in v.message
            del eng._allocator._refs[9999]
            eng._san.violations.clear()
        eng.generate_blocking(PROMPT, GREEDY)
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


def test_refcount_audit_catches_injected_double_free(san_env):
    eng = _engine(**PAGED)
    try:
        q = eng.submit(PROMPT, SamplingParams(
            temperature=0.0, max_new_tokens=32))
        # Catch the request mid-decode: poll under _book until it is
        # admitted and owns blocks, then tamper + audit in the SAME
        # _book hold so it cannot complete underneath us.
        deadline = time.monotonic() + 120
        caught = False
        while not caught and time.monotonic() < deadline:
            with eng._book:
                with eng._rid_lock:
                    reqs = list(eng._requests.values())
                if reqs and reqs[0].block_ids:
                    caught = True
                    req = reqs[0]
                    # A table ref the allocator never granted = double
                    # free waiting to happen on release.
                    req.block_ids.append(7777)
                    with pytest.raises(GraftsanViolation) as ei:
                        eng._san.audit(eng)
                    v = ei.value.violation
                    assert v.kind == "refcount"
                    assert "double free" in v.message
                    req.block_ids.pop()
                    eng._san.violations.clear()
            if not caught:
                time.sleep(0.005)
        assert caught, "request never observed mid-decode"
        while q.get(timeout=120) is not None:
            pass
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Determinism: perturbation streams + bit-exact output
# ---------------------------------------------------------------------------


def test_perturb_streams_split_and_deterministic():
    a, b = Sanitizer(seed=3), Sanitizer(seed=3)
    for _ in range(50):
        a.perturb("dispatch")
        a.perturb("reap")
        b.perturb("dispatch")
        b.perturb("reap")
    # same seed, same sites -> same stream position
    assert a._sched_rng.random() == b._sched_rng.random()
    # boundary draws come from the independent fetcher stream: burning
    # them must not move the scheduler stream (chaos RNG-split rule)
    c, d = Sanitizer(seed=3), Sanitizer(seed=3)
    for _ in range(50):
        c.perturb("boundary")
    assert c._sched_rng.random() == d._sched_rng.random()
    assert c._fetch_rng.random() != d._fetch_rng.random()


@pytest.mark.parametrize("mode", ["dense", "paged", "chunked"])
def test_greedy_output_bit_identical_with_sanitizer(mode, monkeypatch):
    ekw = {"dense": {}, "paged": PAGED, "chunked": CHUNKED}[mode]
    monkeypatch.delenv("GRAFTSAN", raising=False)
    eng = _engine(**ekw)
    try:
        want = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
    finally:
        eng.stop()

    monkeypatch.setenv("GRAFTSAN", "1")
    monkeypatch.setenv("GRAFTSAN_SEED", "0")
    eng = _engine(**ekw)
    try:
        got = eng.generate_blocking(PROMPT, GREEDY)["token_ids"]
        assert eng._san is not None
        assert eng._san.violations == []
        assert eng._san.audits > 0  # the boundary audit actually ran
    finally:
        eng.stop()
    assert got == want


# ---------------------------------------------------------------------------
# Fuzz soak: mixed dense/paged/chunked under the sanitizer
# ---------------------------------------------------------------------------


def _run_soak(eng, n, seed, cancel_frac=0.1):
    """Submit n requests (sizes drawn main-thread from a fixed seed so
    a run replays exactly), consume each from its own waiter thread,
    cancel a fraction mid-stream. Returns (finished, hung)."""
    rng = random.Random(seed)
    threads = []

    def consume(q, want_cancel):
        sent = False
        while True:
            item = q.get(timeout=300)
            if item is None:
                return
            if want_cancel and not sent and "error" not in item:
                sent = True
                eng.cancel(q.rid)

    for i in range(n):
        plen = rng.choice((5, 8, 13, 21))
        prompt = [2 + (i + j) % 200 for j in range(plen)]
        sp = SamplingParams(temperature=0.0,
                            max_new_tokens=rng.choice((4, 8)))
        want_cancel = rng.random() < cancel_frac
        try:
            q = eng.submit(prompt, sp)
        except RuntimeError:  # shed under load: an outcome, not a hang
            continue
        t = threading.Thread(target=consume, args=(q, want_cancel),
                             daemon=True)
        t.start()
        threads.append(t)

    stop_by = time.monotonic() + 300
    hung = 0
    for t in threads:
        t.join(timeout=max(0.0, stop_by - time.monotonic()))
        if t.is_alive():
            hung += 1
    return len(threads), hung


@pytest.mark.fuzz
@pytest.mark.slow
@pytest.mark.parametrize("mode", ["dense", "paged", "chunked"])
def test_graftsan_soak_mixed(mode, monkeypatch):
    """>=200 requests across the three modes (make fuzz-graftsan): the
    sanitizer's witness + audits stay silent on the real engine, every
    waiter sees a sentinel, nothing leaks."""
    monkeypatch.setenv("GRAFTSAN", "1")
    seed = int(os.environ.get("GRAFTSAN_SEED", "0"))
    monkeypatch.setenv("GRAFTSAN_SEED", str(seed))
    n = max(1, int(os.environ.get("FUZZ_EXAMPLES", "210")) // 3)
    ekw = {"dense": {}, "paged": PAGED, "chunked": CHUNKED}[mode]
    eng = _engine(max_slots=8, max_queue=4 * n, **ekw)
    try:
        finished, hung = _run_soak(eng, n, seed=seed)
        assert hung == 0, f"{hung} waiters never saw a sentinel"
        assert finished > 0
        assert eng.drain(timeout=300) is True
        assert eng._san.audits > 0
        assert eng._san.violations == [], [
            v.render() for v in eng._san.violations]
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
