"""Orchestrator tests: graph semantics (mirrors reference engine unit tests,
SURVEY.md §4 — hardcoded impls, no microservices) plus end-to-end walks
against real in-process unit servers (fixed-output model trick from
testing/docker/fixed-model)."""

import asyncio
import os
import threading

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.orchestrator.batcher import MicroBatcher
from seldon_tpu.orchestrator.spec import (
    PredictorSpec,
    PredictiveUnit,
    default_unit_types,
    load_predictor_spec,
    validate_spec,
)
from seldon_tpu.orchestrator.server import EngineServer, GraphReadyChecker
from seldon_tpu.orchestrator.walker import PredictorEngine
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime.wrapper import build_grpc_server


def spec_from(d) -> PredictorSpec:
    s = PredictorSpec.from_dict(d)
    default_unit_types(s.graph)
    return s


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


# ---------------------------------------------------------------------------
# Hardcoded graphs (no network)
# ---------------------------------------------------------------------------


def test_simple_model_graph():
    spec = spec_from(
        {"name": "p", "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}}
    )
    eng = PredictorEngine(spec)
    req = payloads.build_message(np.array([[1.0, 2.0]]), kind="ndarray")
    out = run(eng.predict(req))
    arr = payloads.get_data_from_message(out)
    np.testing.assert_allclose(arr, [[0.9, 0.05, 0.05]])
    assert out.meta.requestPath["m"] == "m"
    assert out.meta.puid


def test_abtest_routing_and_request_path():
    spec = spec_from(
        {
            "name": "p",
            "graph": {
                "name": "ab",
                "implementation": "RANDOM_ABTEST",
                "children": [
                    {"name": "a", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "implementation": "SIMPLE_MODEL"},
                ],
            },
        }
    )
    eng = PredictorEngine(spec)
    branches = set()
    for i in range(40):
        req = payloads.build_message(np.array([[1.0]]), kind="ndarray")
        req.meta.puid = f"req-{i}"
        out = run(eng.predict(req))
        b = out.meta.routing["ab"]
        branches.add(b)
        # requestPath contains only the taken branch.
        taken = "a" if b == 0 else "b"
        other = "b" if b == 0 else "a"
        assert taken in out.meta.requestPath
        assert other not in out.meta.requestPath
        # Same puid must route identically (deterministic hash).
        out2 = run(eng.predict(req))
        assert out2.meta.routing["ab"] == b
    assert branches == {0, 1}  # both branches exercised over 40 puids


def test_average_combiner_graph():
    spec = spec_from(
        {
            "name": "p",
            "graph": {
                "name": "c",
                "implementation": "AVERAGE_COMBINER",
                "children": [
                    {"name": "a", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "implementation": "SIMPLE_MODEL"},
                ],
            },
        }
    )
    eng = PredictorEngine(spec)
    out = run(eng.predict(payloads.build_message(np.array([[1.0]]), kind="ndarray")))
    arr = payloads.get_data_from_message(out)
    np.testing.assert_allclose(arr, [[0.9, 0.05, 0.05]])  # mean of identical
    assert set(out.meta.requestPath) == {"c", "a", "b"}


def test_validate_spec_catches_bad_graphs():
    bad = PredictorSpec.from_dict(
        {"name": "p", "graph": {"name": "r", "type": "ROUTER"}}
    )
    problems = validate_spec(bad)
    assert any("no children" in p for p in problems)
    dup = spec_from(
        {
            "name": "p",
            "graph": {
                "name": "x",
                "implementation": "SIMPLE_MODEL",
                "children": [{"name": "x", "implementation": "SIMPLE_MODEL"}],
            },
        }
    )
    assert any("duplicate" in p for p in validate_spec(dup))


def test_load_predictor_spec_from_env(monkeypatch):
    import base64
    import json

    d = {"name": "p", "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}}
    monkeypatch.setenv(
        "ENGINE_PREDICTOR", base64.b64encode(json.dumps(d).encode()).decode()
    )
    spec = load_predictor_spec()
    assert spec.graph.name == "m"


# ---------------------------------------------------------------------------
# Real microservice units over sockets (fixed-output model trick)
# ---------------------------------------------------------------------------


class FixedModel:
    """Reference testing/docker/fixed-model/ModelV1.py analogue."""

    def __init__(self, values, image="fixed:0.1"):
        self.values = np.asarray(values, dtype=np.float64)
        self.image = image

    def predict(self, X, names, meta=None):
        return np.tile(self.values, (np.asarray(X).shape[0], 1))

    def tags(self):
        return {"image": self.image}


class FixedRouter:
    def __init__(self, branch):
        self.branch = branch
        self.feedback_seen = []

    def route(self, X, names):
        return self.branch

    def send_feedback(self, features, names, reward, truth, routing=None):
        self.feedback_seen.append((reward, routing))


@pytest.fixture()
def unit_servers():
    """Spin up gRPC unit servers; yields {name: (port, user_obj)}."""
    servers = []
    units = {}

    def serve(name, obj):
        srv = build_grpc_server(obj)
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        servers.append(srv)
        units[name] = (port, obj)

    serve("m1", FixedModel([[1, 2, 3, 4]], image="fixed:0.1"))
    serve("m2", FixedModel([[5, 6, 7, 8]], image="fixed:0.2"))
    serve("r", FixedRouter(1))
    yield units
    for s in servers:
        s.stop(0)


def graph_with_router(units):
    return spec_from(
        {
            "name": "p",
            "graph": {
                "name": "router",
                "type": "ROUTER",
                "endpoint": {
                    "service_host": "127.0.0.1",
                    "service_port": units["r"][0],
                    "type": "GRPC",
                },
                "children": [
                    {
                        "name": "m1",
                        "type": "MODEL",
                        "image": "fixed:0.1",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": units["m1"][0],
                            "type": "GRPC",
                        },
                    },
                    {
                        "name": "m2",
                        "type": "MODEL",
                        "image": "fixed:0.2",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": units["m2"][0],
                            "type": "GRPC",
                        },
                    },
                ],
            },
        }
    )


def test_router_graph_over_grpc(unit_servers):
    eng = PredictorEngine(graph_with_router(unit_servers))

    async def go():
        req = payloads.build_message(np.array([[1.0, 2.0]]), kind="dense")
        out = await eng.predict(req)
        await eng.close()
        return out

    out = run(go())
    arr = payloads.get_data_from_message(out)
    np.testing.assert_allclose(arr, [[5, 6, 7, 8]])  # router sent to m2
    assert out.meta.routing["router"] == 1
    assert out.meta.requestPath["m2"] == "fixed:0.2"
    assert "m1" not in out.meta.requestPath
    # tags from the serving unit propagate
    assert out.meta.tags["image"].string_value == "fixed:0.2"


def test_feedback_follows_routing(unit_servers):
    eng = PredictorEngine(graph_with_router(unit_servers))

    async def go():
        fb = pb.Feedback()
        fb.reward = 0.75
        fb.response.meta.puid = "x"
        fb.response.meta.routing["router"] = 1
        fb.request.CopyFrom(
            payloads.build_message(np.array([[1.0]]), kind="dense")
        )
        await eng.send_feedback(fb)
        await eng.close()

    run(go())
    router_obj = unit_servers["r"][1]
    assert router_obj.feedback_seen, "router should receive feedback"
    assert router_obj.feedback_seen[0][0] == 0.75


def test_feedback_reward_hook_records_counter(unit_servers):
    """Engine-level rewards ride the dedicated reward_hook into the
    built-in counter — a fabricated custom pb.Metric would collide with
    that counter's registry name and be silently dropped (r5 fix)."""
    from seldon_tpu.runtime.metrics_server import ServerMetrics

    sm = ServerMetrics()
    seen = []
    eng = PredictorEngine(
        graph_with_router(unit_servers),
        reward_hook=lambda unit, r: (seen.append(unit.name),
                                     sm.record_reward(unit.name, r)),
    )

    async def go():
        fb = pb.Feedback()
        fb.reward = 0.75
        fb.response.meta.puid = "x"
        await eng.send_feedback(fb)
        await eng.close()

    run(go())
    assert seen, "reward hook should fire for model/router units"
    body, _ = sm.export()
    # The SAMPLE line, not just the header (# HELP/# TYPE lines exist
    # even when nothing was recorded).
    assert b'seldon_api_model_feedback_reward_total{unit="' in body
    assert b"} 0.75" in body


def test_combiner_over_microservices(unit_servers):
    spec = spec_from(
        {
            "name": "p",
            "graph": {
                "name": "comb",
                "implementation": "AVERAGE_COMBINER",
                "children": [
                    {
                        "name": "m1",
                        "type": "MODEL",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": unit_servers["m1"][0],
                            "type": "GRPC",
                        },
                    },
                    {
                        "name": "m2",
                        "type": "MODEL",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": unit_servers["m2"][0],
                            "type": "GRPC",
                        },
                    },
                ],
            },
        }
    )
    eng = PredictorEngine(spec)

    async def go():
        out = await eng.predict(
            payloads.build_message(np.array([[0.0]]), kind="dense")
        )
        await eng.close()
        return out

    out = run(go())
    arr = payloads.get_data_from_message(out)
    np.testing.assert_allclose(arr, [[3, 4, 5, 6]])  # mean of [1..4],[5..8]


# ---------------------------------------------------------------------------
# Micro-batcher
# ---------------------------------------------------------------------------


class CountingModel:
    def __init__(self):
        self.calls = 0
        self.rows = []

    def predict(self, X, names, meta=None):
        X = np.asarray(X)
        self.calls += 1
        self.rows.append(X.shape[0])
        return X * 2.0


def test_batcher_fuses_concurrent_requests():
    obj = CountingModel()
    srv = build_grpc_server(obj)
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        unit = PredictiveUnit.from_dict(
            {
                "name": "m",
                "type": "MODEL",
                "endpoint": {
                    "service_host": "127.0.0.1",
                    "service_port": port,
                    "type": "GRPC",
                },
            }
        )
        from seldon_tpu.orchestrator.client import InternalClient

        async def go():
            batcher = MicroBatcher(max_batch_size=64, window_ms=20.0)
            client = InternalClient()
            reqs = [
                payloads.build_message(
                    np.full((1, 3), float(i)), kind="dense"
                )
                for i in range(8)
            ]
            for i, r in enumerate(reqs):
                r.meta.puid = f"p{i}"
            outs = await asyncio.gather(
                *(batcher.call(unit, r, client) for r in reqs)
            )
            await client.close()
            return outs, batcher

        outs, batcher = run(go())
        # All 8 requests answered correctly (row i doubled).
        for i, o in enumerate(outs):
            arr = payloads.get_data_from_message(o)
            np.testing.assert_allclose(arr, np.full((1, 3), 2.0 * i))
            assert o.meta.puid == f"p{i}"
        # They fused into far fewer leaf calls than 8.
        assert obj.calls < 8
        assert batcher.stats["fused_calls"] >= 1
    finally:
        srv.stop(0)


# ---------------------------------------------------------------------------
# Engine server (REST external surface)
# ---------------------------------------------------------------------------


def test_engine_server_multipart_prediction():
    """multipart/form-data predictions: file parts -> binData/strData,
    plain fields -> JSON subtrees (reference engine
    RestClientController.java:152-201)."""
    spec = spec_from(
        {"name": "p", "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}}
    )

    async def go():
        import aiohttp

        server = EngineServer(spec=spec, http_port=0, grpc_port=0)
        await server.start(host="127.0.0.1")
        url = f"http://127.0.0.1:{server.http_port}"
        async with aiohttp.ClientSession() as s:
            # Binary file part under binData + a meta JSON field.
            form = aiohttp.FormData()
            form.add_field("binData", b"\x00\x01\xffpayload",
                           filename="blob.bin",
                           content_type="application/octet-stream")
            form.add_field("meta", '{"tags": {"src": "upload"}}')
            async with s.post(f"{url}/api/v0.1/predictions", data=form) as r:
                body = await r.json()
                status = r.status
            # strData file part (case-insensitive key, reference parity).
            form2 = aiohttp.FormData()
            form2.add_field("strdata", b"hello text",
                            filename="doc.txt", content_type="text/plain")
            async with s.post(f"{url}/api/v0.1/predictions", data=form2) as r2:
                status2 = r2.status
                body2 = await r2.json()
        await server.stop()
        return status, body, status2, body2

    status, body, status2, body2 = run(go())
    assert status == 200, body
    # binData input has no array kind -> model answers in dense form.
    assert body["data"]["names"] == ["proba0", "proba1", "proba2"]
    assert body["meta"]["tags"]["src"] == "upload"  # meta field parsed
    assert status2 == 200, body2


def test_parse_multipart_message_fields():
    """_merge_multipart maps parts onto the SeldonMessage oneof."""
    import base64

    from seldon_tpu.core.http import _merge_multipart

    class FileLike:
        def __init__(self, data):
            import io
            self.file = io.BytesIO(data)

    form = {
        "binData": FileLike(b"\x01\x02\x03"),
        "meta": '{"puid": "abc"}',
    }
    msg = _merge_multipart(form, pb.SeldonMessage)
    assert msg.binData == b"\x01\x02\x03"
    assert msg.meta.puid == "abc"

    msg2 = _merge_multipart({"strData": FileLike(b"text here")},
                            pb.SeldonMessage)
    assert msg2.strData == "text here"
    # Plain base64 text field under binData.
    msg3 = _merge_multipart(
        {"bindata": base64.b64encode(b"zz").decode()}, pb.SeldonMessage
    )
    assert msg3.binData == b"zz"


def test_engine_server_rest_roundtrip():
    spec = spec_from(
        {"name": "p", "graph": {"name": "m", "implementation": "SIMPLE_MODEL"}}
    )

    async def go():
        import aiohttp

        server = EngineServer(spec=spec, http_port=0, grpc_port=0)
        await server.start(host="127.0.0.1")
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{server.http_port}"
            body = {"data": {"ndarray": [[1.0, 2.0]]}}
            async with s.post(f"{url}/api/v0.1/predictions", json=body) as r:
                assert r.status == 200
                out = await r.json()
            async with s.get(f"{url}/ready") as r:
                ready_status = r.status
            async with s.get(f"{url}/pause") as r:
                assert r.status == 200
            async with s.post(f"{url}/api/v0.1/predictions", json=body) as r:
                paused_status = r.status
            async with s.get(f"{url}/unpause") as r:
                assert r.status == 200
            async with s.get(f"{url}/prometheus") as r:
                prom = await r.text()
        await server.stop()
        return out, ready_status, paused_status, prom

    out, ready_status, paused_status, prom = run(go())
    assert out["data"]["ndarray"] == [[0.9, 0.05, 0.05]]
    assert ready_status == 200
    assert paused_status == 503
    assert "engine" in prom or "seldon" in prom or prom  # prometheus text


def test_multiworker_engine_shares_port():
    """--workers N: worker processes share ports via SO_REUSEPORT and all
    serve the graph (reference's Java engine used every core; the asyncio
    engine scales with processes)."""
    import json as _json
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        http_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        grpc_port = s.getsockname()[1]

    proc = subprocess.Popen(
        [sys.executable, "-m", "seldon_tpu.orchestrator.server",
         "--workers", "2", "--http-port", str(http_port),
         "--grpc-port", str(grpc_port), "--no-batching"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(os.path.dirname(__file__)),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        body = _json.dumps({"data": {"ndarray": [[1.0]]}}).encode()
        deadline = time.time() + 60
        out = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{http_port}/api/v0.1/predictions",
                    data=body, headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as r:
                    out = _json.loads(r.read())
                break
            except Exception:
                if proc.poll() is not None:
                    raise AssertionError(
                        proc.stdout.read().decode()[-2000:]
                    )
                time.sleep(0.3)
        assert out is not None, "engine never came up"
        # SIMPLE_MODEL fallback graph answered.
        assert out["meta"]["requestPath"], out
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Engine -> unit identity headers (reference Seldon-model-name/image/version,
# InternalPredictionService.java:191-370)
# ---------------------------------------------------------------------------


def test_identity_headers_parse_image_tag():
    from seldon_tpu.orchestrator.client import identity_headers

    u = PredictiveUnit(name="clf", image="repo/img:1.2")
    assert identity_headers(u) == {
        "seldon-model-name": "clf",
        "seldon-model-image": "repo/img",
        "seldon-model-version": "1.2",
    }
    bare = PredictiveUnit(name="clf", image="repo/img")
    assert identity_headers(bare)["seldon-model-image"] == "repo/img"
    assert identity_headers(bare)["seldon-model-version"] == ""


def test_identity_headers_sent_on_every_rest_hop():
    """Each engine->unit REST call carries the hop's identity headers."""
    from aiohttp import web

    from seldon_tpu.core.http import PROTO_CONTENT_TYPE

    seen = {}

    async def go():
        async def handle(request: web.Request) -> web.Response:
            seen[request.headers["seldon-model-name"]] = {
                "image": request.headers.get("seldon-model-image"),
                "version": request.headers.get("seldon-model-version"),
            }
            out = payloads.build_message(np.array([[1.0]]), kind="dense")
            return web.Response(
                body=out.SerializeToString(),
                content_type=PROTO_CONTENT_TYPE.split(";")[0],
            )

        app = web.Application()
        app.router.add_post("/predict", handle)
        app.router.add_post("/transform-input", handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        s = spec_from(
            {
                "name": "p",
                "graph": {
                    "name": "t",
                    "type": "TRANSFORMER",
                    "image": "trans:0.3",
                    "endpoint": {
                        "service_host": "127.0.0.1",
                        "service_port": port,
                        "type": "REST",
                    },
                    "children": [
                        {
                            "name": "m",
                            "type": "MODEL",
                            "image": "model:0.7",
                            "endpoint": {
                                "service_host": "127.0.0.1",
                                "service_port": port,
                                "type": "REST",
                            },
                        }
                    ],
                },
            }
        )
        eng = PredictorEngine(s)
        req = payloads.build_message(np.array([[1.0, 2.0]]), kind="dense")
        await eng.predict(req)
        await eng.close()
        await runner.cleanup()

    run(go())
    assert seen == {
        "t": {"image": "trans", "version": "0.3"},
        "m": {"image": "model", "version": "0.7"},
    }


def test_identity_headers_registry_port_and_digest():
    from seldon_tpu.orchestrator.client import identity_headers

    # Untagged image on a port-qualified registry: the ':' belongs to the
    # registry, not a tag.
    u = PredictiveUnit(name="m", image="localhost:5000/team/model")
    assert identity_headers(u) == {
        "seldon-model-name": "m",
        "seldon-model-image": "localhost:5000/team/model",
        "seldon-model-version": "",
    }
    # Tagged image on a port-qualified registry.
    u = PredictiveUnit(name="m", image="localhost:5000/team/model:2.1")
    h = identity_headers(u)
    assert h["seldon-model-image"] == "localhost:5000/team/model"
    assert h["seldon-model-version"] == "2.1"
    # Digest ref: no tag to extract.
    u = PredictiveUnit(name="m", image="repo/img@sha256:abc123")
    h = identity_headers(u)
    assert h["seldon-model-image"] == "repo/img@sha256:abc123"
    assert h["seldon-model-version"] == ""


def test_identity_metadata_sent_on_grpc_hop():
    """gRPC hops carry the identity as (lowercase) gRPC metadata, observed
    by a real server interceptor (build_grpc_server(interceptors=...))."""
    import grpc as _grpc

    seen = {}

    class MetaInterceptor(_grpc.ServerInterceptor):
        def intercept_service(self, continuation, details):
            md = dict(details.invocation_metadata)
            if "seldon-model-name" in md:
                seen[md["seldon-model-name"]] = (
                    md.get("seldon-model-image"),
                    md.get("seldon-model-version"),
                )
            return continuation(details)

    srv = build_grpc_server(
        FixedModel([[1.0, 2.0]], image="fixed:0.1"),
        interceptors=[MetaInterceptor()],
    )
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    s = spec_from(
        {
            "name": "p",
            "graph": {
                "name": "m",
                "type": "MODEL",
                "image": "img:9.9",
                "endpoint": {
                    "service_host": "127.0.0.1",
                    "service_port": port,
                    "type": "GRPC",
                },
            },
        }
    )
    eng = PredictorEngine(s)

    async def go():
        req = payloads.build_message(np.array([[1.0, 2.0]]), kind="dense")
        out = await eng.predict(req)
        await eng.close()
        return out

    run(go())
    srv.stop(0)
    assert seen == {"m": ("img", "9.9")}, seen


def test_engine_calls_json_rest_unit():
    """A foreign-language JSON-only REST unit (the docs/wrappers.md
    contract — mirrored on examples/wrappers/go/server.go's behavior)
    serves inside a graph when its endpoint declares content: json."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class GoLikeUnit(BaseHTTPRequestHandler):
        def do_POST(self):
            assert self.headers["Content-Type"] == "application/json"
            body = _json.loads(
                self.rfile.read(int(self.headers["Content-Length"]))
            )
            rows = [[v * 2 for v in row]
                    for row in body.get("data", {}).get("ndarray", [])]
            out = {
                "meta": {**body.get("meta", {}),
                         "tags": {"server": "go-doubler"}},
                "data": {"names": ["doubled"], "ndarray": rows},
            }
            payload = _json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), GoLikeUnit)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        eng = PredictorEngine(spec_from({
            "name": "p",
            "graph": {
                "name": "gounit",
                "type": "MODEL",
                "image": "go-doubler:1",
                "endpoint": {
                    "service_host": "127.0.0.1",
                    "service_port": port,
                    "type": "REST",
                    "content": "json",
                },
            },
        }))
        msg = payloads.build_message(
            np.array([[1.0, 2.5]]), names=["a", "b"], kind="ndarray"
        )

        async def run():
            # Single loop for predict AND close: the client session's
            # transports belong to this loop.
            out = await eng.predict(msg)
            await eng.close()
            return out

        out = asyncio.run(run())
        arr = payloads.get_data_from_message(out)
        np.testing.assert_allclose(np.asarray(arr, float), [[2.0, 5.0]])
        assert "gounit" in out.meta.requestPath
        assert out.meta.puid
    finally:
        srv.shutdown()
        t.join(timeout=5)


def test_json_rest_unit_malformed_body_is_unit_failure():
    """A 200 with an unparseable body from a foreign unit must surface
    as UnitCallError (-> ENGINE_UNIT_FAILURE), not an engine crash."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class BrokenUnit(BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers["Content-Length"]))
            payload = b'{"data": {"ndarray": [[1.0'  # truncated JSON
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), BrokenUnit)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        eng = PredictorEngine(spec_from({
            "name": "p",
            "graph": {
                "name": "broken", "type": "MODEL", "image": "broken:1",
                "endpoint": {"service_host": "127.0.0.1",
                             "service_port": port, "type": "REST",
                             "content": "json"},
            },
        }))
        msg = payloads.build_message(np.array([[1.0]]), kind="ndarray")

        async def run():
            from seldon_tpu.orchestrator.client import UnitCallError

            try:
                await eng.predict(msg)
                raise AssertionError("expected UnitCallError")
            except UnitCallError as e:
                assert "unparseable" in str(e)
            finally:
                await eng.close()

        asyncio.run(run())
    finally:
        srv.shutdown()
        t.join(timeout=5)
