"""Docs that are generated must not drift from their source of truth."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_reference_up_to_date():
    """docs/api-reference.md == tools/gen_api_reference.py's output
    (the doc is generated from core/openapi.py — the same spec served
    live at /seldon.json)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_api_reference.py"),
         "--check"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr


def test_api_reference_documents_meta_merge():
    """The VERDICT-required Meta semantics are spelled out: tag override
    order, routing bookkeeping, metric accumulation."""
    with open(os.path.join(REPO, "docs", "api-reference.md")) as f:
        doc = f.read()
    for needle in ("Meta merge semantics", "tags", "routing",
                   "requestPath", "metrics", "puid", "multipart"):
        assert needle in doc, f"api-reference.md missing {needle!r}"
